# Developer entry points.  `test` = tier-1 (fast, chaos excluded via the
# slow marker) followed by the chaos suite; `chaos` = the fault-injection
# suite alone, fixed seed (docs/ROBUSTNESS.md).
PY ?= python
CTT_CHAOS_SEED ?= 7

.PHONY: test tier1 chaos native clean

test: tier1 chaos

tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

chaos:
	JAX_PLATFORMS=cpu CTT_CHAOS_SEED=$(CTT_CHAOS_SEED) \
		$(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

native:
	$(MAKE) -C native

clean:
	$(MAKE) -C native clean
