# Developer entry points.
#   test            = lint, then tier-1 (fast; chaos excluded via the slow
#                     marker), then tier-2, then the full chaos suite
#   lint            = ctlint static analysis (docs/ANALYSIS.md): the
#                     executor-contract / atomic-write / lock-discipline /
#                     fault-coverage / jit-hygiene / drain-safety rules;
#                     exit 1 on findings (CI gate)
#   tier1           = the fast suite alone
#   tier2           = the slow-marked non-chaos tests: a handful of
#                     compile-heavy e2e variants (~2 min of XLA compiles)
#                     whose coverage overlaps faster tier-1 siblings; kept
#                     out of tier1 so the fast gate stays under its time
#                     budget, still part of `make test`
#   chaos           = the whole fault-injection suite, fixed seed — kills/
#                     resume, the silent-failure scenarios (hang, chunk
#                     corruption, job loss), and the resource-exhaustion /
#                     preemption scenario from the graceful-degradation layer
#   chaos-resource  = only the resource chaos: watershed->graph->multicut
#                     under seeded oom+enospc faults and a real SIGTERM
#                     mid-run (drain -> requeue-exit -> resume), asserting a
#                     bit-identical final segmentation (docs/ROBUSTNESS.md
#                     "Graceful degradation"); tier-1 stays fast because the
#                     chaos+slow markers keep it out of `tier1`
#   failures-report = one-screen post-mortem of a run's failures.json
#                     (pass TMP=/path/to/tmp_folder or .../failures.json),
#                     plus the per-task chunk-IO metrics when recorded and
#                     the trace summary when the run was traced
#                     (CTT_TRACE=1; docs/OBSERVABILITY.md); use
#                     `python scripts/failures_report.py --json TMP` for
#                     the machine-readable combined document
#   progress        = live run status from the heartbeat files and block
#                     markers (pass TMP=/path/to/tmp_folder): per-task
#                     state (done / in-flight / stalled? / failed), blocks
#                     markered, quarantines, stale-heartbeat warnings
#                     (docs/OBSERVABILITY.md); rc 1 when anything is
#                     stalled or failed
#   bench-io        = IO-amplification bench (docs/PERFORMANCE.md
#                     "Chunk-aware I/O"): the halo'd watershed sweep with
#                     the decompressed-chunk cache off vs on, asserting
#                     bit-identical outputs; cpu backend, <60 s
#   bench-fuse      = task-graph-fusion bench (docs/PERFORMANCE.md
#                     "Task-graph fusion"): the watershed->graph->costs->
#                     multicut workflow with in-memory handoffs off vs on,
#                     recording intermediate bytes written, wall time, and
#                     bit-identity into BENCH_r08.json; cpu backend (a
#                     <10 s correctness smoke twin runs inside tier1 via
#                     tests/test_handoff.py)
#   bench-sweep     = dispatch-amortization bench (docs/PERFORMANCE.md
#                     "Sharded sweeps"): per-block dispatch vs one sharded
#                     program per Morton batch at 64^3/16^3, recording
#                     throughput, dispatch counts, and bit-identity into
#                     BENCH_r07.json; cpu backend, <30 s (a <10 s smoke
#                     twin runs inside tier1 via tests/test_sharded.py)
#   bench-ragged    = ragged paged-pool bench (docs/PERFORMANCE.md "Ragged
#                     sweeps"): an edge/split-heavy sweep on a non-pow2
#                     27-block grid (clipped edges + 8 forced degrade-
#                     splits) run per-block vs through the paged block
#                     pool, recording compiled-dispatch counts (>=8x
#                     fewer), ragged-lane attribution, and bit-identity
#                     into BENCH_r11.json; cpu backend, <10 s (a smoke
#                     twin runs inside tier1 via tests/test_ragged.py)
#   bench-device    = device-resident data-plane bench (docs/PERFORMANCE.md
#                     "Device-resident data plane"): the BENCH_r11 ragged
#                     grid swept host-staged vs through the HBM-resident
#                     content-addressed page pool, recording h2d bytes
#                     (warm re-sweeps re-address resident pages), dispatch
#                     wall time, hit/reuse attribution, and bit-identity
#                     into BENCH_r12.json; cpu backend, <10 s (a smoke
#                     twin runs inside tier1 via tests/test_device_plane.py)
#   bench-solve     = distributed-agglomeration bench (docs/PERFORMANCE.md
#                     "Distributed agglomeration"): the >=100k-edge
#                     solver-scale instance solved single-host vs over the
#                     Morton-octant reduce tree (in-process + a 2-worker
#                     multihost group), recording the energy gap (<=0.1%),
#                     determinism, and bit-identity into BENCH_r09.json;
#                     cpu backend, <30 s (a <10 s smoke twin runs inside
#                     tier1 via tests/test_reduce_tree.py)
#   bench-reduce    = collective-reduce-plane bench (docs/PERFORMANCE.md
#                     "Collective reduce plane"): the >=100k-edge instance
#                     solved on the host level engine, the 2-worker
#                     filesystem packet plane, the collective plane (one
#                     jitted program + one all_gather hop per tree level;
#                     >=2x fewer dispatches/level, zero packet files), and
#                     the force-disabled fallback arm (degraded:
#                     packet_plane attributed, bit-identical) into
#                     BENCH_r16.json; cpu backend (a <10 s smoke twin
#                     runs inside tier1 via tests/test_reduce_plane.py)
#   bench-serve     = traffic-shaped service bench (docs/SERVING.md): an
#                     open-loop load generator (Poisson arrivals, mixed
#                     request classes, 2 tenants + an aggressor phase)
#                     against the resident server, recording p50/p99
#                     latency, throughput, the cold-vs-warm split, and
#                     per-tenant fairness into BENCH_r10.json; cpu
#                     backend (a <10 s smoke twin runs inside tier1 via
#                     tests/test_serve.py)
#   bench-fleet     = fleet supervised-traffic bench (docs/SERVING.md
#                     "Supervision"): open-loop Poisson two-tenant traffic
#                     against a supervised 3-member fleet with the GATEWAY
#                     child SIGKILLed mid-arrivals (restarted as
#                     incarnation 2 on the same port, routing view rebuilt
#                     cold from disk) and one member SIGKILLed (adopted by
#                     a survivor AND respawned on a fresh dir, serving
#                     again before the run ends), recording zero lost
#                     acknowledged requests (of >= 30 acked), gateway/
#                     member-kill p99 (within 3x the failover floor:
#                     warm p99 + one restart / detection window), and
#                     bit-identity into BENCH_r15.json; cpu backend,
#                     <90 s (the chaos e2e twin is
#                     tests/test_chaos.py -k fleet)
#   chaos-wedge     = only the gray-failure chaos: SIGSTOP a fleet member
#                     under live traffic — breaker opens, survivor adopts
#                     + mints the fence epoch, SIGCONT'd zombie
#                     self-drains rc 115 with zero double-execution
#   chaos-gateway   = only the supervisor chaos: SIGKILL the gateway child
#                     AND a member under live two-tenant traffic — the
#                     supervisor restarts the gateway as incarnation 2,
#                     every acked request completes with zero client
#                     resubmission, the dead member is adopted AND
#                     respawned on a fresh dir before the drain (rc 114)
#   bench-trajectory= aggregate the BENCH_r01..r16 headline numbers into
#                     one table (stdout + rewritten into docs/PERFORMANCE.md
#                     "Performance trajectory"), so the perf history is
#                     readable without opening ten JSON files
#   serve-smoke     = service-mode smoke (docs/SERVING.md): start the
#                     resident server, submit concurrent tiny workflows
#                     from two tenants, assert both complete with
#                     warm-cache reuse visible in io_metrics; <10 s, cpu
#   scrub-smoke     = self-healing smoke (docs/SERVING.md "Self-healing"):
#                     the <10 s tier-1 twin of the corruption chaos e2e —
#                     an in-process server completes a request, a stored
#                     block is rotted at rest, the scrubber finds and
#                     repairs it from lineage, and the output stays
#                     bit-identical; runs inside tier1 via
#                     tests/test_selfheal.py
#   supervise-demo  = smoke-check recipe: watershed workflow on the
#                     stub-slurm cluster target under an injected job loss,
#                     printing the supervisor's resubmission log
PY ?= python
CTT_CHAOS_SEED ?= 7
TMP ?= /tmp/ctt_run

.PHONY: test lint tier1 tier2 chaos chaos-resource chaos-wedge \
	chaos-gateway \
	failures-report progress \
	bench-io bench-sweep bench-fuse bench-ragged bench-device bench-solve \
	bench-reduce bench-serve bench-fleet \
	bench-trajectory serve-smoke scrub-smoke supervise-demo native clean

test: lint tier1 tier2 chaos

lint:
	$(PY) -m cluster_tools_tpu.lint

tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

tier2:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'slow and not chaos' \
		--continue-on-collection-errors -p no:cacheprovider

chaos:
	JAX_PLATFORMS=cpu CTT_CHAOS_SEED=$(CTT_CHAOS_SEED) \
		$(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

chaos-resource:
	JAX_PLATFORMS=cpu CTT_CHAOS_SEED=$(CTT_CHAOS_SEED) \
		$(PY) -m pytest tests/test_chaos.py -q -m chaos \
		-k resource -p no:cacheprovider

chaos-wedge:
	JAX_PLATFORMS=cpu CTT_CHAOS_SEED=$(CTT_CHAOS_SEED) \
		$(PY) -m pytest tests/test_chaos.py -q -m chaos \
		-k sigstop -p no:cacheprovider

chaos-gateway:
	JAX_PLATFORMS=cpu CTT_CHAOS_SEED=$(CTT_CHAOS_SEED) \
		$(PY) -m pytest tests/test_chaos.py -q -m chaos \
		-k gateway -p no:cacheprovider

failures-report:
	$(PY) scripts/failures_report.py $(TMP)

progress:
	$(PY) scripts/progress.py $(TMP)

bench-io:
	JAX_PLATFORMS=cpu $(PY) bench.py --io

bench-sweep:
	JAX_PLATFORMS=cpu $(PY) bench.py --sweep

bench-fuse:
	JAX_PLATFORMS=cpu $(PY) bench.py --fuse

bench-ragged:
	JAX_PLATFORMS=cpu $(PY) bench.py --ragged

bench-device:
	JAX_PLATFORMS=cpu $(PY) bench.py --device-plane

bench-solve:
	JAX_PLATFORMS=cpu $(PY) bench.py --solve

bench-reduce:
	JAX_PLATFORMS=cpu $(PY) bench.py --reduce-plane

bench-serve:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve

bench-fleet:
	JAX_PLATFORMS=cpu $(PY) bench.py --fleet

serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve.py -q \
		-k serve_smoke -p no:cacheprovider

scrub-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_selfheal.py -q \
		-k scrub_smoke -p no:cacheprovider

bench-trajectory:
	$(PY) scripts/bench_trajectory.py --write

supervise-demo:
	JAX_PLATFORMS=cpu $(PY) scripts/supervise_demo.py

native:
	$(MAKE) -C native

clean:
	$(MAKE) -C native clean
