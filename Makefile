# Developer entry points.  `test` = tier-1 (fast, chaos excluded via the
# slow marker) followed by the chaos suite; `chaos` = the fault-injection
# suite alone, fixed seed — kills/resume plus the silent-failure scenarios
# (hang, chunk corruption, job loss) from ISSUE 3; `supervise-demo` = a
# smoke-check recipe that runs a watershed workflow on the stub-slurm
# cluster target under an injected job loss and prints the supervisor's
# resubmission log (docs/ROBUSTNESS.md).
PY ?= python
CTT_CHAOS_SEED ?= 7

.PHONY: test tier1 chaos supervise-demo native clean

test: tier1 chaos

tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

chaos:
	JAX_PLATFORMS=cpu CTT_CHAOS_SEED=$(CTT_CHAOS_SEED) \
		$(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

supervise-demo:
	JAX_PLATFORMS=cpu $(PY) scripts/supervise_demo.py

native:
	$(MAKE) -C native

clean:
	$(MAKE) -C native clean
