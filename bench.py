"""North-star benchmark: fused blockwise watershed+CCL to globally merged labels.

Mirrors BASELINE.json's metric ("voxels/sec on CREMI blockwise watershed+CCL;
wall-clock to merged labels").  The whole pipeline — halo exchange, fused
DT-watershed per slab, two-pass union-find CC merge — runs as ONE compiled
SPMD program over the device mesh (see cluster_tools_tpu/parallel/pipeline.py).

The reference publishes no numbers (BASELINE.json "published": {}), so
``vs_baseline`` is measured against the equivalent single-core host (scipy)
pipeline run in-process on the same data — the reference's per-job compute
path without scheduler overhead, i.e. a *generous* stand-in for one slurm
worker of its 32-node baseline.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np


from __graft_entry__ import _synthetic_boundaries


def _host_baseline_vps(vol: np.ndarray, threshold: float) -> float:
    """voxels/sec of the equivalent scipy pipeline (single core, in-process)."""
    from scipy import ndimage

    t0 = time.perf_counter()
    fg = vol < threshold
    dist = ndimage.distance_transform_edt(fg)
    maxima = (
        ndimage.maximum_filter(dist, size=3) == dist
    ) & fg
    seeds, _ = ndimage.label(maxima)
    hmap = np.clip(vol * 255, 0, 255).astype(np.uint8)
    ndimage.watershed_ift(hmap, seeds.astype(np.int32))
    ndimage.label(fg)  # the CC pass
    dt = time.perf_counter() - t0
    return vol.size / dt


def main():
    import jax

    from cluster_tools_tpu.parallel.mesh import backend_devices, make_mesh, mesh_axis_sizes
    from cluster_tools_tpu.parallel.pipeline import make_ws_ccl_step

    try:
        devices = backend_devices("tpu")
        backend = "tpu"
    except RuntimeError:
        devices = backend_devices("local")
        backend = "cpu"
    mesh = make_mesh(len(devices), axis_names=("dp", "sp"), devices=devices)
    sizes = mesh_axis_sizes(mesh)
    dp, sp = sizes["dp"], sizes["sp"]

    threshold = 0.45
    if backend == "tpu":
        batch, z, y, x = dp, sp * 128, 128, 128
    else:
        batch, z, y, x = dp, sp * 16, 64, 64
    vol = _synthetic_boundaries((batch, z, y, x))

    step = make_ws_ccl_step(mesh, halo=4, threshold=threshold)
    # compile + warm up
    jax.block_until_ready(step(vol))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(step(vol))
        times.append(time.perf_counter() - t0)
    vps = vol.size / min(times)

    # host baseline on a crop, extrapolated per-voxel
    crop = vol[0, : min(64, z), : min(64, y), : min(64, x)]
    base_vps = _host_baseline_vps(crop, threshold)

    print(
        json.dumps(
            {
                "metric": "fused watershed+CCL merged labels",
                "value": round(vps, 1),
                "unit": "voxels/sec",
                "vs_baseline": round(vps / base_vps, 3),
                "backend": backend,
                "mesh": {"dp": dp, "sp": sp},
                "volume": list(vol.shape),
                "baseline": "single-core scipy pipeline (reference per-job compute path)",
                "baseline_voxels_per_sec": round(base_vps, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
