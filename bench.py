"""North-star benchmark: fused blockwise watershed+CCL to globally merged labels.

Mirrors BASELINE.json's metric ("voxels/sec on CREMI blockwise watershed+CCL;
wall-clock to merged labels") and covers the BASELINE config list:

- config 1: connected components on a 512^3 binary volume (tiled two-level CCL)
- config 2: distance-transform watershed, halo=32 (fused DT+seeds+flood)
- config 3: watershed + label-merge to globally merged labels (the fused SPMD
  step — per-shard watershed, cross-shard union-find collectives); this is
  the headline metric
- config 4: region-adjacency graph + multicut (GAEC) agglomeration on the
  watershed fragments of a crop

Hardening (round-1 postmortem: rc=124 with no output):

- The accelerator backend is probed in a SUBPROCESS with a timeout; on
  timeout/failure the bench pins CPU and still emits its JSON line.
- Every stage prints a timestamped line to STDERR; stdout carries exactly one
  JSON line.

Honest timing (round-3 postmortem): on the tunneled ``axon`` platform,
``jax.block_until_ready`` returns after *enqueue*, not completion — round 2's
numbers were transfer/dispatch artifacts.  Every timed region here therefore
synchronizes by fetching a scalar element of each output (a real device
round-trip, ~tens of ms, included in the measurement), and the benchmark
volume is synthesized ON DEVICE (the tunnel moves host arrays at ~50MB/s;
uploading a 537MB volume per run would swamp compute).  The per-stage
breakdown (VERDICT r2 #2) goes to stderr and the JSON ``stages_ms`` object.

The reference publishes no numbers (BASELINE.json "published": {}), so
``vs_baseline`` measures against the equivalent single-core host (scipy)
pipeline on the same data — one worker of the reference's 32-node baseline —
and ``vs_32core`` divides by 32 as the whole-cluster stand-in.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

_T0 = time.monotonic()
PROBE_TIMEOUT = float(os.environ.get("CT_BENCH_PROBE_TIMEOUT", "240"))
ACCEL_PLATFORMS = ("tpu", "axon")

# persistent compile cache (accelerator runs only: the tiled Mosaic kernels
# take minutes to compile at 512^3, and cache hits make repeat runs start
# timing within seconds; XLA:CPU AOT cache entries reload with
# machine-feature mismatch warnings, so CPU runs skip it)
_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
)


def log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:.1f}s] {msg}", file=sys.stderr, flush=True)


def _probe_accelerator(timeout: float) -> str | None:
    """Return the accelerator platform name, or None — probed in a subprocess.

    The subprocess inherits the session env (so the axon plugin registers
    exactly as it would in-process) and reports the first non-cpu platform it
    sees.  A timeout/crash means "accelerator unusable": the parent then pins
    itself to CPU *before* its own first backend init, never touching the
    tunnel.
    """
    code = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "plats = sorted({d.platform for d in jax.devices()})\n"
        # a REAL computation with a d2h fetch: a half-up tunnel lists its\n
        # devices but wedges on compute — that state must fall back to CPU\n
        "assert float(jnp.arange(8.0).sum()) == 28.0\n"
        "print('PROBE_RESULT:' + ','.join(plats), flush=True)\n"
    )
    log(f"probing accelerator backend in subprocess (timeout {timeout:.0f}s)")
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        log("probe TIMED OUT — accelerator tunnel unresponsive, falling back to cpu")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        return None
    for line in stdout.splitlines():
        if line.startswith("PROBE_RESULT:"):
            plats = line.split(":", 1)[1].split(",")
            accel = [p for p in plats if p in ACCEL_PLATFORMS]
            log(f"probe saw platforms {plats}; accelerator: {accel or None}")
            return accel[0] if accel else None
    log(
        "probe produced no result "
        f"(rc={proc.returncode}, stderr tail: {stderr.strip()[-300:]!r})"
    )
    return None


def _sync(out) -> None:
    """Force completion by fetching one element of every output leaf.

    ``block_until_ready`` is NOT sufficient on the tunneled axon platform —
    it returns after enqueue.  A d2h fetch of a single element cannot
    complete before the producing computation has.
    """
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        arr = leaf.ravel()[0] if getattr(leaf, "ndim", 0) else leaf
        np.asarray(jax.device_get(arr))


def _timeit(name, fn, *args, runs=3):
    """(best_seconds, last_output); compiles on the first (untimed) call."""
    out = fn(*args)
    _sync(out)
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    log(f"{name}: best of {runs} = {best:.3f}s")
    return best, out


def _host_baseline_vps(vol: np.ndarray, threshold: float) -> float:
    """voxels/sec of the equivalent scipy pipeline (single core, in-process).

    Timed through ``_timeit`` (untimed warm-up + best-of-2) so the
    baseline gets the identical protocol to the headline measurements."""
    from scipy import ndimage

    def pipeline():
        fg = vol < threshold
        dist = ndimage.distance_transform_edt(fg)
        maxima = (ndimage.maximum_filter(dist, size=3) == dist) & fg
        seeds, _ = ndimage.label(maxima)
        hmap = np.clip(vol * 255, 0, 255).astype(np.uint8)
        ndimage.watershed_ift(hmap, seeds.astype(np.int32))
        ndimage.label(fg)  # the CC pass
        return 0

    best, _ = _timeit("host baseline pipeline", pipeline, runs=2)
    return vol.size / best


def _host_rag_gaec(seg: np.ndarray, boundaries: np.ndarray) -> float:
    """Wall-clock of a single-core numpy RAG + host GAEC on the same crop."""
    t0 = time.perf_counter()
    pairs = []
    vals = []
    for axis in range(3):
        sl_a = tuple(slice(0, -1) if d == axis else slice(None) for d in range(3))
        sl_b = tuple(slice(1, None) if d == axis else slice(None) for d in range(3))
        u, v = seg[sl_a].ravel(), seg[sl_b].ravel()
        m = (u != v) & (u != 0) & (v != 0)
        pairs.append(
            np.stack([np.minimum(u[m], v[m]), np.maximum(u[m], v[m])], 1)
        )
        vals.append(np.maximum(boundaries[sl_a].ravel()[m], boundaries[sl_b].ravel()[m]))
    pr = np.concatenate(pairs)
    bv = np.concatenate(vals)
    uv, inv, sizes = np.unique(pr, axis=0, return_inverse=True, return_counts=True)
    mean = np.zeros(len(uv))
    np.add.at(mean, inv.ravel(), bv)
    mean /= sizes
    from cluster_tools_tpu.tasks.costs import compute_costs
    from cluster_tools_tpu.ops.multicut import greedy_additive

    dense = np.unique(uv)
    remap = {int(g): i for i, g in enumerate(dense)}
    e = np.array([[remap[int(a)], remap[int(b)]] for a, b in uv], np.int64)
    costs = compute_costs(mean.astype(np.float32))
    greedy_additive(len(dense), e, costs)
    return time.perf_counter() - t0


def _solver_scale_bench(g=33, seed=0):
    """Parallel GAEC (ops/contraction.py numpy rounds) vs the sequential
    pure-Python heap at RAG scale (>= 100k edges): records the speedup and
    the multicut-energy gap — the acceptance pair for the round engine
    (ISSUE 1: >= 5x faster, energy within 2%)."""
    import cluster_tools_tpu.native as native
    from cluster_tools_tpu.ops import multicut as mc
    from cluster_tools_tpu.ops.contraction import gaec_parallel
    from cluster_tools_tpu.utils.synthetic import grid_rag

    n, edges, costs = grid_rag(g=g, seed=seed)

    # the heap baseline must be the PYTHON heap (the pre-engine solver),
    # not the native C++ twin — disable the native ladder for one call
    with native.force_python():
        t0 = time.perf_counter()
        lab_heap = mc.greedy_additive(n, edges, costs)
        t_heap = time.perf_counter() - t0

    t_par = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        lab_par = gaec_parallel(n, edges, costs, impl="numpy")
        t_par = min(t_par, time.perf_counter() - t0)
    e_heap = mc.multicut_energy(edges, costs, lab_heap)
    e_par = mc.multicut_energy(edges, costs, lab_par)
    gap_pct = 100.0 * (e_par - e_heap) / max(abs(e_heap), 1e-12)
    log(
        f"config 4 solver scale ({len(edges)} edges): python heap "
        f"{t_heap:.3f}s, parallel numpy {t_par:.3f}s "
        f"({t_heap / t_par:.1f}x), energy gap {gap_pct:+.2f}%"
    )
    return {
        "n_edges": int(len(edges)),
        "python_heap_seconds": round(t_heap, 3),
        "parallel_numpy_seconds": round(t_par, 3),
        "speedup": round(t_heap / t_par, 1),
        "energy_gap_pct": round(gap_pct, 3),
    }


def io_bench():
    """IO-amplification config (docs/PERFORMANCE.md "Chunk-aware I/O").

    Runs the halo'd single-pass watershed sweep twice over the same on-disk
    zarr volume — decompressed-chunk cache OFF, then ON — and records
    bytes-read-from-storage, the amplification over the inner volume, the
    off/on reduction, the cache counters (hit/miss/coalesce), and whether
    the two label outputs are bit-identical (they must be: the cache is a
    pure IO optimization).  cpu backend, sized for <60 s: ``make bench-io``.
    Emits exactly one JSON line on stdout.
    """
    from __graft_entry__ import _force_cpu_platform

    _force_cpu_platform(8)
    import shutil
    import tempfile

    from scipy import ndimage

    from cluster_tools_tpu.io import chunk_cache
    from cluster_tools_tpu.runtime.task import build
    from cluster_tools_tpu.tasks.watershed import WatershedLocal
    from cluster_tools_tpu.utils.volume_utils import file_reader

    ext = int(os.environ.get("CT_BENCH_IO_EXTENT", "64"))
    block = int(os.environ.get("CT_BENCH_IO_BLOCK", "16"))
    halo = int(os.environ.get("CT_BENCH_IO_HALO", "8"))
    shape = (ext,) * 3
    root = tempfile.mkdtemp(prefix="ctt_io_bench_")
    log(
        f"io bench: volume {shape}, blocks {block}^3 (= chunks), "
        f"halo {halo} -> outer {(block + 2 * halo)}^3"
    )
    rng = np.random.default_rng(0)
    vol = ndimage.gaussian_filter(rng.random(shape), 2.0)
    vol = ((vol - vol.min()) / (vol.max() - vol.min())).astype(np.float32)
    path = os.path.join(root, "io.zarr")
    container = file_reader(path)
    src = container.create_dataset(
        "boundaries", shape=shape, chunks=(block,) * 3, dtype="float32"
    )
    src[...] = vol

    inner_bytes = int(vol.nbytes)
    env_before = os.environ.get("CTT_CHUNK_CACHE")
    runs = {}
    outs = {}
    try:
        for mode in ("off", "on"):
            os.environ["CTT_CHUNK_CACHE"] = "1" if mode == "on" else "0"
            # fresh cache per run: zeroed counters, nothing resident
            chunk_cache.configure(max_bytes=64 << 20)
            snap = chunk_cache.snapshot()
            t0 = time.perf_counter()
            task = WatershedLocal(
                tmp_folder=os.path.join(root, f"tmp_{mode}"),
                config_dir=os.path.join(root, "config"),
                max_jobs=4,
                input_path=path,
                input_key="boundaries",
                output_path=path,
                output_key=f"ws_{mode}",
                block_shape=[block] * 3,
                halo=[halo] * 3,
                threshold=0.5,
                impl="legacy",
            )
            if not build([task]):
                raise RuntimeError(f"io bench watershed run '{mode}' failed")
            seconds = time.perf_counter() - t0
            stats = chunk_cache.delta(snap)
            runs[mode] = dict(stats, seconds=round(seconds, 3))
            outs[mode] = np.asarray(file_reader(path)[f"ws_{mode}"][...])
            log(
                f"io bench cache={mode}: {seconds:.1f}s, "
                f"{stats['bytes_from_storage'] / 1e6:.1f}MB from storage "
                f"for {stats['bytes_served'] / 1e6:.1f}MB served "
                f"(hits {stats['hits']}, misses {stats['misses']}, "
                f"coalesced {stats['coalesced']})"
            )
    finally:
        if env_before is None:
            os.environ.pop("CTT_CHUNK_CACHE", None)
        else:
            os.environ["CTT_CHUNK_CACHE"] = env_before
        chunk_cache.configure()
        shutil.rmtree(root, ignore_errors=True)

    off = runs["off"]["bytes_from_storage"]
    on = max(1, runs["on"]["bytes_from_storage"])
    rec = {
        "metric": "io_amplification_halo_sweep",
        "backend": "cpu",
        "volume": list(shape),
        "block_shape": [block] * 3,
        "chunks": [block] * 3,
        "halo": [halo] * 3,
        "inner_bytes": inner_bytes,
        "cache_off": runs["off"],
        "cache_on": runs["on"],
        "amplification_off": round(off / inner_bytes, 2),
        "amplification_on": round(on / inner_bytes, 2),
        "bytes_read_reduction": round(off / on, 2),
        "bit_identical": bool(np.array_equal(outs["off"], outs["on"])),
        "schedule": "morton",
    }
    print(json.dumps(rec), flush=True)
    log("io bench done")
    return rec


def fuse_bench(smoke=False):
    """Task-graph-fusion config (docs/PERFORMANCE.md "Task-graph fusion").

    Runs the watershed -> graph -> features -> costs -> multicut -> write
    workflow twice over the same on-disk boundary volume — in-memory
    handoffs OFF (every producer->consumer hop pays a store+load
    round-trip, today's baseline), then ON (intermediates live in host RAM,
    spill-to-storage as the fallback) — and records the intermediate bytes
    written to storage, end-to-end wall time, the handoff counters, and
    whether the final segmentations are bit-identical (they must be: the
    fusion layer is a pure IO optimization).  cpu backend; ``make
    bench-fuse`` writes BENCH_r08.json.  ``smoke=True`` is the <10 s
    tier-1 variant (16^3 volume, no file output).  Emits exactly one JSON
    line on stdout and returns the record.
    """
    from __graft_entry__ import _force_cpu_platform

    _force_cpu_platform(8)
    import shutil
    import tempfile

    from scipy import ndimage

    from cluster_tools_tpu.runtime import handoff
    from cluster_tools_tpu.runtime.task import build
    from cluster_tools_tpu.utils.volume_utils import file_reader
    from cluster_tools_tpu.workflows import MulticutSegmentationWorkflow

    ext = 16 if smoke else int(os.environ.get("CT_BENCH_FUSE_EXTENT", "32"))
    block = 8
    root = tempfile.mkdtemp(prefix="ctt_fuse_bench_")
    shape = (ext,) * 3
    log(f"fuse bench: volume {shape}, blocks {block}^3, handoffs off vs on")
    rng = np.random.default_rng(0)
    vol = ndimage.gaussian_filter(rng.random(shape), 2.0)
    vol = ((vol - vol.min()) / (vol.max() - vol.min())).astype(np.float32)

    def _tree_bytes(*paths):
        total = 0
        for p in paths:
            if not os.path.isdir(p):
                continue
            for dirpath, _dirs, files in os.walk(p):
                for f in files:
                    try:
                        total += os.path.getsize(os.path.join(dirpath, f))
                    except OSError:
                        pass
        return total

    runs, segs = {}, {}
    # a discarded warmup run compiles every kernel shape first, so the
    # off/on timings compare IO paths, not compile caches (the smoke twin
    # skips it — it asserts correctness, not timing)
    modes = ("on", "off") if smoke else ("warmup", "on", "off")
    for mode in modes:
        base = os.path.join(root, mode)
        cdir = os.path.join(base, "config")
        os.makedirs(cdir, exist_ok=True)
        with open(f"{cdir}/global.config.tmp", "w") as f:
            json.dump(
                {"block_shape": [block] * 3,
                 "memory_handoffs": mode == "on"},
                f,
            )
        os.replace(f"{cdir}/global.config.tmp", f"{cdir}/global.config")
        path = os.path.join(base, "data.zarr")
        src = file_reader(path).create_dataset(
            "bmap", shape=shape, chunks=(block,) * 3, dtype="float32"
        )
        src[...] = vol
        tmp_folder = os.path.join(base, "tmp")
        snap = handoff.snapshot()
        t0 = time.perf_counter()
        wf = MulticutSegmentationWorkflow(
            tmp_folder=tmp_folder, config_dir=cdir, max_jobs=4,
            target="local", input_path=path, input_key="bmap",
            ws_path=path, ws_key="ws", output_path=path, output_key="seg",
            threshold=0.5, halo=[2] * 3, beta=0.5,
        )
        if not build([wf]):
            raise RuntimeError(f"fuse bench workflow run '{mode}' failed")
        seconds = time.perf_counter() - t0
        if mode == "warmup":
            continue
        # intermediate storage footprint: the supervoxel dataset plus the
        # graph/multicut artifact dirs (solver checkpoints excluded: they
        # are crash-resume state, not a producer->consumer hop)
        inter_bytes = _tree_bytes(
            os.path.join(path, "ws"),
            os.path.join(tmp_folder, "graph"),
            os.path.join(tmp_folder, "multicut"),
        )
        stats = handoff.delta(snap)
        runs[mode] = dict(
            {k: int(v) for k, v in stats.items()},
            seconds=round(seconds, 3),
            intermediate_bytes_written=int(inter_bytes),
        )
        segs[mode] = np.asarray(file_reader(path)["seg"][...])
        log(
            f"fuse bench handoffs={mode}: {seconds:.1f}s, "
            f"{inter_bytes / 1e6:.2f}MB intermediate storage, "
            f"{stats['handoffs_served']:.0f} served in-memory, "
            f"{stats['bytes_not_stored'] / 1e6:.2f}MB never stored"
        )

    rec = {
        "metric": "task_graph_fusion_workflow",
        "backend": "cpu",
        "volume": list(shape),
        "block_shape": [block] * 3,
        "handoffs_off": runs["off"],
        "handoffs_on": runs["on"],
        "bit_identical": bool(np.array_equal(segs["off"], segs["on"])),
        "zero_intermediate_writes": runs["on"]["intermediate_bytes_written"] == 0,
        # smoke runs skip the warmup pass, so their timings still carry
        # compile noise — the smoke twin asserts correctness, not speed
        "speedup": None if smoke else round(
            runs["off"]["seconds"] / max(runs["on"]["seconds"], 1e-9), 2
        ),
    }
    shutil.rmtree(root, ignore_errors=True)
    if not smoke:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r08.json"
        )
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=2)
        os.replace(tmp, out_path)
    print(json.dumps(rec), flush=True)
    log("fuse bench done")
    return rec


def sweep_bench(smoke=False, n_devices=1):
    """Dispatch-amortization config (docs/PERFORMANCE.md "Sharded sweeps").

    Runs the same halo'd block sweep twice through the BlockwiseExecutor —
    ``sweep_mode="per_block"`` (the historical one-dispatch-per-block path)
    vs ``sweep_mode="sharded"`` (one shard_map program per Morton batch) —
    at the 64^3-volume / 16^3-block geometry where dispatch + host-sync
    overhead dominates tiny per-block kernels, and records throughput, the
    compiled-dispatch counts from the executor's dispatch counters, and
    whether the outputs are bit-identical (they must be: the sharded
    program vmaps the same kernel).  Loads/stores are host-memory arrays so
    the comparison isolates dispatch + executor machinery (the storage path
    has its own config: ``make bench-io``).  A third sub-record exercises
    the device-side halo exchange (``parallel/batch_shard.py``): a slab run
    executed with every interior halo rebuilt on device, asserted
    bit-identical to per-slab overlapped reads.

    ``smoke=True`` is the <10 s tier-1 variant (32^3 volume, no file
    output); the full run writes BENCH_r07.json next to this script.
    Emits exactly one JSON line on stdout and returns the record.
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.parallel.batch_shard import sharded_slab_sweep
    from cluster_tools_tpu.runtime import executor as executor_mod
    from cluster_tools_tpu.runtime import trace as trace_mod
    from cluster_tools_tpu.runtime.executor import BlockwiseExecutor, get_mesh
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.utils.volume_utils import Blocking, pad_block_to

    ext = 32 if smoke else 64
    block, halo = 16, 4
    shape = (ext,) * 3
    outer = tuple(block + 2 * halo for _ in range(3))
    sharded_batch = 8 if smoke else 32
    reps = 1 if smoke else 3
    rng = np.random.default_rng(0)
    vol = rng.random(shape).astype(np.float32)
    # axis-0 halo'd twin for the slab-run reference (the slab sweep only
    # halos the run axis)
    padded = np.pad(
        vol, ((halo, halo), (0, 0), (0, 0)), constant_values=1.0
    )
    blocking = Blocking(shape, (block,) * 3)
    blocks = [
        blocking.get_block(i, halo=(halo,) * 3)
        for i in range(blocking.n_blocks)
    ]
    log(
        f"sweep bench: volume {shape}, blocks {block}^3, halo {halo}, "
        f"{len(blocks)} blocks, sharded batch {sharded_batch}, "
        f"{n_devices} device(s)"
    )

    def kernel(b):
        # the dispatch-bound regime this sweep measures: a boundary-prep
        # pass (axis smoothing + foreground mask, the shape of the
        # thresholding/copy/downscale family) — microseconds of compute
        # per 16^3 block, so per-block dispatch + executor machinery is
        # the dominant cost.  Heavier kernels shrink the ratio toward
        # compute-bound parity; bench-io measures the storage-bound end.
        x = (b + jnp.roll(b, 1, 0) + jnp.roll(b, -1, 0)) / 3.0
        return jnp.where(x < jnp.float32(0.5), x, jnp.float32(1.0))

    def load(b):
        data = vol[b.outer_bb]
        return (pad_block_to(data, outer, constant_values=1.0),)

    runs, outs, run_onces = {}, {}, {}
    for mode in ("per_block", "sharded"):
        out = np.zeros(shape, np.float32)

        def store(b, raw, out=out):
            out[b.bb] = np.asarray(raw)[b.inner_in_outer_bb]

        ex = BlockwiseExecutor(
            target="local",
            n_devices=n_devices,
            io_threads=4,
            max_retries=2,
        )

        def run_once(store_fn, mode=mode, ex=ex):
            # the task trace context (docs/ANALYSIS.md CT008): outside a
            # task class, the executor's spans need an explicit task.run
            # bracket to be attributable on the timeline
            with trace_mod.task_context(f"sweep_{mode}"):
                return ex.map_blocks(
                    kernel,
                    blocks,
                    load,
                    store_fn,
                    failures_path=None,
                    task_name=f"sweep_{mode}",
                    block_deadline_s=None,
                    watchdog_period_s=None,
                    store_verify_fn=None,
                    schedule="morton",
                    sweep_mode=mode,
                    sharded_batch=sharded_batch,
                    device_pool="off",  # dense sweep: no paged staging
                )

        run_onces[mode] = run_once
        run_once(store)  # warm: compile + first-touch outside the clock
        seconds, delta = None, None
        for _ in range(reps):  # best warm rep: the 2-core CI box is noisy
            snap = executor_mod.dispatch_snapshot()
            t0 = time.perf_counter()
            run_once(store)
            t = time.perf_counter() - t0
            if seconds is None or t < seconds:
                seconds = t
                delta = executor_mod.dispatch_delta(snap)
        outs[mode] = out
        runs[mode] = {
            "seconds": round(seconds, 4),
            "dispatches": int(delta["batches_dispatched"]),
            "blocks_per_dispatch": round(
                delta["blocks_dispatched"]
                / max(1, delta["batches_dispatched"]), 2
            ),
            "dispatch_wait_s": round(delta["dispatch_wait_s"], 4),
            "voxels_per_s": int(vol.size / max(seconds, 1e-9)),
        }
        log(
            f"sweep bench {mode}: {seconds * 1000:.1f} ms, "
            f"{runs[mode]['dispatches']} dispatches "
            f"({runs[mode]['blocks_per_dispatch']} blocks each)"
        )

    # device-side halo exchange on a slab run: interior halos rebuilt on
    # device from batch neighbors, bit-identical to the per-block path
    # (jit(vmap) at width 1 over overlapped reads — the vmapped program is
    # the reference; an UN-vmapped kernel call rounds differently under
    # XLA's fusion and is not what the executor ever runs)
    mesh = get_mesh("local", n_devices=n_devices)
    slab_dev = sharded_slab_sweep(
        vol, kernel, mesh, extent=block, halo=halo, fill=1.0
    )
    per_slab = jax.jit(jax.vmap(kernel))
    slab_ref = np.concatenate([
        np.asarray(
            per_slab(padded[None, i * block:(i + 1) * block + 2 * halo])
        )
        for i in range(ext // block)
    ])
    slab_identical = bool(np.array_equal(slab_dev, slab_ref))

    # -- tracer overhead (docs/OBSERVABILITY.md): the same sharded sweep
    # with CTT_TRACE on, best-of-reps vs the traced-off figure above.  The
    # acceptance bar is <5% wall: per-block span cost must stay invisible
    # next to real dispatch + IO work.  The traced outputs must also stay
    # bit-identical — observability cannot perturb results.
    trace_dir = tempfile.mkdtemp(prefix="ctt_bench_trace_")
    shard_dir = os.path.join(trace_dir, trace_mod.TRACE_DIRNAME)
    traced_out = np.zeros(shape, np.float32)

    def store_traced(b, raw, out=traced_out):
        out[b.bb] = np.asarray(raw)[b.inner_in_outer_bb]

    # the measured workload is the WHOLE bench-sweep config (one per-block
    # + one sharded sweep per sample): that is what "overhead on make
    # bench-sweep" means, and at ~40 ms per sample the box's scheduler
    # noise stops drowning the sub-ms tracer cost.  Interleaved off/on
    # pairs cancel drift; min-of-N takes the noise-free floor of each arm.
    # N must be large enough that BOTH arms sample the box's fast phase —
    # this host flips between ~40 ms and ~65 ms regimes that outlast a
    # single pair, so small N occasionally strands one arm in the slow
    # phase and fakes a large overhead either direction.
    def one_bench_sweep():
        run_onces["per_block"](store_traced)
        run_onces["sharded"](store_traced)

    u_times, t_times = [], []
    # GC parity: the traced arm allocates (one tuple + args dict per
    # event), so collection cycles would land disproportionately inside
    # its samples and bill a ~10 ms gen-2 pass to the tracer
    import gc

    gc.collect()
    gc.disable()
    try:
        trace_mod.configure(enabled=True, trace_dir=shard_dir)
        one_bench_sweep()  # warm the traced code paths outside the clock

        # wall A/B cross-check: interleaved, order-alternated pairs, floor
        # vs floor.  On this host the CPU flips between speed phases ~60%
        # apart and throttles under sustained load, so the A/B resolves a
        # few-percent effect only as a sanity band (its sign flips run to
        # run); the headline overhead_frac below is the phase-invariant
        # per-event accounting instead.
        n_ab = 3 if smoke else 8
        for i in range(n_ab):
            order = ("u", "t") if i % 2 == 0 else ("t", "u")
            for which in order:
                if which == "u":
                    trace_mod.configure(enabled=False)
                else:
                    trace_mod.configure(enabled=True, trace_dir=shard_dir)
                t0 = time.perf_counter()
                one_bench_sweep()
                (u_times if which == "u" else t_times).append(
                    time.perf_counter() - t0
                )

        # contended per-event cost, measured adjacent in time: 4 threads
        # (the executor's io_threads) emitting spans concurrently price
        # the GIL handoffs a single-thread microbench would hide.  Both
        # this and the sweep wall scale with the host's current speed
        # phase, so their RATIO is phase-invariant — the property every
        # wall-difference estimator above lacks.
        from concurrent.futures import ThreadPoolExecutor as _TPE

        trace_mod.configure(enabled=True, trace_dir=shard_dir)
        n_threads, per_thread = 4, 10_000

        def _emit(k):
            for j in range(per_thread):
                with trace_mod.span("executor.load", block=j, task="ovh"):
                    pass

        with _TPE(max_workers=n_threads) as tpe:
            list(tpe.map(_emit, range(n_threads)))  # warm
            t0 = time.perf_counter()
            list(tpe.map(_emit, range(n_threads)))
            per_event_s = (
                (time.perf_counter() - t0) / (n_threads * per_thread)
            )
    finally:
        gc.enable()
    # events per bench-sweep: count what ONE traced per_block + sharded
    # pass actually records (the A/B loop above left the buffer holding
    # its last traced sample — clear and re-run one clean pass)
    trace_mod.configure(enabled=True, trace_dir=shard_dir)
    one_bench_sweep()
    trace_mod.flush()
    trace_summary = trace_mod.write_timeline(trace_dir) or {}
    trace_events = int(trace_summary.get("n_events", 0))

    # controlled wall A/B: the wall cost of exactly the event volume one
    # bench sweep records, measured on a fixed host-side workload (no XLA
    # dispatch, no IO, no thread pool) where a sub-ms on/off delta
    # actually RESOLVES.  This is the real wall measurement backing the
    # <5% bar — the sweep-level A/B above upper-bounds scheduler noise on
    # shared hosts, not the tracer.  gc stays enabled (the traced arm's
    # per-event allocations are billed to it); min-of-N floors discard
    # samples that caught a collection pass or a speed-phase flip.
    ctl_work = np.full((32, 32), 0.5, np.float32)
    n_ctl_events = max(trace_events, 1)

    def _controlled_pass():
        acc = ctl_work
        for j in range(n_ctl_events):
            with trace_mod.span("executor.load", block=j, task="ctl"):
                acc = ctl_work @ ctl_work
        return acc

    ctl_u, ctl_t = [], []
    _controlled_pass()  # warm
    for i in range(4 if smoke else 16):
        for which in (("u", "t") if i % 2 == 0 else ("t", "u")):
            if which == "u":
                trace_mod.configure(enabled=False)
            else:
                trace_mod.configure(enabled=True, trace_dir=shard_dir)
            t0 = time.perf_counter()
            _controlled_pass()
            (ctl_u if which == "u" else ctl_t).append(
                time.perf_counter() - t0
            )
    ctl_delta_s = min(ctl_t) - min(ctl_u)
    trace_mod.configure(enabled=False)  # back to the traced-off default
    untraced_s, traced_s = min(u_times), min(t_times)
    # the headline: phase-invariant per-event accounting — what the
    # recorded events actually cost on the untraced wall.  The wall A/B
    # floors ride along as the sanity band (noise-limited on this host).
    trace_overhead = (trace_events * per_event_s) / max(untraced_s, 1e-9)
    ab_frac = (traced_s - untraced_s) / max(untraced_s, 1e-9)
    trace_rec = {
        "overhead_frac": round(trace_overhead, 4),
        "per_event_us": round(per_event_s * 1e6, 3),
        "events_per_sweep": trace_events,
        "untraced_seconds": round(untraced_s, 4),
        "ab_traced_seconds": round(traced_s, 4),
        # raw (unclamped — a negative value shows the A/B is noise-limited
        # on this host, which is the honest reading)
        "ab_overhead_frac": round(ab_frac, 4),
        # the wall-measured tracer cost of one sweep's event volume, on a
        # workload where the delta resolves; overhead_frac scales it to
        # the untraced sweep wall (same event count)
        "controlled": {
            "n_events": n_ctl_events,
            "untraced_ms": round(min(ctl_u) * 1e3, 3),
            "traced_ms": round(min(ctl_t) * 1e3, 3),
            "wall_delta_ms": round(ctl_delta_s * 1e3, 3),
            "per_event_us": round(ctl_delta_s / n_ctl_events * 1e6, 3),
            "overhead_frac": round(
                ctl_delta_s / max(untraced_s, 1e-9), 4
            ),
        },
        "bit_identical": bool(np.array_equal(traced_out, outs["sharded"])),
    }
    log(
        f"sweep bench traced: {trace_events} events/sweep x "
        f"{per_event_s * 1e6:.2f} us = "
        f"{100.0 * trace_overhead:.1f}% overhead on "
        f"{untraced_s * 1000:.1f} ms (controlled wall: "
        f"{ctl_delta_s * 1e3:.2f} ms = "
        f"{100.0 * ctl_delta_s / max(untraced_s, 1e-9):.1f}%; "
        f"sweep A/B floors: {100.0 * ab_frac:.1f}%, noise-limited)"
    )

    pb, sh = runs["per_block"], runs["sharded"]
    rec = {
        "metric": "sharded_sweep_dispatch",
        "backend": "cpu",
        "smoke": bool(smoke),
        "volume": list(shape),
        "block_shape": [block] * 3,
        "halo": [halo] * 3,
        "n_devices": int(n_devices),
        "sharded_batch": int(sharded_batch),
        "per_block": pb,
        "sharded": sh,
        "throughput_ratio": round(pb["seconds"] / sh["seconds"], 2),
        "dispatch_reduction": round(
            pb["dispatches"] / max(1, sh["dispatches"]), 2
        ),
        "bit_identical": bool(
            np.array_equal(outs["per_block"], outs["sharded"])
        ),
        "device_halo_slab_identical": slab_identical,
        "schedule": "morton",
        "trace": trace_rec,
    }
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r07.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"sweep bench done -> {path}")
    return rec


def ragged_bench(smoke=False, n_devices=1):
    """Ragged paged-pool config (docs/PERFORMANCE.md "Ragged sweeps").

    The regime real volumes live in: a NON-power-of-two grid (27 blocks of
    16^3 over a 44^3 volume — every face block volume-edge-clipped, so the
    un-padded loads come back in many distinct shapes) with FORCED
    degrade-splits (a seeded ``min_voxels``-gated OOM makes 8 full-size
    blocks fail at load so they re-execute as 2^3 halo-correct sub-blocks
    each).  The per-block fallback — what this workload degraded to before
    the paged block pool — pays one compiled dispatch per block plus one
    per sub-block; the ragged path packs the mixed-shape lanes AND the
    split sub-blocks through the paged pool
    (``parallel/block_pool.py``) and dispatches ONE descriptor-driven
    program per batch.  Records both arms' dispatch counts from the
    executor's counters, the ragged-lane attribution (padding lanes,
    pool pages), warm wall time, and bit-identity (elementwise kernel —
    the shape-local contract of docs/PERFORMANCE.md "Ragged sweeps").

    ``smoke=True`` is the <10 s tier-1 variant (single rep, no file
    output); the full run writes BENCH_r11.json next to this script.
    Emits exactly one JSON line on stdout and returns the record.
    """
    import jax.numpy as jnp

    from cluster_tools_tpu.runtime import executor as executor_mod
    from cluster_tools_tpu.runtime import faults as faults_mod
    from cluster_tools_tpu.runtime import trace as trace_mod
    from cluster_tools_tpu.runtime.executor import BlockwiseExecutor
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.utils.volume_utils import Blocking

    shape = (44, 44, 44)
    block, halo = 16, (4, 4, 4)
    sharded_batch = 32
    reps = 1 if smoke else 3
    rng = np.random.default_rng(0)
    vol = rng.random(shape).astype(np.float32)
    blocking = Blocking(shape, (block,) * 3)
    blocks = [
        blocking.get_block(i, halo=halo) for i in range(blocking.n_blocks)
    ]
    # forced splits: the 8 low-corner-octant blocks have >= 20^3-voxel
    # outer regions; the min_voxels gate makes every full-size load fail
    # while their ~16^3 sub-blocks fit — the physical OOM model
    split_ids = sorted(
        blocking.grid_position_to_id(pos) for pos in np.ndindex(2, 2, 2)
    )
    fault_cfg = {
        "seed": 7,
        "faults": [{
            "site": "load", "kind": "oom", "blocks": split_ids,
            "min_voxels": 6000, "fail_attempts": 10**6,
        }],
    }
    log(
        f"ragged bench: volume {shape}, blocks {block}^3 "
        f"({blocking.n_blocks}-block non-pow2 grid, edge-clipped), "
        f"{len(split_ids)} forced splits, sharded batch {sharded_batch}"
    )

    def kernel(b):
        # elementwise boundary-prep pass (threshold family): microseconds
        # per block, so dispatch count is the cost that matters — and the
        # shape-local contract of the ragged path holds trivially
        return jnp.where(b < jnp.float32(0.5), b * 2 + jnp.float32(0.25),
                         jnp.float32(1.0))

    def run_arm(mode, ragged):
        out = np.zeros(shape, np.float32)

        def load(b):
            return (vol[b.outer_bb],)  # exact clipped shapes — no padding

        def store(b, raw):
            out[b.bb] = np.asarray(raw)[b.inner_in_outer_bb]

        ex = BlockwiseExecutor(
            target="local", n_devices=n_devices, io_threads=4,
            max_retries=2, backoff_base=1e-4,
        )
        seconds, delta, summary = None, None, None
        for rep in range(reps + 1):  # rep 0 warms the compiled programs
            out[:] = 0
            faults_mod.configure(fault_cfg)
            snap = executor_mod.dispatch_snapshot()
            t0 = time.perf_counter()
            with trace_mod.task_context(f"ragged_{mode}_{ragged}"):
                summary = ex.map_blocks(
                    kernel, blocks, load, store,
                    failures_path=None, task_name=f"ragged_{mode}",
                    block_deadline_s=None, watchdog_period_s=None,
                    store_verify_fn=None,
                    schedule="morton", sweep_mode=mode,
                    sharded_batch=sharded_batch, ragged=ragged,
                    device_pool="off",  # measures the host-staged baseline
                    splittable=True, split_halo=halo,
                    min_block_shape=(4, 4, 4), degrade_wait_s=0.05,
                )
            t = time.perf_counter() - t0
            faults_mod.reset()
            if rep == 0:
                continue
            if seconds is None or t < seconds:
                seconds = t
                delta = executor_mod.dispatch_delta(snap)
        rec = {
            "seconds": round(seconds, 4),
            "dispatches": int(delta["batches_dispatched"]),
            "blocks_per_dispatch": round(
                delta["blocks_dispatched"]
                / max(1, delta["batches_dispatched"]), 2
            ),
            "ragged_batches": int(delta["ragged_batches"]),
            "lanes_padded": int(delta["lanes_padded"]),
            "pages_in_use": int(delta["pages_in_use"]),
            "n_split": int(summary.get("n_split", 0)),
            "n_sub_blocks": int(summary.get("n_sub_blocks", 0)),
        }
        log(
            f"ragged bench {mode}/ragged={ragged}: {seconds * 1000:.1f} ms, "
            f"{rec['dispatches']} dispatches "
            f"({rec['ragged_batches']} ragged, "
            f"{rec['n_sub_blocks']} sub-blocks)"
        )
        return out, rec

    # the per-block fallback this workload used to degrade to: one
    # dispatch per block, one jit dispatch per split sub-block
    out_pb, pb = run_arm("per_block", "off")
    out_rg, rg = run_arm("sharded", "auto")

    rec = {
        "metric": "ragged_paged_sweep",
        "backend": "cpu",
        "smoke": bool(smoke),
        "volume": list(shape),
        "block_shape": [block] * 3,
        "halo": list(halo),
        "grid": list(blocking.grid_shape),
        "n_devices": int(n_devices),
        "sharded_batch": int(sharded_batch),
        "forced_split_blocks": len(split_ids),
        "per_block": pb,
        "ragged": rg,
        "dispatch_reduction": round(
            pb["dispatches"] / max(1, rg["dispatches"]), 2
        ),
        "throughput_ratio": round(pb["seconds"] / rg["seconds"], 2),
        "bit_identical": bool(np.array_equal(out_pb, out_rg)),
        "schedule": "morton",
    }
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r11.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"ragged bench done -> {path}")
    return rec


def device_plane_bench(smoke=False, n_devices=1):
    """Device-resident data plane (docs/PERFORMANCE.md "Device-resident
    data plane").

    The BENCH_r11 ragged grid (27 mixed-shape blocks of 16^3 over a 44^3
    volume, every face block edge-clipped) swept twice per arm —
    host-staged (``device_pool="off"``: every batch re-uploads its page
    pool) vs device-resident (the content-addressed HBM pool of
    ``parallel/device_pool.py``: pages upload once, later batches and the
    warm re-sweep re-address resident slots).  Records each arm's warm
    dispatch wall time and h2d traffic from the device-plane counters,
    the resident arm's hit/reuse attribution, and bit-identity of the
    outputs — the pool must be a pure staging change.

    ``smoke=True`` is the <10 s tier-1 variant (single rep, no file
    output); the full run writes BENCH_r12.json next to this script.
    Emits exactly one JSON line on stdout and returns the record.
    """
    import jax.numpy as jnp

    from cluster_tools_tpu.parallel import device_pool as device_pool_mod
    from cluster_tools_tpu.runtime import executor as executor_mod
    from cluster_tools_tpu.runtime import trace as trace_mod
    from cluster_tools_tpu.runtime.executor import BlockwiseExecutor
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.utils.volume_utils import Blocking

    shape = (44, 44, 44)
    block, halo = 16, (4, 4, 4)
    sharded_batch = 32
    reps = 1 if smoke else 3
    rng = np.random.default_rng(0)
    vol = rng.random(shape).astype(np.float32)
    blocking = Blocking(shape, (block,) * 3)
    blocks = [
        blocking.get_block(i, halo=halo) for i in range(blocking.n_blocks)
    ]
    log(
        f"device-plane bench: volume {shape}, blocks {block}^3 "
        f"({blocking.n_blocks}-block non-pow2 grid, edge-clipped), "
        f"host-staged vs device-resident, sharded batch {sharded_batch}"
    )

    def kernel(b):
        return jnp.where(b < jnp.float32(0.5), b * 2 + jnp.float32(0.25),
                         jnp.float32(1.0))

    def run_arm(dev):
        out = np.zeros(shape, np.float32)

        def load(b):
            return (vol[b.outer_bb],)

        def store(b, raw):
            out[b.bb] = np.asarray(raw)[b.inner_in_outer_bb]

        ex = BlockwiseExecutor(
            target="local", n_devices=n_devices, io_threads=4,
            max_retries=2, backoff_base=1e-4,
        )
        device_pool_mod.reset()  # each arm starts from a cold pool
        seconds, delta, summary = None, None, None
        for rep in range(reps + 1):  # rep 0 warms programs (and arenas)
            out[:] = 0
            snap = device_pool_mod.snapshot()
            t0 = time.perf_counter()
            with trace_mod.task_context(f"device_plane_{dev}"):
                summary = ex.map_blocks(
                    kernel, blocks, load, store,
                    failures_path=None, task_name=f"device_plane_{dev}",
                    block_deadline_s=None, watchdog_period_s=None,
                    store_verify_fn=None,
                    schedule="morton", sweep_mode="sharded",
                    sharded_batch=sharded_batch, ragged="auto",
                    device_pool=dev,
                )
            t = time.perf_counter() - t0
            if rep == 0:
                continue
            if seconds is None or t < seconds:
                seconds = t
                delta = device_pool_mod.delta(snap)
        rec = {
            "seconds": round(seconds, 4),
            "h2d_bytes": int(delta["h2d_bytes"]),
            "bytes_not_staged": int(delta["bytes_not_staged"]),
            "device_pool_hits": int(delta["device_pool_hits"]),
            "device_batches_staged": int(delta["device_batches_staged"]),
            "resident_bytes": int(
                summary.get("device_pool_resident_bytes", 0)
            ),
        }
        log(
            f"device-plane bench {dev}: {seconds * 1000:.1f} ms, "
            f"{rec['h2d_bytes']} h2d B, "
            f"{rec['bytes_not_staged']} B not staged "
            f"({rec['device_pool_hits']} page hits)"
        )
        return out, rec

    out_host, host = run_arm("off")
    out_dev, dev = run_arm("on")
    device_pool_mod.reset()

    rec = {
        "metric": "device_resident_data_plane",
        "backend": "cpu",
        "smoke": bool(smoke),
        "volume": list(shape),
        "block_shape": [block] * 3,
        "halo": list(halo),
        "grid": list(blocking.grid_shape),
        "n_devices": int(n_devices),
        "sharded_batch": int(sharded_batch),
        "host_staged": host,
        "device_resident": dev,
        "h2d_reduction": round(
            host["h2d_bytes"] / max(1, dev["h2d_bytes"]), 2
        ),
        "wall_ratio": round(host["seconds"] / dev["seconds"], 2),
        "bit_identical": bool(np.array_equal(out_host, out_dev)),
        "schedule": "morton",
    }
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r12.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"device-plane bench done -> {path}")
    return rec


def solve_bench(smoke=False):
    """Distributed-agglomeration config (docs/PERFORMANCE.md "Distributed
    agglomeration"): the >=100k-edge solver-scale instance of BENCH_r06
    (``grid_rag(g=33)``) solved three ways —

    1. single-host parallel GAEC (the host rung of ops/contraction.py):
       the reference energy and wall time,
    2. the Morton-octant reduce tree in one process
       (``parallel/reduce_tree.py``, frontier-aware contraction rounds,
       run twice to prove the merged labeling is deterministic),
    3. the same tree over a 2-process multihost worker group
       (``solve_over_workers``: jax.distributed worker wiring, boundary
       packets as the inter-host reduce hops), asserted bit-identical to
       the in-process tree.

    Records the energy gap vs the single-host solve (acceptance:
    |gap| <= 0.1%), determinism, and per-path wall times.  ``smoke=True``
    is the <10 s tier-1 variant (g=12, no file output); the full run
    writes BENCH_r09.json next to this script.  Emits exactly one JSON
    line on stdout and returns the record.
    """
    import tempfile

    from cluster_tools_tpu.ops.contraction import parallel_contraction
    from cluster_tools_tpu.ops.multicut import multicut_energy
    from cluster_tools_tpu.parallel import reduce_tree as rt
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.utils.synthetic import grid_rag

    g = 12 if smoke else 33
    shards = 4 if smoke else 8
    fanout = 2
    n_workers = 2
    n, edges, costs = grid_rag(g=g, seed=0)
    impl = rt._host_impl()  # same concrete rung everywhere -> bit-comparable
    log(
        f"solve bench: grid_rag g={g} ({len(edges)} edges, {n} nodes), "
        f"{shards} shards, fanout {fanout}, impl {impl}"
    )

    t0 = time.perf_counter()
    lab_single = parallel_contraction(
        n, edges, costs.reshape(-1, 1), "max", 0.0, impl=impl
    )
    t_single = time.perf_counter() - t0
    e_single = multicut_energy(edges, costs, lab_single)

    pos = np.stack(np.unravel_index(np.arange(n), (g, g, g)), axis=1)
    node_shard = rt.morton_node_shards(pos, shards)
    solver = rt.default_tree_solver("max", 0.0, impl=impl)
    t0 = time.perf_counter()
    lab_tree, info = rt.sharded_solve(
        n, edges, costs, node_shard, fanout=fanout, solver=solver,
        max_workers=4,
    )
    t_tree = time.perf_counter() - t0
    lab_rerun, _ = rt.sharded_solve(
        n, edges, costs, node_shard, fanout=fanout, solver=solver,
        max_workers=1,
    )
    deterministic = bool(np.array_equal(lab_tree, lab_rerun))
    e_tree = multicut_energy(edges, costs, lab_tree)
    gap_pct = 100.0 * (e_tree - e_single) / max(abs(e_single), 1e-12)
    log(
        f"solve bench: single-host {t_single:.3f}s E={e_single:.1f} | "
        f"reduce tree {t_tree:.3f}s E={e_tree:.1f} "
        f"(gap {gap_pct:+.4f}%, deterministic={deterministic})"
    )

    scratch = tempfile.mkdtemp(prefix="ctt_solve_bench_")
    t0 = time.perf_counter()
    lab_workers, winfo = rt.solve_over_workers(
        n, edges, costs, node_shard, fanout=fanout, n_workers=n_workers,
        scratch_dir=scratch,
    )
    t_workers = time.perf_counter() - t0
    workers_identical = bool(np.array_equal(lab_workers, lab_tree))
    e_workers = multicut_energy(edges, costs, lab_workers)
    gap_workers = 100.0 * (e_workers - e_single) / max(abs(e_single), 1e-12)
    log(
        f"solve bench: {n_workers}-worker group {t_workers:.3f}s "
        f"E={e_workers:.1f} (gap {gap_workers:+.4f}%, "
        f"bit-identical to in-process tree: {workers_identical})"
    )

    rec = {
        "metric": "distributed_agglomeration_solve",
        "backend": "cpu",
        "smoke": bool(smoke),
        "impl": impl,
        "n_nodes": int(n),
        "n_edges": int(len(edges)),
        "solver_shards": int(shards),
        "reduce_fanout": int(fanout),
        "single_host": {
            "seconds": round(t_single, 4),
            "energy": round(e_single, 3),
        },
        "reduce_tree": {
            "seconds": round(t_tree, 4),
            "energy": round(e_tree, 3),
            "energy_gap_pct": round(gap_pct, 4),
            "deterministic_across_reruns": deterministic,
            "levels": info["levels"],
            "boundary_edges_root": info["boundary_edges_root"],
        },
        "worker_group": {
            "workers": int(n_workers),
            "seconds": round(t_workers, 4),
            "energy": round(e_workers, 3),
            "energy_gap_pct": round(gap_workers, 4),
            "bit_identical_to_in_process": workers_identical,
        },
        "gap_within_0p1pct": bool(
            abs(gap_pct) <= 0.1 and abs(gap_workers) <= 0.1
        ),
    }
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r09.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"solve bench done -> {path}")
    return rec


def reduce_plane_bench(smoke=False):
    """Collective reduce plane vs the filesystem packet plane
    (docs/PERFORMANCE.md "Collective reduce plane") on the >=100k-edge
    solver-scale instance (``grid_rag(g=33)``), four arms:

    1. **host arm** (``reduce_plane="packet"`` in-process): the per-round
       host dispatch baseline — ``contraction_dispatches`` counts one
       dispatch per contraction round per group,
    2. **worker packet arm** (2-process ``solve_over_workers``): the
       filesystem packet plane proper; counts the ``packet_*.npz`` hops
       it writes,
    3. **collective arm** (``reduce_plane="collective"``): one jitted
       shard_map program + one all_gather hop per tree level
       (``collective_hops == levels``, ``contraction_dispatches ==
       levels``, zero packet files by construction),
    4. **fallback arm** (``CT_COLLECTIVES_DISABLED=1`` + demanded
       collective): the degrade ladder — bit-identical labels with
       ``degraded:packet_plane`` attributed in failures.json.

    Acceptance: >=2x fewer host dispatches per tree level on the
    collective arm, ``packet_fallbacks == 0`` on the happy path, and all
    arms bit-identical.  ``smoke=True`` is the <10 s tier-1 variant
    (g=12, no worker arm, no file output); the full run writes
    BENCH_r16.json next to this script.  Emits one JSON line on stdout.
    """
    import glob as glob_mod
    import tempfile

    # the collective plane needs a multi-device mesh: force the virtual
    # 8-device CPU platform (same as tests/conftest.py) BEFORE the jax
    # backend initializes — on one device the plane refuses and every
    # arm would silently measure the host path
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    from cluster_tools_tpu.parallel import reduce_tree as rt
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.utils.synthetic import grid_rag

    g = 12 if smoke else 33
    shards = 4 if smoke else 8
    fanout = 2
    n, edges, costs = grid_rag(g=g, seed=0)
    pos = np.stack(np.unravel_index(np.arange(n), (g, g, g)), axis=1)
    node_shard = rt.morton_node_shards(pos, shards)
    log(
        f"reduce-plane bench: grid_rag g={g} ({len(edges)} edges, {n} "
        f"nodes), {shards} shards, fanout {fanout}"
    )

    def solve(plane, **kw):
        snap = rt.solve_snapshot()
        t0 = time.perf_counter()
        labels, info = rt.sharded_solve(
            n, edges, costs, node_shard, fanout=fanout,
            reduce_plane=plane, **kw,
        )
        return labels, info, time.perf_counter() - t0, rt.solve_delta(snap)

    # 1. host arm: the per-round dispatch baseline
    lab_h, info_h, t_host, d_host = solve("packet", max_workers=4)
    levels = len(info_h["levels"])

    # 2. worker packet arm: the filesystem plane, hops counted as files
    packet_files = None
    t_workers = None
    workers_identical = None
    if not smoke:
        scratch = tempfile.mkdtemp(prefix="ctt_reduce_plane_")
        t0 = time.perf_counter()
        lab_w, _ = rt.solve_over_workers(
            n, edges, costs, node_shard, fanout=fanout, n_workers=2,
            scratch_dir=scratch, reduce_plane="packet",
        )
        t_workers = time.perf_counter() - t0
        packet_files = len(
            glob_mod.glob(os.path.join(scratch, "packet_*.npz"))
        )
        workers_identical = bool(np.array_equal(lab_w, lab_h))

    # 3. collective arm: one program + one hop per level
    lab_c, info_c, t_coll, d_coll = solve("collective")
    collective_identical = bool(np.array_equal(lab_c, lab_h))

    # 4. fallback arm: force-disabled collectives ride the degrade ladder
    fail_dir = tempfile.mkdtemp(prefix="ctt_reduce_fallback_")
    failures_path = os.path.join(fail_dir, "failures.json")
    os.environ["CT_COLLECTIVES_DISABLED"] = "1"
    try:
        lab_f, info_f, t_fb, d_fb = solve(
            "collective", max_workers=4,
            failures_path=failures_path, task_name="reduce_plane_bench",
        )
    finally:
        del os.environ["CT_COLLECTIVES_DISABLED"]
    fallback_identical = bool(np.array_equal(lab_f, lab_h))
    with open(failures_path) as f:
        fb_records = [
            r["resolution"] for r in json.load(f)["records"]
            if r["task"] == "reduce_plane_bench"
        ]

    host_per_level = d_host["contraction_dispatches"] / max(1, levels)
    coll_per_level = d_coll["contraction_dispatches"] / max(1, levels)
    dispatch_ratio = host_per_level / max(1e-9, coll_per_level)
    log(
        f"reduce-plane bench: host {t_host:.3f}s "
        f"({host_per_level:.1f} dispatches/level) | collective "
        f"{t_coll:.3f}s ({coll_per_level:.1f}/level, "
        f"{d_coll['collective_hops']} hops, "
        f"{d_coll['bytes_over_interconnect']} B over interconnect) | "
        f"fallback {t_fb:.3f}s ({fb_records or 'no record'}) | "
        f"bit-identical c={collective_identical} f={fallback_identical}"
    )

    rec = {
        "metric": "collective_reduce_plane",
        "backend": "cpu",
        "smoke": bool(smoke),
        "n_nodes": int(n),
        "n_edges": int(len(edges)),
        "solver_shards": int(shards),
        "tree_levels": int(levels),
        "host_arm": {
            "seconds": round(t_host, 4),
            "contraction_dispatches": int(d_host["contraction_dispatches"]),
            "dispatches_per_level": round(host_per_level, 2),
        },
        "packet_worker_arm": None if smoke else {
            "workers": 2,
            "seconds": round(t_workers, 4),
            "packet_files_written": int(packet_files),
            "bit_identical_to_host": workers_identical,
        },
        "collective_arm": {
            "seconds": round(t_coll, 4),
            "contraction_dispatches": int(d_coll["contraction_dispatches"]),
            "dispatches_per_level": round(coll_per_level, 2),
            "collective_hops": int(d_coll["collective_hops"]),
            "bytes_over_interconnect": int(d_coll["bytes_over_interconnect"]),
            "packet_fallbacks": int(d_coll["packet_fallbacks"]),
            "packet_files_written": 0,  # never touches the filesystem
            "bit_identical_to_host": collective_identical,
        },
        "fallback_arm": {
            "seconds": round(t_fb, 4),
            "packet_fallbacks": int(d_fb["packet_fallbacks"]),
            "resolutions": fb_records,
            "bit_identical_to_host": fallback_identical,
        },
        "dispatch_ratio_host_over_collective": round(dispatch_ratio, 2),
        "accepted": bool(
            dispatch_ratio >= 2.0
            and d_coll["collective_hops"] == levels
            and d_coll["packet_fallbacks"] == 0
            and collective_identical
            and fallback_identical
            and "degraded:packet_plane" in fb_records
        ),
    }
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r16.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"reduce-plane bench done -> {path}")
    return rec


def _latency_stats(samples):
    """p50/p95/p99/mean seconds over a list of latencies (None-safe)."""
    if not samples:
        return None
    xs = np.asarray(sorted(samples), dtype=np.float64)
    return {
        "n": int(xs.size),
        "p50_s": round(float(np.percentile(xs, 50)), 4),
        "p95_s": round(float(np.percentile(xs, 95)), 4),
        "p99_s": round(float(np.percentile(xs, 99)), 4),
        "mean_s": round(float(xs.mean()), 4),
        "max_s": round(float(xs.max()), 4),
    }


def _poisson_gaps(rng, n, mean_gap_s):
    """Seeded open-loop arrival schedule: exponential inter-arrival
    gaps (the first request fires immediately)."""
    gaps = rng.exponential(mean_gap_s, size=n)
    gaps[0] = 0.0
    return [float(g) for g in gaps]


def serve_bench(smoke=False):
    """Traffic-shaped service bench (docs/SERVING.md): the first bench row
    measured against the resident server instead of a batch invocation.

    Starts the serve CLI as a FRESH subprocess (a true cold process: the
    compiled-program, chunk, and handoff caches start empty) with two
    tenants, then drives open-loop traffic over the local HTTP endpoint:

    - **cold**: one request per class (watershed / connected_components /
      inference) — each pays its shape's full compile+IO cold tax;
    - **warm solo**: Poisson arrivals (seeded exponential gaps) of mixed
      classes from the well-behaved tenant against the now-warm server —
      client-observed p50/p99 per class, throughput, and the cold/warm
      split the resident process exists to win;
    - **contended**: the same Poisson pattern while an aggressor tenant
      floods its own queue — per-tenant admission (quotas + DRR dispatch)
      must keep the well-behaved tenant's p99 within 2x its solo value
      while the aggressor eats typed 429 backpressure;
    - **drain**: SIGTERM, asserting the rolling-restart contract (rc 114).

    Every request's output is compared bit-for-bit against a solo batch
    run of the same class executed in THIS process — service mode is a
    residency optimization, never a numerics change.  ``make bench-serve``
    writes BENCH_r10.json; ``smoke=True`` shrinks the request counts and
    skips the file write.  Emits exactly one JSON line on stdout.
    """
    from __graft_entry__ import _force_cpu_platform

    _force_cpu_platform(8)
    import shutil
    import signal
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp
    from scipy import ndimage

    from cluster_tools_tpu.models import UNet3D
    from cluster_tools_tpu.runtime.server import ServeClient, ServeRejected
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.runtime.supervision import REQUEUE_EXIT_CODE
    from cluster_tools_tpu.runtime.task import build
    from cluster_tools_tpu.tasks.connected_components import (
        ConnectedComponentsWorkflow,
    )
    from cluster_tools_tpu.tasks.inference import (
        InferenceWorkflow,
        save_checkpoint,
    )
    from cluster_tools_tpu.tasks.watershed import WatershedWorkflow
    from cluster_tools_tpu.utils.volume_utils import file_reader

    shape, block = (16, 16, 16), 8
    n_warm = 6 if smoke else 18
    n_contended = 6 if smoke else 12
    n_aggressor = 6 if smoke else 8
    # offered load ~50% of the 2-worker capacity for the mixed service
    # times (watershed is host-bound at ~6s; cc/inference sub-second
    # warm): open-loop at sane utilization, not an overload test
    mean_gap = 1.0 if smoke else 2.5
    root = tempfile.mkdtemp(prefix="ctt_serve_bench_")
    log(f"serve bench: {shape} volumes, {n_warm} warm + "
        f"{n_contended} contended requests, open-loop")

    # -- shared inputs ----------------------------------------------------
    rng = np.random.default_rng(0)
    data = os.path.join(root, "data.zarr")
    f = file_reader(data)
    bmap = ndimage.gaussian_filter(rng.random(shape), 2.0)
    bmap = ((bmap - bmap.min()) / (bmap.max() - bmap.min())).astype(
        np.float32
    )
    f.create_dataset("bmap", shape=shape, chunks=(block,) * 3,
                     dtype="float32")[...] = bmap
    mask = (rng.random(shape) > 0.5).astype(np.float32)
    f.create_dataset("mask", shape=shape, chunks=(block,) * 3,
                     dtype="float32")[...] = mask
    raw = rng.random(shape).astype(np.float32)
    f.create_dataset("raw", shape=shape, chunks=(block,) * 3,
                     dtype="float32")[...] = raw
    # depth-2 UNet: a model whose cold tax is genuinely compile-dominated
    # (the cached-shape class the warm split headlines); still sub-second
    # warm at 16^3
    model_cfg = {"name": "unet3d", "out_channels": 2, "base_features": 8,
                 "depth": 2, "norm": None}
    model = UNet3D(out_channels=2, base_features=8, depth=2, norm=None)
    variables = model.init(
        jax.random.PRNGKey(2), jnp.zeros((1, block, block, block, 1))
    )
    ckpt = os.path.join(root, "model.npz")
    save_checkpoint(ckpt, variables)

    # -- request classes (the params half of a /submit payload) -----------
    def _cls_params(cls, out_key):
        if cls == "watershed":
            return dict(input_path=data, input_key="bmap",
                        output_path=data, output_key=out_key,
                        threshold=0.5, halo=[4] * 3)
        if cls == "connected_components":
            return dict(input_path=data, input_key="mask",
                        output_path=data, output_key=out_key,
                        threshold=0.5)
        if cls == "inference":
            return dict(input_path=data, input_key="raw",
                        output_path=data, output_key=out_key,
                        checkpoint_path=ckpt, model=dict(model_cfg),
                        halo=[4] * 3, normalize_range=[0.0, 1.0])
        raise ValueError(cls)

    classes = ("watershed", "connected_components", "inference")

    # -- solo batch references (THIS process; the bit-identity oracle) ----
    wf_cls = {"watershed": WatershedWorkflow,
              "connected_components": ConnectedComponentsWorkflow,
              "inference": InferenceWorkflow}
    refs, solo_batch_s = {}, {}
    for cls in classes:
        base = os.path.join(root, f"ref_{cls}")
        cdir = os.path.join(base, "config")
        os.makedirs(cdir, exist_ok=True)
        # plain batch semantics (handoffs off): the oracle is the storage
        # path every batch user runs today; PR-8 guarantees the fused
        # (handoffs-on) server runs stay bit-identical to it
        fu.atomic_write_json(
            os.path.join(cdir, "global.config"),
            {"block_shape": [block] * 3, "memory_handoffs": False},
        )
        t0 = time.perf_counter()
        ok = build([wf_cls[cls](
            tmp_folder=os.path.join(base, "tmp"), config_dir=cdir,
            max_jobs=2, target="local",
            **_cls_params(cls, f"ref_{cls}"),
        )])
        if not ok:
            raise RuntimeError(f"serve bench reference run failed: {cls}")
        solo_batch_s[cls] = round(time.perf_counter() - t0, 3)
        refs[cls] = np.asarray(file_reader(data)[f"ref_{cls}"][...])
    log(f"references built: { {c: solo_batch_s[c] for c in classes} }")

    # -- the resident server (fresh subprocess = true cold start) ----------
    srv = os.path.join(root, "srv")
    os.makedirs(srv, exist_ok=True)
    # 3 workers vs quota sum 2+1: the aggressor's single in-flight slot
    # cannot subtract from the steady tenant's two — quota isolation is
    # capacity planning, DRR covers the dispatch order
    fu.atomic_write_json(os.path.join(srv, "serve_config.json"), {
        "max_workers": 3,
        "tenants": {
            "steady": {"max_inflight": 2, "max_queue_depth": 64},
            # a short queue on purpose: the flood must hit the typed
            # 429 backpressure, not rot in an unbounded queue
            "aggressor": {"max_inflight": 1, "max_queue_depth": 3},
        },
    })
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.abspath(__file__))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "cluster_tools_tpu.serve",
         "--base-dir", srv, "--config",
         os.path.join(srv, "serve_config.json")],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        endpoint = os.path.join(srv, "server.json")
        deadline = time.monotonic() + 120
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"serve bench server died rc={proc.returncode}:\n"
                    f"{proc.stdout.read()[-4000:]}"
                )
            try:
                with open(endpoint) as fh:
                    doc = json.load(fh)
                if doc.get("pid") == proc.pid:
                    break
            except (OSError, ValueError):
                pass
            if time.monotonic() > deadline:
                proc.kill()
                raise RuntimeError("serve bench server never bound")
            time.sleep(0.05)
        client = ServeClient(doc["host"], doc["port"], timeout_s=60.0)

        seq = [0]
        outputs = []  # (cls, out_key) for the bit-identity sweep

        def _payload(tenant, cls):
            seq[0] += 1
            rid = f"{tenant}-{seq[0]:03d}"
            out_key = f"out_{rid}"
            outputs.append((cls, out_key))
            return dict(
                tenant=tenant, request_id=rid, workflow=cls,
                config=dict(
                    tmp_folder=os.path.join(root, "req", rid),
                    global_config={"block_shape": [block] * 3},
                    params=_cls_params(cls, out_key),
                ),
            )

        def _run_open_loop(schedule, rejected=None):
            """Submit (gap_s, payload) pairs open-loop; returns
            ``{request_id: (client_latency_s, class, service_s)}`` and the
            phase wall.  Client latency includes queue wait (the number a
            caller experiences); ``service_s`` is the server-side ``run_s``
            (what residency actually saves, queue-independent)."""
            lat, threads, errors = {}, [], []
            t_phase = time.perf_counter()
            for gap, payload in schedule:
                time.sleep(gap)
                rid = payload["request_id"]
                cls = payload["workflow"]
                t0 = time.perf_counter()
                try:
                    client.submit(**payload)
                except ServeRejected as e:
                    if rejected is None:
                        raise
                    rejected.append((rid, e.code))
                    outputs.remove((cls, payload["config"]["params"]
                                    ["output_key"]))
                    continue

                def _wait(rid=rid, cls=cls, t0=t0):
                    # raising in a Thread only prints to stderr — collect and
                    # re-raise after join, or a failed request would silently
                    # drop out of the latency stats
                    try:
                        rec = client.wait(rid, timeout_s=600, poll_s=0.02)
                        if rec.get("state") != "done":
                            raise RuntimeError(f"request {rid} ended {rec}")
                        lat[rid] = (
                            time.perf_counter() - t0, cls,
                            float(rec.get("run_s") or 0.0),
                        )
                    except Exception as e:
                        errors.append(e)

                th = threading.Thread(target=_wait)
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            if errors:
                raise errors[0]
            return lat, time.perf_counter() - t_phase

        # -- phase 1: cold (one request per class, sequential) -----------------
        cold_s, cold_service_s = {}, {}
        for cls in classes:
            lat, _ = _run_open_loop([(0.0, _payload("steady", cls))])
            client_s, _, service_s = next(iter(lat.values()))
            cold_s[cls] = round(client_s, 3)
            cold_service_s[cls] = round(service_s, 3)
        log(f"cold (service): {cold_service_s}")

        # -- phase 2: warm solo (Poisson, mixed classes, one tenant) -----------
        arr_rng = np.random.default_rng(42)
        schedule = [
            (gap, _payload("steady", classes[i % len(classes)]))
            for i, gap in enumerate(
                _poisson_gaps(arr_rng, n_warm, mean_gap)
            )
        ]
        warm_lat, warm_wall = _run_open_loop(schedule)
        warm_by_cls = {
            cls: _latency_stats(
                [s for s, c, _ in warm_lat.values() if c == cls]
            )
            for cls in classes
        }
        warm_service_by_cls = {
            cls: _latency_stats(
                [sv for _, c, sv in warm_lat.values() if c == cls]
            )
            for cls in classes
        }
        warm_all = _latency_stats([s for s, _, _ in warm_lat.values()])
        throughput = round(len(warm_lat) / warm_wall, 3)
        log(f"warm solo: p50 {warm_all['p50_s']}s p99 {warm_all['p99_s']}s, "
            f"{throughput} req/s")

        # -- phase 2b: the cold/warm split, apples to apples -------------------
        # one request per class, SEQUENTIAL like the cold phase was: the
        # split compares residency (compiled programs + chunk cache warm),
        # not concurrency (concurrent sweeps contend for the CPU and the
        # process-wide XLA dispatch lock, inflating service times for cold
        # and warm alike)
        warm_seq_service_s = {}
        for cls in classes:
            lat, _ = _run_open_loop([(0.0, _payload("steady", cls))])
            warm_seq_service_s[cls] = round(next(iter(lat.values()))[2], 3)
        log(f"warm sequential (service): {warm_seq_service_s}")

        # -- phase 3: contended (same steady pattern + aggressor flood) --------
        rejected = []
        agg_sched = [
            (0.05, _payload("aggressor", "watershed"))
            for _ in range(n_aggressor)
        ]
        steady_sched = [
            (gap, _payload("steady", classes[i % len(classes)]))
            for i, gap in enumerate(
                _poisson_gaps(arr_rng, n_contended, mean_gap)
            )
        ]
        agg_result = {}

        def _flood():
            lat, _ = _run_open_loop(agg_sched, rejected=rejected)
            agg_result.update(lat)

        flood_th = threading.Thread(target=_flood)
        flood_th.start()
        cont_lat, _ = _run_open_loop(steady_sched)
        flood_th.join()
        cont_all = _latency_stats([s for s, _, _ in cont_lat.values()])
        agg_all = _latency_stats([s for s, _, _ in agg_result.values()])
        p99_ratio = round(cont_all["p99_s"] / max(warm_all["p99_s"], 1e-9), 3)
        log(f"contended: steady p99 {cont_all['p99_s']}s "
            f"(x{p99_ratio} of solo), aggressor p99 "
            f"{agg_all['p99_s'] if agg_all else None}s, "
            f"{len(rejected)} typed rejections")

        # -- /status + drain ---------------------------------------------------
        status = client.status()
        tenants_snap = status["server"]["tenants"]
        proc.send_signal(signal.SIGTERM)
        drain_rc = proc.wait(timeout=120)
    finally:
        # leaked-server reap: whatever happened above — assertion,
        # timeout, exception — the resident server must not outlive
        # the bench (stray servers burn CPU and are the prime
        # suspect when tier-1 drifts toward its wall-clock ceiling)
        if proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=30)
            except Exception:
                pass

    # -- bit-identity sweep: every served output == its solo reference -----
    out = file_reader(data, "r")
    bit_identical = all(
        np.array_equal(np.asarray(out[key][...]), refs[cls])
        for cls, key in outputs
    )

    # the cold/warm split keys on SERVICE latency (server-side run_s):
    # queue wait is a property of the offered load, not of residency.
    # "inference" is the cached-shape class — its cold tax is dominated
    # by the model's per-shape compiled program, exactly the asset a
    # resident process keeps warm (watershed is host-work-bound and
    # cannot show the compile win; its warm gain is the chunk cache's)
    cached_cls = "inference"
    warm_speedup = {
        cls: round(
            cold_service_s[cls] / max(warm_seq_service_s[cls], 1e-9), 2
        )
        for cls in classes
    }
    rec = {
        "metric": "service_mode_traffic",
        "backend": "cpu",
        "volume": list(shape),
        "block_shape": [block] * 3,
        "classes": list(classes),
        "tenants": 2,
        "max_workers": 3,
        "arrivals": {"process": "poisson", "mean_gap_s": mean_gap,
                     "seed": 42},
        "solo_batch_s": solo_batch_s,
        "cold_s": cold_s,
        "cold_service_s": cold_service_s,
        "warm": warm_by_cls,
        "warm_service": warm_service_by_cls,
        "warm_sequential_service_s": warm_seq_service_s,
        "warm_aggregate": warm_all,
        "throughput_rps": throughput,
        "warm_speedup_p50": warm_speedup,
        "cached_shape_class": cached_cls,
        "warm_speedup_cached_shape": warm_speedup.get(cached_cls),
        "fairness": {
            "steady_solo_p99_s": warm_all["p99_s"],
            "steady_contended_p99_s": cont_all["p99_s"],
            "p99_ratio_under_aggressor": p99_ratio,
            "aggressor": {
                "submitted": n_aggressor,
                "completed": len(agg_result),
                "rejected_typed": len(rejected),
                "stats": agg_all,
            },
        },
        "tenant_snapshot": {
            name: {k: s[k] for k in
                   ("submitted", "dispatched", "completed", "rejected")}
            for name, s in tenants_snap.items()
        },
        "requests_total": seq[0],
        "bit_identical": bool(bit_identical),
        "drain_rc": drain_rc,
        "acceptance": {
            "warm_p50_beats_cold_5x": bool(
                warm_speedup.get(cached_cls, 0) >= 5.0
            ),
            "steady_p99_within_2x_solo": bool(p99_ratio <= 2.0),
            "bit_identical": bool(bit_identical),
            "drain_rc_114": drain_rc == REQUEUE_EXIT_CODE,
        },
    }
    shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r10.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"serve bench done -> {path}")
    return rec


def fleet_bench(smoke=False):
    """Fleet supervised-traffic bench (docs/SERVING.md "Supervision"):
    open-loop Poisson two-tenant traffic against a *supervised* 3-member
    fleet, with a **gateway-kill** (SIGKILL the gateway child) phase and
    a **member-kill** (SIGKILL one member) phase.

    - **warm**: after one cold request per tenant pins affinity, Poisson
      arrivals of connected-components requests measure the fleet's warm
      client-observed p50/p99 — the baseline every failure phase is
      judged against;
    - **gateway-kill**: the gateway child is SIGKILLed after half the
      arrivals — the supervisor restarts it as incarnation 2 on the SAME
      port, the restarted gateway rebuilds its routing view cold from
      disk (member dirs, journals, adoption claims), and every
      already-acknowledged request completes with ZERO client
      resubmission (clients ride ``wait(across_restarts=True)``); the
      kill→rebooted latency is recorded;
    - **member-kill**: one member is SIGKILLed after half the arrivals —
      a survivor adopts its journal (the BENCH_r13 failover), AND the
      supervisor respawns the lost capacity on a FRESH base dir; the
      bench then drives new-tenant probe bursts until the respawned
      member has served a request, proving capacity actually healed;
    - bars: zero lost acknowledged requests out of >= 30 acked,
      gateway-kill-phase p99 and member-kill-phase p99 within 3x their
      *failover floor* (warm p99 + one measured gateway restart, resp.
      warm p99 + the dead-member detection window — the unavoidable cost
      a request pays when it spans the failure; bare 3x-warm would be
      vacuous against a ~0.2s warm p99), incarnation bumped exactly
      once, the dead member both adopted and respawned on a fresh dir,
      the respawned member served traffic before the run ended,
      bit-identical outputs, drain rc 114.

    ``make bench-fleet`` writes BENCH_r15.json; ``smoke=True`` shrinks
    the request counts and skips the file write.  Emits exactly one JSON
    line on stdout.
    """
    from __graft_entry__ import _force_cpu_platform

    _force_cpu_platform(8)
    import shutil
    import signal
    import subprocess
    import tempfile
    import threading

    from cluster_tools_tpu.runtime.server import ServeClient
    from cluster_tools_tpu.runtime.supervision import REQUEUE_EXIT_CODE
    from cluster_tools_tpu.runtime.task import build
    from cluster_tools_tpu.tasks.connected_components import (
        ConnectedComponentsWorkflow,
    )
    from cluster_tools_tpu.utils import function_utils as fu
    from cluster_tools_tpu.utils.volume_utils import file_reader

    shape, block = (16, 16, 16), 8
    n_warm = 6 if smoke else 12
    n_gk = 6 if smoke else 12
    n_mk = 6 if smoke else 12
    mean_gap = 0.3 if smoke else 0.4
    root = tempfile.mkdtemp(prefix="ctt_fleet_bench_")
    log(f"fleet bench: supervised 3-member fleet, {n_warm} warm + "
        f"{n_gk} gateway-kill + {n_mk} member-kill phase requests, "
        f"open-loop poisson (mean gap {mean_gap}s)")

    rng = np.random.default_rng(0)
    vol = (rng.random(shape) > 0.5).astype("float32")
    data = os.path.join(root, "data.zarr")
    ds = file_reader(data).create_dataset(
        "mask", shape=shape, chunks=(block,) * 3, dtype="float32")
    ds[...] = vol

    # -- solo batch reference (bit-identity oracle) ------------------------
    ref_dir = os.path.join(root, "ref")
    os.makedirs(os.path.join(ref_dir, "config"), exist_ok=True)
    with open(os.path.join(ref_dir, "config", "global.config"), "w") as f:
        json.dump({"block_shape": [block] * 3,
                   "memory_handoffs": True}, f)
    t0 = time.monotonic()
    assert build([ConnectedComponentsWorkflow(
        tmp_folder=os.path.join(ref_dir, "tmp"),
        config_dir=os.path.join(ref_dir, "config"),
        max_jobs=2, target="local",
        input_path=data, input_key="mask",
        output_path=data, output_key="ref_seg", threshold=0.5,
    )])
    solo_batch_s = round(time.monotonic() - t0, 4)
    ref_seg = np.asarray(file_reader(data, "r")["ref_seg"][...])

    # -- the fleet: supervisor -> gateway child + 3 members ----------------
    fleet_dir = os.path.join(root, "fleet")
    cfg_path = os.path.join(root, "fleet.json")
    health_interval_s, member_stale_s = 0.2, 1.0
    with open(cfg_path, "w") as f:
        json.dump({
            "members": 3,
            "gateway": {
                "health_interval_s": health_interval_s,
                "member_stale_s": member_stale_s,
                "call_timeout_s": 2.0, "breaker_threshold": 2,
                "breaker_cooldown_s": 0.75, "hedge_max_delay_s": 0.4,
            },
            "server": {"max_workers": 2},
            "supervisor": {"poll_s": 0.2, "gateway_stale_s": 4.0},
        }, f)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    fleet_log = os.path.join(root, "fleet.log")
    with open(fleet_log, "w") as lf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_tools_tpu.fleet",
             "--base-dir", fleet_dir, "--config", cfg_path],
            env=env, cwd=repo, text=True,
            stdout=lf, stderr=subprocess.STDOUT,
        )

    def _fleet_log_tail(n=4000):
        try:
            with open(fleet_log) as lf:
                return lf.read()[-n:]
        except OSError:
            return "<no fleet log>"

    def payload(tenant, rid, out_key):
        return dict(
            tenant=tenant, request_id=rid,
            workflow="connected_components",
            config=dict(
                tmp_folder=os.path.join(root, "req_" + rid),
                global_config={"block_shape": [block] * 3},
                params=dict(input_path=data, input_key="mask",
                            output_path=data, output_key=out_key,
                            threshold=0.5),
            ),
        )

    lats = {"warm": [], "gateway_kill": [], "member_kill": []}
    states = {}
    outputs = []
    lock = threading.Lock()

    def drive(phase, tenant, rid, key):
        c = ServeClient.from_endpoint_file(fleet_dir)
        t_start = time.monotonic()
        sdoc = c.submit(retry_s=120, **payload(tenant, rid, key))
        t_sub = time.monotonic()
        rec = c.wait(rid, timeout_s=600, across_restarts=True)
        lat = time.monotonic() - t_start
        if os.environ.get("CT_BENCH_DEBUG"):
            log(f"DEBUG {rid}: via {sdoc.get('member')} submit "
                f"{t_sub - t_start:.2f}s total {lat:.2f}s "
                f"state {rec.get('state')}")
        with lock:
            lats[phase].append(lat)
            states[rid] = rec.get("state")

    sup_path = os.path.join(fleet_dir, "supervisor_state.json")
    drain_rc = None
    try:
        # the supervised boot contract: supervisor_state.json names a
        # booted gateway child, and the endpoint file is that child's
        # (the endpoint pid is the GATEWAY's, never the supervisor's)
        endpoint = os.path.join(fleet_dir, "server.json")
        deadline = time.monotonic() + 180
        while True:
            if proc.poll() is not None:
                raise AssertionError(
                    f"fleet died on startup rc={proc.returncode}:\n"
                    f"{_fleet_log_tail()}")
            sup = fu.read_json_if_valid(sup_path) or {}
            gw = sup.get("gateway") or {}
            doc = fu.read_json_if_valid(endpoint) or {}
            if (sup.get("pid") == proc.pid and gw.get("booted")
                    and doc.get("role") == "gateway"
                    and doc.get("pid") == gw.get("pid")):
                gw_pid = gw["pid"]
                break
            assert time.monotonic() < deadline, \
                "supervised gateway never bound"
            time.sleep(0.05)
        client = ServeClient.from_endpoint_file(fleet_dir)

        # -- cold: one request per tenant pins affinity (not measured) -----
        homes = {}
        for tenant in ("alice", "bob"):
            rid, key = f"{tenant}_cold", f"seg_{tenant}_cold"
            doc = client.submit(retry_s=120, **payload(tenant, rid, key))
            homes[tenant] = doc["member"]
            outputs.append(key)
            rec = client.wait(rid, timeout_s=600)
            assert rec["state"] == "done", rec
            with lock:
                states[rid] = rec.get("state")

        # -- warm phase: poisson arrivals, no failures ---------------------
        arrival_rng = np.random.default_rng(42)
        threads = []
        for i, gap in enumerate(_poisson_gaps(arrival_rng, n_warm,
                                              mean_gap)):
            time.sleep(gap)
            tenant = ("alice", "bob")[i % 2]
            rid, key = f"{tenant}_w{i}", f"seg_{tenant}_w{i}"
            outputs.append(key)
            t = threading.Thread(target=drive,
                                 args=("warm", tenant, rid, key))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=600)
        warm_stats = _latency_stats(lats["warm"])
        log(f"fleet warm phase: p50 {warm_stats['p50_s']}s, "
            f"p99 {warm_stats['p99_s']}s")

        # -- gateway-kill phase: SIGKILL the gateway child mid-arrivals ----
        t_kill = [None]
        restart_s = [None]

        def watch_restart():
            # kill -> rebooted latency: SIGKILL -> the supervisor's state
            # file shows incarnation 2 booted (cold-rebuilt, same port)
            while time.monotonic() - t_kill[0] < 60:
                s = fu.read_json_if_valid(sup_path) or {}
                g = s.get("gateway") or {}
                if g.get("incarnation") == 2 and g.get("booted"):
                    restart_s[0] = round(time.monotonic() - t_kill[0], 3)
                    return
                time.sleep(0.05)

        watcher = None
        threads = []
        for i, gap in enumerate(_poisson_gaps(arrival_rng, n_gk,
                                              mean_gap)):
            time.sleep(gap)
            if i == n_gk // 2:
                log(f"fleet gateway-kill phase: SIGKILL gateway child "
                    f"(pid {gw_pid})")
                t_kill[0] = time.monotonic()
                os.kill(gw_pid, signal.SIGKILL)
                watcher = threading.Thread(target=watch_restart)
                watcher.start()
            tenant = ("alice", "bob")[i % 2]
            rid, key = f"{tenant}_g{i}", f"seg_{tenant}_g{i}"
            outputs.append(key)
            t = threading.Thread(target=drive,
                                 args=("gateway_kill", tenant, rid, key))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=600)
        if watcher is not None:
            watcher.join(timeout=60)
        gk_stats = _latency_stats(lats["gateway_kill"])
        sup = fu.read_json_if_valid(sup_path) or {}
        gw = sup.get("gateway") or {}
        gw_incarnation = gw.get("incarnation")
        gw_restarts = gw.get("restarts")
        log(f"fleet gateway-kill phase: p50 {gk_stats['p50_s']}s, "
            f"p99 {gk_stats['p99_s']}s (rebooted as incarnation "
            f"{gw_incarnation} after {restart_s[0]}s)")

        # -- member-kill phase: SIGKILL alice's home mid-arrivals ----------
        victim_m = homes["alice"]
        victim_m_dir = os.path.join(fleet_dir, "members", victim_m)
        victim_m_pid = (fu.read_json_if_valid(
            os.path.join(victim_m_dir, "server.json")) or {}).get("pid")
        assert victim_m_pid and victim_m_pid not in (proc.pid, gw_pid)
        threads = []
        for i, gap in enumerate(_poisson_gaps(arrival_rng, n_mk,
                                              mean_gap)):
            time.sleep(gap)
            if i == n_mk // 2:
                log(f"fleet member-kill phase: SIGKILL member {victim_m} "
                    f"(pid {victim_m_pid})")
                os.kill(victim_m_pid, signal.SIGKILL)
            tenant = ("alice", "bob")[i % 2]
            rid, key = f"{tenant}_m{i}", f"seg_{tenant}_m{i}"
            outputs.append(key)
            t = threading.Thread(target=drive,
                                 args=("member_kill", tenant, rid, key))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=600)
        mk_stats = _latency_stats(lats["member_kill"])
        log(f"fleet member-kill phase: p50 {mk_stats['p50_s']}s, "
            f"p99 {mk_stats['p99_s']}s")

        # a survivor adopted the dead member's journal...
        adopter_m = None
        adopt_deadline = time.monotonic() + 60
        while time.monotonic() < adopt_deadline:
            fstate = fu.read_json_if_valid(
                os.path.join(fleet_dir, "fleet_state.json")) or {}
            for ev in fstate.get("adoptions") or []:
                if ev.get("member") == victim_m:
                    adopter_m = ev.get("adopter")
            if adopter_m:
                break
            time.sleep(0.1)
        assert adopter_m, "killed member was never adopted"

        # ...AND the supervisor respawned the capacity on a FRESH dir
        repl, fresh_dir = None, False
        heal_deadline = time.monotonic() + 120
        while time.monotonic() < heal_deadline:
            sup = fu.read_json_if_valid(sup_path) or {}
            members = sup.get("members") or {}
            for name, m in members.items():
                if (name.startswith(victim_m + "-r")
                        and m.get("state") == "running"):
                    repl = name
                    fresh_dir = m.get("base_dir") != victim_m_dir
            fstate = fu.read_json_if_valid(
                os.path.join(fleet_dir, "fleet_state.json")) or {}
            if repl and ((fstate.get("members") or {}).get(repl)
                         or {}).get("alive"):
                break
            time.sleep(0.1)
        assert repl, "supervisor never respawned the killed member"
        log(f"fleet member-kill phase: {victim_m} adopted by {adopter_m}; "
            f"respawned as {repl} (fresh_dir={fresh_dir})")

        # the healed capacity must actually SERVE: burst new-tenant
        # probes (back-to-back submits spread over all live members via
        # the provisional queue bump) until the respawned member answers
        repl_probe, probes = None, 0
        probe_deadline = time.monotonic() + 120
        while repl_probe is None and time.monotonic() < probe_deadline:
            burst = []
            for _ in range(3):
                rid = f"probe_{probes}"
                key = f"seg_probe_{probes}"
                doc = client.submit(
                    retry_s=120, **payload(f"carol{probes}", rid, key))
                outputs.append(key)
                burst.append((rid, doc.get("member")))
                probes += 1
            for rid, via in burst:
                rec = client.wait(rid, timeout_s=600,
                                  across_restarts=True)
                with lock:
                    states[rid] = rec.get("state")
                if via == repl and repl_probe is None:
                    repl_probe = rid
        assert repl_probe, "respawned member never served a request"
        log(f"fleet heal: respawned member {repl} served {repl_probe} "
            f"({probes} probes)")

        # every acknowledged request completed — zero resubmission
        lost = [rid for rid, st in states.items() if st != "done"]

        with open(os.path.join(fleet_dir, "fleet_state.json")) as f:
            fstate = json.load(f)
        aff = fstate["affinity"]
        hit_rate = aff["hits"] / max(1, aff["hits"] + aff["misses"])
        adoptions = fstate["adoptions"]
        fleet_incarnation = fstate.get("incarnation")

        proc.send_signal(signal.SIGTERM)
        drain_rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=30)
            except Exception:
                pass
        # a reaped supervisor orphans gateway + members — never leak a
        # resident server past the bench (fresh-dir respawns included)
        members_root = os.path.join(fleet_dir, "members")
        names = (os.listdir(members_root)
                 if os.path.isdir(members_root) else [])
        for name in names:
            ep = os.path.join(members_root, name, "server.json")
            mpid = (fu.read_json_if_valid(ep) or {}).get("pid")
            if mpid:
                try:
                    os.kill(int(mpid), signal.SIGKILL)
                except OSError:
                    pass
        gdoc = fu.read_json_if_valid(
            os.path.join(fleet_dir, "server.json")) or {}
        if gdoc.get("role") == "gateway" and gdoc.get("pid"):
            try:
                os.kill(int(gdoc["pid"]), signal.SIGKILL)
            except OSError:
                pass

    # -- bit-identity sweep: every served output == the solo reference -----
    out = file_reader(data, "r")
    bit_identical = all(
        np.array_equal(np.asarray(out[key][...]), ref_seg)
        for key in outputs
    )
    # the failure-phase tail is judged against its *failover floor* — the
    # unavoidable cost a request pays when it spans the failure window
    # (warm service + one gateway restart, or warm service + one dead-
    # member detection window).  Bare 3x-warm would be vacuous here: warm
    # p99 is ~0.2s while a python process restart alone is ~1.5s, so the
    # meaningful bar is "the tail is EXPLAINED by the failover, with no
    # unaccounted stall on top".
    gk_floor = warm_stats["p99_s"] + (restart_s[0] or 60.0)
    mk_floor = (warm_stats["p99_s"] + member_stale_s
                + 3 * health_interval_s)
    gk_ratio = round(gk_stats["p99_s"] / max(gk_floor, 1e-9), 2)
    mk_ratio = round(mk_stats["p99_s"] / max(mk_floor, 1e-9), 2)
    rec = {
        "metric": "fleet_supervised_traffic",
        "backend": "cpu",
        "volume": list(shape),
        "block_shape": [block] * 3,
        "members": 3,
        "tenants": 2,
        "arrivals": {"process": "poisson", "mean_gap_s": mean_gap,
                     "seed": 42},
        "solo_batch_s": solo_batch_s,
        "warm": warm_stats,
        "gateway_kill_phase": {
            **gk_stats,
            "restart_latency_s": restart_s[0],
            "incarnation": gw_incarnation,
            "gateway_restarts": gw_restarts,
        },
        "gateway_kill_floor_s": round(gk_floor, 4),
        "gateway_kill_p99_over_floor": gk_ratio,
        "member_kill_phase": {
            **mk_stats,
            "victim": victim_m,
            "adopter": adopter_m,
            "replacement": repl,
            "fresh_dir": bool(fresh_dir),
            "replacement_served": repl_probe,
            "probes": probes,
        },
        "member_kill_floor_s": round(mk_floor, 4),
        "member_kill_p99_over_floor": mk_ratio,
        "acked": len(states),
        "lost_acked": lost,
        "affinity": {
            "hits": aff["hits"], "misses": aff["misses"],
            # first-touch pins (probe tenants) — excluded from hit_rate
            # since r16: counting them as misses was the r13→r15 "drop"
            "cold_pins": aff.get("cold_pins", 0),
            "hit_rate": round(hit_rate, 4),
        },
        "adoptions": adoptions,
        "incarnation": fleet_incarnation,
        "bit_identical": bool(bit_identical),
        "drain_rc": drain_rc,
        "acceptance": {
            "zero_lost_acked": not lost,
            "acked_ge_30": len(states) >= (15 if smoke else 30),
            "gateway_kill_p99_within_3x_floor": bool(gk_ratio <= 3.0),
            "member_kill_p99_within_3x_floor": bool(mk_ratio <= 3.0),
            "incarnation_bumped_exactly_once": bool(
                gw_incarnation == 2 and gw_restarts == 1
                and fleet_incarnation == 2),
            "adopted_and_respawned_fresh_dir": bool(
                adopter_m and repl and fresh_dir),
            "respawned_member_served": repl_probe is not None,
            "bit_identical": bool(bit_identical),
            "drain_rc_114": drain_rc == REQUEUE_EXIT_CODE,
        },
    }
    if os.environ.get("CT_BENCH_DEBUG"):
        log(f"DEBUG fleet log kept at {fleet_log}")
    else:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(rec), flush=True)
    if not smoke:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r15.json"
        )
        fu.atomic_write_json(path, rec)
        log(f"fleet bench done -> {path}")
    return rec


def main():
    log(f"start; env JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    probed = os.environ.get("CT_BENCH_ACCEL")
    if probed is not None:
        # the orchestrator already probed once; don't burn rung budget
        # re-discovering the same backend in every subprocess
        accel = None if probed == "none" else probed
        log(f"accelerator pre-probed by orchestrator: {accel}")
    elif os.environ.get("JAX_PLATFORMS") == "cpu":
        log("JAX_PLATFORMS=cpu pinned by caller; skipping accelerator probe")
        accel = None
    else:
        accel = _probe_accelerator(PROBE_TIMEOUT)
    # bench choice, ALL substrates: sparse seed-plateau labeling (exact
    # below ~6% maxima density — the bench volume measures ~1.4%; any
    # truncation lands in the JSON's overflow flag).  Drops the largest
    # single contributor to the fused step's remote-compile cost AND a
    # full tiled-CCL pass at runtime; the cpu smoke's device-shaped
    # sub-entry measures the same program that ships on the accelerator.
    # compile_table.py sets the same default so its persistent-cache
    # entries match this program.
    os.environ.setdefault("CT_SEED_CCL", "sparse")
    # fill machinery follows the library's substrate-aware auto default
    # (dense on cpu, capacity on tpu — see tile_ws); bench and the
    # compile probes resolve it identically by backend, so cache entries
    # stay consistent without a pin here
    if accel is None:
        from __graft_entry__ import _force_cpu_platform

        _force_cpu_platform(8)
    else:
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)

    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.ops.tile_ccl import label_components_tiled
    from cluster_tools_tpu.ops.tile_ws import dt_watershed_tiled
    from cluster_tools_tpu.parallel.mesh import make_mesh, mesh_axis_sizes
    from cluster_tools_tpu.parallel.pipeline import make_ws_ccl_step

    log("initializing backend")
    devices = []
    if accel is not None:
        devices = [d for d in jax.devices() if d.platform in ACCEL_PLATFORMS]
        if not devices:
            log("accelerator vanished between probe and init; using cpu")
    if devices:
        backend = devices[0].platform
    else:
        devices = jax.devices("cpu")
        backend = "cpu"
    log(f"backend={backend}, {len(devices)} device(s): {devices[0]!r}")

    mesh = make_mesh(len(devices), axis_names=("dp", "sp"), devices=devices)
    sizes = mesh_axis_sizes(mesh)
    dp, sp = sizes["dp"], sizes["sp"]

    threshold = 0.45
    on_accel = backend in ACCEL_PLATFORMS
    if on_accel:
        # BASELINE config 2 scale: 512-extent volume, halo=32.  The extent
        # is env-tunable for de-risked partial runs (a 256-extent on-chip
        # run compiles the same programs at smaller tile grids); the
        # recorded headline config remains the 512 default
        ext = int(os.environ.get("CT_BENCH_EXTENT", "512"))
        halo = 32
        batch, z, y, x = dp, sp * max(halo, ext // sp), ext, ext
    else:
        # smoke fallback only: the box has ~2 cores, so the virtual mesh is
        # ~serial — the extent balances non-toy shapes (r4 verdict weak #2)
        # against the driver's window; CT_BENCH_EXTENT_CPU de-risks reruns
        halo = 8
        ext = int(os.environ.get("CT_BENCH_EXTENT_CPU", "48"))
        batch, z, y, x = dp, sp * max(halo, ext), ext, 2 * ext
    log(f"mesh dp={dp} sp={sp}; volume ({batch},{z},{y},{x}), halo={halo}")

    # deterministic CREMI-like boundary map, synthesized ON DEVICE (see
    # module docstring: the tunnel cannot feed host arrays at benchmark rate)
    # 12 box passes per axis give ~20-voxel objects — the scale of CREMI
    # neurites at native resolution; the old 4 passes left ~5-voxel noise
    # plateaus, an adversarial regime no EM volume exhibits (the capacity
    # audit in docs/PERFORMANCE.md measured its basin-face load).  Recorded
    # in the JSON as synth_box_passes.
    synth_passes = int(os.environ.get("CT_BENCH_SYNTH_PASSES", "12"))

    @jax.jit
    def synth(key):
        v = jax.random.uniform(key, (batch, z, y, x), jnp.float32)
        for axis in range(1, 4):
            for _ in range(synth_passes):
                v = (v + jnp.roll(v, 1, axis) + jnp.roll(v, -1, axis)) / 3.0
        lo, hi = v.min(), v.max()
        return (v - lo) / jnp.maximum(hi - lo, 1e-6)

    t0 = time.perf_counter()
    vol = synth(jax.random.PRNGKey(0))
    _sync(vol)
    log(f"on-device synthetic volume ready in {time.perf_counter() - t0:.1f}s")

    min_seed_distance = 2.0  # reference configs suppress sub-voxel seed plateaus

    # soft deadline + shielding are needed from the first measured section:
    # every section must be skippable once the orchestrator's reserved tail
    # begins (see the secondary-section comment below)
    soft_deadline_at = float(
        os.environ.get("CT_BENCH_SOFT_DEADLINE_AT", "1e18")
    )

    def _shielded(name, fn, default=None):
        if time.time() > soft_deadline_at:
            log(f"{name} SKIPPED: past soft deadline; finishing the JSON")
            return default
        try:
            return fn()
        except Exception as e:  # pragma: no cover - hardware-dependent
            log(f"{name} FAILED: {type(e).__name__}: {str(e)[:200]}")
            return default

    rung_mode = bool(os.environ.get("CT_BENCH_SOFT_DEADLINE_AT"))
    base_vps = None
    # provenance of base_vps, carried into every emitted JSON record so a
    # nominal fallback can never masquerade as a measurement (advisor r4):
    # "measured" | "rung_cache" | "nominal_fallback"
    base_src = {"v": None}

    def _compute_baseline():
        # size-matched single-core scipy baseline.  A smaller crop reads
        # systematically faster per voxel (cache locality + EDT scaling),
        # which would understate vs_baseline; on the cpu smoke the volume is
        # small enough to match exactly, on the accelerator cap the scipy
        # run at 256^3 (512^3 would add minutes of wall-clock + ~1GB float64
        # EDT for a ~15% per-voxel drift)
        crop_n = 256 if on_accel else None
        # the orchestrator's rungs are separate processes benching the same
        # synthetic volume: the identical host-side number is cached across
        # them (keyed by backend+geometry) instead of re-paying the scipy
        # pipeline inside each rung's capped window
        cache_key = f"/tmp/ct_bench_base_{backend}_{z}x{y}x{x}_{os.getppid()}"
        try:
            with open(cache_key) as f:
                bv = float(f.read())
            log(f"host baseline from rung cache: {bv:,.0f} voxels/s")
            base_src["v"] = "rung_cache"
            return bv
        except (OSError, ValueError):
            pass
        crop = np.asarray(
            vol[0][:crop_n, :crop_n, :crop_n] if crop_n else vol[0]
        )
        log(f"running single-core scipy baseline on {crop.shape}")
        bv = _shielded(
            "host baseline", lambda: _host_baseline_vps(crop, threshold)
        )
        if bv is not None:
            try:
                with open(cache_key, "w") as f:
                    f.write(str(bv))
            except OSError:
                pass
        base_src["v"] = "measured"
        if bv is None:
            # the contract guarantees vs_baseline in the JSON: fall back to
            # the last recorded figure for this host class rather than
            # dividing by nothing; baseline_source in the record marks it
            bv = 3.39e6 if on_accel else 1.0e6
            base_src["v"] = "nominal_fallback"
            log(f"baseline fell back to nominal {bv:,.0f} voxels/s")
        log(f"baseline throughput: {bv:,.0f} voxels/s (single core)")
        return bv

    def _provisional(value_vps, path, extra=None):
        # a salvageable JSON line for orchestrator-rung mode only (the
        # orchestrator forwards exactly one line; direct runs must emit a
        # single line).  If the rung is later killed mid-compile, the
        # orchestrator salvages the LAST of these — each one printed here
        # supersedes the previous with strictly more evidence.
        if not rung_mode:
            return
        rec = {
            "metric": "fused watershed+CCL merged labels",
            "value": round(value_vps, 1),
            "unit": "voxels/sec",
            "vs_baseline": (
                round(value_vps / base_vps, 3) if base_vps else None
            ),
            "vs_32core": (
                round(value_vps / (32 * base_vps), 3) if base_vps else None
            ),
            "backend": backend,
            "impl": impl_env or "auto",
            "headline_path": path,
            "baseline_source": base_src["v"],
            "provisional": True,
        }
        rec.update(extra or {})
        print(json.dumps(rec), flush=True)

    # ---- on-accel pre-pass: configs 1 and 2 BEFORE the fused compile ----
    # The fused step is by far the biggest program in the bench (~6.3k HLO
    # lines vs ~1.4k for the tiled CCL); on the tunneled backend its remote
    # compile has exceeded every rung cap so far, and a killed rung used to
    # lose the whole run.  Measuring the two component programs first (and
    # printing a provisional line after each) banks on-chip evidence no
    # matter what the fused compile does.
    t_cc = t_ws = None
    configs_impl = None
    pre_state = {}
    impl_env = os.environ.get("CT_BENCH_IMPL")
    if on_accel and impl_env != "legacy":
        # the legacy rung is the guaranteed-completion last resort: it must
        # reach its (small, always-compiling) fused program without risking
        # a tiled-kernel wedge first, so it skips the pre-pass
        pre_impl = configs_impl = (
            "auto" if impl_env in (None, "split") else impl_env
        )

        def _config1_pre():
            # pre_impl is never "legacy" here (the legacy rung skips the
            # pre-pass), so this is always the tiled path
            fg3 = (vol < threshold)[0]
            cc1 = jax.jit(lambda m: label_components_tiled(m, impl=pre_impl))
            t_cc, (_, cc_ovf) = _timeit(
                "config 1: tiled CCL on binary mask", cc1, fg3
            )
            log(f"config 1 overflow={bool(cc_ovf)}")
            pre_state["cc_overflow"] = bool(cc_ovf)
            return t_cc

        t_cc = _shielded("config 1 (pre)", _config1_pre)
        if t_cc is not None:
            # configs 1/2 process ONE volume (vol[0]), not the dp batch
            _provisional(
                vol[0].size / t_cc, "provisional_ccl_only",
                {"config1_ccl_seconds": round(t_cc, 3)},
            )

        def _config2_pre():
            ws1 = jax.jit(
                lambda b: dt_watershed_tiled(
                    b, threshold=threshold, dt_max_distance=float(halo),
                    min_seed_distance=min_seed_distance, impl=pre_impl,
                )
            )
            t_ws, (ws_lab1, ws_ovf) = _timeit(
                "config 2: fused DT watershed", ws1, vol[0]
            )
            log(f"config 2 overflow={bool(ws_ovf)}")
            # keep the fragment labels: config 4 (RAG+multicut) runs on
            # them when the fused step never materializes its own
            pre_state["ws_labels"] = ws_lab1
            pre_state["ws_overflow"] = bool(ws_ovf)
            return t_ws

        # the split rung exists to avoid the dt_ws monolith (the program
        # that has wedged remote compiles): it goes straight to the staged
        # chain, whose stages are each strictly smaller than config 2
        t_ws = (
            None if impl_env == "split"
            else _shielded("config 2 (pre)", _config2_pre)
        )
        # host-side baseline before the fused compile (no chip involvement;
        # cached in /tmp so the auto/xla rung subprocesses pay it once):
        # every later provisional and the final JSON carry a real
        # vs_baseline even if the tunnel wedges from here on
        base_vps = _compute_baseline()
        if t_cc is not None and t_ws is not None:
            # ws + cc sequential on one chip is the fused step's compute
            # content minus the (single-shard-trivial) merge — an honest,
            # clearly-labeled stand-in until the fused number lands
            _provisional(
                vol[0].size / (t_ws + t_cc),
                "provisional_ws_plus_cc_sequential",
                {
                    "config1_ccl_seconds": round(t_cc, 3),
                    "config2_ws_seconds": round(t_ws, 3),
                },
            )
        elif t_cc is not None:
            _provisional(
                vol[0].size / t_cc, "provisional_ccl_only",
                {"config1_ccl_seconds": round(t_cc, 3)},
            )

    # ---- headline / config 3: fused watershed + merged-CC step ----
    # impl ladder: the Mosaic kernels are the fast path, but the headline
    # JSON must survive a compile/runtime failure on whatever hardware state
    # the driver finds — fall back to the portable tiled XLA kernels, then
    # to the round-2 legacy kernels, before giving up.  In orchestrated mode
    # (the default entry path) each impl runs in its own subprocess with a
    # wall-clock cap, because a wedged remote compile HANGS rather than
    # raising — an in-process ladder cannot recover from that.
    step = None
    split_stage_ms = None
    headline_impl = "none"
    if impl_env == "split":
        # staged chain: four per-stage programs with device-resident
        # intermediates (parallel/split_pipeline.py) — each strictly
        # smaller than the fused monolith whose remote compile has
        # exceeded every cap (r4).  Compiles run smallest-program-first
        # by construction of the chain order.
        from cluster_tools_tpu.parallel.split_pipeline import (
            make_ws_ccl_split,
        )

        split_step = make_ws_ccl_split(
            mesh, halo=halo, threshold=threshold,
            dt_max_distance=float(halo),
            min_seed_distance=min_seed_distance, impl="auto",
            stitch_ws_threshold=threshold,
        )

        def _timed_chain(v):
            # per-stage sync-by-fetch timing; the LAST run's stage splits
            # are recorded (stage sums track the chain total closely)
            marks = []

            def sync(name, *arrs):
                _sync(arrs)
                marks.append((name, time.perf_counter()))

            t0 = time.perf_counter()
            marks.append(("start", t0))
            out = split_step.run_staged(v, sync)
            _sync(out)
            nonlocal split_stage_ms
            split_stage_ms = {
                f"{name}_ms": round((t - prev) * 1000, 1)
                for (_, prev), (name, t) in zip(marks, marks[1:])
            }
            return out

        log("config 3 (headline): compiling staged split chain (4 programs)")
        step = _timed_chain
        headline_impl = "auto"
    else:
        for impl in ((impl_env,) if impl_env else ("auto", "xla", "legacy")):
            try:
                candidate = make_ws_ccl_step(
                    mesh, halo=halo, threshold=threshold,
                    dt_max_distance=float(halo),
                    min_seed_distance=min_seed_distance, impl=impl,
                    # config 3 is "to merged labels": fragments stitch across
                    # sp cuts by face consensus (free at sp=1 — no cuts exist)
                    stitch_ws_threshold=threshold,
                )
                log(
                    f"config 3 (headline): compiling fused ws+ccl step "
                    f"(impl={impl})"
                )
                out0 = candidate(vol)
                _sync(out0)
                step = candidate
                headline_impl = impl
                break
            except Exception as e:
                log(f"impl={impl} FAILED: {type(e).__name__}: {str(e)[:300]}")
    headline_path = (
        "split_programs_single_chip (staged device chain)"
        if impl_env == "split" else "device_fused_step"
    )
    if step is None and t_cc is not None and t_ws is not None:
        # every fused impl raised, but the pre-pass measured both component
        # programs: finish the run with the split headline (ws + cc
        # sequential, device-resident — the fused step's compute content
        # minus the single-shard-trivial merge) instead of dying and
        # leaving only a salvaged provisional.  Honestly labeled.
        log(
            "every fused-step impl failed; headline falls back to the "
            "split ws+cc programs"
        )
        t_fused = t_ws + t_cc
        vps = vol[0].size / t_fused
        headline_impl = configs_impl
        headline_path = "split_programs_single_chip (fused compile failed)"
        ws_lab = pre_state["ws_labels"][None]
        # the split measurement is only as reliable as BOTH its halves
        overflow = bool(pre_state.get("ws_overflow", False)) or bool(
            pre_state.get("cc_overflow", False)
        )
    elif step is None:
        raise RuntimeError("every fused-step impl failed; see stderr")
    else:
        # the fused step materializes its own labels: release the pre-pass
        # volume (~512MB HBM at bench scale) before the big program runs
        pre_state.pop("ws_labels", None)
        profile_dir = os.environ.get("CT_BENCH_PROFILE")
        if profile_dir:
            # SURVEY.md §5.1: per-kernel traces on demand — view with
            # tensorboard or xprof.  One profiled run after warmup.
            log(f"profiling one step into {profile_dir}")
            with jax.profiler.trace(profile_dir):
                out0 = step(vol)
                _sync(out0)
        t_fused, out = _timeit("fused ws+ccl step", step, vol)
        ws_lab, cc_lab, n_fg, overflow = out
        n_fg = int(n_fg)
        overflow = bool(overflow)
        vps = vol.size / t_fused
        log(
            f"fused: {vps:,.0f} voxels/s, n_fg={n_fg}, overflow={overflow}"
        )
    # provisional headline line NOW (supersedes the pre-pass provisionals):
    # if a later section wedges and the rung is killed, the orchestrator
    # salvages stdout and the last JSON line still carries the measurement
    # (the complete line replaces it later)
    _provisional(
        vps, headline_path,
        {"impl": headline_impl, "best_run_seconds": round(t_fused, 3)},
    )

    # secondary sections are individually shielded (_shielded above): a
    # fault in any of them (the tunnel has crashed mid-session before) must
    # not cost the headline JSON line, and they are skipped wholesale past
    # the soft deadline — the orchestrator sets it from ITS rung timer, so
    # child startup/import lag cannot erode the reserved tail.
    # secondary sections follow the impl the headline proved viable: if the
    # Mosaic path hung/failed and the ladder fell to xla/legacy, re-trying
    # Mosaic here would wedge the whole run
    sub_impl = "xla" if headline_impl in ("xla", "legacy") else "auto"
    if configs_impl is None:
        configs_impl = "legacy" if headline_impl == "legacy" else sub_impl

    # ---- configs 1/2: measured in the on-accel pre-pass above; on the cpu
    # smoke (no pre-pass) they run here, after the headline, with the impl
    # the headline proved viable ----
    if t_cc is None:

        def _config1():
            fg3 = (vol < threshold)[0]
            if headline_impl == "legacy":
                from cluster_tools_tpu.ops.ccl import label_components

                cc1 = jax.jit(lambda m: (label_components(m), False))
            else:
                cc1 = jax.jit(
                    lambda m: label_components_tiled(m, impl=sub_impl)
                )
            t_cc, (_, cc_ovf) = _timeit(
                "config 1: tiled CCL on binary mask", cc1, fg3
            )
            log(f"config 1 overflow={bool(cc_ovf)}")
            return t_cc

        t_cc = _shielded("config 1", _config1)

    # the split rung must NEVER compile the dt_ws monolith — avoiding its
    # cap-exceeding remote compile is the rung's entire purpose, and a
    # hang here (shielding catches exceptions, not wedges) would cost the
    # complete staged-chain JSON after the headline already landed.  Its
    # ws evidence is the per-stage split timings instead.
    if t_ws is None and impl_env != "split":

        def _config2():
            if headline_impl == "legacy":
                from cluster_tools_tpu.ops.watershed import (
                    distance_transform_watershed,
                )

                ws1 = jax.jit(
                    lambda b: (
                        distance_transform_watershed(
                            b, threshold=threshold,
                            min_seed_distance=min_seed_distance,
                            dt_max_distance=float(halo),
                        ),
                        False,
                    )
                )
            else:
                ws1 = jax.jit(
                    lambda b: dt_watershed_tiled(
                        b, threshold=threshold, dt_max_distance=float(halo),
                        min_seed_distance=min_seed_distance, impl=sub_impl,
                    )
                )
            t_ws, (_, ws_ovf) = _timeit(
                "config 2: fused DT watershed", ws1, vol[0]
            )
            log(f"config 2 overflow={bool(ws_ovf)}")
            return t_ws

        t_ws = _shielded("config 2", _config2)

    # ---- exact global EDT (capability the reference lacked blockwise) ----
    def _exact_edt():
        from cluster_tools_tpu.parallel.distributed_edt import (
            distributed_distance_transform,
        )

        fn = jax.jit(
            lambda v: distributed_distance_transform(v < threshold, mesh)
        )
        t_edt, _ = _timeit("exact global EDT (uncapped)", fn, vol[0], runs=2)
        return t_edt

    t_exact_edt = _shielded("exact EDT", _exact_edt)

    # ---- per-stage breakdown (VERDICT r2 #2) ----
    def _stages():
        from cluster_tools_tpu.ops.edt import distance_transform_squared
        from cluster_tools_tpu.ops.watershed import local_maxima

        stages = {}
        b0 = vol[0]
        fgm = jax.jit(lambda v: (v < threshold))
        stages["threshold"], fg_ = _timeit("stage threshold", fgm, b0, runs=2)
        edt = jax.jit(
            lambda m: distance_transform_squared(
                m, max_distance=float(halo), impl=sub_impl
            )
        )
        stages["edt"], dist_ = _timeit("stage edt", edt, fg_, runs=2)
        msd2 = min_seed_distance * min_seed_distance
        mx = jax.jit(lambda d, m: local_maxima(d, 1) & m & (d >= msd2))
        stages["maxima"], maxima_ = _timeit("stage maxima", mx, dist_, fg_, runs=2)
        # time the seed-labeling program the fused step ACTUALLY runs
        # (CT_SEED_CCL governs both, set above for every substrate)
        if os.environ.get("CT_SEED_CCL") == "sparse":
            from cluster_tools_tpu.ops.tile_ccl import label_components_sparse

            sccl = jax.jit(lambda m: label_components_sparse(m)[0])
        else:
            sccl = jax.jit(
                lambda m: label_components_tiled(m, impl=sub_impl)[0]
            )
        stages["seed_ccl"], _ = _timeit("stage seed CCL", sccl, maxima_, runs=2)
        return stages

    stages = _shielded("stages", _stages, default={}) or {}
    if t_ws is not None:
        stages["ws_total"] = t_ws
    if t_cc is not None:
        stages["cc_total"] = t_cc
    stages_ms = {k: round(v * 1000, 1) for k, v in stages.items()}
    if split_stage_ms:
        # per-program splits of the staged-chain headline (sync-by-fetch
        # between programs; from the LAST timed run)
        stages_ms.update({f"split_{k}": v for k, v in split_stage_ms.items()})
    log(f"stages: {stages_ms}")

    # ---- split-vs-fused A/B (r4 verdict #2): the staged chain timed on
    # the same substrate as the fused headline, so the on-chip decision
    # between the two execution modes is a recorded measurement ----
    def _split_ab():
        if impl_env == "split" or headline_impl == "legacy" or step is None:
            return None
        from cluster_tools_tpu.parallel.split_pipeline import (
            make_ws_ccl_split,
        )

        sstep = make_ws_ccl_split(
            mesh, halo=halo, threshold=threshold,
            dt_max_distance=float(halo),
            min_seed_distance=min_seed_distance, impl=sub_impl,
            stitch_ws_threshold=threshold,
        )
        marks = {}

        def sync(name, *arrs):
            _sync(arrs)
            marks[name] = time.perf_counter()

        def chain():
            marks.clear()
            marks["start"] = time.perf_counter()
            return sstep.run_staged(vol, sync)

        # _timeit protocol (warm-up pays the 4 stage compiles + best-of-2);
        # marks keep the LAST run's stage splits
        t_split, _ = _timeit("split chain", chain, runs=2)
        names = ["start", "seeds", "flow", "fill", "cc"]
        stage_ms = {
            f"{b}_ms": round((marks[b] - marks[a]) * 1000, 1)
            for a, b in zip(names, names[1:])
        }
        log(
            f"split chain: {t_split:.3f}s vs fused {t_fused:.3f}s "
            f"({t_split / t_fused:.2f}x); stages {stage_ms}"
        )
        return {
            "seconds": round(t_split, 3),
            "voxels_per_sec": round(vol.size / t_split, 1),
            "overhead_vs_fused": round(t_split / t_fused, 3),
            "stage_ms": stage_ms,
            "note": "4 per-stage programs, device-resident intermediates "
            "(parallel/split_pipeline.py); warm-run best-of-2",
        }

    split_ab = _shielded("split chain A/B", _split_ab)

    # ---- host baseline (computed in the on-accel pre-pass, here on cpu) --
    if base_vps is None:
        base_vps = _compute_baseline()

    # headline selection (VERDICT r3 weak #1): on the cpu smoke fallback the
    # device-shaped tiled/XLA step measures the substrate (a 1-core host
    # running an 8-way virtual mesh serially), not the design — its number
    # reads ~100x under the baseline and says nothing about TPU.  There the
    # headline becomes the host fallback pipeline the framework ships
    # (ops/host.py, the watershed task's impl="host" path), measured on the
    # full volume; the device-shaped number stays as configs.ws_ccl_fused.
    headline_vps = vps
    if not on_accel:
        from cluster_tools_tpu.ops.host import host_ws_ccl

        full = np.asarray(vol[0])

        def _host_headline():
            # identical protocol to every device measurement: _timeit's
            # untimed warm-up + best-of-3 (the native kernels put single
            # runs well under a second, so the extra runs cost little and
            # de-noise the recorded number on the shared 2-core box)
            best, _ = _timeit(
                "cpu headline (host pipeline)",
                lambda: host_ws_ccl(
                    full, threshold,
                    dt_max_distance=float(halo),
                    min_seed_distance=min_seed_distance,
                )[2],
                runs=3,
            )
            return full.size / best

        host_vps = _shielded(
            "cpu headline (shipped host pipeline, full volume)",
            _host_headline,
        )
        if host_vps is not None:
            headline_vps = host_vps
            headline_path = "host_fallback_pipeline (ops/host.py; cpu smoke)"
            log(f"cpu headline: host pipeline {host_vps:,.0f} voxels/s")

    # ---- config 4: RAG + multicut agglomeration on ws-fragment crops ----
    # ISSUE 1 rework: BENCH_r05's 1.655s at 32^3 timed ONE cold run of the
    # unfused path (device RAG -> host np.unique remap -> Python heap GAEC),
    # conflating jit compile with execution.  Now the fused program
    # (ops/rag.py::block_rag_fused: RAG -> probs_to_costs -> dense remap,
    # one jit) feeds the round-based parallel GAEC (ops/contraction.py);
    # cold (first call, compile included) and warm (best-of-3) are recorded
    # separately with extraction vs solve attributed, and the crop sweep
    # runs on cpu too (small sizes) so the device-vs-host crossover is
    # recorded on every backend (VERDICT r3 weak #4).
    def _config4():
        from cluster_tools_tpu.ops.contraction import gaec_parallel
        from cluster_tools_tpu.ops.rag import block_rag_fused

        def one(rag_n):
            seg_crop = np.asarray(ws_lab[0, :rag_n, :rag_n, :rag_n])
            bnd_crop = np.asarray(vol[0, :rag_n, :rag_n, :rag_n])

            def fused_once():
                t0 = time.perf_counter()
                nodes, edges, costs, _sizes, _mean = block_rag_fused(
                    seg_crop, bnd_crop
                )
                t_extract = time.perf_counter() - t0
                t0 = time.perf_counter()
                gaec_parallel(len(nodes), edges, costs)
                return t_extract, time.perf_counter() - t0, len(edges)

            cold_ex, cold_solve, n_edges = fused_once()
            warm = [fused_once() for _ in range(3)]
            warm_ex = min(w[0] for w in warm)
            warm_solve = min(w[1] for w in warm)
            t_host = _host_rag_gaec(seg_crop, bnd_crop)
            log(
                f"config 4: fused RAG+parallel GAEC on {seg_crop.shape}: "
                f"cold {cold_ex + cold_solve:.3f}s, "
                f"warm {warm_ex + warm_solve:.3f}s (extract {warm_ex:.3f}s "
                f"+ solve {warm_solve:.3f}s), host {t_host:.3f}s "
                f"({n_edges} edges)"
            )
            return {
                "crop": list(seg_crop.shape),
                "cold_seconds": round(cold_ex + cold_solve, 3),
                "warm_seconds": round(warm_ex + warm_solve, 3),
                "extract_warm_seconds": round(warm_ex, 3),
                "solve_warm_seconds": round(warm_solve, 3),
                "host_seconds": round(t_host, 3),
                "n_edges": int(n_edges),
            }

        sweep_sizes = (64, 128, 256) if on_accel else (16, 24, 32)
        sweep = [one(rag_n) for rag_n in sweep_sizes]
        out = dict(sweep[-1])
        out["crossover_sweep"] = sweep[:-1]
        # smallest crop where the warm device path matches the host — the
        # point below which blocks should take the host rung
        out["device_host_crossover_crop"] = next(
            (
                s["crop"][0]
                for s in sweep
                if s["warm_seconds"] <= s["host_seconds"]
            ),
            None,
        )
        out["solver_scale"] = _shielded(
            "config 4 solver scale", _solver_scale_bench
        )
        return out

    rag_result = _shielded("config 4", _config4)

    result = {
        "metric": "fused watershed+CCL merged labels",
        "value": round(headline_vps, 1),
        "unit": "voxels/sec",
        "vs_baseline": round(headline_vps / base_vps, 3),
        "vs_32core": round(headline_vps / (32 * base_vps), 3),
        "backend": backend,
        "impl": headline_impl,
        "headline_path": headline_path,
        "mesh": {"dp": dp, "sp": sp},
        "collectives_measured": dp * sp > 1,
        "volume": list(vol.shape),
        "synth_box_passes": synth_passes,
        "halo": halo,
        "overflow": overflow,
        "timing": "sync-by-scalar-fetch (block_until_ready does not block on axon)",
        "baseline": "single-core scipy pipeline (reference per-job compute path)",
        "baseline_voxels_per_sec": round(base_vps, 1),
        "baseline_source": base_src["v"],
        "best_run_seconds": round(t_fused, 3),
        "stages_ms": stages_ms,
        "configs": {
            # configs 1/2 provenance: the pre-pass measures them with its
            # own impl BEFORE the headline ladder resolves, which can
            # differ from the headline's impl on a direct (non-rung) run
            "configs_impl": configs_impl,
            "cc_binary_512": None if t_cc is None else {
                "seconds": round(t_cc, 3),
                "voxels_per_sec": round(vol[0].size / t_cc, 1),
            },
            "dt_watershed_halo": None if t_ws is None else {
                "seconds": round(t_ws, 3),
                "voxels_per_sec": round(vol[0].size / t_ws, 1),
            },
            "ws_ccl_fused": {
                "seconds": round(t_fused, 3),
                "voxels_per_sec": round(vps, 1),
                **(
                    {"note": "staged 4-program chain, device-resident "
                     "intermediates (the fused monolith was not attempted "
                     "in this rung)"}
                    if "staged device chain" in headline_path
                    else {"note": "split ws+cc sequential sum — the fused "
                          "program itself never compiled (see headline_path)"}
                    if headline_path.startswith("split_programs") else {}
                ),
            },
            "split_chain": split_ab,
            "rag_multicut_crop": rag_result,
            "exact_edt_global": None if t_exact_edt is None else {
                "seconds": round(t_exact_edt, 3),
                "voxels_per_sec": round(vol[0].size / t_exact_edt, 1),
                "note": "uncapped exact global EDT — not computable "
                "blockwise in the reference at all",
            },
            "teravoxel_multihost": {
                "status": "not benchable on this rig (single chip); the "
                "capability is exercised by dryrun_multichip's 2-axis "
                "decomposition with int32-safe compaction and the "
                "multi-process DCN pod test (tests/test_multihost.py)",
            },
        },
    }
    print(json.dumps(result), flush=True)
    log("done")


def orchestrate() -> None:
    """Run the impl ladder as wall-clock-capped subprocesses.

    A wedged remote compile on the tunneled backend HANGS the process instead
    of raising (observed: >20min inside one Mosaic compile at 512^3), so the
    in-process try/except ladder cannot recover from it.  Each rung runs the
    full bench with ``CT_BENCH_IMPL`` pinned; the first rung to emit a JSON
    line wins.  Budgeted so the final (legacy) rung — which has always
    completed in under ~2 minutes — is never starved.
    """
    budget = float(os.environ.get("CT_BENCH_BUDGET", "1350"))
    deadline = _T0 + budget
    # per-rung caps are env-tunable so a manual run can grant the Mosaic
    # compile a longer window (e.g. to populate the persistent cache once)
    # without changing the driver-facing defaults
    rungs = (
        ("auto", float(os.environ.get("CT_BENCH_CAP_AUTO", "600"))),
        # staged chain: four programs, each strictly smaller than the fused
        # monolith — the structural answer to the r4 finding that the
        # monolith's remote compile exceeds every cap for BOTH kernel
        # families while its components compile fine
        ("split", float(os.environ.get("CT_BENCH_CAP_SPLIT", "600"))),
        ("xla", float(os.environ.get("CT_BENCH_CAP_XLA", "480"))),
        ("legacy", float("inf")),
    )
    log(f"orchestrator: subprocess impl ladder, budget {budget:.0f}s")
    # probe ONCE here; rungs inherit the verdict instead of spending up to
    # PROBE_TIMEOUT each re-probing the same backend
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        accel = None
    else:
        accel = _probe_accelerator(min(PROBE_TIMEOUT, max(60.0, budget / 5)))
    os.environ["CT_BENCH_ACCEL"] = accel or "none"
    if accel is None:
        # no tunnel, no hang risk: run in-process, uncapped (the subprocess
        # ladder exists to bound wedged remote compiles, not CPU work)
        # CT_BENCH_IMPL stays unset so main() keeps the full
        # ("auto", "xla", "legacy") fallback ladder — on cpu a failure
        # raises instead of hanging, so the in-process ladder is safe
        log("orchestrator: no accelerator; running in-process on cpu")
        main()
        return
    best_partial = None
    for i, (impl, cap) in enumerate(rungs):
        remaining = deadline - time.monotonic()
        reserve = 240.0 * (len(rungs) - 1 - i)  # keep room for later rungs
        tmo = min(cap, remaining - reserve)
        if tmo < 60:
            log(f"orchestrator: skip impl={impl}, no budget ({remaining:.0f}s left)")
            continue
        log(f"orchestrator: impl={impl}, cap {tmo:.0f}s")
        # reserve a tail of the rung for the baseline + JSON emit; relative
        # to the HARD cap so the protection cannot collapse at small caps
        reserve = min(120.0, max(45.0, tmo * 0.25))
        env = dict(
            os.environ,
            CT_BENCH_IMPL=impl,
            CT_BENCH_SOFT_DEADLINE_AT=str(time.time() + tmo - reserve),
        )
        # child stdout goes to a FILE, not a pipe: a killed rung's partial
        # output (the provisional headline JSON) is salvageable
        out_path = f"/tmp/ct_bench_rung_{impl}_{os.getpid()}.out"
        with open(out_path, "w") as out_f:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                stdout=out_f,
                env=env,
                start_new_session=True,
            )
            timed_out = False
            try:
                proc.wait(timeout=tmo)
            except subprocess.TimeoutExpired:
                timed_out = True
                log(f"orchestrator: impl={impl} exceeded {tmo:.0f}s; killing rung")
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait()
        try:
            with open(out_path) as f:
                stdout = f.read()
        except OSError:
            stdout = ""
        json_lines = [
            ln for ln in stdout.splitlines() if ln.startswith("{")
        ]
        if proc.returncode == 0 and json_lines:
            try:
                done_path = json.loads(json_lines[-1]).get(
                    "headline_path", ""
                )
            except ValueError:
                done_path = ""
            if not str(done_path).startswith("split_programs"):
                line = json_lines[-1]
                # a complete split record from a FASTER impl beats a true
                # fused number from the legacy kernels (the split is the
                # shipped fast path minus a single-shard-trivial merge,
                # honestly labeled; legacy is ~50x off the tiled kernels)
                if impl == "legacy" and best_partial is not None:
                    try:
                        bp = json.loads(best_partial)
                        this = json.loads(line)
                        if str(bp.get("headline_path", "")).startswith(
                            "split_programs"
                        ) and (bp.get("value") or 0) > (
                            this.get("value") or 0
                        ):
                            log(
                                "orchestrator: emitting the faster split "
                                "record over the legacy fused number"
                            )
                            line = best_partial
                    except ValueError:
                        pass
                print(line, flush=True)
                log(f"orchestrator: impl={impl} succeeded")
                return
            # the rung completed but its fused compile FAILED (split
            # fallback headline): keep the complete record as the fallback
            # and let the remaining impls try for a real fused number
            log(
                f"orchestrator: impl={impl} completed with a split "
                "fallback headline; trying the next rung for a fused one"
            )
        if json_lines:
            line = json_lines[-1]
            try:
                path = json.loads(line).get("headline_path", "")
            except ValueError:
                path = ""
            if path == "device_fused_step":
                # rung died/was killed after the fused measurement landed:
                # a real fused number beats falling through to a slower rung
                print(line, flush=True)
                log(
                    f"orchestrator: impl={impl} salvaged a fused provisional "
                    f"(rc={proc.returncode}, timed_out={timed_out})"
                )
                return
            # component-only provisional (configs 1/2 measured, fused not):
            # keep the most-complete one (ws+cc carries strictly more
            # evidence than ccl-only; the two kinds' values are not
            # comparable since ccl-only omits t_ws), value-tiebreak within
            # a kind; remaining rungs still try for a complete fused line
            _rank = {
                # a measured staged chain beats the ws+cc arithmetic sum
                "split_programs_single_chip (staged device chain)": 4,
                "split_programs_single_chip (fused compile failed)": 3,
                "provisional_ws_plus_cc_sequential": 2,
                "provisional_ccl_only": 1,
            }

            def _key(ln):
                try:
                    d = json.loads(ln)
                except ValueError:
                    return (0, 0.0)
                return (
                    _rank.get(d.get("headline_path"), 0),
                    d.get("value") or 0.0,
                )

            if best_partial is None or _key(line) > _key(best_partial):
                best_partial = line
            log(
                f"orchestrator: impl={impl} left a component-only "
                f"provisional (rc={proc.returncode}, timed_out={timed_out}); "
                "trying the next rung"
            )
            continue
        log(f"orchestrator: impl={impl} failed (rc={proc.returncode})")
    if best_partial is not None:
        print(best_partial, flush=True)
        log("orchestrator: no rung finished a fused step; emitting the best "
            "component-only provisional")
        return
    raise RuntimeError("orchestrator: every impl rung failed; see stderr")


if __name__ == "__main__":
    # drain safety (docs/ANALYSIS.md CT006): a scheduler SIGTERM mid-bench
    # must exit with the requeue code, not a crash traceback — the bench
    # drives real task DAGs whose markers/manifests the drain protocol
    # flushes before DrainInterrupt reaches this frame
    from cluster_tools_tpu.runtime.supervision import (
        REQUEUE_EXIT_CODE,
        DrainInterrupt,
    )

    try:
        if "--io" in sys.argv or os.environ.get("CT_BENCH_IO"):
            io_bench()
        elif "--sweep" in sys.argv or os.environ.get("CT_BENCH_SWEEP"):
            sweep_bench()
        elif "--ragged" in sys.argv or os.environ.get("CT_BENCH_RAGGED"):
            ragged_bench(smoke="--smoke" in sys.argv)
        elif "--device-plane" in sys.argv \
                or os.environ.get("CT_BENCH_DEVICE_PLANE"):
            device_plane_bench(smoke="--smoke" in sys.argv)
        elif "--fuse" in sys.argv or os.environ.get("CT_BENCH_FUSE"):
            fuse_bench()
        elif "--solve" in sys.argv or os.environ.get("CT_BENCH_SOLVE"):
            solve_bench()
        elif "--reduce-plane" in sys.argv \
                or os.environ.get("CT_BENCH_REDUCE"):
            reduce_plane_bench(smoke="--smoke" in sys.argv)
        elif "--serve" in sys.argv or os.environ.get("CT_BENCH_SERVE"):
            serve_bench(smoke="--smoke" in sys.argv)
        elif "--fleet" in sys.argv or os.environ.get("CT_BENCH_FLEET"):
            fleet_bench(smoke="--smoke" in sys.argv)
        elif os.environ.get("CT_BENCH_IMPL"):
            main()
        else:
            orchestrate()
    except DrainInterrupt as e:
        print(f"bench: DRAINED ({e.reason}); exiting {REQUEUE_EXIT_CODE}",
              file=sys.stderr)
        sys.exit(REQUEUE_EXIT_CODE)
