"""North-star benchmark: fused blockwise watershed+CCL to globally merged labels.

Mirrors BASELINE.json's metric ("voxels/sec on CREMI blockwise watershed+CCL;
wall-clock to merged labels").  The whole pipeline — halo exchange, fused
DT-watershed per slab, two-pass union-find CC merge — runs as ONE compiled
SPMD program over the device mesh (see cluster_tools_tpu/parallel/pipeline.py).

Hardened for the driver session (round-1 postmortem: rc=124 with no output):

- The accelerator backend is probed in a SUBPROCESS with a timeout.  The
  session's ``axon`` PJRT plugin dials a TPU tunnel on first backend init,
  which can hang for many minutes when the tunnel is down; a hung probe must
  not take the whole benchmark with it.  On probe timeout/failure the bench
  forces ``JAX_PLATFORMS=cpu`` and still emits its JSON line.
- Every stage prints a timestamped progress line to STDERR (stdout carries
  exactly one JSON line), so a driver-side timeout leaves a diagnosable tail.
- Volume sizes adapt to the backend: BASELINE.md-scale (512-extent,
  halo>=16) on an accelerator, reduced sizes on the CPU fallback.

The reference publishes no numbers (BASELINE.json "published": {}), so
``vs_baseline`` measures against the equivalent single-core host (scipy)
pipeline run in-process on the same data — the reference's per-job compute
path without scheduler overhead, i.e. one worker of its 32-node baseline.
``vs_32core`` divides by 32 as the whole-cluster stand-in.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

_T0 = time.monotonic()
PROBE_TIMEOUT = float(os.environ.get("CT_BENCH_PROBE_TIMEOUT", "240"))
ACCEL_PLATFORMS = ("tpu", "axon")  # platforms treated as the bench target


def log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:.1f}s] {msg}", file=sys.stderr, flush=True)


def _probe_accelerator(timeout: float) -> str | None:
    """Return the accelerator platform name, or None — probed in a subprocess.

    The subprocess inherits the session env (so the axon plugin registers
    exactly as it would in-process) and reports the first non-cpu platform it
    sees.  A timeout/crash means "accelerator unusable": the parent then pins
    itself to CPU *before* its own first backend init, never touching the
    tunnel.
    """
    code = (
        "import jax\n"
        "plats = sorted({d.platform for d in jax.devices()})\n"
        "print('PROBE_RESULT:' + ','.join(plats), flush=True)\n"
    )
    log(f"probing accelerator backend in subprocess (timeout {timeout:.0f}s)")
    # own session + process-group kill: the PJRT plugin may spawn tunnel
    # helpers that inherit the pipes and would keep communicate() blocked
    # forever after a plain subprocess.run timeout kill
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        log("probe TIMED OUT — accelerator tunnel unresponsive, falling back to cpu")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        return None
    for line in stdout.splitlines():
        if line.startswith("PROBE_RESULT:"):
            plats = line.split(":", 1)[1].split(",")
            accel = [p for p in plats if p in ACCEL_PLATFORMS]
            log(f"probe saw platforms {plats}; accelerator: {accel or None}")
            return accel[0] if accel else None
    log(
        "probe produced no result "
        f"(rc={proc.returncode}, stderr tail: {stderr.strip()[-300:]!r})"
    )
    return None


def _host_baseline_vps(vol: np.ndarray, threshold: float) -> float:
    """voxels/sec of the equivalent scipy pipeline (single core, in-process)."""
    from scipy import ndimage

    t0 = time.perf_counter()
    fg = vol < threshold
    dist = ndimage.distance_transform_edt(fg)
    maxima = (ndimage.maximum_filter(dist, size=3) == dist) & fg
    seeds, _ = ndimage.label(maxima)
    hmap = np.clip(vol * 255, 0, 255).astype(np.uint8)
    ndimage.watershed_ift(hmap, seeds.astype(np.int32))
    ndimage.label(fg)  # the CC pass
    dt = time.perf_counter() - t0
    return vol.size / dt


def main():
    log(f"start; env JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        log("JAX_PLATFORMS=cpu pinned by caller; skipping accelerator probe")
        accel = None
    else:
        accel = _probe_accelerator(PROBE_TIMEOUT)
    if accel is None:
        # pin to CPU before the first in-process backend init (env + config,
        # beating the sitecustomize's own jax.config.update)
        from __graft_entry__ import _force_cpu_platform

        _force_cpu_platform(8)

    import jax

    from __graft_entry__ import _synthetic_boundaries
    from cluster_tools_tpu.parallel.mesh import make_mesh, mesh_axis_sizes
    from cluster_tools_tpu.parallel.pipeline import make_ws_ccl_step

    log("initializing backend")
    devices = []
    if accel is not None:
        devices = [d for d in jax.devices() if d.platform in ACCEL_PLATFORMS]
        if not devices:
            log("accelerator vanished between probe and init; using cpu")
    if devices:
        backend = devices[0].platform
    else:
        devices = jax.devices("cpu")
        backend = "cpu"
    log(f"backend={backend}, {len(devices)} device(s): {devices[0]!r}")

    mesh = make_mesh(len(devices), axis_names=("dp", "sp"), devices=devices)
    sizes = mesh_axis_sizes(mesh)
    dp, sp = sizes["dp"], sizes["sp"]

    threshold = 0.45
    if backend in ACCEL_PLATFORMS:
        # BASELINE.md scale: 512-extent volume, halo >= 16 (config 2);
        # each sp shard's z-slab must stay >= halo for the exchange
        halo = 16
        batch, z, y, x = dp, sp * max(halo, 512 // sp), 512, 512
    else:
        halo = 8
        batch, z, y, x = dp, sp * max(halo, 32), 128, 128
    log(f"mesh dp={dp} sp={sp}; volume ({batch},{z},{y},{x}), halo={halo}")
    vol = _synthetic_boundaries((batch, z, y, x))
    log("synthetic volume ready")

    # EDT capped at the halo scale: beyond it, distances are halo-clipped
    # anyway, and the cascade cost is linear in the cap
    step = make_ws_ccl_step(
        mesh, halo=halo, threshold=threshold, dt_max_distance=float(halo)
    )
    log("compiling + warming up fused ws+ccl step")
    t0 = time.perf_counter()
    jax.block_until_ready(step(vol))
    log(f"compile+warmup done in {time.perf_counter() - t0:.1f}s")

    times = []
    for i in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(step(vol))
        times.append(time.perf_counter() - t0)
        log(f"timed run {i + 1}/3: {times[-1]:.3f}s")
    vps = vol.size / min(times)
    log(f"device throughput: {vps:,.0f} voxels/s")

    # host baseline on a crop, extrapolated per-voxel
    crop_z, crop_yx = min(128, z), min(128, y)
    crop = vol[0, :crop_z, :crop_yx, :crop_yx]
    log(f"running single-core scipy baseline on crop {crop.shape}")
    base_vps = _host_baseline_vps(np.asarray(crop), threshold)
    log(f"baseline throughput: {base_vps:,.0f} voxels/s (single core)")

    print(
        json.dumps(
            {
                "metric": "fused watershed+CCL merged labels",
                "value": round(vps, 1),
                "unit": "voxels/sec",
                "vs_baseline": round(vps / base_vps, 3),
                "vs_32core": round(vps / (32 * base_vps), 3),
                "backend": backend,
                "mesh": {"dp": dp, "sp": sp},
                "volume": list(vol.shape),
                "halo": halo,
                "baseline": "single-core scipy pipeline (reference per-job compute path)",
                "baseline_voxels_per_sec": round(base_vps, 1),
                "best_run_seconds": round(min(times), 3),
            }
        ),
        flush=True,
    )
    log("done")


if __name__ == "__main__":
    main()
