"""cluster_tools_tpu: a TPU-native framework for distributed blockwise
processing of very large 3-D volumetric images.

A ground-up re-design of the capabilities of ``cluster_tools`` (the
luigi/slurm-based blockwise segmentation framework; see SURVEY.md) for TPU
hardware: per-block compute kernels are JAX/Pallas functions batched over a
``jax.sharding.Mesh``; halo exchange and the two-pass label union-find merge
run as ICI collectives (``shard_map`` + ``ppermute``/``all_gather``); chunked
N5/zarr IO streams from host into HBM via tensorstore.

Layer map (bottom-up, mirroring SURVEY.md §1 but TPU-first):

- L0' ``ops/``       device kernels: CCL, EDT, watershed, union-find, segment ops
- L1' ``io/`` +
       ``utils/``    tensorstore/h5py volume IO, block-grid math, halo/bb math
- L2' ``runtime/``   task DAG + execution targets (local CPU mesh / TPU mesh),
                     idempotent success-manifest resume (replaces luigi+slurm)
- L3' ``tasks/``     the op/task library (connected_components, watershed,
                     graph, features, multicut, ...)
- L4' ``workflows``  end-to-end segmentation workflow compositions
- ``parallel/``      mesh construction, spatial sharding, halo exchange
- ``models/``        flax models for the inference task (boundary/affinity CNNs)
"""

__version__ = "0.1.0"
