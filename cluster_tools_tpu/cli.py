"""Thin command-line entry point (L5 of SURVEY.md §1).

The reference's "CLI" was a user driver script calling ``luigi.build`` with
a workflow + config_dir (SURVEY.md §1 L5).  The rebuild ships the same shape
as a real entry point:

    python -m cluster_tools_tpu.cli run <workflow> --config config.json
    python -m cluster_tools_tpu.cli configs <workflow> --out config_dir/
    python -m cluster_tools_tpu.cli report <tmp_folder>

``run`` reads ONE json with {tmp_folder, config_dir, max_jobs, target,
params: {...}} and builds the named workflow; ``configs`` materializes a
workflow's default task configs into a config_dir for editing (the
reference's ``get_config`` pattern); ``report`` prints the runtime table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


WORKFLOWS = {
    # name -> "module:Class"
    "connected_components": "cluster_tools_tpu.tasks.connected_components:ConnectedComponentsWorkflow",
    "thresholded_components": "cluster_tools_tpu.tasks.thresholded_components:ThresholdedComponentsWorkflow",
    "watershed": "cluster_tools_tpu.tasks.watershed:WatershedWorkflow",
    "fused_segmentation": "cluster_tools_tpu.tasks.fused:FusedSegmentationWorkflow",
    "multicut": "cluster_tools_tpu.workflows:MulticutSegmentationWorkflow",
    "lifted_multicut": "cluster_tools_tpu.workflows:LiftedMulticutSegmentationWorkflow",
    "agglomerative_clustering": "cluster_tools_tpu.workflows:AgglomerativeClusteringWorkflow",
    "mutex_watershed": "cluster_tools_tpu.tasks.mutex_watershed:MwsWorkflow",
    "stitching": "cluster_tools_tpu.tasks.stitching:StitchingWorkflow",
    "relabel": "cluster_tools_tpu.tasks.relabel:RelabelWorkflow",
    "size_filter": "cluster_tools_tpu.tasks.postprocess:SizeFilterWorkflow",
    "graph_ws_size_filter": "cluster_tools_tpu.tasks.postprocess:GraphWatershedSizeFilterWorkflow",
    "fill_holes": "cluster_tools_tpu.tasks.postprocess:FillHolesWorkflow",
    "cc_on_segmentation": "cluster_tools_tpu.tasks.postprocess:ConnectedComponentsOnSegmentationWorkflow",
    "downscaling": "cluster_tools_tpu.tasks.downscaling:DownscalingWorkflow",
    "copy_volume": "cluster_tools_tpu.tasks.copy_volume:CopyVolumeWorkflow",
    "inference": "cluster_tools_tpu.tasks.inference:InferenceWorkflow",
    "ilastik_prediction": "cluster_tools_tpu.tasks.ilastik:IlastikPredictionWorkflow",
    "morphology": "cluster_tools_tpu.tasks.morphology:MorphologyWorkflow",
    "node_labels": "cluster_tools_tpu.tasks.node_labels:NodeLabelWorkflow",
    "evaluation": "cluster_tools_tpu.tasks.evaluation:EvaluationWorkflow",
    "skeletons": "cluster_tools_tpu.tasks.skeletons:SkeletonWorkflow",
    "meshes": "cluster_tools_tpu.tasks.meshes:MeshWorkflow",
    "transformations": "cluster_tools_tpu.tasks.transformations:TransformationsWorkflow",
    "distances": "cluster_tools_tpu.tasks.distances:PairwiseDistanceWorkflow",
    "statistics": "cluster_tools_tpu.tasks.statistics:DataStatisticsWorkflow",
    "paintera_conversion": "cluster_tools_tpu.tasks.paintera:PainteraConversionWorkflow",
    "paintera_to_bdv": "cluster_tools_tpu.tasks.paintera:PainteraToBdvWorkflow",
}


def _resolve(name: str):
    import importlib

    try:
        spec = WORKFLOWS[name]
    except KeyError:
        raise SystemExit(
            f"unknown workflow {name!r}; available:\n  "
            + "\n  ".join(sorted(WORKFLOWS))
        )
    mod_name, cls_name = spec.split(":")
    return getattr(importlib.import_module(mod_name), cls_name)


def cmd_run(args) -> int:
    from .runtime.supervision import REQUEUE_EXIT_CODE, DrainInterrupt
    from .runtime.task import build

    with open(args.config) as f:
        cfg = json.load(f)
    if cfg.get("target", "local") != "tpu":
        # non-tpu targets must never initialize the accelerator backend:
        # platform-pinning sitecustomize hooks (jax_platforms="axon,cpu")
        # make the first jax.devices() call block on an unreachable chip
        # even for pure-host work, and the env var alone cannot override
        # them (see bench.py / tests/conftest.py for the same pattern)
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    cls = _resolve(args.workflow)
    wf = cls(
        tmp_folder=cfg["tmp_folder"],
        config_dir=cfg.get("config_dir", cfg["tmp_folder"]),
        max_jobs=int(cfg.get("max_jobs", 4)),
        target=cfg.get("target", "local"),
        **cfg.get("params", {}),
    )
    try:
        ok = build([wf], rerun=args.rerun)
    except DrainInterrupt as e:
        # graceful preemption (CT006): markers/manifests are flushed —
        # exit with the requeue code so the scheduler resubmits us, and
        # the resumed run picks up at block grain behind the markers
        print(f"DRAINED ({e.reason}); exiting {REQUEUE_EXIT_CODE} for requeue")
        return REQUEUE_EXIT_CODE
    print("SUCCESS" if ok else "FAILED (see logs in tmp_folder)")
    return 0 if ok else 1


def cmd_configs(args) -> int:
    import importlib
    import inspect

    from .runtime.task import BaseTask, WorkflowBase

    cls = _resolve(args.workflow)
    os.makedirs(args.out, exist_ok=True)
    get_config = getattr(cls, "get_config", None)
    if get_config is not None and get_config is not BaseTask.get_config:
        # workflow defines its own aggregator (workflows.py pattern); let
        # real failures inside it propagate rather than silently falling
        # back to an incomplete module scan
        configs = get_config()
    else:
        # task-module workflow: aggregate the defaults of every task family
        # defined in the workflow's module (the reference pattern: one
        # `<task_name>.config` per task).  ``task_name in vars(obj)``
        # excludes abstract helpers that merely inherit BaseTask's name.
        configs = {"global": BaseTask.default_global_config()}
        mod = importlib.import_module(cls.__module__)
        for obj in vars(mod).values():
            if (
                inspect.isclass(obj)
                and issubclass(obj, BaseTask)
                and not issubclass(obj, WorkflowBase)
                and obj.__name__.endswith("Base")
                and "task_name" in vars(obj)
            ):
                configs[obj.task_name] = obj.default_task_config()
    from .utils.task_utils import dump_config

    for name, cfg in configs.items():
        path = os.path.join(
            args.out, "global.config" if name == "global" else f"{name}.config"
        )
        dump_config(path, cfg)
        print("wrote", path)
    return 0


def cmd_report(args) -> int:
    from .utils.parse_utils import report

    print(report(args.tmp_folder, n_voxels=args.n_voxels))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cluster_tools_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("run", help="run a workflow from a json config")
    pr.add_argument("workflow", help="workflow name (see `configs --list`)")
    pr.add_argument("--config", required=True, help="run config json")
    pr.add_argument("--rerun", action="store_true", help="ignore success targets")
    pr.set_defaults(fn=cmd_run)

    pc = sub.add_parser("configs", help="materialize default task configs")
    pc.add_argument("workflow")
    pc.add_argument("--out", required=True, help="config_dir to write into")
    pc.set_defaults(fn=cmd_configs)

    pp = sub.add_parser("report", help="runtime report for a tmp_folder")
    pp.add_argument("tmp_folder")
    pp.add_argument("--n-voxels", type=int, default=None)
    pp.set_defaults(fn=cmd_report)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
