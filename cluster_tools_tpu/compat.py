"""jax version-compatibility shims.

One import site for API surface that moved between jax releases, so the
rest of the package (and the tests) can write against the modern spelling
without mutating the global ``jax`` namespace.

``typeof``: modern jax's ``jax.typeof`` (the aval of a value, carrying
``vma`` under shard_map); older jax spells it ``jax.core.get_aval`` (no
``vma`` attribute — callers already treat it as optional).

``shard_map``: modern jax exposes it as ``jax.shard_map`` with a
``check_vma=`` keyword; older jax only has
``jax.experimental.shard_map.shard_map`` with ``check_rep=``.  On old jax
the replication checker also has no rule for ``lax.while_loop`` (every
kernel here carries one) — its check is advisory, so it defaults off
there rather than rejecting programs the modern checker accepts.
"""

from __future__ import annotations

import jax

if hasattr(jax, "typeof"):
    typeof = jax.typeof
else:  # pragma: no cover - exercised only on old jax
    from jax.core import get_aval as typeof

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised only on old jax
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        kwargs.setdefault("check_rep", False)
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
