"""Fleet-mode CLI entry: a supervised serving fleet — one supervisor
process owning a gateway subprocess and M pipeline-server subprocesses.

Usage (docs/SERVING.md "Fleet" / "Supervision")::

    python -m cluster_tools_tpu.fleet --base-dir /srv/fleet \\
        [--members 2] [--port 0] [--config fleet.json] [--tpu]
    python -m cluster_tools_tpu.fleet --status /srv/fleet
    python -m cluster_tools_tpu.fleet --drain /srv/fleet [--member m0]

The supervisor (this process) closes the serving fleet's last
single-point-of-failure loops:

* **Crash-only gateway** — the gateway runs as its own subprocess (the
  hidden ``--gateway-child`` mode) watched with the same heartbeat/pid
  machinery members get.  A dead or wedged gateway is SIGKILLed and
  restarted under a crash-loop budget; the restarted incarnation rebuilds
  routes/affinity/adoption state cold from member truth on disk
  (``FleetGateway._rebuild_from_disk``), re-binds the same port, and
  bumps the incarnation counter in ``fleet_state.json``.  Clients riding
  ``submit(retry_s=...)`` / ``wait(across_restarts=True)`` never observe
  a lost acknowledged request across the restart.

* **Closed-loop member lifecycle** — the reaper's decision table
  (:func:`classify_member_exit`, unit-tested): rc 114 = drained
  (expected, retire), rc 115 = fenced (the journal was adopted by a
  survivor; the old dir IS the adoption record, so capacity respawns on
  a *fresh* base dir), anything else = crash (exponential-backoff
  respawn on the same dir under the adoption-claim protocol — the
  supervisor never fights an in-flight adoption, and a member that got
  adopted while backing off comes back on a fresh dir instead).  A
  lineage over the respawn budget is quarantined
  (``quarantined:member_crash_loop``).

* **Backlog-driven scaling** — sustained queue/breaker pressure grows
  the fleet up to ``max_members``; sustained idleness drains the
  emptiest member down to ``min_members``.  Every decision is HELD while
  any adoption, drain, respawn, or boot is in flight.

Every respawn/restart/scale decision is one typed record in the
supervisor's lifecycle ledger (``lifecycle.log``, the journal's CRC
framing) AND one trace instant (ctlint CT014), and is rendered by
``scripts/progress.py`` from ``supervisor_state.json``.

``--config`` names a JSON document: ``{"members": N, "gateway": {...},
"server": {...}, "supervisor": {poll_s, gateway_stale_s,
gateway_max_restarts, member_max_respawns, respawn_backoff_s,
respawn_backoff_max_s, min_members, max_members, scale_up_backlog,
scale_sustain_s, scale_idle_s}}``.

SIGTERM drains the whole fleet through the standard protocol: gateway
child and every member exit ``REQUEUE_EXIT_CODE`` (114) and so does this
process, so rolling restarts ride the same requeue protocol as every
other preempted job.  ``--status`` prints the gateway's ``/status``
document and exits with its ``rc``.  ``--drain`` SIGTERMs the emptiest
member (scale-down).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from .runtime import journal as journal_mod
from .runtime import netio
from .runtime import trace as trace_mod
from .runtime.fleet import (
    FLEET_STATE_FILENAME,
    GATEWAY_UID,
    FleetGateway,
    acquire_adoption_claim,
    read_adoption_claim,
    release_adoption_claim,
)
from .runtime.server import ENDPOINT_FILENAME
from .runtime.supervision import (
    FENCED_EXIT_CODE,
    REQUEUE_EXIT_CODE,
    DrainInterrupt,
    HeartbeatWriter,
    drain_reason,
    drain_requested,
    install_drain_handler,
    read_heartbeat,
)
from .utils import function_utils as fu

#: durable fleet membership — written by the supervisor, read by every
#: gateway incarnation at boot (a restarted gateway must know members
#: added after the fleet booted)
MEMBERS_FILENAME = "members.json"
#: the supervisor's operator view (scripts/progress.py renders it)
SUPERVISOR_STATE_FILENAME = "supervisor_state.json"
#: the supervisor's decision ledger: typed lifecycle records under the
#: journal's CRC/fsync framing (NOT a request journal — adoption rules
#: do not apply to it)
LIFECYCLE_LOG_FILENAME = "lifecycle.log"
SUPERVISOR_UID = "supervisor"

# -- typed lifecycle records (the decision ledger's vocabulary) ---------------
GATEWAY_START = "gateway_start"
GATEWAY_RESTART = "gateway_restart"
GATEWAY_QUARANTINED = "gateway_quarantined"
MEMBER_SPAWN = "member_spawn"
MEMBER_RESPAWN = "member_respawn"
MEMBER_CRASHED = "member_crashed"
MEMBER_ADOPTED = "member_adopted"
MEMBER_DRAINED = "member_drained"
MEMBER_FENCED = "member_fenced"
MEMBER_QUARANTINED = "member_quarantined"
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"

QUARANTINE_MEMBER = "quarantined:member_crash_loop"
QUARANTINE_GATEWAY = "quarantined:gateway_crash_loop"


def classify_member_exit(rc: int) -> str:
    """The reaper's decision table (docs/SERVING.md "Supervision"):
    what one member exit code means for the fleet's capacity.

    * ``"drained"`` (rc 114) — the standard requeue exit: expected
      during fleet drain and after a scale-down/operator drain; the
      member is retired, never respawned.
    * ``"fenced"`` (rc 115) — a survivor adopted this member's journal
      while it was wedged.  The old base dir is the adoption record;
      capacity respawns on a FRESH dir, the old dir is never reused.
    * ``"crashed"`` (anything else, signals included) — respawn with
      exponential backoff on the same dir under the adoption-claim
      protocol, unless the gateway's failover adopts it first.
    """
    if rc == REQUEUE_EXIT_CODE:
        return "drained"
    if rc == FENCED_EXIT_CODE:
        return "fenced"
    return "crashed"


def split_generation(name: str) -> tuple:
    """``"m0" -> ("m0", 0)``, ``"m0-r2" -> ("m0", 2)``: a respawned
    member's fresh-dir name carries its lineage + generation, so crash
    budgets follow the lineage, not the dir."""
    stem, sep, tail = name.rpartition("-r")
    if sep and stem and tail.isdigit():
        return stem, int(tail)
    return name, 0


def fresh_member_name(name: str) -> str:
    """The next fresh-dir name in a lineage: ``m0 -> m0-r1 -> m0-r2``."""
    lineage, gen = split_generation(name)
    return f"{lineage}-r{gen + 1}"


def _load_fleet_config(path):
    if not path:
        return {}
    with open(path) as f:
        return json.load(f)


class FleetSupervisor:
    """The fleet's outermost loop: spawn members + the gateway child,
    then watch, heal, and scale until drained.  Single-threaded on
    purpose — every spawn/reap/scale decision happens on one thread, so
    there is no lock for a slow subprocess call to wedge (ctlint
    CT012/CT014)."""

    def __init__(self, base_dir: str, n_members: int, port: int = 0,
                 cfg: Optional[Dict[str, Any]] = None,
                 tpu: bool = False, config_path: Optional[str] = None):
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.cfg = dict(cfg or {})
        self.config_path = config_path
        self.tpu = bool(tpu)
        gw = dict(self.cfg.get("gateway") or {})
        self.health_interval_s = max(
            0.05, float(gw.get("health_interval_s", 1.0))
        )
        self.member_stale_s = max(0.1, float(gw.get("member_stale_s", 6.0)))
        self.max_member_queue = max(1, int(gw.get("max_member_queue", 64)))
        sup = dict(self.cfg.get("supervisor") or {})
        self.poll_s = max(0.05, float(sup.get("poll_s", 0.5)))
        self.gateway_stale_s = max(
            1.0, float(sup.get("gateway_stale_s", 8.0))
        )
        self.gateway_max_restarts = max(
            1, int(sup.get("gateway_max_restarts", 5))
        )
        self.gateway_backoff_s = max(
            0.0, float(sup.get("gateway_backoff_s", 0.5))
        )
        self.member_max_respawns = max(
            1, int(sup.get("member_max_respawns", 5))
        )
        # default crash backoff sits past the gateway's own detection +
        # adoption window: when survivors exist, adoption (which strands
        # nothing) should win the race over a same-dir respawn
        self.respawn_backoff_s = max(0.2, float(sup.get(
            "respawn_backoff_s",
            2.0 * self.member_stale_s + 2.0 * self.health_interval_s,
        )))
        self.respawn_backoff_max_s = max(
            self.respawn_backoff_s,
            float(sup.get("respawn_backoff_max_s", 30.0)),
        )
        self.min_members = max(1, int(sup.get("min_members", n_members)))
        self.max_members = max(
            self.min_members, int(sup.get("max_members", n_members + 2))
        )
        self.scale_up_backlog = float(sup.get(
            "scale_up_backlog", 0.8 * self.max_member_queue
        ))
        self.scale_sustain_s = float(sup.get("scale_sustain_s", 5.0))
        self.scale_idle_s = float(sup.get("scale_idle_s", 30.0))
        self.member_root = os.path.join(self.base_dir, "members")
        self.server_cfg_path: Optional[str] = None
        if self.cfg.get("server"):
            self.server_cfg_path = os.path.join(
                self.base_dir, "member_config.json"
            )
            fu.atomic_write_json(self.server_cfg_path, self.cfg["server"])
        #: name -> member record; this dict is the supervisor's truth
        #: about the PROCESSES (the gateway's fleet_state.json is the
        #: truth about routing/health)
        self.members: Dict[str, Dict[str, Any]] = {}
        self.gateway_proc: Optional[subprocess.Popen] = None
        self.gateway_pid: Optional[int] = None
        self.gateway_port = int(port)
        self.gateway_restarts = 0
        self.gateway_started_at: Optional[float] = None
        self.gateway_booted = False
        self.gateway_failed = False
        self.last_scale = {
            "decision": "none", "reason": "boot",
            "time": trace_mod.walltime(),
        }
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        #: same-dir respawn claims held until the fresh server's endpoint
        #: names its pid (a late survivor must not adopt a booting journal)
        self._pending_release: List[Dict[str, Any]] = []
        self._ledger: Optional[journal_mod.Journal] = None
        self._heartbeat: Optional[HeartbeatWriter] = None
        # a supervisor restarted over an existing fleet dir continues the
        # incarnation sequence, never reuses one
        prior = fu.read_json_if_valid(
            os.path.join(self.base_dir, SUPERVISOR_STATE_FILENAME)
        ) or {}
        self.incarnation = int(
            (prior.get("gateway") or {}).get("incarnation") or 0
        )

    # -- the decision ledger ----------------------------------------------
    def _journal_decision(self, typ: str, member: str, **fields) -> None:
        """Every supervisor decision is one typed record in the
        lifecycle ledger AND one trace instant (ctlint CT014): the
        respawn/scale history is replayable from disk and attributable
        on the trace timeline."""
        fields = {k: v for k, v in fields.items() if v is not None}
        try:
            self._ledger.append_transition(typ, member, **fields)
        except Exception:
            pass  # the ledger is attribution; a full disk must not kill us
        trace_mod.instant(f"fleet.{typ}", member=member, **fields)

    # -- spawning ----------------------------------------------------------
    def _spawn_member(self, name: str, mdir: str,
                      record: str = MEMBER_SPAWN, **fields) -> Any:
        """Start one member server subprocess; journals the decision
        (``record``) before returning.  Used at boot, for respawns, and
        for scale-up."""
        os.makedirs(mdir, exist_ok=True)
        cmd = [
            sys.executable, "-m", "cluster_tools_tpu.serve",
            "--base-dir", mdir,
        ]
        if self.server_cfg_path:
            cmd += ["--config", self.server_cfg_path]
        if self.tpu:
            cmd += ["--tpu"]
        proc = subprocess.Popen(cmd)
        m = self.members.setdefault(name, {
            "name": name, "base_dir": mdir, "respawns": 0,
            "registered": False, "last_rc": None, "drain_requested": False,
        })
        m.update(
            proc=proc, pid=proc.pid, state="running",
            spawned_at=time.monotonic(), backoff_until=None,
        )
        self._journal_decision(
            record, name, pid=proc.pid, dir=os.path.basename(mdir),
            **fields,
        )
        return proc

    def _spawn_gateway(self, reason: str) -> Any:
        """Start (or restart) the gateway child.  The incarnation is
        bumped and durably recorded BEFORE the child boots — a
        supervisor crash between spawn and state write must never let
        two gateway lives share an epoch."""
        self.incarnation += 1
        self._write_state()
        cmd = [
            sys.executable, "-m", "cluster_tools_tpu.fleet",
            "--gateway-child", "--base-dir", self.base_dir,
            "--port", str(self.gateway_port),
            "--incarnation", str(self.incarnation),
        ]
        if self.config_path:
            cmd += ["--config", self.config_path]
        proc = subprocess.Popen(cmd)
        self.gateway_proc = proc
        self.gateway_pid = proc.pid
        self.gateway_booted = False
        self.gateway_started_at = time.monotonic()
        self._journal_decision(
            GATEWAY_START if reason == "boot" else GATEWAY_RESTART,
            "gateway", pid=proc.pid, incarnation=self.incarnation,
            reason=reason,
        )
        return proc

    def _write_members_file(self) -> None:
        """Durable membership for gateway (re)boots.  Fenced/adopted old
        dirs stay listed — they are the adoption records a cold gateway
        rebuilds ``adopted_by`` from; only retired (scaled-down) members
        leave the roster."""
        docs = [
            {"name": n, "base_dir": m["base_dir"]}
            for n, m in self.members.items() if m["state"] != "retired"
        ]
        fu.atomic_write_json(
            os.path.join(self.base_dir, MEMBERS_FILENAME),
            {"version": 1, "members": docs},
        )

    # -- gateway plane -----------------------------------------------------
    def _gateway_call(self, method: str, path: str,
                      body=None) -> tuple:
        try:
            return netio.http_json_call(
                "127.0.0.1", int(self.gateway_port), method, path, body,
                timeout_s=5.0, site="net_member", member="gateway",
            )
        except (OSError, ValueError):
            return 0, {}

    def _tick_gateway(self) -> None:
        proc = self.gateway_proc
        if proc is None or self.gateway_failed:
            return
        rc = proc.poll()
        now = time.monotonic()
        if rc is None and not self.gateway_booted:
            doc = fu.read_json_if_valid(
                os.path.join(self.base_dir, ENDPOINT_FILENAME)
            ) or {}
            if doc.get("pid") == proc.pid and doc.get("role") == "gateway":
                self.gateway_booted = True
                self.gateway_port = int(doc.get("port") or
                                        self.gateway_port)
                print(
                    f"fleet gateway on {doc.get('host')}:{doc.get('port')}"
                    f" (base_dir={self.base_dir}, incarnation="
                    f"{self.incarnation})",
                    flush=True,
                )
            elif now - (self.gateway_started_at or now) > 120.0:
                rc = self._kill_gateway()  # never bound: wedged at boot
            else:
                return
        wedged = False
        if rc is None and self.gateway_booted:
            hb = read_heartbeat(self.base_dir, GATEWAY_UID) or {}
            age = None
            if hb.get("time") is not None:
                age = max(0.0, trace_mod.walltime() - float(hb["time"]))
            # only this incarnation's silence counts: right after a
            # restart the file still carries the predecessor's last pulse
            uptime = now - (self.gateway_started_at or now)
            if (age is None or age > self.gateway_stale_s) and (
                uptime > self.gateway_stale_s
            ):
                wedged = True
        if rc is None and not wedged:
            return
        reason = (
            "wedged:heartbeat_stale" if rc is None else f"exit_rc_{rc}"
        )
        if rc is None:
            rc = self._kill_gateway()
        if drain_requested():
            return  # the drain path owns shutdown now
        self.gateway_restarts += 1
        if self.gateway_restarts > self.gateway_max_restarts:
            self.gateway_failed = True
            self._journal_decision(
                GATEWAY_QUARANTINED, "gateway",
                restarts=self.gateway_restarts, reason=reason,
            )
            try:
                fu.record_failures(
                    fu.failures_path(self.base_dir),
                    "fleet.supervisor",
                    [{
                        "block_id": "gateway:crash_loop",
                        "sites": {"failover": 1},
                        "error": (
                            f"gateway crash loop: {self.gateway_restarts} "
                            f"restarts (last: {reason})"
                        ),
                        "quarantined": True,
                        "resolved": False,
                        "resolution": QUARANTINE_GATEWAY,
                    }],
                )
            except Exception:
                pass
            print(
                f"gateway crash loop ({self.gateway_restarts} restarts); "
                "quarantining the fleet", file=sys.stderr, flush=True,
            )
            return
        backoff = min(
            10.0, self.gateway_backoff_s * (2 ** (self.gateway_restarts - 1))
        )
        if backoff:
            time.sleep(backoff)
        print(
            f"gateway died ({reason}); restarting as incarnation "
            f"{self.incarnation + 1}",
            flush=True,
        )
        self._spawn_gateway(reason)

    def _kill_gateway(self) -> Optional[int]:
        """Crash-only discipline: a wedged gateway is SIGKILLed, never
        pleaded with — its replacement rebuilds from disk."""
        proc = self.gateway_proc
        if proc is None:
            return None
        try:
            proc.kill()
        except OSError:
            pass
        try:
            return proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            return None

    # -- member plane ------------------------------------------------------
    def _tick_members(self) -> None:
        """Reap exits and run the decision table
        (:func:`classify_member_exit`) on each one."""
        for name, m in list(self.members.items()):
            proc = m.get("proc")
            if proc is None or m["state"] != "running":
                continue
            rc = proc.poll()
            if rc is None:
                continue
            m["last_rc"] = rc
            verdict = classify_member_exit(rc)
            if verdict == "drained":
                m["state"] = "drained"
                self._journal_decision(
                    MEMBER_DRAINED, name, rc=rc,
                    scale_down=bool(m.get("drain_requested")) or None,
                )
                print(f"member {name} drained (rc {rc}); retiring",
                      flush=True)
                self._retire_member(name)
            elif verdict == "fenced":
                m["state"] = "fenced"
                self._journal_decision(MEMBER_FENCED, name, rc=rc)
                print(
                    f"member {name} exited FENCED (rc {rc}): journal "
                    "adopted by a survivor; respawning capacity on a "
                    "fresh dir",
                    flush=True,
                )
                m["respawns"] += 1
                self._replace_on_fresh_dir(name)
            else:
                attempts = int(m["respawns"])
                if attempts >= self.member_max_respawns:
                    self._quarantine_member(name, rc)
                    continue
                delay = min(
                    self.respawn_backoff_max_s,
                    self.respawn_backoff_s * (2 ** attempts),
                )
                m["state"] = "backoff"
                m["backoff_until"] = time.monotonic() + delay
                self._journal_decision(
                    MEMBER_CRASHED, name, rc=rc,
                    respawn_in_s=round(delay, 3),
                )
                print(
                    f"member {name} crashed (rc {rc}); respawn in "
                    f"{delay:.1f}s (attempt {attempts + 1}/"
                    f"{self.member_max_respawns})",
                    flush=True,
                )

    def _quarantine_member(self, name: str, rc: int) -> None:
        m = self.members[name]
        m["state"] = "quarantined"
        self._journal_decision(
            MEMBER_QUARANTINED, name, rc=rc, respawns=m["respawns"],
        )
        try:
            fu.record_failures(
                fu.failures_path(self.base_dir),
                "fleet.supervisor",
                [{
                    "block_id": f"member:{name}:crash_loop",
                    "sites": {"failover": 1},
                    "error": (
                        f"member {name} crash loop: {m['respawns']} "
                        f"respawns exhausted (last rc {rc})"
                    ),
                    "quarantined": True,
                    "resolved": False,
                    "resolution": QUARANTINE_MEMBER,
                    "member": name,
                }],
            )
        except Exception:
            pass
        print(
            f"member {name} quarantined after {m['respawns']} respawns "
            f"(last rc {rc}): {QUARANTINE_MEMBER}",
            file=sys.stderr, flush=True,
        )

    def _replace_on_fresh_dir(self, name: str) -> None:
        """Capacity back after an adoption: the old dir is the adoption
        record (rc-115 discipline: never reused), the lineage continues
        on a fresh dir under the same crash budget."""
        m = self.members[name]
        if m["respawns"] > self.member_max_respawns:
            self._quarantine_member(name, int(m.get("last_rc") or 0))
            return
        new_name = fresh_member_name(name)
        while new_name in self.members:
            new_name = fresh_member_name(new_name)
        new_dir = os.path.join(self.member_root, new_name)
        self._spawn_member(
            new_name, new_dir, record=MEMBER_RESPAWN,
            fresh_dir=True, replaces=name, attempt=m["respawns"],
        )
        self.members[new_name]["respawns"] = m["respawns"]
        self._write_members_file()

    def _respawn_pending(self) -> None:
        """Crashed members past their backoff.  The supervisor never
        fights the gateway's failover: an already-adopted member comes
        back on a fresh dir, a claim in flight postpones, and the
        same-dir path only runs once a live gateway has had a full
        detection window and still nobody claimed the journal."""
        now = time.monotonic()
        for name, m in list(self.members.items()):
            if m["state"] != "backoff" or now < (m.get("backoff_until")
                                                 or 0.0):
                continue
            fs = self._fleet_state() or {}
            view = (fs.get("members") or {}).get(name) or {}
            if view.get("adopted_by"):
                # the gateway won the race: old dir = adoption record
                self._journal_decision(
                    MEMBER_ADOPTED, name, adopter=view["adopted_by"],
                )
                m["respawns"] += 1
                m["state"] = "adopted"
                self._replace_on_fresh_dir(name)
                continue
            if read_adoption_claim(m["base_dir"]) is not None:
                m["backoff_until"] = now + self.health_interval_s
                continue
            gw_uptime = now - (self.gateway_started_at or now)
            gateway_settled = (
                self.gateway_booted
                and self.gateway_proc is not None
                and self.gateway_proc.poll() is None
                and gw_uptime > (
                    self.member_stale_s + 3.0 * self.health_interval_s
                )
            )
            if not gateway_settled:
                m["backoff_until"] = now + self.health_interval_s
                continue
            claim = acquire_adoption_claim(
                m["base_dir"], by=f"respawn:{name}", pid=os.getpid(),
            )
            if claim is None:
                m["backoff_until"] = now + self.health_interval_s
                continue
            # fence the dead incarnation before its successor boots,
            # same as the gateway's own respawn path
            journal_mod.mint_fence(m["base_dir"], by=f"respawn:{name}")
            m["respawns"] += 1
            self._spawn_member(
                name, m["base_dir"], record=MEMBER_RESPAWN,
                fresh_dir=False, attempt=m["respawns"],
                rc=m.get("last_rc"),
            )
            self._pending_release.append({
                "name": name, "claim": claim, "deadline": now + 120.0,
            })

    def _release_pending(self) -> None:
        """Release same-dir respawn claims once the fresh server's
        endpoint names its pid (it owns its journal again) — or on
        boot failure/timeout, so adoption can take over."""
        for rec in list(self._pending_release):
            m = self.members.get(rec["name"])
            if m is None:
                self._pending_release.remove(rec)
                continue
            proc = m.get("proc")
            doc = fu.read_json_if_valid(
                os.path.join(m["base_dir"], ENDPOINT_FILENAME)
            ) or {}
            booted = proc is not None and doc.get("pid") == proc.pid
            died = proc is not None and proc.poll() is not None
            if booted or died or time.monotonic() > rec["deadline"]:
                release_adoption_claim(m["base_dir"], rec["claim"])
                self._pending_release.remove(rec)

    def _tick_registration(self) -> None:
        """Tell the gateway about members it did not boot with
        (fresh-dir respawns, scale-ups).  Best-effort every tick: a
        gateway that was down catches up here, or at its next cold boot
        from ``members.json``."""
        if not self.gateway_booted:
            return
        for name, m in self.members.items():
            if m.get("registered") or m["state"] not in ("running",):
                continue
            status, doc = self._gateway_call(
                "POST", "/members",
                {"op": "add", "name": name, "base_dir": m["base_dir"]},
            )
            if status == 200 or (
                status == 409 and doc.get("error") == "member_exists"
            ):
                m["registered"] = True

    def _retire_member(self, name: str) -> None:
        """A drained member leaves the roster: retired from the gateway
        table (so scale-down can never trigger a noise adoption of its
        journal) and from ``members.json``."""
        m = self.members[name]
        m["state"] = "retired"
        self._gateway_call(
            "POST", "/members", {"op": "retire", "name": name},
        )
        self._write_members_file()

    # -- scaling -----------------------------------------------------------
    def _note_scale(self, decision: str, reason: str) -> None:
        if (self.last_scale.get("decision") == decision
                and self.last_scale.get("reason") == reason):
            return
        self.last_scale = {
            "decision": decision, "reason": reason,
            "time": trace_mod.walltime(),
        }

    def _fleet_state(self) -> Optional[Dict[str, Any]]:
        """The gateway's view, only if fresh — a stale file (gateway
        down) must not drive scale decisions."""
        fs = fu.read_json_if_valid(
            os.path.join(self.base_dir, FLEET_STATE_FILENAME)
        )
        if not fs:
            return None
        age = trace_mod.walltime() - float(fs.get("time") or 0)
        if age > 5.0 * self.health_interval_s + 5.0:
            return None
        return fs

    def _tick_scaling(self) -> None:
        """Backlog-driven scaling, chaos-proof by construction: HOLD
        whenever any adoption, drain, respawn, or boot is in flight —
        a scale decision never fights the lifecycle machinery."""
        now = time.monotonic()
        fs = self._fleet_state()
        if fs is None or not self.gateway_booted:
            self._note_scale("hold", "gateway not ready")
            self._pressure_since = self._idle_since = None
            return
        members_view = fs.get("members") or {}
        live = [
            v for v in members_view.values()
            if v.get("alive") and not v.get("draining")
            and not v.get("adopted_by")
        ]
        dead_unadopted = list(fs.get("dead_unadopted") or [])
        draining = [
            n for n, v in members_view.items() if v.get("draining")
        ]
        pending = [
            n for n, m in self.members.items()
            if m["state"] == "backoff"
            or (m["state"] == "running"
                and not (members_view.get(n) or {}).get("alive"))
        ]
        if dead_unadopted or draining or pending or self._pending_release:
            self._note_scale(
                "hold",
                f"lifecycle in flight (dead={len(dead_unadopted)} "
                f"draining={len(draining)} booting_or_backoff="
                f"{len(pending)})",
            )
            self._pressure_since = self._idle_since = None
            return
        backlog = sum(
            int(v.get("queued") or 0) + int(v.get("inflight") or 0)
            for v in live
        )
        # only LIVE members' breakers are pressure: a dead-and-adopted
        # member's breaker stays open forever, and its capacity was
        # already replaced by the fresh-dir respawn — counting it would
        # scale up once per sustain window until max_members
        breakers_open = sum(
            1 for v in live
            if ((v.get("breaker") or {}).get("state") == "open")
        )
        per_member = backlog / max(1, len(live))
        if (per_member >= self.scale_up_backlog or breakers_open) and (
            len(live) < self.max_members
        ):
            self._idle_since = None
            if self._pressure_since is None:
                self._pressure_since = now
                self._note_scale(
                    "hold",
                    f"pressure building (backlog={backlog} "
                    f"breakers_open={breakers_open})",
                )
                return
            if now - self._pressure_since < self.scale_sustain_s:
                return
            self._pressure_since = None
            idx = 0
            while f"s{idx}" in self.members:
                idx += 1
            name = f"s{idx}"
            self._journal_decision(
                SCALE_UP, name, backlog=backlog,
                per_member=round(per_member, 2),
                breakers_open=breakers_open, live=len(live),
            )
            self._spawn_member(
                name, os.path.join(self.member_root, name),
                record=MEMBER_SPAWN, scale_up=True,
            )
            self._write_members_file()
            self._note_scale(
                "scale_up",
                f"sustained backlog {backlog} over {len(live)} members",
            )
            return
        if backlog == 0 and len(live) > self.min_members:
            self._pressure_since = None
            if self._idle_since is None:
                self._idle_since = now
                return
            if now - self._idle_since < self.scale_idle_s:
                return
            self._idle_since = None
            status, doc = self._gateway_call("POST", "/drain", {})
            if status == 200 and doc.get("member"):
                target = str(doc["member"])
                tm = self.members.get(target)
                if tm is not None:
                    tm["drain_requested"] = True
                self._journal_decision(
                    SCALE_DOWN, target, live=len(live),
                    idle_s=round(self.scale_idle_s, 1),
                )
                self._note_scale(
                    "scale_down",
                    f"idle {self.scale_idle_s:.0f}s with {len(live)} "
                    "members",
                )
            return
        self._pressure_since = self._idle_since = None
        self._note_scale("hold", "steady")

    # -- operator view -----------------------------------------------------
    def _state_doc(self) -> Dict[str, Any]:
        now = time.monotonic()
        gw_proc = self.gateway_proc
        hb = read_heartbeat(self.base_dir, GATEWAY_UID) or {}
        hb_age = None
        if hb.get("time") is not None:
            hb_age = max(0.0, trace_mod.walltime() - float(hb["time"]))
        members = {}
        for n, m in self.members.items():
            backoff_remaining = None
            if m["state"] == "backoff" and m.get("backoff_until"):
                backoff_remaining = max(0.0, m["backoff_until"] - now)
            members[n] = {
                "base_dir": m["base_dir"],
                "pid": m.get("pid"),
                "state": m["state"],
                "respawns": int(m["respawns"]),
                "last_rc": m.get("last_rc"),
                "backoff_remaining_s": (
                    round(backoff_remaining, 3)
                    if backoff_remaining is not None else None
                ),
                "quarantined": m["state"] == "quarantined",
            }
        crash_loops = sorted(
            n for n, m in self.members.items()
            if m["state"] == "quarantined"
        )
        return {
            "version": 1,
            "role": "supervisor",
            "uid": SUPERVISOR_UID,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "time": trace_mod.walltime(),
            "base_dir": self.base_dir,
            "gateway": {
                "pid": self.gateway_pid,
                "incarnation": self.incarnation,
                "alive": bool(gw_proc is not None
                              and gw_proc.poll() is None),
                "booted": self.gateway_booted,
                "restarts": self.gateway_restarts,
                "port": self.gateway_port,
                "heartbeat_age_s": (
                    round(hb_age, 3) if hb_age is not None else None
                ),
                "quarantined": self.gateway_failed,
            },
            "members": members,
            "scale": dict(self.last_scale),
            "crash_loops": crash_loops,
            "gateway_crash_loop": self.gateway_failed,
        }

    def _write_state(self) -> None:
        try:
            fu.atomic_write_json(
                os.path.join(self.base_dir, SUPERVISOR_STATE_FILENAME),
                self._state_doc(),
            )
        except OSError:
            pass  # best-effort; the supervisor outlives a full disk

    # -- boot + drain ------------------------------------------------------
    def _wait_members_boot(self, deadline_s: float = 120.0) -> bool:
        """Wait for each member's endpoint file to name its CURRENT pid
        (a stale file from a previous incarnation must not fake a live
        boot)."""
        deadline = time.monotonic() + deadline_s
        for name, m in self.members.items():
            while True:
                doc = fu.read_json_if_valid(
                    os.path.join(m["base_dir"], ENDPOINT_FILENAME)
                )
                proc = m["proc"]
                if doc and doc.get("pid") == proc.pid:
                    break
                if proc.poll() is not None:
                    print(
                        f"member {name} died during boot "
                        f"(rc {proc.returncode})", file=sys.stderr,
                    )
                    return False
                if time.monotonic() > deadline:
                    print(f"member {name} did not bind in time",
                          file=sys.stderr)
                    return False
                time.sleep(0.1)
        return True

    def _drain_all(self) -> None:
        """The standard protocol fleet-wide: SIGTERM the gateway child
        (exits 114), then every live member (each drains at its safe
        boundaries and exits 114)."""
        proc = self.gateway_proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                rc = proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
            print(f"gateway exited rc {rc}", flush=True)
        for name, m in self.members.items():
            p = m.get("proc")
            if p is not None and p.poll() is None:
                p.terminate()
        for name, m in self.members.items():
            p = m.get("proc")
            if p is None:
                continue
            try:
                rc = p.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            print(f"member {name} exited rc {rc}", flush=True)
        self._write_state()

    def run(self) -> int:
        install_drain_handler()
        self._ledger = journal_mod.Journal(
            os.path.join(self.base_dir, LIFECYCLE_LOG_FILENAME)
        )
        self._ledger.recover()
        self._heartbeat = HeartbeatWriter(
            self.base_dir, SUPERVISOR_UID, interval_s=2.0
        ).start()
        try:
            for m in list(self.members.values()):
                self._spawn_member(m["name"], m["base_dir"])
                m["registered"] = True  # the gateway boots with them
            self._write_members_file()
            if not self._wait_members_boot():
                self._drain_all()
                return 1
            self._spawn_gateway("boot")
            while not drain_requested():
                if self.gateway_failed:
                    self._drain_all()
                    return 1
                self._tick_gateway()
                self._tick_members()
                self._respawn_pending()
                self._release_pending()
                self._tick_registration()
                self._tick_scaling()
                self._write_state()
                time.sleep(self.poll_s)
            self._drain_all()
            print(
                f"DRAINED ({drain_reason() or 'drain requested'}); "
                f"exiting {REQUEUE_EXIT_CODE} for requeue",
                flush=True,
            )
            return REQUEUE_EXIT_CODE
        finally:
            if self._heartbeat is not None:
                self._heartbeat.stop()
            if self._ledger is not None:
                self._ledger.close()

    def seed_members(self, n_members: int) -> None:
        """Register the boot-time roster (``members/m0..mN``) without
        spawning yet — :meth:`run` spawns them."""
        for i in range(n_members):
            name = f"m{i}"
            mdir = os.path.join(self.member_root, name)
            os.makedirs(mdir, exist_ok=True)
            self.members[name] = {
                "name": name, "base_dir": mdir, "proc": None, "pid": None,
                "state": "running", "respawns": 0, "registered": True,
                "last_rc": None, "backoff_until": None,
                "drain_requested": False,
            }


# -- CLI ----------------------------------------------------------------------


def cmd_status(base_dir: str) -> int:
    from .runtime.server import ServeClient

    client = ServeClient.from_endpoint_file(base_dir)
    doc = client.status()
    print(json.dumps(doc, indent=2))
    return int(doc.get("rc") or 0)


def cmd_drain(base_dir: str, member=None) -> int:
    from .runtime.server import ServeClient

    client = ServeClient.from_endpoint_file(base_dir)
    status, doc = client._call(
        "POST", "/drain", {"member": member} if member else {},
    )
    print(json.dumps(doc, indent=2))
    return 0 if status == 200 else 1


def _run_gateway_child(args) -> int:
    """The hidden ``--gateway-child`` entry: the gateway as its OWN
    crash-only process.  Membership comes from ``members.json`` (so a
    restarted incarnation knows members added mid-run), state comes
    from :meth:`FleetGateway._rebuild_from_disk`, and ``spawn`` is None
    — respawns are the supervisor's job now."""
    base_dir = os.path.abspath(args.base_dir)
    cfg = _load_fleet_config(args.config)
    gw_cfg = dict(cfg.get("gateway") or {})
    doc = fu.read_json_if_valid(
        os.path.join(base_dir, MEMBERS_FILENAME)
    ) or {}
    member_dirs = [
        str(m["base_dir"]) for m in (doc.get("members") or [])
        if m.get("base_dir")
    ]
    if not member_dirs:
        print("gateway-child: empty or missing members.json",
              file=sys.stderr)
        return 1
    install_drain_handler()
    gateway = FleetGateway(
        base_dir=base_dir,
        member_dirs=member_dirs,
        port=args.port,
        affinity=bool(gw_cfg.get("affinity", True)),
        health_interval_s=float(gw_cfg.get("health_interval_s", 1.0)),
        member_stale_s=float(gw_cfg.get("member_stale_s", 6.0)),
        max_member_queue=int(gw_cfg.get("max_member_queue", 64)),
        call_timeout_s=float(gw_cfg.get("call_timeout_s", 10.0)),
        failover=str(gw_cfg.get("failover", "adopt")),
        spawn=None,
        breaker_threshold=int(gw_cfg.get("breaker_threshold", 2)),
        breaker_cooldown_s=float(gw_cfg.get("breaker_cooldown_s", 2.0)),
        hedge=bool(gw_cfg.get("hedge", True)),
        hedge_min_delay_s=float(gw_cfg.get("hedge_min_delay_s", 0.05)),
        hedge_max_delay_s=float(gw_cfg.get("hedge_max_delay_s", 2.0)),
        incarnation=int(args.incarnation),
    )
    gateway.start()
    try:
        gateway.serve_until_drained()
    except DrainInterrupt as e:
        # CT006/CT012: a drained gateway is a requeue, not a crash
        print(
            f"gateway DRAINED ({e.reason}); exiting {REQUEUE_EXIT_CODE}",
            flush=True,
        )
        return REQUEUE_EXIT_CODE
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cluster_tools_tpu.fleet",
        description="supervised serving fleet: supervisor + gateway + M "
                    "pipeline servers (docs/SERVING.md \"Fleet\")",
    )
    p.add_argument("--base-dir", required=False,
                   help="fleet scratch dir (gateway state + members/mN "
                        "server dirs)")
    p.add_argument("--members", type=int, default=None,
                   help="number of member servers to spawn (default 2)")
    p.add_argument("--port", type=int, default=0,
                   help="gateway bind port (default 0 = ephemeral, see "
                        "server.json)")
    p.add_argument("--config", default=None,
                   help="fleet config json: members/gateway/server/"
                        "supervisor keys")
    p.add_argument("--tpu", action="store_true",
                   help="skip the cpu platform pin on members (requests "
                        "may target the accelerator)")
    p.add_argument("--status", metavar="BASE_DIR", default=None,
                   help="print a running gateway's /status and exit with "
                        "its rc")
    p.add_argument("--drain", metavar="BASE_DIR", default=None,
                   help="SIGTERM the emptiest member of a running fleet "
                        "(scale-down; rc 114 on the member)")
    p.add_argument("--member", default=None,
                   help="with --drain: the member to drain instead of "
                        "the emptiest")
    p.add_argument("--gateway-child", action="store_true",
                   help=argparse.SUPPRESS)  # internal: supervisor's child
    p.add_argument("--incarnation", type=int, default=1,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.status:
        return cmd_status(args.status)
    if args.drain:
        return cmd_drain(args.drain, member=args.member)
    if not args.base_dir:
        p.error("--base-dir is required (unless --status/--drain)")
    if args.gateway_child:
        return _run_gateway_child(args)

    cfg = _load_fleet_config(args.config)
    n_members = int(
        args.members if args.members is not None
        else cfg.get("members", 2)
    )
    if n_members < 1:
        p.error("--members must be >= 1")
    supervisor = FleetSupervisor(
        args.base_dir, n_members, port=args.port, cfg=cfg,
        tpu=args.tpu, config_path=args.config,
    )
    supervisor.seed_members(n_members)
    return supervisor.run()


if __name__ == "__main__":
    sys.exit(main())
