"""Fleet-mode CLI entry: a gateway fronting M pipeline servers.

Usage (docs/SERVING.md "Fleet")::

    python -m cluster_tools_tpu.fleet --base-dir /srv/fleet \\
        [--members 2] [--port 0] [--config fleet.json] [--tpu]
    python -m cluster_tools_tpu.fleet --status /srv/fleet
    python -m cluster_tools_tpu.fleet --drain /srv/fleet [--member m0]

Spawns ``--members`` pipeline-server subprocesses (each a standard
``cluster_tools_tpu.serve`` process under ``<base_dir>/members/mN``) and a
:class:`~cluster_tools_tpu.runtime.fleet.FleetGateway` routing to them:
tenant-affinity placement with least-queue fallback, health checking, and
journal-handoff failover — when a member dies, a surviving member adopts
its journal under an exclusive claim and finishes every acknowledged
request with zero client resubmission; with no survivor the gateway
respawns the member on its own base dir and boot replay does the rest.

``--config`` names a JSON document: ``{"members": N, "gateway":
{affinity, health_interval_s, member_stale_s, max_member_queue, failover},
"server": {...per-member cluster_tools_tpu.serve config...}}``.

SIGTERM drains the whole fleet through the standard protocol: the gateway
stops routing, every member is SIGTERMed and drains at its safe
boundaries (each exits ``REQUEUE_EXIT_CODE``), and this process exits
``REQUEUE_EXIT_CODE`` (114) so rolling restarts ride the same requeue
protocol as every other preempted job.  ``--status`` prints the gateway's
``/status`` document and exits with its ``rc`` (1 while a member is dead
and unadopted).  ``--drain`` SIGTERMs the emptiest member (scale-down).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time


def _load_fleet_config(path):
    if not path:
        return {}
    with open(path) as f:
        return json.load(f)


def cmd_status(base_dir: str) -> int:
    from .runtime.server import ServeClient

    client = ServeClient.from_endpoint_file(base_dir)
    doc = client.status()
    print(json.dumps(doc, indent=2))
    return int(doc.get("rc") or 0)


def cmd_drain(base_dir: str, member=None) -> int:
    from .runtime.server import ServeClient

    client = ServeClient.from_endpoint_file(base_dir)
    status, doc = client._call(
        "POST", "/drain", {"member": member} if member else {},
    )
    print(json.dumps(doc, indent=2))
    return 0 if status == 200 else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cluster_tools_tpu.fleet",
        description="serving fleet: gateway + M pipeline servers "
                    "(docs/SERVING.md \"Fleet\")",
    )
    p.add_argument("--base-dir", required=False,
                   help="fleet scratch dir (gateway state + members/mN "
                        "server dirs)")
    p.add_argument("--members", type=int, default=None,
                   help="number of member servers to spawn (default 2)")
    p.add_argument("--port", type=int, default=0,
                   help="gateway bind port (default 0 = ephemeral, see "
                        "server.json)")
    p.add_argument("--config", default=None,
                   help="fleet config json: members/gateway/server keys")
    p.add_argument("--tpu", action="store_true",
                   help="skip the cpu platform pin on members (requests "
                        "may target the accelerator)")
    p.add_argument("--status", metavar="BASE_DIR", default=None,
                   help="print a running gateway's /status and exit with "
                        "its rc")
    p.add_argument("--drain", metavar="BASE_DIR", default=None,
                   help="SIGTERM the emptiest member of a running fleet "
                        "(scale-down; rc 114 on the member)")
    p.add_argument("--member", default=None,
                   help="with --drain: the member to drain instead of "
                        "the emptiest")
    args = p.parse_args(argv)

    if args.status:
        return cmd_status(args.status)
    if args.drain:
        return cmd_drain(args.drain, member=args.member)
    if not args.base_dir:
        p.error("--base-dir is required (unless --status/--drain)")

    from .runtime.fleet import FleetGateway
    from .runtime.server import ENDPOINT_FILENAME
    from .runtime.supervision import (
        FENCED_EXIT_CODE,
        REQUEUE_EXIT_CODE,
        DrainInterrupt,
        install_drain_handler,
    )
    from .utils import function_utils as fu

    cfg = _load_fleet_config(args.config)
    n_members = int(
        args.members if args.members is not None
        else cfg.get("members", 2)
    )
    if n_members < 1:
        p.error("--members must be >= 1")
    base_dir = os.path.abspath(args.base_dir)
    member_root = os.path.join(base_dir, "members")
    member_dirs = [
        os.path.join(member_root, f"m{i}") for i in range(n_members)
    ]
    for d in member_dirs:
        os.makedirs(d, exist_ok=True)
    server_cfg_path = None
    if cfg.get("server"):
        server_cfg_path = os.path.join(base_dir, "member_config.json")
        fu.atomic_write_json(server_cfg_path, cfg["server"])

    procs = {}
    procs_lock = threading.Lock()

    def spawn(name: str, mdir: str):
        """Start (or restart) one member server subprocess; returns its
        pid.  Used at boot AND as the gateway's no-survivor respawn
        callback — the fresh server's own boot replay finishes the
        journal it is booting on."""
        cmd = [
            sys.executable, "-m", "cluster_tools_tpu.serve",
            "--base-dir", mdir,
        ]
        if server_cfg_path:
            cmd += ["--config", server_cfg_path]
        if args.tpu:
            cmd += ["--tpu"]
        proc = subprocess.Popen(cmd)
        with procs_lock:
            procs[name] = proc
        return proc.pid

    fenced_seen = set()

    def reap_loop():
        """Collect member exit statuses so dead members never zombie —
        death detection itself is the gateway's (healthz + heartbeat +
        pid liveness).  A FENCED exit (rc 115) is surfaced distinctly:
        that member's journal was adopted by a survivor while it was
        wedged, and it must NOT be respawned onto the same base dir."""
        while not stop_reaping.is_set():
            with procs_lock:
                live = list(procs.items())
            for name, proc in live:
                rc = proc.poll()
                if rc == FENCED_EXIT_CODE and name not in fenced_seen:
                    fenced_seen.add(name)
                    print(
                        f"member {name} exited FENCED (rc {rc}): journal "
                        "adopted by a survivor; not respawning",
                        flush=True,
                    )
            stop_reaping.wait(1.0)

    for d in member_dirs:
        spawn(os.path.basename(d), d)
    # wait for each member's endpoint file to name its CURRENT pid (a
    # stale file from a previous incarnation must not fake a live boot)
    boot_deadline = time.monotonic() + 120.0
    for d in member_dirs:
        name = os.path.basename(d)
        while True:
            doc = fu.read_json_if_valid(
                os.path.join(d, ENDPOINT_FILENAME)
            )
            with procs_lock:
                proc = procs[name]
            if doc and doc.get("pid") == proc.pid:
                break
            if proc.poll() is not None:
                print(f"member {name} died during boot "
                      f"(rc {proc.returncode})", file=sys.stderr)
                return 1
            if time.monotonic() > boot_deadline:
                print(f"member {name} did not bind in time",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)

    gw_cfg = dict(cfg.get("gateway") or {})
    gateway = FleetGateway(
        base_dir=base_dir,
        member_dirs=member_dirs,
        port=args.port,
        affinity=bool(gw_cfg.get("affinity", True)),
        health_interval_s=float(gw_cfg.get("health_interval_s", 1.0)),
        member_stale_s=float(gw_cfg.get("member_stale_s", 6.0)),
        max_member_queue=int(gw_cfg.get("max_member_queue", 64)),
        call_timeout_s=float(gw_cfg.get("call_timeout_s", 10.0)),
        failover=str(gw_cfg.get("failover", "adopt")),
        spawn=spawn,
        # gray-failure knobs (docs/SERVING.md "Gray failures")
        breaker_threshold=int(gw_cfg.get("breaker_threshold", 2)),
        breaker_cooldown_s=float(gw_cfg.get("breaker_cooldown_s", 2.0)),
        hedge=bool(gw_cfg.get("hedge", True)),
        hedge_min_delay_s=float(gw_cfg.get("hedge_min_delay_s", 0.05)),
        hedge_max_delay_s=float(gw_cfg.get("hedge_max_delay_s", 2.0)),
    )
    stop_reaping = threading.Event()
    reaper = threading.Thread(target=reap_loop, name="fleet-reaper",
                              daemon=True)
    reaper.start()
    install_drain_handler()
    gateway.start()
    print(
        f"fleet gateway on {gateway.host}:{gateway.port} "
        f"(base_dir={base_dir}, members={n_members}, "
        f"failover={gateway.failover})",
        flush=True,
    )
    try:
        gateway.serve_until_drained()
    except DrainInterrupt as e:
        # CT006/CT012: a drained fleet is a requeue, not a crash — drain
        # every member through the standard SIGTERM protocol (each exits
        # REQUEUE_EXIT_CODE) and exit the same way ourselves
        stop_reaping.set()
        with procs_lock:
            live = dict(procs)
        for name, proc in live.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in live.items():
            try:
                rc = proc.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
            print(f"member {name} exited rc {rc}", flush=True)
        print(
            f"DRAINED ({e.reason}); exiting {REQUEUE_EXIT_CODE} for requeue",
            flush=True,
        )
        return REQUEUE_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
