from .chunk_cache import (
    ChunkCache,
    cache_enabled as chunk_cache_enabled,
    get_chunk_cache,
)
from .containers import (
    ChunkCorruptionError,
    H5Container,
    MemoryContainer,
    ZarrContainer,
    checksums_enabled,
    open_container,
)
from .verified import (
    MissingSidecarError,
    ProductCorruptionError,
    mark_product,
)
