from .containers import (
    ChunkCorruptionError,
    H5Container,
    MemoryContainer,
    ZarrContainer,
    checksums_enabled,
    open_container,
)
