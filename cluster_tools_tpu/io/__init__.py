from .containers import open_container, ZarrContainer, H5Container, MemoryContainer
