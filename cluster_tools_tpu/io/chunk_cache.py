"""Process-wide decompressed-chunk cache with single-flight loads.

The storage-amplification problem (docs/PERFORMANCE.md "Chunk-aware I/O"):
halo'd block reads overlap their neighbors' chunks, so every boundary chunk
of a sweep is read — and *decompressed* — once per neighboring block.  At
the BASELINE config-2 geometry (64^3 inner blocks, halo=32, chunks =
block_shape) each outer read covers 3^3 = 27 chunks for 1 chunk of inner
volume, and interior chunks are decompressed up to 27 times per sweep.
Bytes-read-from-storage, not compute, then dominates the IO-bound stages.

This module is the fix: a byte-bounded, process-wide LRU of *decompressed*
chunks keyed by ``(dataset, chunk_index)``.  ``Dataset.__getitem__`` /
``read_async`` (:mod:`.containers`) assemble halo'd region reads from cached
chunks and send only miss-chunks to tensorstore.  Two properties matter as
much as the LRU itself:

- **Single-flight**: concurrent loads of the same chunk (the executor's IO
  pool reads many overlapping halos at once) share ONE in-flight storage
  read.  The first caller becomes the *owner* and performs the read; later
  callers *wait* on the owner's completion instead of racing a duplicate
  read (counted as ``coalesced``).
- **Coherence**: writes evict every overlapping chunk (after any injected
  silent corruption has landed, so the cache never shadows what storage
  holds), and a read that fails — an injected ``io_read`` fault, a storage
  error, or a checksum mismatch against the PR-3 digest sidecars — never
  populates the cache (corrupt assemblies are evicted before the error
  propagates).  ``verify_region`` / the executor's ``region_verifier``
  re-read raw storage bytes, bypassing the cache, so post-store integrity
  checks always see the disk.

Budget: ``CTT_CHUNK_CACHE_BYTES`` sets the byte bound explicitly; the
default is ``min(1 GiB, MemAvailable/8)`` via the same
:func:`~cluster_tools_tpu.runtime.supervision.host_mem_available_bytes`
probe that drives PR-4's admission control — and the executor's automatic
``inflight_byte_budget`` subtracts this cache budget, so cache + in-flight
batches together stay inside the headroom envelope.  ``CTT_CHUNK_CACHE=0``
is the kill switch: reads bypass the cache entirely (counted as
``direct_reads``) and behave exactly as before this layer existed.

Counters (``hits`` / ``misses`` / ``coalesced`` / ``evictions`` /
``invalidations`` / ``bytes_from_storage`` / ``bytes_served`` /
``direct_reads``) are process-wide; the task runtime snapshots them around
each task and writes the per-task delta to ``io_metrics.json`` next to
``failures.json`` (rendered by ``scripts/failures_report.py``), and
``bench.py --io`` records the cache-off vs cache-on amplification.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

#: counter names, fixed so snapshots/deltas stay schema-stable
STAT_KEYS = (
    "hits",
    "misses",
    "coalesced",
    "evictions",
    "invalidations",
    "bytes_from_storage",
    "bytes_served",
    "direct_reads",
    "stall_fallbacks",
)


class ChunkWaitTimeout(Exception):
    """A coalesced waiter outlived its patience for a shared in-flight
    load (:func:`stall_wait_s`): the underlying storage read is stalled.
    Callers fall back to an independent direct read so hung storage cannot
    serialize every consumer of one chunk behind it — in particular the
    hang defense's speculative re-execution must make progress that is
    independent of the read it is routing around."""


def cache_enabled() -> bool:
    """Chunk caching on stored-region reads (default on);
    ``CTT_CHUNK_CACHE=0`` is the kill switch — every read goes straight to
    storage, exactly the pre-cache behavior."""
    return os.environ.get("CTT_CHUNK_CACHE", "1").lower() not in (
        "0", "false", "off",
    )


def stall_wait_s() -> float:
    """Patience for a coalesced wait on a shared in-flight chunk load
    before falling back to an independent read (``CTT_CHUNK_CACHE_WAIT_S``,
    default 30 s — generous for healthy storage, finite for a wedged
    filesystem call)."""
    try:
        return float(os.environ.get("CTT_CHUNK_CACHE_WAIT_S", "30"))
    except ValueError:
        return 30.0


def _default_budget() -> int:
    env = os.environ.get("CTT_CHUNK_CACHE_BYTES")
    if env:
        return max(0, int(env))
    avail = None
    try:
        from ..runtime.supervision import host_mem_available_bytes

        avail = host_mem_available_bytes()
    except Exception:  # pragma: no cover - probe is /proc-based
        avail = None
    if avail:
        return int(min(1 << 30, avail // 8))
    return 256 << 20


class _InFlight:
    """One in-flight chunk load shared by its owner and any waiters."""

    __slots__ = ("event", "value", "exc", "doomed")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.exc: Optional[BaseException] = None
        # a write raced this load: serve the value to waiters but do NOT
        # cache it (the bytes read may predate the write)
        self.doomed = False


class ChunkCache:
    """Byte-bounded LRU of decompressed chunk arrays + single-flight loads.

    The protocol is a three-way ``get_or_begin``: ``HIT`` returns the cached
    array, ``OWNER`` hands the caller a token — it must perform the storage
    read and settle the token with :meth:`complete` or :meth:`fail` (waiters
    block on it) — and ``WAIT`` hands back another owner's token to
    :meth:`wait` on.  Cached arrays are shared read-only; callers must copy
    out of them, never mutate them.
    """

    HIT, OWNER, WAIT = "hit", "owner", "wait"

    def __init__(self, max_bytes: Optional[int] = None):
        self.max_bytes = int(
            _default_budget() if max_bytes is None else max_bytes
        )
        self._lock = threading.Lock()
        self._data: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._bytes = 0
        self._inflight: Dict[tuple, _InFlight] = {}
        self.stats: Dict[str, int] = {k: 0 for k in STAT_KEYS}

    # -- single-flight protocol -------------------------------------------
    def get_or_begin(self, key: tuple):
        """(HIT, array) | (OWNER, token) | (WAIT, token) for ``key``."""
        with self._lock:
            arr = self._data.get(key)
            if arr is not None:
                self._data.move_to_end(key)
                self.stats["hits"] += 1
                return self.HIT, arr
            inf = self._inflight.get(key)
            if inf is not None:
                self.stats["coalesced"] += 1
                return self.WAIT, inf
            inf = _InFlight()
            self._inflight[key] = inf
            self.stats["misses"] += 1
            return self.OWNER, inf

    def complete(self, key: tuple, token: _InFlight, value: np.ndarray):
        """Owner's storage read landed: publish to waiters and cache it
        (unless a concurrent write doomed the load or it exceeds the
        budget)."""
        value = np.asarray(value)
        with self._lock:
            self.stats["bytes_from_storage"] += int(value.nbytes)
            if (
                not token.doomed
                and 0 < value.nbytes <= self.max_bytes
            ):
                old = self._data.pop(key, None)
                if old is not None:
                    self._bytes -= int(old.nbytes)
                self._data[key] = value
                self._bytes += int(value.nbytes)
                while self._bytes > self.max_bytes and self._data:
                    _, evicted = self._data.popitem(last=False)
                    self._bytes -= int(evicted.nbytes)
                    self.stats["evictions"] += 1
            self._inflight.pop(key, None)
            token.value = value
        token.event.set()

    def fail(self, key: tuple, token: _InFlight, exc: BaseException):
        """Owner's storage read failed: propagate to waiters, cache nothing."""
        with self._lock:
            self._inflight.pop(key, None)
            token.exc = exc
        token.event.set()

    @staticmethod
    def wait(
        token: _InFlight, timeout: Optional[float] = None
    ) -> np.ndarray:
        """Block until the shared load settles; raises the owner's storage
        error, or :class:`ChunkWaitTimeout` after ``timeout`` seconds (the
        caller then reads independently)."""
        if not token.event.wait(timeout):
            raise ChunkWaitTimeout()
        if token.exc is not None:
            raise token.exc
        return token.value

    # -- coherence ---------------------------------------------------------
    def invalidate(self, keys: Iterable[tuple]) -> None:
        """Evict ``keys``; in-flight loads of them are doomed (served to
        their waiters but not cached) — a racing read must not publish
        pre-write bytes."""
        with self._lock:
            for key in keys:
                arr = self._data.pop(key, None)
                if arr is not None:
                    self._bytes -= int(arr.nbytes)
                    self.stats["invalidations"] += 1
                inf = self._inflight.get(key)
                if inf is not None:
                    inf.doomed = True

    def invalidate_dataset(self, dataset_id) -> None:
        """Evict every chunk of one dataset (un-regionable writes)."""
        with self._lock:
            hits = [k for k in self._data if k[0] == dataset_id]
            for key in hits:
                self._bytes -= int(self._data.pop(key).nbytes)
                self.stats["invalidations"] += 1
            for key, inf in self._inflight.items():
                if key[0] == dataset_id:
                    inf.doomed = True

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0

    # -- accounting --------------------------------------------------------
    def record_served(self, nbytes: int) -> None:
        with self._lock:
            self.stats["bytes_served"] += int(nbytes)

    def record_direct(self, nbytes: int) -> None:
        """An uncached region read (kill switch, fancy indexing, chunkless
        dataset): bytes from storage == bytes served, by definition."""
        with self._lock:
            self.stats["direct_reads"] += 1
            self.stats["bytes_from_storage"] += int(nbytes)
            self.stats["bytes_served"] += int(nbytes)

    def record_stall_fallback(self, nbytes: int) -> None:
        """A waiter timed out on a stalled shared load and read the chunk
        independently (:class:`ChunkWaitTimeout`)."""
        with self._lock:
            self.stats["stall_fallbacks"] += 1
            self.stats["bytes_from_storage"] += int(nbytes)
        # a stall fallback is exactly the silent latency event the unified
        # timeline exists to surface (docs/OBSERVABILITY.md): mark it as an
        # instant so the wedged storage read is visible next to the block
        # whose patience it burned
        from ..runtime import trace as trace_mod

        trace_mod.instant("chunk_cache.stall_fallback", nbytes=int(nbytes))

    @property
    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)


# -- module-level singleton ---------------------------------------------------

_cache: Optional[ChunkCache] = None
_singleton_lock = threading.Lock()


def get_chunk_cache() -> ChunkCache:
    """The process-wide cache (budget from ``CTT_CHUNK_CACHE_BYTES`` /
    MemAvailable at first use)."""
    global _cache
    if _cache is None:
        with _singleton_lock:
            if _cache is None:
                _cache = ChunkCache()
    return _cache


def configure(max_bytes: Optional[int] = None) -> ChunkCache:
    """Install a fresh cache (tests / bench A-B runs): empties the cache
    and zeroes the counters."""
    global _cache
    with _singleton_lock:
        _cache = ChunkCache(max_bytes)
    return _cache


def snapshot() -> Dict[str, int]:
    """Copy of the process-wide counters — pair with :func:`delta` to
    attribute IO to one task/run."""
    cache = get_chunk_cache()
    with cache._lock:
        return dict(cache.stats)


def delta(snap: Dict[str, int]) -> Dict[str, int]:
    """Counter movement since ``snap`` (non-negative; a ``configure``
    between snapshots clamps to the new totals)."""
    cache = get_chunk_cache()
    with cache._lock:
        cur = dict(cache.stats)
    return {k: max(0, cur.get(k, 0) - snap.get(k, 0)) for k in STAT_KEYS}
