"""Chunked-array containers: the framework's inter-stage data plane.

The reference used z5py (C++ N5/zarr bindings) plus h5py as its entire
inter-job data plane (SURVEY.md §2d).  Here the same role is played by
**tensorstore** (Google's C++ chunked-array library, zarr + N5 drivers) with
h5py for HDF5 inputs, behind one small uniform API:

    f = open_container("/data/seg.n5")          # or .zarr / .h5
    ds = f.create_dataset("labels", shape=..., chunks=..., dtype="uint64")
    ds[bb] = block          # numpy in / numpy out
    arr = ds[bb]

Datasets are addressed by key (group paths like ``volumes/raw`` work).
``__getitem__``/``__setitem__`` are synchronous numpy round-trips;
``read_async``/``write_async`` return storage-level futures, consumed by the
bounded-window pipelines in :mod:`cluster_tools_tpu.io.prefetch` and by
``BlockwiseExecutor``'s batch assembly.

Data integrity (docs/ROBUSTNESS.md "Silent failures"): every stored block
region gets a CRC32 digest *sidecar* (``<dataset>/.ctt_checksums/`` for
zarr/N5, in-memory for ``memory://``), written after the data lands.  Reads
whose bounding box exactly matches a recorded region are verified against
the digest; a mismatch raises the typed :class:`ChunkCorruptionError`, which
the executor treats as a retriable-then-repairable fault (re-store, or
recompute the owning block through the same compiled kernel).  Writes that
overlap a recorded region invalidate its stale digest.  The async
``read_async``/``write_async`` paths verify/record on ``.result()`` — the
same sites and accounting as the synchronous paths, so prefetched IO is not
a hole in the fault model.  ``CTT_CHECKSUMS=0`` disables the whole layer
(HDF5 never has it: a single shared file has no place for per-region
sidecars).

Chunk-aware reads (docs/PERFORMANCE.md "Chunk-aware I/O"): tensorstore
``Dataset`` region reads are assembled from the process-wide decompressed-
chunk cache (:mod:`.chunk_cache`) — only miss-chunks hit storage, with
single-flight deduplication across concurrent halo reads.  Writes evict
every overlapping chunk; faulted or corruption-failing reads never leave
chunks resident; ``verify_region`` and the raw ``_read_back`` path bypass
the cache so integrity checks always see storage bytes.  ``CTT_CHUNK_CACHE=0``
restores the direct-read behavior exactly.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import zlib
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from . import chunk_cache as _chunk_cache

try:
    import tensorstore as ts
except ImportError:  # pragma: no cover - tensorstore is expected in this image
    ts = None

try:
    import h5py
except ImportError:  # pragma: no cover
    h5py = None


_ZARR_EXTS = (".zarr", ".zr", ".n5")
_H5_EXTS = (".h5", ".hdf5", ".hdf")

_faults_mod = None


def _faults():
    global _faults_mod
    if _faults_mod is None:
        from ..runtime import faults as _fm

        _faults_mod = _fm
    return _faults_mod


def _inject(site: str, voxels: Optional[int] = None) -> Optional[int]:
    """Fault-injection hook for the container IO layer (sites ``io_read`` /
    ``io_write``; see runtime/faults.py).  A no-op unless an injector is
    configured — chaos tests exercise the executor's load/store retries
    against storage-level failures through this.  The block id is inherited
    from the executor's thread-local :func:`~...runtime.faults.block_context`
    and returned so async completions can reuse it.  ``voxels`` (the write's
    element count, when the caller knows it) feeds the ``min_voxels`` gate
    of resource faults — full-size writes fail, split sub-writes fit."""
    fm = _faults()
    block_id = fm.current_block_id()
    fm.get_injector().maybe_fail(site, block_id, voxels=voxels)
    return block_id


def _hang(site: str, block_id: Optional[int]) -> None:
    _faults().get_injector().maybe_hang(site, block_id)


def checksums_enabled() -> bool:
    """Digest sidecars on stored regions (default on); ``CTT_CHECKSUMS=0``
    is the kill switch for workloads where the extra sidecar IO hurts."""
    return os.environ.get("CTT_CHECKSUMS", "1").lower() not in (
        "0", "false", "off",
    )


class ChunkCorruptionError(RuntimeError):
    """A stored region's bytes no longer match its digest sidecar: the data
    was corrupted *on storage* after a successful write (bit rot, torn
    chunk, misbehaving storage layer).  The executor treats this as
    retriable (re-read), then repairable (re-store / recompute the owning
    block through the same compiled kernel)."""

    def __init__(self, label: str, region, expected, actual):
        self.label = label
        self.region = tuple(region)
        self.expected = expected
        self.actual = actual
        super().__init__(
            f"chunk corruption in {label} region "
            + "x".join(f"[{a}:{b}]" for a, b in self.region)
            + f": stored digest {expected}, read digest {actual}"
        )


def _norm_region(bb, shape) -> Optional[Tuple[Tuple[int, int], ...]]:
    """Resolve a numpy-style index to ``((start, stop), ...)`` per axis, or
    None when it is not a plain step-1 slice box (fancy/int indexing has no
    region identity to checksum)."""
    if bb is Ellipsis:
        return tuple((0, int(s)) for s in shape)
    if isinstance(bb, slice):
        bb = (bb,)
    if not isinstance(bb, tuple):
        return None
    if any(b is Ellipsis for b in bb):
        i = next(j for j, b in enumerate(bb) if b is Ellipsis)
        bb = bb[:i] + (slice(None),) * (len(shape) - len(bb) + 1) + bb[i + 1:]
    if len(bb) < len(shape):
        bb = bb + (slice(None),) * (len(shape) - len(bb))
    if len(bb) != len(shape):
        return None
    out = []
    for sl, s in zip(bb, shape):
        if not isinstance(sl, slice) or sl.step not in (None, 1):
            return None
        start, stop, _ = sl.indices(int(s))
        out.append((int(start), max(int(start), int(stop))))
    return tuple(out)


def _region_shape(region) -> Tuple[int, ...]:
    return tuple(b - a for a, b in region)


def _regions_overlap(r1, r2) -> bool:
    return len(r1) == len(r2) and all(
        a1 < b2 and a2 < b1 for (a1, b1), (a2, b2) in zip(r1, r2)
    )


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


class _ChecksumIndex:
    """Digest sidecars for stored regions: one tiny JSON per region under
    ``<dataset>/.ctt_checksums/`` (filesystem containers) or an in-memory
    dict (``memory://``).  Per-region files keep parallel block writers
    conflict-free — the same reason block writes must tile whole chunks.

    The set of on-disk region keys is cached per index (seeded by ONE
    ``listdir`` on first write, then maintained incrementally), so the
    overlap-invalidation scan is an in-memory set walk instead of a
    directory listing per block write — a run storing N blocks would
    otherwise pay O(N^2) filesystem work.  Regions recorded by *other*
    handles after seeding are invisible to the scan; that only matters for
    concurrently-overlapping writers, which the chunk-alignment contract
    already forbids."""

    def __init__(self, dirpath: Optional[str] = None):
        self._dir = dirpath
        self._mem: Optional[Dict] = {} if dirpath is None else None
        self._fs_keys: Optional[set] = None  # lazy on-disk region cache
        self._lock = threading.Lock()

    def _known_regions(self) -> set:
        """Cached set of regions with an on-disk sidecar (call under
        ``_lock``); seeded once from the directory."""
        if self._fs_keys is None:
            keys = set()
            if self._dir is not None and os.path.isdir(self._dir):
                for fname in os.listdir(self._dir):
                    r = self._parse(fname)
                    if r is not None:
                        keys.add(r)
            self._fs_keys = keys
        return self._fs_keys

    @staticmethod
    def _key(region) -> str:
        return "r_" + "_".join(f"{a}-{b}" for a, b in region)

    @staticmethod
    def _parse(name: str):
        if not (name.startswith("r_") and name.endswith(".json")):
            return None
        try:
            return tuple(
                (int(p.split("-")[0]), int(p.split("-")[1]))
                for p in name[2:-len(".json")].split("_")
            )
        except (ValueError, IndexError):
            return None

    def record(self, region, value: np.ndarray) -> None:
        entry = {
            "algo": "crc32",
            "crc": _crc(value),
            "dtype": value.dtype.str,
            "shape": list(value.shape),
        }
        self.invalidate_overlaps(region)
        if self._mem is not None:
            with self._lock:
                self._mem[region] = entry
            return
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(self._dir, self._key(region) + ".json")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(entry, f)
        os.replace(tmp, path)
        with self._lock:
            self._known_regions().add(region)

    def lookup(self, region) -> Optional[Dict]:
        if self._mem is not None:
            with self._lock:
                return self._mem.get(region)
        path = os.path.join(self._dir, self._key(region) + ".json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def invalidate_overlaps(self, region) -> None:
        """Drop digests of regions intersecting ``region`` — a partial
        overwrite makes them stale, and a stale digest would turn a later
        valid read into a false corruption alarm.  Walks the cached key
        set, not the directory (see class docstring)."""
        if self._mem is not None:
            with self._lock:
                for r in [r for r in self._mem if _regions_overlap(r, region)]:
                    del self._mem[r]
            return
        if self._dir is None:
            return
        with self._lock:
            known = self._known_regions()
            hits = [r for r in known if _regions_overlap(r, region)]
            for r in hits:
                known.discard(r)
        for r in hits:
            try:
                os.unlink(os.path.join(self._dir, self._key(r) + ".json"))
            except OSError:
                pass

    def drop(self, region) -> None:
        """Delete ONE region's sidecar (the injected sidecar-loss fault
        rides this; stale-overlap semantics stay with
        :meth:`invalidate_overlaps`)."""
        if self._mem is not None:
            with self._lock:
                self._mem.pop(region, None)
            return
        if self._dir is None:
            return
        with self._lock:
            self._known_regions().discard(region)
        try:
            os.unlink(os.path.join(self._dir, self._key(region) + ".json"))
        except OSError:
            pass

    def regions(self) -> list:
        """Every region with a recorded sidecar.  Filesystem indexes
        answer from the DIRECTORY — the scrubber's work list must see
        sidecars written by other processes/handles, not this handle's
        incremental cache — memory indexes from the dict."""
        if self._mem is not None:
            with self._lock:
                return list(self._mem)
        out = []
        if self._dir is not None and os.path.isdir(self._dir):
            for fname in os.listdir(self._dir):
                r = self._parse(fname)
                if r is not None:
                    out.append(r)
        return out


# async completion hooks (verify / record digest) ride on prefetch's
# future-mapping adapter — the async IO paths stay inside the same fault
# model as the sync ones, at the moment the data is actually consumed
from .prefetch import _MappedFuture as _WrappedFuture  # noqa: E402


class _ChecksumOps:
    """Shared digest behavior for datasets that support it.  Subclasses
    provide ``_read_back(bb)`` (raw region read, no injection) and
    ``_write_raw(bb, value)`` (raw write, no sidecar) plus ``_checksums``
    and ``_label`` attributes."""

    #: read-site tag carried into typed ``corrupt:<site>`` errors
    #: (io/verified.py): "storage" for stored datasets, "memory" for the
    #: in-memory container, "handoff" for live handoff targets, "spill"
    #: for a handoff's storage spill copy (stamped by ``spill()``)
    _read_site = "storage"

    def _read_back(self, bb) -> np.ndarray:  # pragma: no cover - abstract
        raise NotImplementedError

    def _write_raw(self, bb, value) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _after_write(self, bb, value: np.ndarray, block_id) -> None:
        """Record the region digest, then apply any injected silent
        corruption (bit-flip the stored bytes — or delete the fresh
        sidecar, ``mode='sidecar'`` — so only checksum verification, or
        its absence, can tell)."""
        region = _norm_region(bb, self.shape)
        if region is not None and checksums_enabled():
            if value.shape == _region_shape(region):
                self._checksums.record(region, value)
            else:
                # broadcast / scalar fill: no digestable identity, but any
                # previous digest for this box is now stale
                self._checksums.invalidate_overlaps(region)
        mode = _faults().get_injector().chunk_corrupt("io_write", block_id)
        if mode == "sidecar":
            if region is not None:
                self._checksums.drop(region)
        elif mode:
            bad = np.ascontiguousarray(value).copy()
            if bad.size and bad.dtype.itemsize:
                bad.reshape(-1).view(np.uint8)[0] ^= 0x01
            self._write_raw(bb, bad)

    def _apply_read_rot(self, bb, block_id) -> None:
        """Injected at-rest damage surfacing at the read site
        (``kind='corrupt'`` at ``io_read``, runtime/faults.py): flip one
        STORED byte of the region (sidecar untouched) or delete its
        sidecar (``mode='sidecar'``) just before the read proceeds — the
        verifying reader must catch the former, the missing-sidecar
        policy decides the latter."""
        mode = _faults().get_injector().chunk_corrupt("io_read", block_id)
        if not mode:
            return
        region = _norm_region(bb, self.shape)
        if region is None:
            return
        if mode == "sidecar":
            self._checksums.drop(region)
            return
        bad = np.ascontiguousarray(self._read_back(bb)).copy()
        if bad.size and bad.dtype.itemsize:
            bad.reshape(-1).view(np.uint8)[0] ^= 0x01
            self._write_raw(bb, bad)
            # the rot lives on STORAGE: resident clean chunks must not
            # shadow it, or the read under test never sees the damage
            inval = getattr(self, "_invalidate_cached_region", None)
            if inval is not None:
                inval(bb)

    def _postread(self, bb, arr: np.ndarray, evict=None) -> np.ndarray:
        """The verifying-reader tail of every region read
        (:mod:`cluster_tools_tpu.io.verified`): digest verification, the
        per-store missing-sidecar policy, and the lineage-repair hook on
        mismatch.  Returns the (possibly repaired and re-read) array;
        raises the typed ``corrupt:<site>`` error when the bytes stay
        bad."""
        from . import verified as _verified

        return _verified.postread(self, bb, arr, evict=evict)

    def _verify_read(self, bb, arr: np.ndarray) -> None:
        if not checksums_enabled():
            return
        region = _norm_region(bb, self.shape)
        if region is None:
            return
        entry = self._checksums.lookup(region)
        if entry is None:
            return
        if (
            list(entry.get("shape", [])) != list(arr.shape)
            or entry.get("dtype") != arr.dtype.str
        ):
            return  # stale sidecar (shape/dtype drifted): not verifiable
        actual = _crc(arr)
        if actual != entry.get("crc"):
            raise ChunkCorruptionError(self._label, region, entry.get("crc"), actual)

    def verify_region(self, bb) -> None:
        """Read back a stored region and check it against its digest
        sidecar; raises :class:`ChunkCorruptionError` on mismatch, no-op
        when no digest exists.  The executor's store path calls this so
        corruption is caught while the writer still holds the clean data
        (retry) or can recompute it (quarantine repair)."""
        if not checksums_enabled():
            return
        region = _norm_region(bb, self.shape)
        if region is None or self._checksums.lookup(region) is None:
            return
        self._verify_read(bb, np.asarray(self._read_back(bb)))

    def checksum_regions(self) -> list:
        """Every region with a recorded digest sidecar — the scrubber's
        work list (``runtime/scrub.py``).  Disk truth for filesystem-
        backed indexes: sidecars written by other handles/processes are
        visible."""
        return self._checksums.regions()

    def checksum_entry(self, bb) -> Optional[Dict]:
        """The digest sidecar entry for ``bb``'s exact region (``crc`` /
        ``dtype`` / ``shape``), or None when unrecorded — lets the
        scrubber budget bytes without reading the data."""
        region = _norm_region(bb, self.shape)
        return None if region is None else self._checksums.lookup(region)


# numpy dtype -> zarr v2 dtype string
def _zarr_dtype(dtype) -> str:
    return np.dtype(dtype).newbyteorder("<").str


def _n5_dtype(dtype) -> str:
    return np.dtype(dtype).name


class _CachedReadPlan:
    """Phase-1 state of a chunk-assembled region read: the resolved region
    plus one (key, chunk_box, kind, handle) step per covering chunk, where
    owned miss-chunks carry their already-issued tensorstore futures."""

    __slots__ = ("region", "steps")

    def __init__(self, region, steps):
        self.region = region
        self.steps = steps


class Dataset(_ChecksumOps):
    """A chunked dataset backed by tensorstore."""

    def __init__(self, store, attrs_path: Optional[str] = None,
                 checksum_dir: Optional[str] = None, label: str = ""):
        self._store = store
        self._attrs_path = attrs_path
        self._checksums = _ChecksumIndex(
            checksum_dir
            if checksum_dir is not None
            else (os.path.join(os.path.dirname(attrs_path), ".ctt_checksums")
                  if attrs_path else None)
        )
        self._label = label or (attrs_path or "<dataset>")
        # chunk-cache identity: the container path + key, stable across
        # handle instances in this process (two open_container calls on the
        # same store must share — and mutually invalidate — cache entries);
        # anonymous store-only datasets fall back to per-instance identity
        self._cache_id = (
            self._label if (label or attrs_path) else f"ts-anon-{id(self)}"
        )

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._store.shape)

    @property
    def dtype(self):
        return np.dtype(self._store.dtype.numpy_dtype)

    @property
    def chunks(self) -> Tuple[int, ...]:
        return tuple(self._store.chunk_layout.read_chunk.shape)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def _read_back(self, bb) -> np.ndarray:
        # raw storage read, no cache: verify_region / region_verifier must
        # check the bytes on DISK, not a resident copy
        return np.asarray(self._store[bb].read().result())

    def _write_raw(self, bb, value) -> None:
        self._store[bb].write(value).result()

    # -- chunk-assembled reads (docs/PERFORMANCE.md "Chunk-aware I/O") ------
    def _chunk_cover(self, region):
        """[(cache_key, chunk_box), ...] covering ``region``, or None when
        the dataset has no usable chunk grid."""
        chunks = self.chunks
        shape = self.shape
        if (
            not chunks
            or len(chunks) != len(shape)
            or any(int(c) <= 0 for c in chunks)
        ):
            return None
        ranges = [
            range(a // c, (b + c - 1) // c) if b > a else range(0)
            for (a, b), c in zip(region, chunks)
        ]
        cover = []
        for idx in itertools.product(*ranges):
            box = tuple(
                (i * c, min((i + 1) * c, s))
                for i, c, s in zip(idx, chunks, shape)
            )
            cover.append(((self._cache_id, idx), box))
        return cover

    def _begin_cached_read(self, bb):
        """Phase 1 (issue) of a cache-assembled read: take a HIT/OWNER/WAIT
        ticket per covering chunk and issue one tensorstore read per owned
        miss-chunk — every miss of the region is in flight together.
        Returns None when the read cannot go through the cache (kill
        switch, zero budget, fancy indexing, chunkless store).

        Owner tokens are settled by a done-callback on the storage future —
        when the READ lands, not when (or whether) anyone resolves the
        plan.  A ``read_async`` future dropped without ``.result()`` (an
        abandoned retry attempt, an early-exiting prefetch consumer) must
        not strand later readers of the same chunks on an unsettled
        in-flight token."""
        if not _chunk_cache.cache_enabled():
            return None
        cache = _chunk_cache.get_chunk_cache()
        if cache.max_bytes <= 0:
            return None
        region = _norm_region(bb, self.shape)
        if region is None:
            return None
        # bulk-read bypass: a region that would consume over half the
        # budget cannot be cached without flushing the resident halo
        # working set the cache exists to keep (and gains nothing from
        # per-chunk assembly) — serve it as one direct storage read
        region_bytes = int(
            np.prod([b - a for a, b in region], dtype=np.int64)
        ) * self.dtype.itemsize
        if region_bytes > cache.max_bytes // 2:
            return None
        cover = self._chunk_cover(region)
        if cover is None:
            return None
        steps = []
        for key, box in cover:
            kind, handle = cache.get_or_begin(key)
            if kind == cache.OWNER:
                cbb = tuple(slice(a, b) for a, b in box)
                try:
                    fut = self._store[cbb].read()
                except Exception as e:
                    cache.fail(key, handle, e)
                    raise

                def _settle(f, key=key, token=handle):
                    try:
                        cache.complete(key, token, np.asarray(f.result()))
                    except Exception as e:
                        cache.fail(key, token, e)

                fut.add_done_callback(_settle)
            steps.append((key, box, kind, handle))
        # an exception mid-loop leaves already-issued owners to their
        # callbacks: every begun token settles itself, no waiter can hang
        return _CachedReadPlan(region, steps)

    def _finish_cached_read(self, plan: _CachedReadPlan) -> np.ndarray:
        """Phase 2 (resolve): wait for the in-flight chunk loads (owned
        ones settle via their storage-future callbacks) and assemble the
        region from chunk slices.  A waiter stalled past the patience
        window (:func:`~cluster_tools_tpu.io.chunk_cache.stall_wait_s`)
        falls back to an independent direct read, so one wedged storage
        call cannot serialize every consumer of a chunk behind it — the
        hang defense's speculative re-execution stays independent of the
        read it is routing around.  The first chunk failure is raised
        after the loop, keeping shared tokens consistent."""
        from ..runtime import trace as trace_mod

        cache = _chunk_cache.get_chunk_cache()
        region = plan.region
        patience = _chunk_cache.stall_wait_s()
        out = np.empty(_region_shape(region), self.dtype)
        first_exc: Optional[BaseException] = None
        # one assembly span per region read (not per chunk — a halo'd read
        # covers dozens): hit/miss/coalesced-wait composition in the args,
        # duration = the storage latency the cache failed to hide
        # (docs/OBSERVABILITY.md).  The composition scans are gated on the
        # tracer so the default-off hot read path stays a true no-op
        if trace_mod.enabled():
            n_hits = sum(
                1 for _k, _b, kind, _h in plan.steps if kind == cache.HIT
            )
            n_waits = sum(
                1 for _k, _b, kind, _h in plan.steps if kind == cache.WAIT
            )
            assemble_span = trace_mod.span(
                "chunk_cache.assemble", n_chunks=len(plan.steps),
                hits=n_hits, misses=len(plan.steps) - n_hits - n_waits,
                waits=n_waits,
            )
        else:
            assemble_span = trace_mod.span("chunk_cache.assemble")
        with assemble_span:
            for key, box, kind, handle in plan.steps:
                if first_exc is not None:
                    # fail fast: owner tokens settle via their storage-future
                    # callbacks regardless, so there is nothing to wait out —
                    # waiting (or stall-fallback-reading) chunks whose bytes
                    # will be discarded only delays the error
                    continue
                try:
                    if kind == cache.HIT:
                        chunk = handle
                    else:
                        try:
                            if kind == cache.WAIT:
                                # the single-flight wait: time spent behind
                                # ANOTHER reader's in-flight storage read
                                with trace_mod.span("chunk_cache.wait"):
                                    chunk = cache.wait(
                                        handle, timeout=patience
                                    )
                            else:
                                chunk = cache.wait(handle, timeout=patience)
                        except _chunk_cache.ChunkWaitTimeout:
                            cbb = tuple(slice(a, b) for a, b in box)
                            chunk = np.asarray(
                                self._store[cbb].read().result()
                            )
                            cache.record_stall_fallback(chunk.nbytes)
                except Exception as e:
                    first_exc = e
                    continue
                src, dst = [], []
                for (ra, rb), (ca, cb) in zip(region, box):
                    lo, hi = max(ra, ca), min(rb, cb)
                    src.append(slice(lo - ca, hi - ca))
                    dst.append(slice(lo - ra, hi - ra))
                out[tuple(dst)] = chunk[tuple(src)]
        if first_exc is not None:
            raise first_exc
        cache.record_served(out.nbytes)
        return out

    def _evict_plan(self, plan: _CachedReadPlan) -> None:
        _chunk_cache.get_chunk_cache().invalidate(
            [key for key, _b, _k, _h in plan.steps]
        )

    def _invalidate_cached_region(self, bb) -> None:
        """Write coherence: drop every cached chunk the write overlaps —
        AFTER the write (and any injected silent corruption) landed, so the
        cache never shadows what storage holds.  Runs even with the kill
        switch flipped: entries cached while it was on must not survive a
        write."""
        cache = _chunk_cache.get_chunk_cache()
        region = _norm_region(bb, self.shape)
        cover = None if region is None else self._chunk_cover(region)
        if cover is None:
            cache.invalidate_dataset(self._cache_id)
            return
        cache.invalidate([key for key, _box in cover])

    def __getitem__(self, bb) -> np.ndarray:
        bid = _inject("io_read")
        _hang("io_read", bid)
        self._apply_read_rot(bb, bid)
        plan = self._begin_cached_read(bb)
        if plan is None:
            arr = np.asarray(self._store[bb].read().result())
            _chunk_cache.get_chunk_cache().record_direct(arr.nbytes)
            return self._postread(bb, arr)
        arr = self._finish_cached_read(plan)
        # a failed digest verify must not leave the bad chunks resident:
        # the verifying reader evicts before attempting lineage repair
        return self._postread(bb, arr, evict=lambda: self._evict_plan(plan))

    def __setitem__(self, bb, value) -> None:
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        value = np.asarray(value, dtype=self.dtype)
        try:
            self._store[bb].write(value).result()
            self._after_write(bb, value, bid)
        finally:
            # in a finally: a write that RAISES may still have landed some
            # chunks (partial multi-chunk store, ENOSPC mid-region, sidecar
            # failure after the data landed) — stale pre-write entries must
            # not outlive any of those either
            self._invalidate_cached_region(bb)

    def read_async(self, bb):
        """Start an async read; returns a future with ``.result()`` -> numpy.
        Injection fires at issue (same accounting as ``__getitem__``);
        digest verification runs on ``.result()``, where the data lands.
        Cache-assembled reads issue their miss-chunk storage reads at call
        time (so a batch's chunk IO is in flight together) and assemble +
        verify on ``.result()``."""
        bid = _inject("io_read")
        self._apply_read_rot(bb, bid)
        plan = self._begin_cached_read(bb)
        if plan is None:
            fut = self._store[bb].read()

            def finish(raw):
                _hang("io_read", bid)
                arr = np.asarray(raw)
                _chunk_cache.get_chunk_cache().record_direct(arr.nbytes)
                return self._postread(bb, arr)

            return _WrappedFuture(fut, finish)

        def finish_cached(_):
            _hang("io_read", bid)
            arr = self._finish_cached_read(plan)
            return self._postread(
                bb, arr, evict=lambda: self._evict_plan(plan)
            )

        return _WrappedFuture(_ImmediateFuture(None), finish_cached)

    def write_async(self, bb, value):
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        value = np.asarray(value, dtype=self.dtype)
        fut = self._store[bb].write(value)
        # evict when the STORAGE write lands, not when (or whether) the
        # caller resolves the future — an abandoned write_async must not
        # leave stale pre-write chunks resident (the write-side twin of
        # the read path's owner-token callbacks)
        fut.add_done_callback(lambda _f: self._invalidate_cached_region(bb))

        def finish(_):
            _hang("io_write", bid)
            try:
                # resolve the storage write INSIDE the guarded region: a
                # failed multi-chunk write may still have landed some
                # chunks, and the sidecar/corruption hook can raise after
                # the data landed — stale entries must survive neither
                fut.result()
                self._after_write(bb, value, bid)
            finally:
                self._invalidate_cached_region(bb)
            return None

        return _WrappedFuture(_ImmediateFuture(None), finish)

    # -- attributes (json sidecar, mirroring z5py/zarr .zattrs) -------------
    @property
    def attrs(self) -> Dict:
        if self._attrs_path is None or not os.path.exists(self._attrs_path):
            return {}
        with open(self._attrs_path) as f:
            return json.load(f)

    def update_attrs(self, **kwargs) -> None:
        if self._attrs_path is None:
            raise RuntimeError("dataset has no attribute store")
        attrs = self.attrs
        attrs.update(kwargs)
        # atomic: a kill mid-write must not tear the sidecar (it is shared
        # with external zarr/N5 readers)
        tmp = f"{self._attrs_path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(attrs, f, indent=2, default=_json_default)
        os.replace(tmp, self._attrs_path)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not json-serializable: {type(o)}")


class _ImmediateFuture:
    """Future-shim for backends whose reads/writes complete synchronously."""

    def __init__(self, v):
        self._v = v

    def result(self):
        return self._v


def _clamp_chunks(chunks, shape):
    """Chunks capped at the dataset shape — the creation rule, reused by
    existing-dataset validation so both paths compare like for like."""
    return tuple(int(min(c, s)) for c, s in zip(chunks, shape))


def _check_existing(
    key, have_shape, have_dtype, want_shape, want_dtype,
    have_chunks=None, want_chunks=None,
):
    if tuple(have_shape) != tuple(int(s) for s in want_shape) or np.dtype(
        have_dtype
    ) != np.dtype(want_dtype):
        raise ValueError(
            f"dataset {key!r} exists with shape {tuple(have_shape)} / dtype "
            f"{np.dtype(have_dtype)}, requested {tuple(want_shape)} / "
            f"{np.dtype(want_dtype)}"
        )
    if have_chunks is None or want_chunks is None:
        return
    have_chunks = tuple(int(c) for c in have_chunks)
    want_chunks = tuple(int(c) for c in want_chunks)
    # race safety (SURVEY.md §5.2): parallel block writes are conflict-free
    # only when every written block tiles whole chunks — i.e. the requested
    # block grid is a per-axis integer multiple of the existing chunks.
    # Finer-than-existing blocks would share chunks between writers.
    if len(have_chunks) != len(want_chunks) or any(
        w % h for w, h in zip(want_chunks, have_chunks)
    ):
        raise ValueError(
            f"dataset {key!r} exists with chunks {have_chunks}, requested "
            f"{want_chunks} — blocks must tile whole chunks (per-axis "
            "integer multiples) for chunk-aligned parallel writes; use a "
            "matching block_shape or a fresh dataset"
        )



class ZarrContainer:
    """A zarr (v2) or N5 container on the local filesystem, via tensorstore."""

    def __init__(self, path: str, mode: str = "a"):
        if ts is None:
            raise ImportError("tensorstore is required for zarr/n5 containers")
        self.path = os.path.abspath(path)
        self.mode = mode
        self.is_n5 = self.path.endswith(".n5")
        self._cache: Dict[str, Dataset] = {}
        self._lock = threading.Lock()
        if mode != "r":
            os.makedirs(self.path, exist_ok=True)
            marker = os.path.join(
                self.path, "attributes.json" if self.is_n5 else ".zgroup"
            )
            if not os.path.exists(marker):
                # atomic (CT002): concurrent jobs opening the same container
                # race this creation; a reader must see a whole marker
                tmp = f"{marker}.tmp.{os.getpid()}.{threading.get_ident()}"
                with open(tmp, "w") as f:
                    json.dump(
                        {"n5": "2.0.0"} if self.is_n5 else {"zarr_format": 2}, f
                    )
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, marker)

    # -- internal ----------------------------------------------------------
    def _spec(self, key: str, metadata: Optional[dict] = None, create: bool = False):
        spec = {
            "driver": "n5" if self.is_n5 else "zarr",
            "kvstore": {"driver": "file", "path": os.path.join(self.path, key)},
            "recheck_cached_data": "open",
        }
        if metadata is not None:
            spec["metadata"] = metadata
        if create:
            spec["create"] = True
            spec["open"] = True
        return spec

    def _attrs_path(self, key: str) -> str:
        fname = "attributes.json" if self.is_n5 else ".zattrs"
        return os.path.join(self.path, key, fname)

    # -- public api --------------------------------------------------------
    def create_dataset(
        self,
        key: str,
        shape: Sequence[int],
        chunks: Sequence[int],
        dtype,
        compression: Optional[str] = "gzip",
        exist_ok: bool = True,
        fill_value: int = 0,
    ) -> Dataset:
        if self.mode == "r":
            raise PermissionError(f"container {self.path} opened read-only")
        shape = [int(s) for s in shape]
        chunks = list(_clamp_chunks(chunks, shape))
        if self.is_n5:
            comp = {"type": compression if compression else "raw"}
            # the N5 spec stores dimensions fastest-varying-first (F-order);
            # we write spec-compliant metadata and present C-order through a
            # tensorstore transpose in _open_store, so z5py/Java-N5 readers
            # see the same axis order as our numpy API
            metadata = {
                "dimensions": shape[::-1],
                "blockSize": chunks[::-1],
                "dataType": _n5_dtype(dtype),
                "compression": comp,
            }
        else:
            comp = (
                {"id": "zlib", "level": 1}
                if compression == "gzip"
                else None
            )
            metadata = {
                "shape": shape,
                "chunks": chunks,
                "dtype": _zarr_dtype(dtype),
                "compressor": comp,
                "fill_value": fill_value,
            }
        try:
            store = self._open_store(key, metadata, create=True)
            # a FRESH dataset now lives at this identity: chunks cached
            # under it belong to a deleted/recreated predecessor (e.g. an
            # output store torn down and rebuilt between in-process runs)
            # and must not be served against the new data
            _chunk_cache.get_chunk_cache().invalidate_dataset(
                f"{self.path}:{key}"
            )
        except ValueError:
            if not exist_ok:
                raise
            store = self._open_store(key)
            _check_existing(
                key, store.shape, store.dtype.numpy_dtype, shape, dtype,
                have_chunks=store.chunk_layout.read_chunk.shape,
                want_chunks=chunks,
            )
        ds = Dataset(store, self._attrs_path(key), label=f"{self.path}:{key}")
        with self._lock:
            self._cache[key] = ds
        return ds

    def _open_store(self, key, metadata=None, create=False):
        store = ts.open(self._spec(key, metadata, create=create)).result()
        if self.is_n5:
            # present C-order over the spec-mandated F-order on-disk layout
            store = store.T
        return store

    def require_dataset(self, key: str, **kwargs) -> Dataset:
        # create_dataset's exist_ok path validates shape/dtype of an existing
        # dataset against the request, which a bare self[key] would skip
        return self.create_dataset(key, exist_ok=True, **kwargs)

    def __getitem__(self, key: str) -> Dataset:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        store = self._open_store(key)
        ds = Dataset(store, self._attrs_path(key), label=f"{self.path}:{key}")
        with self._lock:
            self._cache[key] = ds
        return ds

    def __contains__(self, key: str) -> bool:
        d = os.path.join(self.path, key)
        if self.is_n5:
            return os.path.exists(os.path.join(d, "attributes.json"))
        return os.path.exists(os.path.join(d, ".zarray"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def close(self):
        pass


class _H5Dataset:
    """Adapter giving h5py datasets the same surface as :class:`Dataset`.
    No digest sidecars (one shared .h5 file has no safe place for per-region
    metadata under parallel writers), so no ``verify_region`` — callers
    probe for the attribute."""

    def __init__(self, ds):
        self._ds = ds

    shape = property(lambda self: tuple(self._ds.shape))
    dtype = property(lambda self: self._ds.dtype)
    ndim = property(lambda self: self._ds.ndim)

    @property
    def chunks(self):
        return tuple(self._ds.chunks) if self._ds.chunks else tuple(self._ds.shape)

    def __getitem__(self, bb):
        bid = _inject("io_read")
        _hang("io_read", bid)
        return self._ds[bb]

    def __setitem__(self, bb, value):
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        self._ds[bb] = value

    def read_async(self, bb):
        bid = _inject("io_read")
        _hang("io_read", bid)
        return _ImmediateFuture(self._ds[bb])

    def write_async(self, bb, value):
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        self._ds[bb] = value
        return _ImmediateFuture(None)

    @property
    def attrs(self):
        return dict(self._ds.attrs)

    def update_attrs(self, **kwargs):
        self._ds.attrs.update(kwargs)


class H5Container:
    def __init__(self, path: str, mode: str = "a"):
        if h5py is None:
            raise ImportError("h5py is required for hdf5 containers")
        self.path = path
        self._f = h5py.File(path, mode)

    def create_dataset(self, key, shape, chunks, dtype, compression="gzip", exist_ok=True, fill_value=0):
        if key in self._f:
            if not exist_ok:
                raise ValueError(f"dataset {key} exists")
            ds = self._f[key]
            _check_existing(
                key, ds.shape, ds.dtype, shape, dtype,
                have_chunks=ds.chunks,
                want_chunks=_clamp_chunks(chunks, shape),
            )
            return _H5Dataset(ds)
        ds = self._f.create_dataset(
            key,
            shape=tuple(shape),
            chunks=_clamp_chunks(chunks, shape),
            dtype=dtype,
            compression=compression,
            fillvalue=fill_value,
        )
        return _H5Dataset(ds)

    def require_dataset(self, key, **kwargs):
        return self.create_dataset(key, exist_ok=True, **kwargs)

    def __getitem__(self, key):
        return _H5Dataset(self._f[key])

    def __contains__(self, key):
        return key in self._f

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def close(self):
        self._f.close()


class MemoryContainer:
    """In-memory container (tests and tiny pipelines)."""

    _registry: Dict[str, "MemoryContainer"] = {}
    _registry_lock = threading.Lock()

    def __init__(self, path: str = "", mode: str = "a"):
        self.path = path
        self._data: Dict[str, "_MemDataset"] = {}

    @classmethod
    def open(cls, path: str, mode: str = "a") -> "MemoryContainer":
        with cls._registry_lock:
            if path not in cls._registry:
                cls._registry[path] = cls(path)
            return cls._registry[path]

    def create_dataset(self, key, shape, chunks, dtype, compression=None, exist_ok=True, fill_value=0):
        if key in self._data:
            if not exist_ok:
                raise ValueError(f"dataset {key} exists")
            ds = self._data[key]
            _check_existing(
                key, ds.shape, ds.dtype, shape, dtype,
                have_chunks=ds.chunks, want_chunks=chunks,
            )
            return ds
        ds = _MemDataset(np.full(tuple(shape), fill_value, dtype=dtype), tuple(chunks))
        self._data[key] = ds
        return ds

    def require_dataset(self, key, **kwargs):
        return self.create_dataset(key, exist_ok=True, **kwargs)

    def __getitem__(self, key):
        return self._data[key]

    def __contains__(self, key):
        return key in self._data

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def close(self):
        pass


class _MemDataset(_ChecksumOps):
    _read_site = "memory"

    def __init__(self, arr: np.ndarray, chunks: Tuple[int, ...]):
        self._arr = arr
        self.chunks = chunks
        self._attrs: Dict = {}
        self._checksums = _ChecksumIndex(None)
        self._label = "memory://"

    shape = property(lambda self: self._arr.shape)
    dtype = property(lambda self: self._arr.dtype)
    ndim = property(lambda self: self._arr.ndim)

    def _read_back(self, bb):
        return self._arr[bb].copy()

    def _write_raw(self, bb, value):
        self._arr[bb] = value

    def __getitem__(self, bb):
        bid = _inject("io_read")
        _hang("io_read", bid)
        self._apply_read_rot(bb, bid)
        arr = self._arr[bb].copy()
        return self._postread(bb, arr)

    def __setitem__(self, bb, value):
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        value = np.asarray(value, dtype=self._arr.dtype)
        self._arr[bb] = value
        self._after_write(bb, value, bid)

    def read_async(self, bb):
        bid = _inject("io_read")
        _hang("io_read", bid)
        self._apply_read_rot(bb, bid)
        arr = self._arr[bb].copy()
        return _ImmediateFuture(self._postread(bb, arr))

    def write_async(self, bb, value):
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        value = np.asarray(value, dtype=self._arr.dtype)
        self._arr[bb] = value
        self._after_write(bb, value, bid)
        return _ImmediateFuture(None)

    @property
    def attrs(self):
        return dict(self._attrs)

    def update_attrs(self, **kwargs):
        self._attrs.update(kwargs)


class HandoffDataset(_ChecksumOps):
    """A ``memory://``-backed handoff twin of a chunked storage dataset
    (docs/PERFORMANCE.md "Task-graph fusion").

    Producer tasks write blocks into host RAM through the same numpy
    dataset surface the storage-backed :class:`Dataset` exposes, and
    consumer tasks resolve the live handle through
    :mod:`cluster_tools_tpu.runtime.handoff` instead of opening the store —
    the producer->consumer hop skips the storage round-trip entirely.

    Contracts preserved from the storage path:

    - **fault hooks** — every boundary method carries the ``io_read`` /
      ``io_write`` injection + hang hooks (CT004), so chaos reaches the
      in-memory data plane exactly like the storage one,
    - **integrity** — writes record in-memory CRC32 region digests
      (``verify_region`` / the executor's ``region_verifier`` work
      unchanged, including the injected silent-corruption path),
    - **spill** — :meth:`spill` flushes the array chunk-by-chunk through
      the real dataset's write path (digest sidecars recorded per region,
      each region verified back), then delegates every subsequent access
      to the stored copy and releases the RAM.  After a spill, storage is
      the single source of truth.
    """

    _read_site = "handoff"

    def __init__(self, shape, chunks, dtype, store_factory, label: str,
                 fill_value: int = 0):
        shape = tuple(int(s) for s in shape)
        self._arr = np.full(shape, fill_value, dtype=np.dtype(dtype))
        self.chunks = _clamp_chunks(chunks, shape)
        self._checksums = _ChecksumIndex(None)
        self._label = label
        self._store_factory = store_factory
        self._spilled_ds = None
        self._spill_state_lock = threading.Lock()
        self._spill_started = False
        # accumulated bytes counted into the process-wide bytes_not_stored
        # counter; a later spill reconciles them (they DID reach storage)
        self.not_stored_bytes = 0

    # every accessor SNAPSHOTS self._arr before branching: a concurrent
    # spill publishes the storage delegate and then drops the array, so a
    # reader must hold its own reference (the snapshot's bytes stay valid
    # under GC) instead of re-reading the attribute after the check

    @property
    def shape(self):
        arr = self._arr
        return tuple(arr.shape) if arr is not None else self._spilled_ds.shape

    @property
    def dtype(self):
        arr = self._arr
        return arr.dtype if arr is not None else self._spilled_ds.dtype

    ndim = property(lambda self: len(self.shape))

    @property
    def nbytes(self) -> int:
        arr = self._arr
        return 0 if arr is None else int(arr.nbytes)

    def _handoff_counters(self):
        from ..runtime import handoff as _h

        return _h.get_registry()

    def _read_back(self, bb):
        arr = self._arr
        if arr is None:
            return self._spilled_ds._read_back(bb)
        return arr[bb].copy()

    def _write_raw(self, bb, value):
        arr = self._arr
        if arr is None:
            self._spilled_ds._write_raw(bb, value)
        else:
            arr[bb] = value

    def __getitem__(self, bb):
        arr = self._arr
        if arr is None:
            return self._spilled_ds[bb]
        bid = _inject("io_read")
        _hang("io_read", bid)
        self._apply_read_rot(bb, bid)
        out = arr[bb].copy()
        return self._postread(bb, out)

    def __setitem__(self, bb, value):
        arr = self._arr
        if arr is None:
            self._spilled_ds[bb] = value
            return
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        value = np.asarray(value, dtype=arr.dtype)
        arr[bb] = value
        self._after_write(bb, value, bid)
        self.not_stored_bytes += int(value.nbytes)
        self._handoff_counters().bump("bytes_not_stored", int(value.nbytes))

    def read_async(self, bb):
        arr = self._arr
        if arr is None:
            return self._spilled_ds.read_async(bb)
        bid = _inject("io_read")
        _hang("io_read", bid)
        self._apply_read_rot(bb, bid)
        out = arr[bb].copy()
        return _ImmediateFuture(self._postread(bb, out))

    def write_async(self, bb, value):
        arr = self._arr
        if arr is None:
            return self._spilled_ds.write_async(bb, value)
        bid = _inject("io_write", voxels=getattr(value, "size", None))
        _hang("io_write", bid)
        value = np.asarray(value, dtype=arr.dtype)
        arr[bb] = value
        self._after_write(bb, value, bid)
        self.not_stored_bytes += int(value.nbytes)
        self._handoff_counters().bump("bytes_not_stored", int(value.nbytes))
        return _ImmediateFuture(None)

    def verify_region(self, bb) -> None:
        if self._arr is None:
            verify = getattr(self._spilled_ds, "verify_region", None)
            if verify is not None:
                verify(bb)
            return
        super().verify_region(bb)

    def spill(self) -> int:
        """Flush to the storage spill path and delegate from now on.
        Chunk-aligned regions go through the real dataset's write path (one
        digest sidecar per region, like any block store) and are verified
        back, so the stored copy is checksummed before the RAM is released.
        Returns the bytes freed (0 when already spilled/spilling)."""
        with self._spill_state_lock:
            if self._spill_started:
                return 0
            self._spill_started = True
        try:
            arr = self._arr
            ds = self._store_factory()
            regions = []
            ranges = [
                range(0, s, c) for s, c in zip(arr.shape, self.chunks)
            ]
            for begin in itertools.product(*ranges):
                bb = tuple(
                    slice(b, min(b + c, s))
                    for b, c, s in zip(begin, self.chunks, arr.shape)
                )
                ds[bb] = arr[bb]
                regions.append(bb)
            verify = getattr(ds, "verify_region", None)
            if verify is not None:
                for bb in regions:
                    verify(bb)
        except BaseException:
            # a half-written flush must stay retriable: release the guard
            # so the NEXT attempt re-writes every region — otherwise a
            # retry would short-circuit to "done" over a storage copy with
            # fill-value holes
            with self._spill_state_lock:
                self._spill_started = False
            raise
        freed = int(arr.nbytes)
        # the spilled copy keeps the handoff's product identity: reads
        # from it carry the "spill" corruption site, and the producer's
        # missing-sidecar policy travels with the data
        try:
            ds._read_site = "spill"
            pol = getattr(self, "_product_policy", None)
            if pol is not None:
                ds._product_policy = pol
        except AttributeError:
            pass
        # publish the delegate before dropping the array: concurrent
        # readers hold either the array ref (still valid bytes) or see the
        # stored copy — never neither
        self._spilled_ds = ds
        self._arr = None
        return freed

    @property
    def attrs(self) -> Dict:
        ds = self._spilled_ds
        return ds.attrs if ds is not None else {}

    def update_attrs(self, **kwargs) -> None:
        ds = self._spilled_ds
        if ds is None:
            raise RuntimeError(
                "in-memory handoff datasets carry no attribute store"
            )
        ds.update_attrs(**kwargs)


def open_container(path: str, mode: str = "a"):
    """Open a container by extension (SURVEY.md: ``vu.file_reader``)."""
    if path.startswith("memory://"):
        return MemoryContainer.open(path, mode)
    lower = path.lower()
    if lower.endswith(_ZARR_EXTS):
        return ZarrContainer(path, mode)
    if lower.endswith(_H5_EXTS):
        return H5Container(path, mode)
    raise ValueError(
        f"cannot infer container format from {path!r} "
        f"(expected one of {_ZARR_EXTS + _H5_EXTS} or memory://)"
    )
