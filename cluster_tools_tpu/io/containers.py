"""Chunked-array containers: the framework's inter-stage data plane.

The reference used z5py (C++ N5/zarr bindings) plus h5py as its entire
inter-job data plane (SURVEY.md §2d).  Here the same role is played by
**tensorstore** (Google's C++ chunked-array library, zarr + N5 drivers) with
h5py for HDF5 inputs, behind one small uniform API:

    f = open_container("/data/seg.n5")          # or .zarr / .h5
    ds = f.create_dataset("labels", shape=..., chunks=..., dtype="uint64")
    ds[bb] = block          # numpy in / numpy out
    arr = ds[bb]

Datasets are addressed by key (group paths like ``volumes/raw`` work).
``__getitem__``/``__setitem__`` are synchronous numpy round-trips;
``read_async``/``write_async`` return storage-level futures, consumed by the
bounded-window pipelines in :mod:`cluster_tools_tpu.io.prefetch` and by
``BlockwiseExecutor``'s batch assembly.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

try:
    import tensorstore as ts
except ImportError:  # pragma: no cover - tensorstore is expected in this image
    ts = None

try:
    import h5py
except ImportError:  # pragma: no cover
    h5py = None


_ZARR_EXTS = (".zarr", ".zr", ".n5")
_H5_EXTS = (".h5", ".hdf5", ".hdf")

_faults_mod = None


def _inject(site: str) -> None:
    """Fault-injection hook for the container IO layer (sites ``io_read`` /
    ``io_write``; see runtime/faults.py).  A no-op unless an injector is
    configured — chaos tests exercise the executor's load/store retries
    against storage-level failures through this."""
    global _faults_mod
    if _faults_mod is None:
        from ..runtime import faults as _fm

        _faults_mod = _fm
    _faults_mod.get_injector().maybe_fail(site)

# numpy dtype -> zarr v2 dtype string
def _zarr_dtype(dtype) -> str:
    return np.dtype(dtype).newbyteorder("<").str


def _n5_dtype(dtype) -> str:
    return np.dtype(dtype).name


class Dataset:
    """A chunked dataset backed by tensorstore."""

    def __init__(self, store, attrs_path: Optional[str] = None):
        self._store = store
        self._attrs_path = attrs_path

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._store.shape)

    @property
    def dtype(self):
        return np.dtype(self._store.dtype.numpy_dtype)

    @property
    def chunks(self) -> Tuple[int, ...]:
        return tuple(self._store.chunk_layout.read_chunk.shape)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def __getitem__(self, bb) -> np.ndarray:
        _inject("io_read")
        return np.asarray(self._store[bb].read().result())

    def __setitem__(self, bb, value) -> None:
        _inject("io_write")
        value = np.asarray(value, dtype=self.dtype)
        self._store[bb].write(value).result()

    def read_async(self, bb):
        """Start an async read; returns a future with ``.result()`` -> numpy."""
        _inject("io_read")
        return self._store[bb].read()

    def write_async(self, bb, value):
        _inject("io_write")
        value = np.asarray(value, dtype=self.dtype)
        return self._store[bb].write(value)

    # -- attributes (json sidecar, mirroring z5py/zarr .zattrs) -------------
    @property
    def attrs(self) -> Dict:
        if self._attrs_path is None or not os.path.exists(self._attrs_path):
            return {}
        with open(self._attrs_path) as f:
            return json.load(f)

    def update_attrs(self, **kwargs) -> None:
        if self._attrs_path is None:
            raise RuntimeError("dataset has no attribute store")
        attrs = self.attrs
        attrs.update(kwargs)
        # atomic: a kill mid-write must not tear the sidecar (it is shared
        # with external zarr/N5 readers)
        tmp = f"{self._attrs_path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(attrs, f, indent=2, default=_json_default)
        os.replace(tmp, self._attrs_path)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not json-serializable: {type(o)}")


class _ImmediateFuture:
    """Future-shim for backends whose reads/writes complete synchronously."""

    def __init__(self, v):
        self._v = v

    def result(self):
        return self._v


def _clamp_chunks(chunks, shape):
    """Chunks capped at the dataset shape — the creation rule, reused by
    existing-dataset validation so both paths compare like for like."""
    return tuple(int(min(c, s)) for c, s in zip(chunks, shape))


def _check_existing(
    key, have_shape, have_dtype, want_shape, want_dtype,
    have_chunks=None, want_chunks=None,
):
    if tuple(have_shape) != tuple(int(s) for s in want_shape) or np.dtype(
        have_dtype
    ) != np.dtype(want_dtype):
        raise ValueError(
            f"dataset {key!r} exists with shape {tuple(have_shape)} / dtype "
            f"{np.dtype(have_dtype)}, requested {tuple(want_shape)} / "
            f"{np.dtype(want_dtype)}"
        )
    if have_chunks is None or want_chunks is None:
        return
    have_chunks = tuple(int(c) for c in have_chunks)
    want_chunks = tuple(int(c) for c in want_chunks)
    # race safety (SURVEY.md §5.2): parallel block writes are conflict-free
    # only when every written block tiles whole chunks — i.e. the requested
    # block grid is a per-axis integer multiple of the existing chunks.
    # Finer-than-existing blocks would share chunks between writers.
    if len(have_chunks) != len(want_chunks) or any(
        w % h for w, h in zip(want_chunks, have_chunks)
    ):
        raise ValueError(
            f"dataset {key!r} exists with chunks {have_chunks}, requested "
            f"{want_chunks} — blocks must tile whole chunks (per-axis "
            "integer multiples) for chunk-aligned parallel writes; use a "
            "matching block_shape or a fresh dataset"
        )



class ZarrContainer:
    """A zarr (v2) or N5 container on the local filesystem, via tensorstore."""

    def __init__(self, path: str, mode: str = "a"):
        if ts is None:
            raise ImportError("tensorstore is required for zarr/n5 containers")
        self.path = os.path.abspath(path)
        self.mode = mode
        self.is_n5 = self.path.endswith(".n5")
        self._cache: Dict[str, Dataset] = {}
        self._lock = threading.Lock()
        if mode != "r":
            os.makedirs(self.path, exist_ok=True)
            marker = os.path.join(
                self.path, "attributes.json" if self.is_n5 else ".zgroup"
            )
            if not os.path.exists(marker):
                with open(marker, "w") as f:
                    json.dump(
                        {"n5": "2.0.0"} if self.is_n5 else {"zarr_format": 2}, f
                    )

    # -- internal ----------------------------------------------------------
    def _spec(self, key: str, metadata: Optional[dict] = None, create: bool = False):
        spec = {
            "driver": "n5" if self.is_n5 else "zarr",
            "kvstore": {"driver": "file", "path": os.path.join(self.path, key)},
            "recheck_cached_data": "open",
        }
        if metadata is not None:
            spec["metadata"] = metadata
        if create:
            spec["create"] = True
            spec["open"] = True
        return spec

    def _attrs_path(self, key: str) -> str:
        fname = "attributes.json" if self.is_n5 else ".zattrs"
        return os.path.join(self.path, key, fname)

    # -- public api --------------------------------------------------------
    def create_dataset(
        self,
        key: str,
        shape: Sequence[int],
        chunks: Sequence[int],
        dtype,
        compression: Optional[str] = "gzip",
        exist_ok: bool = True,
        fill_value: int = 0,
    ) -> Dataset:
        if self.mode == "r":
            raise PermissionError(f"container {self.path} opened read-only")
        shape = [int(s) for s in shape]
        chunks = list(_clamp_chunks(chunks, shape))
        if self.is_n5:
            comp = {"type": compression if compression else "raw"}
            # the N5 spec stores dimensions fastest-varying-first (F-order);
            # we write spec-compliant metadata and present C-order through a
            # tensorstore transpose in _open_store, so z5py/Java-N5 readers
            # see the same axis order as our numpy API
            metadata = {
                "dimensions": shape[::-1],
                "blockSize": chunks[::-1],
                "dataType": _n5_dtype(dtype),
                "compression": comp,
            }
        else:
            comp = (
                {"id": "zlib", "level": 1}
                if compression == "gzip"
                else None
            )
            metadata = {
                "shape": shape,
                "chunks": chunks,
                "dtype": _zarr_dtype(dtype),
                "compressor": comp,
                "fill_value": fill_value,
            }
        try:
            store = self._open_store(key, metadata, create=True)
        except ValueError:
            if not exist_ok:
                raise
            store = self._open_store(key)
            _check_existing(
                key, store.shape, store.dtype.numpy_dtype, shape, dtype,
                have_chunks=store.chunk_layout.read_chunk.shape,
                want_chunks=chunks,
            )
        ds = Dataset(store, self._attrs_path(key))
        with self._lock:
            self._cache[key] = ds
        return ds

    def _open_store(self, key, metadata=None, create=False):
        store = ts.open(self._spec(key, metadata, create=create)).result()
        if self.is_n5:
            # present C-order over the spec-mandated F-order on-disk layout
            store = store.T
        return store

    def require_dataset(self, key: str, **kwargs) -> Dataset:
        # create_dataset's exist_ok path validates shape/dtype of an existing
        # dataset against the request, which a bare self[key] would skip
        return self.create_dataset(key, exist_ok=True, **kwargs)

    def __getitem__(self, key: str) -> Dataset:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        store = self._open_store(key)
        ds = Dataset(store, self._attrs_path(key))
        with self._lock:
            self._cache[key] = ds
        return ds

    def __contains__(self, key: str) -> bool:
        d = os.path.join(self.path, key)
        if self.is_n5:
            return os.path.exists(os.path.join(d, "attributes.json"))
        return os.path.exists(os.path.join(d, ".zarray"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def close(self):
        pass


class _H5Dataset:
    """Adapter giving h5py datasets the same surface as :class:`Dataset`."""

    def __init__(self, ds):
        self._ds = ds

    shape = property(lambda self: tuple(self._ds.shape))
    dtype = property(lambda self: self._ds.dtype)
    ndim = property(lambda self: self._ds.ndim)

    @property
    def chunks(self):
        return tuple(self._ds.chunks) if self._ds.chunks else tuple(self._ds.shape)

    def __getitem__(self, bb):
        _inject("io_read")
        return self._ds[bb]

    def __setitem__(self, bb, value):
        _inject("io_write")
        self._ds[bb] = value

    def read_async(self, bb):
        _inject("io_read")
        return _ImmediateFuture(self._ds[bb])

    def write_async(self, bb, value):
        _inject("io_write")
        self._ds[bb] = value
        return _ImmediateFuture(None)

    @property
    def attrs(self):
        return dict(self._ds.attrs)

    def update_attrs(self, **kwargs):
        self._ds.attrs.update(kwargs)


class H5Container:
    def __init__(self, path: str, mode: str = "a"):
        if h5py is None:
            raise ImportError("h5py is required for hdf5 containers")
        self.path = path
        self._f = h5py.File(path, mode)

    def create_dataset(self, key, shape, chunks, dtype, compression="gzip", exist_ok=True, fill_value=0):
        if key in self._f:
            if not exist_ok:
                raise ValueError(f"dataset {key} exists")
            ds = self._f[key]
            _check_existing(
                key, ds.shape, ds.dtype, shape, dtype,
                have_chunks=ds.chunks,
                want_chunks=_clamp_chunks(chunks, shape),
            )
            return _H5Dataset(ds)
        ds = self._f.create_dataset(
            key,
            shape=tuple(shape),
            chunks=_clamp_chunks(chunks, shape),
            dtype=dtype,
            compression=compression,
            fillvalue=fill_value,
        )
        return _H5Dataset(ds)

    def require_dataset(self, key, **kwargs):
        return self.create_dataset(key, exist_ok=True, **kwargs)

    def __getitem__(self, key):
        return _H5Dataset(self._f[key])

    def __contains__(self, key):
        return key in self._f

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def close(self):
        self._f.close()


class MemoryContainer:
    """In-memory container (tests and tiny pipelines)."""

    _registry: Dict[str, "MemoryContainer"] = {}
    _registry_lock = threading.Lock()

    def __init__(self, path: str = "", mode: str = "a"):
        self.path = path
        self._data: Dict[str, "_MemDataset"] = {}

    @classmethod
    def open(cls, path: str, mode: str = "a") -> "MemoryContainer":
        with cls._registry_lock:
            if path not in cls._registry:
                cls._registry[path] = cls(path)
            return cls._registry[path]

    def create_dataset(self, key, shape, chunks, dtype, compression=None, exist_ok=True, fill_value=0):
        if key in self._data:
            if not exist_ok:
                raise ValueError(f"dataset {key} exists")
            ds = self._data[key]
            _check_existing(
                key, ds.shape, ds.dtype, shape, dtype,
                have_chunks=ds.chunks, want_chunks=chunks,
            )
            return ds
        ds = _MemDataset(np.full(tuple(shape), fill_value, dtype=dtype), tuple(chunks))
        self._data[key] = ds
        return ds

    def require_dataset(self, key, **kwargs):
        return self.create_dataset(key, exist_ok=True, **kwargs)

    def __getitem__(self, key):
        return self._data[key]

    def __contains__(self, key):
        return key in self._data

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def close(self):
        pass


class _MemDataset:
    def __init__(self, arr: np.ndarray, chunks: Tuple[int, ...]):
        self._arr = arr
        self.chunks = chunks
        self._attrs: Dict = {}

    shape = property(lambda self: self._arr.shape)
    dtype = property(lambda self: self._arr.dtype)
    ndim = property(lambda self: self._arr.ndim)

    def __getitem__(self, bb):
        _inject("io_read")
        return self._arr[bb].copy()

    def __setitem__(self, bb, value):
        _inject("io_write")
        self._arr[bb] = value

    def read_async(self, bb):
        _inject("io_read")
        return _ImmediateFuture(self._arr[bb].copy())

    def write_async(self, bb, value):
        _inject("io_write")
        self._arr[bb] = value
        return _ImmediateFuture(None)

    @property
    def attrs(self):
        return dict(self._attrs)

    def update_attrs(self, **kwargs):
        self._attrs.update(kwargs)


def open_container(path: str, mode: str = "a"):
    """Open a container by extension (SURVEY.md: ``vu.file_reader``)."""
    if path.startswith("memory://"):
        return MemoryContainer.open(path, mode)
    lower = path.lower()
    if lower.endswith(_ZARR_EXTS):
        return ZarrContainer(path, mode)
    if lower.endswith(_H5_EXTS):
        return H5Container(path, mode)
    raise ValueError(
        f"cannot infer container format from {path!r} "
        f"(expected one of {_ZARR_EXTS + _H5_EXTS} or memory://)"
    )
