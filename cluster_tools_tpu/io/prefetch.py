"""Async chunk -> host -> HBM streaming: bounded-window read pipelines.

The reference's jobs overlapped nothing: each block did a synchronous z5 read,
compute, synchronous write (SURVEY.md §3.1 hot loop).  The TPU rebuild's
executor overlaps three stages (reads ahead, device compute, writes behind);
this module supplies the read side as *futures* so that an entire batch of
chunk reads is in flight concurrently inside the storage layer (tensorstore
performs the chunk IO on its own C++ thread pool, no GIL involved) instead of
serializing per block.

Use :class:`BlockPrefetcher` for streaming iteration, or
:func:`async_loader` to build a future-returning ``load_fn`` for
``BlockwiseExecutor`` (which resolves futures batch-at-a-time).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator, Sequence, Tuple

import numpy as np


class _Resolved:
    """Future-like wrapper for values that are already materialized."""

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


def as_future(value):
    """Wrap ``value`` in a .result() interface unless it already has one."""
    return value if hasattr(value, "result") else _Resolved(value)


class _FailedFuture:
    """Future-shim for a read whose *submission* already raised: the error
    surfaces at that item's turn, not at submission time — so one bad item
    cannot take down the reads already in flight behind it."""

    def __init__(self, exc: BaseException):
        self._exc = exc

    def result(self):
        raise self._exc


class BlockPrefetcher:
    """Iterate ``(item, array)`` with a bounded window of in-flight reads.

    ``read_fn(item)`` must return either a numpy array or a future-like
    object with ``.result()`` (e.g. a tensorstore read future from
    ``Dataset.read_async``).  At any moment at most ``depth * batch_size``
    reads are in flight; results are yielded in submission order.

    Batch granularity (docs/PERFORMANCE.md "Sharded sweeps"): a *streaming*
    consumer that drains whole batches (one compiled program per
    ``batch_size`` items — host-side sweeps built on this iterator; the
    BlockwiseExecutor prefetches whole batches through its own pipeline and
    does not use this class) sets ``batch_size`` so the window holds
    ``depth`` batches — batch N+1's reads are all in flight while batch N
    computes.  The bound follows the LIVE batch size: when the consumer
    switches mid-sweep (e.g. degrading from wide batches to per-item
    grain), :meth:`set_batch_size` re-bounds the window at once — already
    in-flight reads are drained, but no new read is submitted until the
    window is back under ``depth * new_batch_size``.  Without it a consumer
    degrading from 16-item batches to single items would keep ``depth * 16``
    reads pinned against a byte budget sized for ``depth * 1``.

    Failure isolation: a read that raises (at submission or at resolution)
    raises from ``__next__`` for ITS item only.  The iterator is a
    hand-written object, not a generator — a generator would be closed by
    the raise and abandon every in-flight future behind it; here the window
    survives, so a consumer that catches the error keeps receiving the
    remaining items (and nothing past the window bound is ever in flight).
    """

    def __init__(
        self,
        read_fn: Callable,
        items: Sequence,
        depth: int = 2,
        batch_size: int = 1,
    ):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        if batch_size < 1:
            raise ValueError("prefetch batch_size must be >= 1")
        self._read_fn = read_fn
        self._items = list(items)
        self._depth = depth
        self._batch_size = int(batch_size)

    def set_batch_size(self, batch_size: int) -> None:
        """Re-bound the window to ``depth * batch_size`` for this and every
        live iterator (the consumer's batch size changed mid-sweep)."""
        if batch_size < 1:
            raise ValueError("prefetch batch_size must be >= 1")
        self._batch_size = int(batch_size)

    @property
    def window_bound(self) -> int:
        return self._depth * self._batch_size

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator[Tuple[object, np.ndarray]]:
        return _PrefetchIterator(self)


class _PrefetchIterator:
    """Iterator state of one :class:`BlockPrefetcher` pass (see its
    docstring for the failure-isolation and live-bound contracts).  The
    window bound is read from the owning prefetcher on every refill, so
    ``set_batch_size`` takes effect immediately."""

    def __init__(self, prefetcher: BlockPrefetcher):
        self._owner = prefetcher
        self._read_fn = prefetcher._read_fn
        self._it = iter(prefetcher._items)
        self._window: deque = deque()
        self._fill()

    def _submit_one(self) -> bool:
        try:
            item = next(self._it)
        except StopIteration:
            return False
        try:
            fut = as_future(self._read_fn(item))
        except Exception as e:
            fut = _FailedFuture(e)
        self._window.append((item, fut))
        return True

    def _fill(self) -> None:
        while len(self._window) < self._owner.window_bound and self._submit_one():
            pass

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[object, np.ndarray]:
        if not self._window:
            if not self._submit_one():
                raise StopIteration
        item, fut = self._window.popleft()
        try:
            arr = np.asarray(fut.result())
        finally:
            # refill after the head resolves: the LIVE bound of in-flight
            # reads holds while waiting, and again while the consumer works
            # — including when the head FAILED (its slot refills, the bound
            # holds, and iteration can continue past the error)
            self._fill()
        return item, arr


class _MappedFuture:
    """Future whose result is transformed on resolution (e.g. padding)."""

    def __init__(self, fut, fn):
        self._fut = fut
        self._fn = fn

    def result(self):
        return self._fn(self._fut.result())


def async_loader(
    dataset,
    bb_fn: Callable,
    *more: Tuple,
    pad_to=None,
    pad_mode: str = "edge",
) -> Callable:
    """Build a future-returning ``load_fn`` for ``BlockwiseExecutor``.

    ``bb_fn(block)`` gives the bounding box to read from ``dataset``; each
    extra ``(dataset_i, bb_fn_i)`` pair adds another input stream.  The
    returned callable issues every read as a storage-level future so the
    executor's batch assembly has all of a batch's chunk IO in flight at
    once.  ``pad_to`` (a uniform outer shape) pads each block on resolution
    — required whenever edge blocks are clipped, since the executor stacks a
    batch into one array.
    """
    streams = ((dataset, bb_fn),) + tuple(more)

    def load(block):
        futs = tuple(ds.read_async(fn(block)) for ds, fn in streams)
        if pad_to is None:
            return futs
        from ..utils.volume_utils import pad_block_to

        return tuple(
            _MappedFuture(
                f, lambda a: pad_block_to(np.asarray(a), pad_to, mode=pad_mode)
            )
            for f in futs
        )

    return load
