"""The verifying reader: every block-product read checks its digest
sidecar, raises a *typed* ``corrupt:<site>`` instead of returning poisoned
bytes, and hands detected corruption to the lineage repair engine
(docs/SERVING.md "Self-healing").

At petabyte scale, silent bit-rot in stored products is a statistical
certainty, not an edge case — a system that only checks integrity at write
time (the PR-5 posture: ``store_verify_fn`` re-reads while the writer
still owns the block) eventually serves corrupt segmentations with a 200.
This module closes the read side of the loop.  It is not a new call for
callers to remember: the container read paths
(:meth:`~cluster_tools_tpu.io.containers._ChecksumOps._postread`) route
every ``ds[bb]`` / ``read_async().result()`` through :func:`postread`, and
ctlint CT011 forbids raw reads of product bytes (``_read_back`` /
``._store[...]`` / sidecar ``open()``) outside ``io/`` — going through the
dataset API *is* going through the verifying reader.

Behavior per read:

- **verify**: a region whose exact box has a recorded digest is CRC-checked
  (this part predates this module); a mismatch now first evicts any cached
  chunks, then asks :mod:`cluster_tools_tpu.runtime.repair` to recompute
  the block from its producing task's inputs.  A successful repair is
  re-read from storage, re-verified, and returned — the caller never sees
  the corruption.  A failed repair raises :class:`ProductCorruptionError`
  with ``code = "corrupt:<site>"`` (site: ``storage`` / ``memory`` /
  ``handoff`` / ``spill`` from the dataset kind).
- **missing-sidecar policy**, for datasets *marked as product stores*
  (:func:`mark_product` — the executor's ``region_verifier`` marks every
  hardened store): an exact, chunk-aligned region read with NO recorded
  digest is a hole in the integrity plane.  Policy ``adopt`` (default)
  warns and hash-and-adopts — the bytes just read become the recorded
  truth; ``strict`` raises :class:`MissingSidecarError` instead (for
  stores whose write path is known to record every block, where a missing
  sidecar can only mean sidecar loss).  Unmarked datasets (raw inputs,
  scratch) are never policed.  Non-aligned reads (halo slabs, thin faces)
  are never policed either — they have no sidecar identity.

``CTT_SIDECAR_POLICY`` sets the process default (``adopt`` / ``strict``);
:func:`mark_product` takes a per-store override.  Counters from
:func:`stats` feed ``/healthz``, ``scrub_state.json``, and
``failures_report.py --json`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import numpy as np

from . import containers as _c

POLICY_ADOPT = "adopt"
POLICY_STRICT = "strict"
_POLICIES = (POLICY_ADOPT, POLICY_STRICT)

#: cap on per-adoption warning log lines (the counter keeps the true total)
_ADOPT_LOG_CAP = 20

_lock = threading.Lock()
_counters: Dict[str, int] = {
    "corrupt_detected": 0,
    "repaired_reads": 0,
    "unrepairable_reads": 0,
    "sidecars_adopted": 0,
    "strict_missing": 0,
}


def default_policy() -> str:
    """Process-wide missing-sidecar policy (``CTT_SIDECAR_POLICY``)."""
    pol = os.environ.get("CTT_SIDECAR_POLICY", POLICY_ADOPT).lower()
    return pol if pol in _POLICIES else POLICY_ADOPT


def mark_product(dataset, policy: Optional[str] = None):
    """Mark ``dataset`` as a block-product store: its exact chunk-aligned
    region reads fall under the missing-sidecar policy, and the scrubber
    may enlist it.  Called by ``executor.region_verifier`` for every
    hardened store, so call sites never wire it separately.  Returns the
    dataset.  No-op for datasets without digest support (HDF5)."""
    if getattr(dataset, "_checksums", None) is None:
        return dataset
    pol = (policy or default_policy()).lower()
    if pol not in _POLICIES:
        raise ValueError(
            f"sidecar policy must be one of {_POLICIES}, got {policy!r}"
        )
    dataset._product_policy = pol
    return dataset


class ProductCorruptionError(_c.ChunkCorruptionError):
    """A block product's bytes failed digest verification at a read site
    and could not be repaired from lineage.  ``code`` is the typed
    resolution string (``corrupt:storage`` / ``corrupt:memory`` /
    ``corrupt:handoff`` / ``corrupt:spill`` / ``corrupt:scrub``) the
    failure report attributes."""

    def __init__(self, site: str, cause: _c.ChunkCorruptionError):
        super().__init__(cause.label, cause.region, cause.expected,
                         cause.actual)
        self.site = str(site)
        self.code = f"corrupt:{self.site}"
        self.args = (f"{self.code}: {cause}",)


class MissingSidecarError(RuntimeError):
    """Strict missing-sidecar policy: a product store's exact region read
    found no digest sidecar — on a store whose write path records every
    block, that can only be sidecar loss, and serving unverifiable bytes
    is refused."""

    def __init__(self, label: str, region, site: str):
        self.label = label
        self.region = tuple(region)
        self.site = str(site)
        self.code = f"corrupt:{self.site}:missing_sidecar"
        super().__init__(
            f"{self.code}: no digest sidecar for {label} region "
            + "x".join(f"[{a}:{b}]" for a, b in self.region)
            + " (strict policy refuses unverifiable product bytes)"
        )


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _counters[key] = _counters.get(key, 0) + n


def stats() -> Dict[str, int]:
    """Verifying-reader counters (docs/OBSERVABILITY.md): corruption
    detected at read, reads healed by lineage repair, reads that stayed
    corrupt, sidecars hash-and-adopted, strict-policy refusals."""
    with _lock:
        return dict(_counters)


def reset_stats() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0


def _chunk_aligned(dataset, region) -> bool:
    """True when every region edge sits on a chunk boundary (or the
    volume edge) — the write contract for parallel block stores, and the
    only reads the missing-sidecar policy may judge (halo slabs and thin
    faces legitimately have no sidecar identity)."""
    chunks = getattr(dataset, "chunks", None)
    shape = dataset.shape
    if not chunks or len(chunks) != len(region):
        return False
    for (a, b), c, s in zip(region, chunks, shape):
        c = int(c)
        if c <= 0 or a % c != 0 or (b % c != 0 and b != int(s)):
            return False
    return True


def _policy_check(dataset, region, arr: np.ndarray, policy: str) -> None:
    """Apply the missing-sidecar policy to one product read whose exact
    region has no recorded digest."""
    if tuple(arr.shape) != _c._region_shape(region):
        return  # not an exact region read; nothing to judge
    if not _chunk_aligned(dataset, region):
        return
    site = getattr(dataset, "_read_site", "storage")
    if policy == POLICY_STRICT:
        _bump("strict_missing")
        raise MissingSidecarError(
            getattr(dataset, "_label", "<dataset>"), region, site
        )
    # adopt: the bytes just read become the recorded truth — warn so an
    # operator can tell adoption (first contact) from sidecar loss
    dataset._checksums.record(region, np.asarray(arr))
    _bump("sidecars_adopted")
    with _lock:
        n = _counters["sidecars_adopted"]
    if n <= _ADOPT_LOG_CAP:
        from ..utils import function_utils as fu

        fu.log(
            f"verified reader: adopted missing digest sidecar for "
            f"{getattr(dataset, '_label', '<dataset>')} region "
            + "x".join(f"[{a}:{b}]" for a, b in region)
            + (" (further adoptions logged only in counters)"
               if n == _ADOPT_LOG_CAP else "")
        )


def _repair_or_raise(dataset, bb, err: _c.ChunkCorruptionError) -> np.ndarray:
    """Detected corruption: hand the region to the lineage repair engine;
    on success re-read from the backing store and re-verify, else raise
    the typed error."""
    site = getattr(dataset, "_read_site", "storage")
    from ..runtime import repair as repair_mod

    if repair_mod.attempt_repair(dataset, err.region, site):
        arr = np.asarray(dataset._read_back(bb))
        try:
            dataset._verify_read(bb, arr)
        except _c.ChunkCorruptionError as still_bad:
            _bump("unrepairable_reads")
            raise ProductCorruptionError(site, still_bad) from err
        _bump("repaired_reads")
        return arr
    _bump("unrepairable_reads")
    raise ProductCorruptionError(site, err) from err


def postread(dataset, bb, arr: np.ndarray, evict=None) -> np.ndarray:
    """The verifying-reader tail of a region read (called by the container
    read paths — not by tasks).  Verifies, repairs, or raises typed; then
    applies the missing-sidecar policy for product stores.  Returns the
    array the caller may use (the repaired re-read on a healed region)."""
    if not _c.checksums_enabled():
        return arr
    try:
        dataset._verify_read(bb, arr)
    except _c.ChunkCorruptionError as err:
        _bump("corrupt_detected")
        if evict is not None:
            # bad chunks must not stay resident: the repair re-read (and
            # every later reader) has to see storage, not the cache
            evict()
        return _repair_or_raise(dataset, bb, err)
    policy = getattr(dataset, "_product_policy", None)
    if policy is not None:
        region = _c._norm_region(bb, dataset.shape)
        if region is not None and dataset._checksums.lookup(region) is None:
            _policy_check(dataset, region, arr, policy)
    return arr
