"""ctlint: repo-native static analysis for the runtime's contracts.

PRs 2-5 built a reliability and IO stack whose guarantees hold only by
convention: every executor call site must plumb the hardening knobs, every
shared manifest must be written atomically, nothing may block while holding
the XLA dispatch or chunk-cache locks, every storage boundary must carry a
fault-injection hook, jitted code must stay pure, and no broad ``except``
may swallow a preemption drain.  ``ctlint`` turns those conventions into
machine-checked rules (docs/ANALYSIS.md), so refactors cannot silently drop
a guarantee:

- **CT001 executor-contract** — ``map_blocks`` / ``BlockwiseExecutor`` /
  ``host_block_map`` call sites must plumb the hardening knobs.
- **CT002 atomic-write discipline** — no bare ``json.dump`` to shared state
  without the temp-file + ``os.replace`` idiom (``fu.atomic_write_json``).
- **CT003 lock discipline** — no lock-order cycles across the runtime's
  locks; no blocking calls under the XLA dispatch / chunk-cache locks.
- **CT004 fault-site coverage** — storage/compute boundaries carry
  injection hooks; every hooked site name is in ``faults.py``'s registry.
- **CT005 jit hygiene** — no side effects, wall-clock, randomness, or
  traced-value Python branches inside jitted code; hashable static args;
  no jit benchmarking without synchronization.
- **CT006 drain safety** — no handler that can swallow ``DrainInterrupt``;
  ``os._exit`` only in ``faults.py``; DAG entry points map drains to
  ``REQUEUE_EXIT_CODE``.

Run ``python -m cluster_tools_tpu.lint`` (or ``make lint``); suppress a
single finding with ``# ctlint: disable=CTnnn`` on (or immediately above)
the offending line.  The module is pure stdlib/ast — it never imports jax
or executes the code it checks.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    Finding,
    LintModule,
    collect_files,
    findings_to_json,
    run_lint,
)
from .rules import RULES  # noqa: F401
