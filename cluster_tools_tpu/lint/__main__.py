"""``python -m cluster_tools_tpu.lint`` — run ctlint and exit 1 on findings.

Usage::

    python -m cluster_tools_tpu.lint                  # lint the repo
    python -m cluster_tools_tpu.lint path/ file.py    # lint specific paths
    python -m cluster_tools_tpu.lint --json           # machine-readable
    python -m cluster_tools_tpu.lint --rules CT002,CT006
    python -m cluster_tools_tpu.lint --list-rules

With no paths, lints the ``cluster_tools_tpu`` package plus the repo's
``scripts/`` and ``bench.py`` when they exist next to it.  Render a saved
``--json`` document with ``scripts/failures_report.py --lint``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import findings_to_json, run_lint
from .rules import RULES


def default_paths() -> list:
    """The package itself + the repo's scripts/ and bench.py when present."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_dir)
    paths = [pkg_dir]
    for extra in ("scripts", "bench.py"):
        p = os.path.join(repo_root, extra)
        if os.path.exists(p):
            paths.append(p)
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cluster_tools_tpu.lint",
        description="repo-native static analysis (docs/ANALYSIS.md)",
    )
    p.add_argument("paths", nargs="*", help="files/dirs (default: the repo)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the findings document as JSON on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule ids + one-line summaries and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule_id, fn in sorted(RULES.items()):
            doc = (fn.__doc__ or "").strip().splitlines()
            print(f"{rule_id}  {doc[0] if doc else ''}")
        return 0

    select = None
    if args.rules:
        select = [r.strip() for r in args.rules.split(",") if r.strip()]
    paths = args.paths or default_paths()
    try:
        findings, stats = run_lint(paths, select=select)
    except ValueError as e:
        print(f"ctlint: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(findings_to_json(findings, stats), indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(
            f"ctlint: {n} finding(s) in {stats['n_files']} file(s)"
            + (f", {stats['n_suppressed']} suppressed"
               if stats["n_suppressed"] else "")
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
