"""ctlint driver: file collection, AST parsing, suppressions, output.

A *rule* is a callable ``rule(module: LintModule) -> Iterable[Finding]``
registered in :data:`cluster_tools_tpu.lint.rules.RULES`.  The driver
parses each file once, hands the shared :class:`LintModule` to every
selected rule, filters findings through the inline suppression map, and
renders text or machine-readable JSON (schema below).

Suppressions::

    risky_call()  # ctlint: disable=CT002
    # ctlint: disable=CT001,CT005   <- applies to the NEXT code line
    # ctlint: disable-file=CT004    <- whole file, any line

Suppressed findings are counted (``n_suppressed``) but not reported, so
opt-outs stay visible as debt instead of vanishing.

JSON schema (``--json``)::

    {"version": 1, "n_files": N, "n_suppressed": N,
     "counts": {"CT001": n, ...},
     "findings": [{"rule", "file", "line", "col", "message"}, ...]}
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: directories never linted (fixtures are linted only when named explicitly)
EXCLUDE_DIR_NAMES = ("__pycache__", "lint_fixtures", ".git")

_SUPPRESS_RE = re.compile(r"#\s*ctlint:\s*disable=([A-Z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*ctlint:\s*disable-file=([A-Z0-9,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at file:line."""

    rule: str
    file: str
    line: int
    col: int
    message: str

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"


class LintModule:
    """One parsed source file shared by every rule.

    ``tree`` is the parsed AST (None for files with syntax errors — rules
    skip those; the driver reports them as CT000).  ``lines`` is the raw
    source split for suppression lookup; ``parents`` maps each AST node to
    its parent so rules can walk enclosing scopes.
    """

    def __init__(self, path: str, source: str):
        self.path = path
        self.name = os.path.basename(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._suppressed_lines: Optional[Dict[int, set]] = None
        self._suppressed_file: Optional[set] = None

    # -- structure helpers -------------------------------------------------
    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        self._parents[child] = node
        return self._parents

    def enclosing(self, node: ast.AST, *types) -> Optional[ast.AST]:
        """Nearest ancestor of ``node`` matching ``types`` (or None)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        return self.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        return self.enclosing(node, ast.ClassDef)

    # -- suppressions ------------------------------------------------------
    def _scan_suppressions(self) -> None:
        per_line: Dict[int, set] = {}
        whole_file: set = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                whole_file.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                continue
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            target = i
            if line.split("#", 1)[0].strip() == "":
                # comment-only line: applies to the next code line
                j = i + 1
                while j <= len(self.lines) and (
                    self.lines[j - 1].strip() == ""
                    or self.lines[j - 1].lstrip().startswith("#")
                ):
                    j += 1
                target = j
            per_line.setdefault(target, set()).update(rules)
        self._suppressed_lines = per_line
        self._suppressed_file = whole_file

    def is_suppressed(self, rule: str, line: int) -> bool:
        if self._suppressed_lines is None:
            self._scan_suppressions()
        if rule in self._suppressed_file:
            return True
        return rule in self._suppressed_lines.get(line, set())


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of ``.py`` files,
    skipping :data:`EXCLUDE_DIR_NAMES` (fixtures lint only when a fixture
    file is named explicitly)."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if d not in EXCLUDE_DIR_NAMES
            )
            for fname in sorted(files):
                if fname.endswith(".py"):
                    out.append(os.path.join(root, fname))
    seen, uniq = set(), []
    for f in out:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


def run_lint(
    paths: Sequence[str],
    rules: Optional[Dict[str, object]] = None,
    select: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Lint ``paths`` with ``rules`` (default: the full registry).

    Returns ``(findings, stats)`` where ``stats`` has ``n_files`` and
    ``n_suppressed``.  Unparseable files yield a CT000 finding (a syntax
    error in production code is never a clean run).
    """
    if rules is None:
        from .rules import RULES as rules  # noqa: N811 - registry import
    selected = dict(rules)
    if select:
        want = set(select)
        unknown = want - set(selected)
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {sorted(unknown)}; "
                f"known: {sorted(selected)}"
            )
        selected = {k: v for k, v in selected.items() if k in want}
    findings: List[Finding] = []
    n_suppressed = 0
    files = collect_files(paths)
    for path in files:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding("CT000", path, 1, 0, f"unreadable: {e}"))
            continue
        module = LintModule(path, source)
        if module.tree is None:
            findings.append(
                Finding("CT000", path, 1, 0, module.parse_error or "parse error")
            )
            continue
        for rule_id, rule in selected.items():
            for finding in rule(module):
                if module.is_suppressed(finding.rule, finding.line):
                    n_suppressed += 1
                else:
                    findings.append(finding)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, {"n_files": len(files), "n_suppressed": n_suppressed}


def findings_to_json(
    findings: Sequence[Finding], stats: Dict[str, int]
) -> Dict[str, object]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "version": 1,
        "n_files": int(stats.get("n_files", 0)),
        "n_suppressed": int(stats.get("n_suppressed", 0)),
        "counts": counts,
        "findings": [f.to_dict() for f in findings],
    }
