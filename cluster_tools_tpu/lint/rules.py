"""The ctlint rule classes CT001-CT015 (docs/ANALYSIS.md).

Every rule is derived from a *real* invariant of this codebase — the
docstring of each checker names the file/contract it guards.  Rules are
pure AST analyses: nothing here imports jax or executes checked code.

Adding a rule: write ``def ctNNN_name(module: LintModule) -> list[Finding]``,
document the invariant, register it in :data:`RULES`, add a firing fixture
+ a clean fixture under ``tests/lint_fixtures/`` and a case in
``tests/test_lint.py`` (the repo-wide clean gate keeps it honest).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, LintModule


# -- shared AST helpers -------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_seg(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def call_attr(call: ast.Call) -> Optional[str]:
    """Last attribute/name segment of a call target, resolving through
    chained calls (``file_reader(p).require_dataset`` -> 'require_dataset'
    where :func:`dotted` gives None)."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def kw_names(call: ast.Call) -> Tuple[Set[str], bool]:
    """(explicit keyword names, has-**splat)."""
    names, splat = set(), False
    for kw in call.keywords:
        if kw.arg is None:
            splat = True
        else:
            names.add(kw.arg)
    return names, splat


def calls_in(node: ast.AST) -> Iterable[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _package_root(path: str) -> Optional[str]:
    """Directory of the ``cluster_tools_tpu`` package containing ``path``
    (for sibling-module resolution), or None outside the package."""
    cur = os.path.dirname(os.path.abspath(path))
    while True:
        if os.path.basename(cur) == "cluster_tools_tpu":
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


# =============================================================================
# CT001 - executor-contract
# =============================================================================

#: knobs every ``map_blocks`` call site must plumb (docs/ROBUSTNESS.md):
#: without them the call silently runs without failure attribution, hang
#: detection, post-store integrity verification, or locality scheduling.
#: ``sweep_mode`` selects the sharded executor path (one compiled program
#: per Morton batch, docs/PERFORMANCE.md "Sharded sweeps") — enforcing it
#: here means the new path is only reachable through config-plumbed call
#: sites, exactly like the per-block knobs.  ``device_pool`` gates the
#: HBM-resident page pool for ragged sweeps (docs/PERFORMANCE.md
#: "Device-resident data plane"): a site that cannot turn it off from
#: config cannot reach the host-staged twin when HBM is contended.
MAP_BLOCKS_KNOBS = frozenset({
    "failures_path",
    "task_name",
    "block_deadline_s",
    "watchdog_period_s",
    "store_verify_fn",
    "schedule",
    "sweep_mode",
    "device_pool",
})

#: constructor knobs: IO pool width and the per-block retry budget must be
#: config-driven, not the hard-coded defaults.
EXECUTOR_KNOBS = frozenset({"io_threads", "max_retries"})

#: hardened host-path knobs: ``host_block_map`` derives the retry/deadline/
#: schedule knobs from the task config itself, but the two wirings it cannot
#: derive — the post-store integrity verifier and the blocking (which also
#: enables the Morton schedule) — must come from the call site whenever the
#: task owns a chunked output dataset (``require_dataset`` in scope).
HOST_MAP_KNOBS = frozenset({"store_verify_fn", "blocking"})

#: knobs every sharded-global-solve call site must plumb
#: (``parallel/reduce_tree.py``, docs/PERFORMANCE.md "Distributed
#: agglomeration"): the shard/fanout knobs must come from the task config
#: (not hard-coded topology) and the failure attribution must be wired so a
#: degraded solve lands in failures.json as ``degraded:unsharded_solve``
#: instead of vanishing.
SOLVE_KNOBS = frozenset({
    "solver_shards",
    "fanout",
    "failures_path",
    "task_name",
    # the collective reduce plane must be switchable from config: a site
    # that cannot force `packet` cannot drill the degrade ladder, and one
    # that cannot force `collective` cannot prove the fast path
    "reduce_plane",
})

#: files that *define* the executor/solve surface (call sites only are
#: checked; reduce_tree.py's internal driver calls are its own contract)
_CT001_DEFINING = ("executor.py", "task.py", "reduce_tree.py")


def ct001_executor_contract(module: LintModule) -> List[Finding]:
    """Executor call sites must plumb the PR 2-5 hardening knobs.

    Guards the hand-plumbed convention ROADMAP item 5 complains about:
    every ``BlockwiseExecutor``/``map_blocks`` site must wire the retry /
    deadline / verify / schedule knobs, every ``host_block_map`` site
    that owns a chunked store must wire ``store_verify_fn`` + ``blocking``,
    and every ``solve_with_reduce_tree`` site (the sharded global solve)
    must plumb the shard/fanout knobs from config plus the
    failures-attribution wiring.  Opt out with ``# ctlint: disable=CT001``
    where a knob is genuinely inapplicable (say why in the comment).
    """
    if module.name in _CT001_DEFINING and "lint_fixtures" not in module.path:
        return []
    out: List[Finding] = []
    for call in calls_in(module.tree):
        name = last_seg(dotted(call.func))
        if name == "map_blocks":
            required = MAP_BLOCKS_KNOBS
        elif name == "BlockwiseExecutor":
            required = EXECUTOR_KNOBS
        elif name == "solve_with_reduce_tree":
            required = SOLVE_KNOBS
        elif name == "host_block_map":
            fn = module.enclosing_function(call)
            scope = fn if fn is not None else module.tree
            if not any(
                call_attr(c) == "require_dataset" for c in calls_in(scope)
            ):
                continue  # no chunked store owned here: nothing to verify
            required = HOST_MAP_KNOBS
        else:
            continue
        present, splat = kw_names(call)
        if splat:
            continue  # knobs forwarded wholesale; not statically checkable
        missing = sorted(required - present)
        if missing:
            out.append(Finding(
                "CT001", module.path, call.lineno, call.col_offset,
                f"{name} call site does not plumb the hardened executor "
                f"knob(s) {missing}; wire them from the task config or "
                "opt out explicitly with a reasoned "
                "'# ctlint: disable=CT001'",
            ))
    return out


# =============================================================================
# CT002 - atomic-write discipline
# =============================================================================

def _scope_is_atomic(module: LintModule, node: ast.AST) -> bool:
    """The enclosing scope *calls* the crash-safe idiom: ``os.replace`` /
    ``os.rename`` on the write path, or the shared helper.  Bare attribute
    mentions do not count — ``path.replace('a', 'b')`` is ``str.replace``,
    not an atomic rename."""
    fn = module.enclosing_function(node)
    scope = fn if fn is not None else module.tree
    for c in calls_in(scope):
        name = dotted(c.func)
        if name in ("os.replace", "os.rename"):
            return True
        if last_seg(name) == "atomic_write_json":
            return True
    return False


def ct002_atomic_writes(module: LintModule) -> List[Finding]:
    """Shared JSON state must be written atomically.

    ``failures.json`` / ``io_metrics.json`` / markers / configs / task
    reports are read by concurrent jobs and by resumed runs; a kill
    mid-write must leave the old document or nothing, never half a
    manifest (``fu.atomic_write_json``: temp file + fsync + ``os.replace``).
    Flags ``json.dump`` (and ``f.write(json.dumps(...))``) in any scope
    with no ``os.replace``/``os.rename``/``atomic_write_json`` evidence.
    """
    out: List[Finding] = []
    for call in calls_in(module.tree):
        name = dotted(call.func)
        is_dump = last_seg(name) == "dump" and (
            name or ""
        ).split(".")[0] in ("json", "ujson")
        is_write_dumps = (
            last_seg(name) == "write"
            and call.args
            and isinstance(call.args[0], ast.Call)
            and last_seg(dotted(call.args[0].func)) == "dumps"
        )
        if not (is_dump or is_write_dumps):
            continue
        if _scope_is_atomic(module, call):
            continue
        out.append(Finding(
            "CT002", module.path, call.lineno, call.col_offset,
            "non-atomic JSON write: a kill mid-write leaves a torn "
            "document for concurrent/resumed readers; use "
            "fu.atomic_write_json (temp file + os.replace) or write to a "
            "temp path and os.replace it",
        ))
    return out


# =============================================================================
# CT003 - lock discipline
# =============================================================================

#: modules participating in the runtime's lock graph (reduce_tree.py: the
#: sharded solve's merge queue + metrics locks)
_CT003_SCOPE = (
    "executor.py", "chunk_cache.py", "supervision.py",
    "function_utils.py", "containers.py", "handoff.py", "reduce_tree.py",
)

#: method/function names that block the calling thread (never allowed
#: while holding any tracked lock: a stuck callee freezes every other
#: thread contending for it)
_BLOCKING_CALLS = {"sleep", "result", "wait", "join"}

#: additionally forbidden under the *hot* locks: the XLA dispatch lock
#: serializes every kernel launch, and the chunk-cache lock serializes
#: every cached read — filesystem or (de)serialization work under either
#: stalls the whole pipeline
_HOT_BLOCKING = {
    "open", "dump", "dumps", "load", "loads", "listdir", "replace",
    "unlink", "remove", "save", "fsync", "makedirs", "read", "write",
}


def _is_hot_lock(module: LintModule, lock_key: str) -> bool:
    if lock_key.endswith("dispatch_lock"):
        return True
    if module.name == "chunk_cache.py" and lock_key == "ChunkCache._lock":
        return True
    return False


def _lock_key(module: LintModule, node: ast.AST) -> Optional[str]:
    """Identity of a lock expression: ``Class.attr`` for ``self.X`` locks,
    the bare name for local/module locks, the callee name for lock-factory
    context managers (``with file_lock(path):``)."""
    name = dotted(node)
    if name is None and isinstance(node, ast.Call):
        name = dotted(node.func)
    if name is None:
        return None
    seg = last_seg(name)
    # a lock is something *named* like one ('_LOCK', 'fail_lock',
    # 'lock_a'); 'block_context' / 'block' / 'blocking' are not locks
    if seg is None:
        return None
    low = seg.lower()
    if not (low.endswith("lock") or low.startswith("lock")) \
            or low.endswith("block"):
        return None
    if name.startswith("self."):
        cls = module.enclosing_class(node)
        return f"{cls.name}.{seg}" if cls is not None else seg
    return seg


class _FnInfo:
    __slots__ = ("node", "locks", "calls")

    def __init__(self, node):
        self.node = node
        self.locks: Set[str] = set()   # locks this function acquires
        self.calls: Set[str] = set()   # last-segment names it calls


def ct003_lock_discipline(module: LintModule) -> List[Finding]:
    """No blocking calls under the runtime's locks; no lock-order cycles.

    The executor's ``dispatch_lock`` exists because two concurrent
    multi-device dispatches deadlock XLA's collective rendezvous; anything
    slow under it (or under the chunk cache's LRU lock) serializes the
    sweep, and any pair of locks taken in opposite orders across
    ``executor.py`` / ``chunk_cache.py`` / ``supervision.py`` /
    ``function_utils.py`` / ``containers.py`` is a latent deadlock.
    Builds a static lock-acquisition graph (with one level of local call
    resolution) and flags (a) blocking calls made while a lock is held,
    (b) cycles in the lock-order graph.
    """
    is_fixture = "ct003" in module.name
    if module.name not in _CT003_SCOPE and not is_fixture:
        return []
    out: List[Finding] = []

    # function table (qualified by class where applicable)
    fns: Dict[str, _FnInfo] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = module.enclosing_class(node)
            qual = f"{cls.name}.{node.name}" if cls else node.name
            info = _FnInfo(node)
            for c in calls_in(node):
                seg = last_seg(dotted(c.func))
                if seg:
                    info.calls.add(seg)
            fns[qual] = info
            fns.setdefault(node.name, info)

    # direct acquisitions + per-with-body analysis
    edges: Set[Tuple[str, str, int]] = set()

    def with_lock_items(w: ast.With) -> List[str]:
        keys = []
        for item in w.items:
            key = _lock_key(module, item.context_expr)
            if key is not None:
                keys.append(key)
        return keys

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        keys = with_lock_items(node)
        if not keys:
            continue
        fn = module.enclosing_function(node)
        if fn is not None:
            cls = module.enclosing_class(node)
            qual = f"{cls.name}.{fn.name}" if cls else fn.name
            if qual in fns:
                fns[qual].locks.update(keys)
        # ordered acquisition within one `with a, b:` statement
        for a, b in zip(keys, keys[1:]):
            edges.add((a, b, node.lineno))
        held = keys[-1]
        hot = any(_is_hot_lock(module, k) for k in keys)
        for stmt in node.body:
            for inner in ast.walk(stmt):
                if isinstance(inner, ast.With):
                    for k in with_lock_items(inner):
                        for h in keys:
                            if k != h:
                                edges.add((h, k, inner.lineno))
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted(inner.func)
                seg = last_seg(name)
                if seg is None:
                    continue
                blocking = seg in _BLOCKING_CALLS or (
                    name or ""
                ).startswith("subprocess.")
                if seg == "join" and isinstance(
                    inner.func, ast.Attribute
                ) and isinstance(inner.func.value, ast.Constant):
                    blocking = False  # "sep".join(...) is not a thread join
                if blocking:
                    out.append(Finding(
                        "CT003", module.path, inner.lineno, inner.col_offset,
                        f"blocking call '{name}' while holding lock "
                        f"'{held}': a stuck callee freezes every thread "
                        "contending for the lock — move the wait outside "
                        "the critical section",
                    ))
                elif hot and (seg in _HOT_BLOCKING or seg == "open"):
                    out.append(Finding(
                        "CT003", module.path, inner.lineno, inner.col_offset,
                        f"IO/serialization call '{name}' under hot lock "
                        f"'{held}' (XLA dispatch / chunk-cache LRU): this "
                        "serializes the whole sweep behind one filesystem "
                        "call — stage the data outside the lock",
                    ))
                # call to a local function that itself takes locks
                callee = fns.get(seg)
                if callee is not None:
                    for k in callee.locks:
                        for h in keys:
                            if k != h:
                                edges.add((h, k, inner.lineno))

    # cycle detection over the lock-order graph
    graph: Dict[str, Set[str]] = {}
    at_line: Dict[Tuple[str, str], int] = {}
    for a, b, line in edges:
        graph.setdefault(a, set()).add(b)
        at_line.setdefault((a, b), line)

    WHITE, GREY, BLACK = 0, 1, 2
    color = {k: WHITE for k in set(graph) | {v for vs in graph.values() for v in vs}}
    reported: Set[frozenset] = set()

    def visit(u: str, stack: List[str]) -> None:
        color[u] = GREY
        stack.append(u)
        for v in sorted(graph.get(u, ())):
            if color[v] == GREY:
                cycle = stack[stack.index(v):] + [v]
                key = frozenset(cycle)
                if key not in reported:
                    reported.add(key)
                    line = at_line.get((u, v), 1)
                    out.append(Finding(
                        "CT003", module.path, line, 0,
                        "lock-order cycle "
                        + " -> ".join(cycle)
                        + ": two threads taking these locks in opposite "
                        "orders deadlock; pick one global order",
                    ))
            elif color[v] == WHITE:
                visit(v, stack)
        stack.pop()
        color[u] = BLACK

    for u in sorted(color):
        if color[u] == WHITE:
            visit(u, [])
    return out


# =============================================================================
# CT004 - fault-site coverage
# =============================================================================

#: fallback registry (kept in sync with runtime/faults.py; the rule reads
#: the real module when it is reachable on disk)
_DEFAULT_SITES = frozenset({
    "load", "store", "io_read", "io_write", "submit", "task",
    "block_done", "task_done", "compute", "kernel", "admit",
    "journal", "journal_append", "journal_replay",
    "net_member", "net_probe", "net_client",
})
_DEFAULT_KINDS = frozenset({
    "error", "oom", "enospc", "hang", "corrupt", "nan",
    "job_loss", "kill", "preempt", "spill", "reject", "torn",
    "net_delay", "net_drop", "net_wedge",
})

#: hook callables whose first positional arg is a site name
_SITE_HOOKS = {
    "maybe_fail", "maybe_hang", "chunk_corrupt", "kill_point",
    "corrupt", "_inject", "_hang",
}

#: dataset IO boundary methods that must carry an injection hook
_BOUNDARY_METHODS = ("__getitem__", "__setitem__", "read_async", "write_async")


def _load_fault_registry(module: LintModule) -> Tuple[Set[str], Set[str]]:
    """(sites, kinds) parsed from the real ``runtime/faults.py`` when
    resolvable from ``module``'s location, else the pinned defaults."""
    root = _package_root(module.path)
    path = os.path.join(root, "runtime", "faults.py") if root else None
    if module.name == "faults.py":
        path = module.path
    if not path or not os.path.isfile(path):
        return set(_DEFAULT_SITES), set(_DEFAULT_KINDS)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return set(_DEFAULT_SITES), set(_DEFAULT_KINDS)
    sites: Set[str] = set()
    kinds: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not targets:
                continue
            name = targets[0]
            if name.endswith("_SITES") and isinstance(node.value, ast.Tuple):
                for el in node.value.elts:
                    s = str_const(el)
                    if s:
                        sites.add(s)
            if name == "_FAIL_KINDS" and isinstance(node.value, ast.Tuple):
                for el in node.value.elts:
                    s = str_const(el)
                    if s:
                        kinds.add(s)
        # kind literals in the validation chain:  kind == "nan"  /
        # kind in ("kill", "preempt")
        if isinstance(node, ast.Compare):
            left = dotted(node.left)
            if last_seg(left) != "kind":
                continue
            for comp in node.comparators:
                s = str_const(comp)
                if s:
                    kinds.add(s)
                if isinstance(comp, (ast.Tuple, ast.List)):
                    for el in comp.elts:
                        s = str_const(el)
                        if s:
                            kinds.add(s)
    sites |= {"kernel", "compute"}  # corrupt-hook + executor compute site
    return (sites or set(_DEFAULT_SITES)), (kinds or set(_DEFAULT_KINDS))


def ct004_fault_site_coverage(module: LintModule) -> List[Finding]:
    """Every IO/compute boundary carries a fault hook; site names and the
    fault-class registry stay consistent.

    The chaos suite only proves what the hooks reach: a Dataset method
    without ``_inject``/``_hang`` is a storage boundary chaos cannot
    exercise, a typo'd site string is a hook that never fires, and a
    shrunken fault-kind registry silently un-tests recovery paths.
    """
    is_fixture = "ct004" in module.name
    sites, kinds = _load_fault_registry(module)
    out: List[Finding] = []

    # (a) site-name vocabulary at every hook call
    for call in calls_in(module.tree):
        seg = last_seg(dotted(call.func))
        if seg not in _SITE_HOOKS or not call.args:
            continue
        site = str_const(call.args[0])
        if site is not None and site not in sites:
            out.append(Finding(
                "CT004", module.path, call.lineno, call.col_offset,
                f"unknown fault site {site!r} passed to {seg} (registry: "
                f"{sorted(sites)}): this hook can never fire — typo, or "
                "register the site in runtime/faults.py",
            ))

    # (b) dataset boundary coverage (container layer + fixtures)
    if module.name == "containers.py" or is_fixture:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef) or "Dataset" not in node.name:
                continue
            for item in node.body:
                if not isinstance(item, ast.FunctionDef):
                    continue
                if item.name not in _BOUNDARY_METHODS:
                    continue
                hooked = any(
                    last_seg(dotted(c.func)) in ("_inject", "maybe_fail")
                    for c in calls_in(item)
                )
                if not hooked:
                    out.append(Finding(
                        "CT004", module.path, item.lineno, item.col_offset,
                        f"storage boundary {node.name}.{item.name} has no "
                        "fault-injection hook (_inject/maybe_fail): chaos "
                        "tests cannot exercise failures at this IO path",
                    ))

    # (c) executor compute/load/store coverage
    if module.name == "executor.py" and "lint_fixtures" not in module.path:
        seen_sites: Set[str] = set()
        kill_sites: Set[str] = set()
        for call in calls_in(module.tree):
            seg = last_seg(dotted(call.func))
            if seg in ("maybe_fail", "maybe_hang") and call.args:
                s = str_const(call.args[0])
                if s:
                    seen_sites.add(s)
            if seg == "kill_point" and call.args:
                s = str_const(call.args[0])
                if s:
                    kill_sites.add(s)
        for required in ("load", "store", "compute"):
            if required not in seen_sites:
                out.append(Finding(
                    "CT004", module.path, 1, 0,
                    f"executor no longer injects faults at site "
                    f"{required!r}: the {required} boundary is chaos-blind",
                ))
        if "block_done" not in kill_sites:
            out.append(Finding(
                "CT004", module.path, 1, 0,
                "executor lost its kill_point('block_done') crossing: "
                "preemption chaos cannot target block completion",
            ))

    # (d) the 12-class registry itself
    if module.name == "faults.py" and "lint_fixtures" not in module.path:
        missing = _DEFAULT_KINDS - kinds
        if missing:
            out.append(Finding(
                "CT004", module.path, 1, 0,
                f"fault-class registry lost kind(s) {sorted(missing)} "
                f"(now: {sorted(kinds)}): recovery paths for them are "
                "untestable",
            ))
    return out


# =============================================================================
# CT005 - jit hygiene
# =============================================================================

#: call prefixes that are side effects / nondeterminism inside a traced
#: function: they run once at trace time, not per execution
_IMPURE_PREFIXES = (
    "time.", "datetime.", "random.", "np.random.", "numpy.random.",
    "os.", "subprocess.", "socket.",
)
_IMPURE_NAMES = {"print", "open", "input", "breakpoint"}

_SYNC_MARKERS = ("block_until_ready", ".item(", "np.asarray", "np.array(",
                 "device_get", "float(")

#: call names that trace their first argument into a compiled program:
#: ``jit`` / ``shard_map`` directly, and the batch-sharding wrappers of
#: the sharded sweep (``parallel/batch_shard.py``): a kernel passed into
#: ``batched_shard_map`` OR the ragged paged wrapper ``ragged_shard_map``
#: (docs/PERFORMANCE.md "Ragged sweeps") is vmapped inside one
#: ``shard_map`` program, so the same purity contract applies.
_JIT_WRAPPERS = ("jit", "shard_map", "batched_shard_map",
                 "ragged_shard_map")


def _jit_target_names(call: ast.Call) -> List[Tuple[str, Set[str]]]:
    """``(function name, partial-bound arg names)`` for every local
    function wrapped by a ``jax.jit(...)``/``shard_map(...)``/
    ``batched_shard_map(...)`` call, unwrapping ``jax.vmap``/
    ``functools.partial`` layers.  Args bound by keyword through
    ``partial`` are compile-time constants, so they count as static for
    the traced-branch check."""
    names: List[Tuple[str, Set[str]]] = []
    stack: List[Tuple[ast.AST, Set[str]]] = [
        (a, set()) for a in call.args[:1]
    ]
    while stack:
        arg, bound = stack.pop()
        if isinstance(arg, ast.Name):
            names.append((arg.id, bound))
        elif isinstance(arg, ast.Call):
            inner_bound = set(bound)
            if last_seg(dotted(arg.func)) == "partial":
                inner_bound |= {
                    kw.arg for kw in arg.keywords if kw.arg is not None
                }
            stack.extend((a, inner_bound) for a in arg.args[:1])
    return names


def _collect_jitted(module: LintModule) -> Dict[str, Dict]:
    """name -> {"node": FunctionDef|Lambda, "static": set[str]} for every
    function statically known to be jitted/shard_mapped in this module."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)
    jitted: Dict[str, Dict] = {}

    def static_names(call: ast.Call, target: Optional[ast.FunctionDef]) -> Set[str]:
        names: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                vals = [kw.value]
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    vals = list(kw.value.elts)
                for v in vals:
                    s = str_const(v)
                    if s:
                        names.add(s)
            if kw.arg == "static_argnums" and target is not None:
                nums = [kw.value]
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    nums = list(kw.value.elts)
                params = [a.arg for a in target.args.args]
                for v in nums:
                    if isinstance(v, ast.Constant) and isinstance(v.value, int):
                        if 0 <= v.value < len(params):
                            names.add(params[v.value])
        return names

    def mark(name: str, node: ast.AST, static: Set[str], call: ast.Call):
        entry = jitted.setdefault(
            name, {"node": node, "static": set(), "call": call}
        )
        entry["static"] |= static

    for node in ast.walk(module.tree):
        # decorator form: @jax.jit / @jit / @partial(jax.jit, ...)
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                dname = dotted(dec)
                if dname and last_seg(dname) == "jit":
                    mark(node.name, node, set(), None)
                elif isinstance(dec, ast.Call):
                    fname = dotted(dec.func)
                    if fname and last_seg(fname) == "jit":
                        mark(node.name, node, static_names(dec, node), dec)
                    elif fname and last_seg(fname) == "partial" and dec.args:
                        inner = dotted(dec.args[0])
                        if inner and last_seg(inner) in _JIT_WRAPPERS:
                            mark(node.name, node, static_names(dec, node), dec)
        # wrapper form: g = jax.jit(f) / jax.jit(vmap(f)) / shard_map(f, ...)
        # / batched_shard_map(f, mesh, batch)
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname and last_seg(fname) in _JIT_WRAPPERS:
                if node.args and isinstance(node.args[0], ast.Lambda):
                    mark(f"<lambda:{node.lineno}>", node.args[0], set(), node)
                for target, bound in _jit_target_names(node):
                    if target in defs:
                        mark(
                            target, defs[target],
                            static_names(node, defs[target]) | bound, node,
                        )
    return jitted


def ct005_jit_hygiene(module: LintModule) -> List[Finding]:
    """Jitted/shard_mapped functions must be pure and benchmarkable.

    Side effects, wall-clock reads, and host randomness inside a traced
    function run once at trace time and silently freeze into the compiled
    program; a Python branch on a traced value raises (or worse, bakes in
    one path) at runtime; an unhashable static arg fails at dispatch; and
    timing a jitted call without synchronization measures dispatch, not
    compute (jax dispatch is async).
    """
    out: List[Finding] = []
    jitted = _collect_jitted(module)

    for name, entry in jitted.items():
        node = entry["node"]
        static = entry["static"]
        node_args = getattr(node, "args", None)
        params = (
            {a.arg for a in node_args.args} if node_args is not None else set()
        ) - static - {"self"}

        for inner in ast.walk(node):
            if isinstance(inner, ast.Call):
                cname = dotted(inner.func)
                if cname is None:
                    continue
                if cname in _IMPURE_NAMES or any(
                    cname.startswith(p) for p in _IMPURE_PREFIXES
                ):
                    out.append(Finding(
                        "CT005", module.path, inner.lineno, inner.col_offset,
                        f"impure call '{cname}' inside jitted function "
                        f"'{name}': it executes once at trace time and "
                        "freezes into the compiled program — hoist it out "
                        "of the traced scope",
                    ))
            # Python control flow on a traced parameter
            if isinstance(inner, (ast.If, ast.While)):
                test = inner.test
                flagged_name: Optional[str] = None
                if isinstance(test, ast.Name) and test.id in params:
                    flagged_name = test.id
                elif isinstance(test, ast.Compare):
                    is_identity = all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops
                    )
                    if not is_identity:
                        for side in [test.left] + list(test.comparators):
                            if isinstance(side, ast.Name) and side.id in params:
                                flagged_name = side.id
                                break
                if flagged_name is not None:
                    out.append(Finding(
                        "CT005", module.path, inner.lineno, inner.col_offset,
                        f"Python branch on traced value '{flagged_name}' "
                        f"inside jitted function '{name}': tracing cannot "
                        "evaluate it — use jnp.where/lax.cond, or mark the "
                        "argument static",
                    ))
        # non-hashable static-arg defaults
        if static and isinstance(node, ast.FunctionDef):
            args = node.args
            defaults = dict(
                zip([a.arg for a in args.args][-len(args.defaults):],
                    args.defaults)
            ) if args.defaults else {}
            for pname in sorted(static):
                d = defaults.get(pname)
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    out.append(Finding(
                        "CT005", module.path, d.lineno, d.col_offset,
                        f"static arg '{pname}' of jitted function '{name}' "
                        "defaults to an unhashable container: jit static "
                        "args must be hashable (use a tuple / frozenset)",
                    ))

    # timing a jitted call without synchronization
    clock_calls = {"time.perf_counter", "time.monotonic", "time.time"}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        clocks = [
            c for c in calls_in(node)
            if (dotted(c.func) or "") in clock_calls
        ]
        if len(clocks) < 2:
            continue
        calls_jitted = any(
            last_seg(dotted(c.func)) in jitted for c in calls_in(node)
        )
        if not calls_jitted:
            continue
        try:
            segment = ast.get_source_segment(module.source, node) or ""
        except Exception:  # pragma: no cover - malformed coords
            segment = ""
        if any(marker in segment for marker in _SYNC_MARKERS):
            continue
        out.append(Finding(
            "CT005", module.path, clocks[0].lineno, clocks[0].col_offset,
            f"'{node.name}' times a jitted call without synchronization "
            "(jax dispatch is async): call block_until_ready (or fetch a "
            "scalar) before reading the clock",
        ))
    return out


# =============================================================================
# CT006 - drain safety
# =============================================================================


def _handler_catches_base(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names: List[Optional[str]] = []
    if isinstance(handler.type, ast.Tuple):
        names = [last_seg(dotted(el)) for el in handler.type.elts]
    else:
        names = [last_seg(dotted(handler.type))]
    return any(n in ("BaseException", "KeyboardInterrupt") for n in names)


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """Only an actual ``raise`` statement counts — a handler that merely
    *inspects* DrainInterrupt (``if isinstance(e, DrainInterrupt): log()``)
    still swallows the drain."""
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def ct006_drain_safety(module: LintModule) -> List[Finding]:
    """Preemption drains must reach the exit code, never a retry loop.

    ``DrainInterrupt`` is a ``BaseException`` precisely so broad ``except
    Exception`` recovery paths cannot swallow a preemption — but a bare
    ``except:`` / ``except BaseException:`` without a re-raise still can,
    ``os._exit`` outside the fault injector skips every flush the drain
    protocol relies on, and an entry point that builds a task DAG without
    mapping ``DrainInterrupt`` to ``REQUEUE_EXIT_CODE`` turns a graceful
    eviction into a crash the scheduler won't requeue.
    """
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ExceptHandler):
            if _handler_catches_base(node) and not _handler_reraises(node):
                what = (
                    "bare 'except:'" if node.type is None
                    else "'except BaseException'"
                )
                out.append(Finding(
                    "CT006", module.path, node.lineno, node.col_offset,
                    f"{what} swallows DrainInterrupt (a BaseException): a "
                    "preemption drain dies here instead of reaching the "
                    "requeue exit — catch Exception, or re-raise "
                    "BaseException/DrainInterrupt",
                ))
        if isinstance(node, ast.Call):
            if dotted(node.func) == "os._exit" and module.name != "faults.py":
                out.append(Finding(
                    "CT006", module.path, node.lineno, node.col_offset,
                    "os._exit outside runtime/faults.py: skips marker/"
                    "manifest flushes and the drain protocol — raise, or "
                    "sys.exit through the entry point",
                ))

    # entry-point contract: __main__ + build() must speak the drain protocol
    has_main_guard = any(
        isinstance(n, ast.If)
        and isinstance(n.test, ast.Compare)
        and isinstance(n.test.left, ast.Name)
        and n.test.left.id == "__name__"
        for n in ast.walk(module.tree)
    )
    if has_main_guard:
        build_calls = [
            c for c in calls_in(module.tree)
            if isinstance(c.func, ast.Name) and c.func.id == "build"
        ]
        if build_calls and not (
            "DrainInterrupt" in module.source
            and "REQUEUE_EXIT_CODE" in module.source
        ):
            c = build_calls[0]
            out.append(Finding(
                "CT006", module.path, c.lineno, c.col_offset,
                "entry point runs a task DAG but never maps DrainInterrupt "
                "to REQUEUE_EXIT_CODE: a SIGTERM mid-run exits as a crash "
                "instead of a scheduler requeue — wrap the build in "
                "'except DrainInterrupt: sys.exit(REQUEUE_EXIT_CODE)'",
            ))
    return out


# =============================================================================
# CT007 - memory-target spill contract
# =============================================================================

#: creation kwargs a handoff_dataset declaration must carry so the storage
#: spill twin can be created (positionally: path, key, shape, chunks, dtype)
_CT007_CREATE_KWS = ("shape", "chunks", "dtype")

#: kwargs a device-rung publish must carry (positionally: path, arrays,
#: producer, failures_path): without them the demote-to-host / host-staged
#: fallback cannot be attributed (``degraded:host_staged`` in failures.json)
#: and the device handoff's spill contract is silently broken.
_CT007_DEVICE_PUBLISH_KWS = ("producer", "failures_path")


def ct007_memory_target_contract(module: LintModule) -> List[Finding]:
    """A task that declares a ``MemoryTarget`` output must wire the spill
    path (docs/PERFORMANCE.md "Task-graph fusion").

    An in-memory handoff is only safe because spill-to-storage is the
    universal fallback: every ``handoff_dataset`` declaration must pass the
    full storage wiring (``path``/``key`` plus ``shape``/``chunks``/
    ``dtype``, or the spill twin cannot be created when admission, headroom
    pressure, or a forced ``spill`` fault demands it), and the returned
    handle must be wired into a post-store ``region_verifier`` somewhere in
    the module so integrity verification covers the in-memory data plane —
    a handoff without a verifier is a storage boundary the PR-3 corruption
    defense cannot see.

    Device-rung declarations (``publish_device_arrays``) carry the same
    obligation one rung up: every publish must wire ``producer`` +
    ``failures_path`` so a demotion or host-staged fallback stays
    attributable (``degraded:host_staged``) instead of silently vanishing
    from the failure ledger.
    """
    if module.name in ("task.py", "handoff.py") \
            and "lint_fixtures" not in module.path:
        return []  # the defining surface, not a call site
    out: List[Finding] = []
    verified: Set[str] = set()
    for call in calls_in(module.tree):
        if last_seg(dotted(call.func)) == "region_verifier" and call.args:
            name = dotted(call.args[0])
            if name:
                verified.add(last_seg(name))

    def _check(call: ast.Call, bound: Optional[str]) -> None:
        present, splat = kw_names(call)
        if splat:
            return  # wiring forwarded wholesale; not statically checkable
        pos = len(call.args)
        missing = []
        # positional args fill path then key (in that order); either may
        # equally come as a keyword — a positional path + key= kwarg is
        # fully wired
        if pos == 0 and not {"path", "key"} <= present:
            missing.append("path/key")
        elif pos == 1 and "key" not in present:
            missing.append("key")
        need = max(0, 5 - pos)
        if need:
            # with pos < 2 the path/key slots are also unfilled; the slice
            # start clamps at 0 so ALL creation kwargs stay required
            # (a negative start would wrap and silently drop 'shape')
            start = max(0, len(_CT007_CREATE_KWS) - need)
            missing += [
                k for k in _CT007_CREATE_KWS[start:]
                if k not in present
            ]
        if missing:
            out.append(Finding(
                "CT007", module.path, call.lineno, call.col_offset,
                f"handoff_dataset declaration misses spill wiring "
                f"{missing}: without the full storage twin spec the "
                "MemoryTarget cannot spill under admission/headroom/fault "
                "pressure and the fallback contract is broken",
            ))
        if bound is None:
            out.append(Finding(
                "CT007", module.path, call.lineno, call.col_offset,
                "handoff_dataset result is not bound to a name: the handle "
                "cannot be wired into a region_verifier, so the in-memory "
                "data plane is invisible to integrity verification",
            ))
        elif bound not in verified:
            out.append(Finding(
                "CT007", module.path, call.lineno, call.col_offset,
                f"handoff handle {bound!r} is never passed to "
                "region_verifier(...) in this module: wire "
                "store_verify_fn=region_verifier(...) so post-store "
                "integrity checks cover the in-memory target too",
            ))

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign):
            call = node.value
            if isinstance(call, ast.Call) \
                    and last_seg(dotted(call.func)) == "handoff_dataset":
                bound = None
                if len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    bound = node.targets[0].id
                _check(call, bound)
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if last_seg(dotted(call.func)) == "handoff_dataset":
                _check(call, None)

    for call in calls_in(module.tree):
        if last_seg(dotted(call.func)) != "publish_device_arrays":
            continue
        present, splat = kw_names(call)
        if splat:
            continue  # wiring forwarded wholesale; not statically checkable
        pos = len(call.args)
        # positional args fill path, arrays, producer, failures_path
        missing = [
            k for i, k in enumerate(_CT007_DEVICE_PUBLISH_KWS)
            if pos < 3 + i and k not in present
        ]
        if missing:
            out.append(Finding(
                "CT007", module.path, call.lineno, call.col_offset,
                f"device handoff publish misses its spill contract "
                f"{missing}: a demotion or host-staged fallback from the "
                "device rung cannot be attributed (degraded:host_staged "
                "in failures.json) without the producer identity and the "
                "failure-ledger path",
            ))
    return out


# =============================================================================
# CT008 - trace hygiene
# =============================================================================

#: direct wall-clock calls banned in ``runtime/`` outside the tracer
#: (docs/OBSERVABILITY.md "Timing discipline"): every duration must come
#: from a trace span (so the timeline, the counters, and the manifests
#: agree on one clock) and every wall timestamp from ``trace.walltime()``.
_CT008_BANNED_CLOCKS = frozenset({"time.time", "time.perf_counter"})

#: orchestration entry points that must run under a task trace context —
#: the spans they emit (executor.load/store/dispatch, host.block,
#: solve.*) are only attributable when a ``task.run``-shaped span
#: brackets them.  Call sites inside a class get the context from
#: ``BaseTask.run``; free functions (bench drivers, scripts) must open
#: one explicitly with ``trace.task_context(...)``.
_CT008_TRACED_CALLS = frozenset({
    "map_blocks",
    "host_block_map",
    "solve_with_reduce_tree",
})


def _in_runtime_package(path: str) -> bool:
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    try:
        return parts[parts.index("cluster_tools_tpu") + 1] == "runtime"
    except (ValueError, IndexError):
        return False


def ct008_trace_hygiene(module: LintModule) -> List[Finding]:
    """The unified tracing plane's two contracts (docs/OBSERVABILITY.md).

    (a) **One clock**: no direct ``time.time()`` / ``time.perf_counter()``
    timing in ``runtime/`` outside ``trace.py`` — durations come from
    trace spans (``trace.span``/``trace.begin``, whose ``end()`` returns
    the elapsed seconds even with the tracer off) and wall timestamps
    from ``trace.walltime()``, so the timeline, the io_metrics counters,
    and the heartbeat/manifest stamps can never disagree about where the
    wall-clock went.  ``time.monotonic()`` deadlines and ``time.sleep``
    backoffs are not timing *measurements* and stay allowed.

    (b) **Attributable spans**: every ``map_blocks`` /
    ``host_block_map`` / ``solve_with_reduce_tree`` call site runs under
    a task trace context — inside a task class (``BaseTask.run`` opens
    the ``task.run`` span) or under an explicit
    ``trace.task_context(...)`` in the enclosing function/module (bench
    drivers, scripts).  Without it, the hot-boundary spans those calls
    emit land on the timeline with no task to belong to.
    """
    out: List[Finding] = []
    is_fixture = "ct008" in module.name

    # -- (a) wall-clock discipline in runtime/ ----------------------------
    if (is_fixture or _in_runtime_package(module.path)) \
            and module.name != "trace.py":
        time_aliases = {"time"}   # names that refer to the time module
        from_time = {}            # local name -> original name in time
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        time_aliases.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    from_time[a.asname or a.name] = a.name
        for call in calls_in(module.tree):
            name = dotted(call.func)
            if name is None:
                continue
            mod, _, attr = name.rpartition(".")
            banned = (
                name in _CT008_BANNED_CLOCKS
                # aliased module form: import time as t; t.perf_counter()
                or (mod in time_aliases
                    and attr in ("time", "perf_counter"))
                # from-import form incl. aliases: from time import
                # perf_counter as pc; pc()
                or from_time.get(name) in ("time", "perf_counter")
            )
            if banned:
                out.append(Finding(
                    "CT008", module.path, call.lineno, call.col_offset,
                    f"direct {name}() timing in runtime/ bypasses the "
                    "tracing plane; measure durations with trace.span/"
                    "trace.begin (end() returns elapsed seconds even with "
                    "the tracer off) and stamp wall clocks with "
                    "trace.walltime()",
                ))

    # -- (b) orchestration calls under a task trace context ---------------
    for call in calls_in(module.tree):
        seg = last_seg(dotted(call.func))
        if seg not in _CT008_TRACED_CALLS:
            continue
        if module.enclosing_class(call) is not None:
            # a method of a task class: BaseTask.run brackets run_impl
            # (and everything it calls) in the task.run span
            continue
        covered = False
        scope: Optional[ast.AST] = module.enclosing_function(call)
        while scope is not None and not covered:
            covered = any(
                last_seg(dotted(c.func)) == "task_context"
                for c in calls_in(scope)
            )
            scope = module.enclosing_function(scope)
        if not covered:
            # module level: a top-level task_context call still counts
            covered = any(
                last_seg(dotted(c.func)) == "task_context"
                and module.enclosing_function(c) is None
                for c in calls_in(module.tree)
            )
        if not covered:
            out.append(Finding(
                "CT008", module.path, call.lineno, call.col_offset,
                f"{seg} call site outside any task class and without a "
                "trace.task_context(...) in scope: its hot-boundary spans "
                "would land on the timeline unattributed — open a task "
                "context (or move the call into a task)",
            ))
    return out


# =============================================================================
# CT009 - service-mode server hygiene
# =============================================================================

#: the service-mode surface (docs/SERVING.md): the resident server, its
#: admission controller, and the serve CLI entry
_CT009_SCOPE = ("server.py", "admission.py", "serve.py")

#: storage-IO call segments additionally banned under the server's
#: bookkeeping locks: every request handler, HTTP thread, and worker
#: contends for the admission/request locks, so one filesystem call under
#: them head-of-line-blocks the whole service
_CT009_IO_CALLS = frozenset({
    "open", "dump", "dumps", "load", "loads", "listdir", "replace",
    "unlink", "remove", "makedirs", "save", "fsync", "read", "write",
    "atomic_write_json", "record_failures", "dump_config", "_write_state",
    "flush_namespace", "_json_report",
})


def _walk_inline(stmt: ast.AST) -> Iterable[ast.AST]:
    """``ast.walk`` minus nested function/lambda bodies: a def or lambda
    under a lock only DEFINES deferred code — what it calls runs after the
    lock is released, so flagging it would be a false positive."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def ct009_server_hygiene(module: LintModule) -> List[Finding]:
    """Service-mode hygiene for the resident server (docs/SERVING.md).

    (a) **Admission-lock discipline**: the admission/request locks guard
    pure bookkeeping only — no blocking calls (``.result``/``sleep``/
    ``wait``/``join``) and no storage IO (``open``/``json.dump``/
    ``atomic_write_json``/``record_failures``/...) while holding them.
    Every submit, worker dispatch, and status probe contends for these
    locks; one slow callee under them freezes the whole service.

    (b) **Attributable request handlers**: every handler that runs a
    workflow (``build(...)``) must do so under BOTH an ambient request
    context (``admission.request_context``/``request_scope`` — handoff
    identities lose their request namespace without it, letting
    concurrent requests over the same paths resolve each other's
    intermediates) and a trace task context (``trace.task_context`` —
    otherwise the request's spans land on the resident timeline with no
    request to belong to).

    (c) **Drain protocol at the entry point**: any caller of
    ``serve_until_drained()`` (which raises ``DrainInterrupt`` after the
    drain finishes) must map it to ``REQUEUE_EXIT_CODE`` — a drained
    server that exits nonzero-as-crash breaks the rolling-restart
    protocol (docs/SERVING.md "Lifecycle").
    """
    is_fixture = "ct009" in module.name
    if module.name not in _CT009_SCOPE and not is_fixture:
        return []
    out: List[Finding] = []

    # -- (a) nothing slow under the server's bookkeeping locks -------------
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        keys = [
            k for k in (
                _lock_key(module, item.context_expr) for item in node.items
            ) if k is not None
        ]
        if not keys:
            continue
        held = keys[-1]
        for stmt in node.body:
            for inner in _walk_inline(stmt):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted(inner.func)
                seg = last_seg(name)
                if seg is None:
                    continue
                if seg in _BLOCKING_CALLS or (name or "").startswith(
                    "subprocess."
                ):
                    if seg == "join" and isinstance(
                        inner.func, ast.Attribute
                    ) and isinstance(inner.func.value, ast.Constant):
                        continue  # "sep".join(...) is not a thread join
                    out.append(Finding(
                        "CT009", module.path, inner.lineno,
                        inner.col_offset,
                        f"blocking call '{name}' while holding server "
                        f"lock '{held}': every submit/dispatch/status "
                        "thread contends for it — wait outside the "
                        "critical section (admission waits on the "
                        "dispatch event, not under the lock)",
                    ))
                elif seg in _CT009_IO_CALLS:
                    out.append(Finding(
                        "CT009", module.path, inner.lineno,
                        inner.col_offset,
                        f"storage IO '{name}' under server lock "
                        f"'{held}': state/failure writes must happen "
                        "after release — snapshot under the lock, write "
                        "outside it",
                    ))

    # -- (b) request handlers run under request + trace contexts -----------
    for call in calls_in(module.tree):
        if last_seg(dotted(call.func)) != "build":
            continue
        covered_req = covered_task = False
        scope: Optional[ast.AST] = module.enclosing_function(call)
        while scope is not None:
            for c in calls_in(scope):
                seg = last_seg(dotted(c.func))
                if seg in ("request_context", "request_scope"):
                    covered_req = True
                elif seg == "task_context":
                    covered_task = True
            scope = module.enclosing_function(scope)
        missing = []
        if not covered_req:
            missing.append("admission.request_context (handoff "
                           "identities lose their request namespace)")
        if not covered_task:
            missing.append("trace.task_context (spans land on the "
                           "resident timeline unattributed)")
        if missing:
            out.append(Finding(
                "CT009", module.path, call.lineno, call.col_offset,
                "request handler runs build() without "
                + " or ".join(missing),
            ))

    # -- (c) serve entry points speak the drain protocol -------------------
    for call in calls_in(module.tree):
        if last_seg(dotted(call.func)) != "serve_until_drained":
            continue
        if not ("DrainInterrupt" in module.source
                and "REQUEUE_EXIT_CODE" in module.source):
            out.append(Finding(
                "CT009", module.path, call.lineno, call.col_offset,
                "serve_until_drained() raises DrainInterrupt after the "
                "drain, but this entry point never maps it to "
                "REQUEUE_EXIT_CODE: a SIGTERM'd server exits as a crash "
                "instead of a rolling-restart requeue",
            ))
    return out


# =============================================================================
# CT010 - durable-journal discipline
# =============================================================================

#: the journal-aware surface (docs/SERVING.md "Durability"): the journal
#: itself plus everything that may hold the server's bookkeeping locks
_CT010_SCOPE = ("journal.py", "server.py", "admission.py", "serve.py")

#: IO methods that, invoked on a journal-named object/path outside
#: journal.py, bypass the one framed+fsync'd append path
_CT010_RAW_IO = frozenset({"write", "writelines", "truncate"})

#: journal-object call segments that do disk IO (an append is an fsync —
#: a disk round trip) and must never run under the server's locks
_CT010_JOURNAL_IO = frozenset({
    "append", "append_transition", "recover", "close", "_journal_append",
})


def _names_journal(name: Optional[str]) -> bool:
    return name is not None and "journal" in name.lower()


def ct010_journal_discipline(module: LintModule) -> List[Finding]:
    """The durable submission journal's three invariants
    (docs/SERVING.md "Durability").

    (a) **One append path**: outside ``runtime/journal.py``, nothing may
    write the journal file directly — no ``open()`` of a journal-named
    path in write/append mode, no ``.write``/``.truncate`` on a
    journal-named handle.  ``Journal.append`` is where the CRC framing
    and the fsync live; a raw write bypasses both and can forge a record
    a replay would trust.

    (b) **Fsync evidence**: the ``append`` method of a ``Journal`` class
    must call ``os.fsync`` — an acknowledgement whose record only made it
    to the page cache is a durability lie under SIGKILL.

    (c) **No journal IO under the server's locks**: a journal append is a
    disk round trip; under the admission/request locks it head-of-line
    blocks every submit, dispatch, and status thread (same reasoning as
    CT009's IO ban, extended to the journal object).
    """
    is_fixture = "ct010" in module.name
    if module.name not in _CT010_SCOPE and not is_fixture:
        return []
    out: List[Finding] = []
    is_journal_module = module.name == "journal.py" and not is_fixture

    # -- (a) raw journal-file IO outside the journal module ----------------
    if not is_journal_module:
        for call in calls_in(module.tree):
            name = dotted(call.func)
            seg = last_seg(name)
            if seg == "open" or name == "os.open":
                touches = any(
                    _names_journal(dotted(a)) or _names_journal(str_const(a))
                    for a in call.args
                )
                # read-mode opens are fine (report tooling scans the
                # journal); only write/append modes forge records.  A
                # mode-less builtin open() defaults to 'r' — read-only;
                # os.open takes flag ints we cannot prove read-only, so
                # it always counts as writable.
                mode = None
                if len(call.args) >= 2:
                    mode = str_const(call.args[1])
                for kw in call.keywords:
                    if kw.arg == "mode":
                        mode = str_const(kw.value)
                if name == "os.open":
                    writable = True
                else:
                    writable = mode is not None and any(
                        c in mode for c in ("w", "a", "+", "x")
                    )
                if touches and writable:
                    out.append(Finding(
                        "CT010", module.path, call.lineno, call.col_offset,
                        "raw open of the journal file outside "
                        "runtime/journal.py: appends must go through "
                        "Journal.append (CRC framing + fsync) — a direct "
                        "write can forge a record replay would trust",
                    ))
            elif seg in _CT010_RAW_IO and isinstance(
                call.func, ast.Attribute
            ):
                base = dotted(call.func.value)
                if _names_journal(base):
                    out.append(Finding(
                        "CT010", module.path, call.lineno, call.col_offset,
                        f"raw '{seg}' on journal handle '{base}' outside "
                        "runtime/journal.py: the one append path is "
                        "Journal.append (CRC framing + fsync)",
                    ))

    # -- (b) fsync evidence in the append path -----------------------------
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef) or "Journal" not in node.name:
            continue
        for item in node.body:
            if not isinstance(item, ast.FunctionDef) or item.name != "append":
                continue
            has_fsync = any(
                last_seg(dotted(c.func)) == "fsync" for c in calls_in(item)
            )
            if not has_fsync:
                out.append(Finding(
                    "CT010", module.path, item.lineno, item.col_offset,
                    f"{node.name}.append has no os.fsync evidence: an "
                    "acknowledgement whose record only reached the page "
                    "cache is a durability lie under SIGKILL — fsync "
                    "before returning",
                ))

    # -- (c) no journal IO under the server's bookkeeping locks ------------
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        keys = [
            k for k in (
                _lock_key(module, item.context_expr) for item in node.items
            ) if k is not None
        ]
        if not keys:
            continue
        held = keys[-1]
        if is_journal_module and held == "Journal._lock":
            continue  # the journal's own lock IS the append serializer
        for stmt in node.body:
            for inner in _walk_inline(stmt):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted(inner.func)
                seg = last_seg(name)
                if seg in _CT010_JOURNAL_IO and _names_journal(name):
                    out.append(Finding(
                        "CT010", module.path, inner.lineno,
                        inner.col_offset,
                        f"journal IO '{name}' while holding server lock "
                        f"'{held}': an append is an fsync — a disk round "
                        "trip that head-of-line blocks every "
                        "submit/dispatch/status thread; journal outside "
                        "the critical section",
                    ))
    return out


# =============================================================================
# CT011 - verified-read discipline
# =============================================================================

#: the verifying reader lives in the io package (docs/SERVING.md
#: "Self-healing"); inside it, raw reads are the implementation
_CT011_IO_PKG = os.path.join("cluster_tools_tpu", "io") + os.sep

#: sidecar directories whose raw traversal outside io/ bypasses the
#: dataset API (scrub/repair must use checksum_regions / verify_region)
_CT011_SIDECAR_DIRS = (".ctt_checksums",)

#: file-read entry points checked for sidecar-path constants
_CT011_OPENERS = frozenset({"open", "fromfile", "memmap", "load"})


def _ct011_outside_io(path: str) -> bool:
    return _CT011_IO_PKG not in os.path.abspath(path)


def ct011_verified_read_discipline(module: LintModule) -> List[Finding]:
    """Every read of a block product goes through the verifying reader
    (docs/SERVING.md "Self-healing").  The container read paths
    (``ds[bb]`` / ``read_async``) ARE the verifying reader — digest
    verification, the missing-sidecar policy, and lineage repair ride
    them — so outside ``cluster_tools_tpu/io/`` nothing may:

    (a) call ``_read_back`` (the raw, verification-free region read);
    (b) read through a dataset's raw ``._store`` handle
        (``ds._store[bb].read()`` returns whatever bytes are on disk,
        poisoned or not);
    (c) ``open()`` / ``np.fromfile`` a digest-sidecar path
        (``.ctt_checksums``) directly — sidecar state must flow through
        ``checksum_regions`` / ``checksum_entry`` / ``verify_region`` so
        the index cache and the policy layer stay coherent.
    """
    out: List[Finding] = []
    if module.tree is None or not _ct011_outside_io(module.path):
        return out
    for call in calls_in(module.tree):
        if isinstance(call.func, ast.Attribute):
            if call.func.attr == "_read_back":
                out.append(Finding(
                    "CT011", module.path, call.lineno, call.col_offset,
                    "raw '_read_back' outside io/: the bytes skip digest "
                    "verification, the missing-sidecar policy, and "
                    "lineage repair — read through the dataset API "
                    "(ds[bb] / read_async), which IS the verifying "
                    "reader",
                ))
                continue
            if call.func.attr in ("read", "write") and any(
                isinstance(n, ast.Attribute) and n.attr == "_store"
                for n in ast.walk(call.func.value)
            ):
                out.append(Finding(
                    "CT011", module.path, call.lineno, call.col_offset,
                    "raw '._store' access outside io/: a store-handle "
                    f"'{call.func.attr}' bypasses the verifying reader "
                    "(and the write-side sidecar recording) — use the "
                    "dataset API",
                ))
                continue
        seg = last_seg(dotted(call.func))
        if seg in _CT011_OPENERS:
            hit = None
            for n in ast.walk(call):
                s = str_const(n)
                if s and any(d in s for d in _CT011_SIDECAR_DIRS):
                    hit = s
                    break
            if hit is not None:
                out.append(Finding(
                    "CT011", module.path, call.lineno, call.col_offset,
                    f"raw '{seg}' of a digest-sidecar path ({hit!r}) "
                    "outside io/: sidecar state must flow through "
                    "checksum_regions/checksum_entry/verify_region so "
                    "the index cache and the missing-sidecar policy "
                    "stay coherent",
                ))
    return out


# =============================================================================
# CT012 - fleet hygiene
# =============================================================================

#: the fleet layer (docs/SERVING.md "Fleet"): the gateway/router module
#: (runtime/fleet.py) and the fleet CLI both answer to the name
_CT012_SCOPE = ("fleet.py",)

#: call segments that do a network round trip (the gateway's member-call
#: helpers plus the stdlib HTTP client surface) — forbidden under the
#: router's locks on top of CT009's blocking/IO sets: one slow member
#: probed under the placement lock head-of-line blocks every submit
_CT012_HTTP_CALLS = frozenset({
    "HTTPConnection", "urlopen", "getresponse", "request",
    "_member_call", "_call", "_call_once", "_probe_member", "healthz",
    "submit",
})

#: the adoption-claim API (runtime/fleet.py) — the only sanctioned
#: doorway to a peer's journal
_CT012_CLAIM_API = frozenset({
    "acquire_adoption_claim", "verify_adoption_claim",
    "read_adoption_claim", "release_adoption_claim", "read_peer_journal",
})

#: read entry points into a peer's journal that must be claim-gated
_CT012_JOURNAL_READS = frozenset({"scan", "recover", "journal_path"})


def ct012_fleet_hygiene(module: LintModule) -> List[Finding]:
    """Fleet-layer hygiene for the gateway/router (docs/SERVING.md
    "Fleet").

    (a) **Placement-lock discipline**: the router's locks guard pure
    bookkeeping (member table, affinity map, route table, counters) —
    no blocking calls, no storage IO, and, the fleet-specific extension,
    no HTTP (member calls, health probes) while holding them.  Every
    submit contends for the placement lock; one slow member probed under
    it freezes the whole fleet's intake.

    (b) **Journal adoption only through the claim API**: a peer's
    journal may only be read via ``read_peer_journal`` /
    ``verify_adoption_claim`` — no raw ``open()`` of a journal-named
    path, and no ``journal.scan``/``recover``/``journal_path`` reach
    into a peer outside a claim-holding scope.  Two servers replaying
    one journal double-run acknowledged work; the O_CREAT|O_EXCL claim
    file is the exactly-one-adopter proof, and this rule is what keeps
    every code path behind it.

    (c) **Drain protocol at the entry point**: any caller of
    ``serve_until_drained()`` must map ``DrainInterrupt`` to
    ``REQUEUE_EXIT_CODE`` (114) — a drained gateway that exits
    nonzero-as-crash breaks the rolling-restart protocol, same contract
    as CT009(c) for the single server.
    """
    is_fixture = "ct012" in module.name
    if module.name not in _CT012_SCOPE and not is_fixture:
        return []
    out: List[Finding] = []

    # -- (a) nothing slow under the router's bookkeeping locks -------------
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        keys = [
            k for k in (
                _lock_key(module, item.context_expr) for item in node.items
            ) if k is not None
        ]
        if not keys:
            continue
        held = keys[-1]
        for stmt in node.body:
            for inner in _walk_inline(stmt):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted(inner.func)
                seg = last_seg(name)
                if seg is None:
                    continue
                if seg in _BLOCKING_CALLS or (name or "").startswith(
                    "subprocess."
                ):
                    if seg == "join" and isinstance(
                        inner.func, ast.Attribute
                    ) and isinstance(inner.func.value, ast.Constant):
                        continue  # "sep".join(...) is not a thread join
                    out.append(Finding(
                        "CT012", module.path, inner.lineno,
                        inner.col_offset,
                        f"blocking call '{name}' while holding router "
                        f"lock '{held}': every submit contends for the "
                        "placement lock — wait outside the critical "
                        "section",
                    ))
                elif seg in _CT012_HTTP_CALLS:
                    out.append(Finding(
                        "CT012", module.path, inner.lineno,
                        inner.col_offset,
                        f"HTTP call '{name}' while holding router lock "
                        f"'{held}': one slow member probed under the "
                        "placement lock head-of-line blocks the whole "
                        "fleet's intake — snapshot under the lock, call "
                        "outside it",
                    ))
                elif seg in _CT009_IO_CALLS:
                    out.append(Finding(
                        "CT012", module.path, inner.lineno,
                        inner.col_offset,
                        f"storage IO '{name}' under router lock "
                        f"'{held}': state/failure writes must happen "
                        "after release — snapshot under the lock, write "
                        "outside it",
                    ))

    # -- (b) peer journals only through the adoption-claim API -------------
    def _claim_gated(call: ast.Call) -> bool:
        scope: Optional[ast.AST] = module.enclosing_function(call)
        while scope is not None:
            for c in calls_in(scope):
                if last_seg(dotted(c.func)) in _CT012_CLAIM_API:
                    return True
            scope = module.enclosing_function(scope)
        return False

    def _journal_arg(call: ast.Call) -> bool:
        # walk arg subtrees: "journal.log" inside os.path.join(...) is
        # still a journal path
        return any(
            _names_journal(dotted(n)) or _names_journal(str_const(n))
            for a in call.args
            for n in ast.walk(a)
        )

    for call in calls_in(module.tree):
        name = dotted(call.func)
        seg = last_seg(name)
        if seg == "open" or name == "os.open":
            if _journal_arg(call):
                out.append(Finding(
                    "CT012", module.path, call.lineno, call.col_offset,
                    "raw open of a journal path in the fleet layer: a "
                    "peer's journal may only be read via "
                    "read_peer_journal under the exclusive adoption "
                    "claim — two servers replaying one journal "
                    "double-run acknowledged work",
                ))
            continue
        if seg in _CT012_JOURNAL_READS:
            journalish = _names_journal(name) or _journal_arg(call)
            if journalish and not _claim_gated(call):
                out.append(Finding(
                    "CT012", module.path, call.lineno, call.col_offset,
                    f"journal read '{name}' outside a claim-holding "
                    "scope: adoption must verify the O_CREAT|O_EXCL "
                    "claim file first (acquire_adoption_claim / "
                    "verify_adoption_claim / read_peer_journal) — the "
                    "claim is the exactly-one-adopter proof",
                ))

    # -- (c) fleet entry points speak the drain protocol -------------------
    for call in calls_in(module.tree):
        if last_seg(dotted(call.func)) != "serve_until_drained":
            continue
        if not ("DrainInterrupt" in module.source
                and "REQUEUE_EXIT_CODE" in module.source):
            out.append(Finding(
                "CT012", module.path, call.lineno, call.col_offset,
                "serve_until_drained() raises DrainInterrupt after the "
                "drain, but this entry point never maps it to "
                "REQUEUE_EXIT_CODE: a SIGTERM'd gateway exits as a "
                "crash instead of a rolling-restart requeue",
            ))
    return out


# =============================================================================
# CT013 - gray-failure hygiene
# =============================================================================

#: outbound-connection constructors that, without an explicit deadline,
#: hang forever on a wedged peer (SYN-acked socket that never answers) —
#: the gray failure the breaker/hedging stack exists to bound
_CT013_NET_CALLS = frozenset({
    "HTTPConnection", "HTTPSConnection", "urlopen", "create_connection",
})

#: write paths that move acknowledged bytes to durable/visible places and
#: must therefore be fence-gated in server code: journal transitions and
#: handoff publishes.  A zombie server that was adopted away and still
#: reaches one of these double-writes acknowledged work.
_CT013_FENCED_WRITES = frozenset({"append_transition", "flush_namespace"})

#: the modules whose writes are fence-gated (the member server surface)
_CT013_FENCE_SCOPE = ("server.py",)


def ct013_grayfail_hygiene(module: LintModule) -> List[Finding]:
    """Gray-failure hygiene (docs/SERVING.md "Gray failures").

    (a) **Every outbound connection carries an explicit deadline**: an
    ``HTTPConnection``/``urlopen``/``create_connection`` without a
    ``timeout`` kwarg blocks forever on a wedged peer — the caller's
    thread is gone, no breaker ever trips, and the fleet degrades
    silently instead of failing over.  All serve-plane HTTP is supposed
    to go through ``runtime/netio.py`` (which always passes one); a raw
    deadline-less call is a hole in the gray-failure defense.

    (b) **Acknowledged writes in server code are fence-gated**: a
    ``journal.append_transition`` / ``handoff.flush_namespace`` call
    site in the member server whose enclosing scope shows no fencing
    evidence — neither a ``fence_guard.check()`` call nor a
    ``Fenced``-handling except — is a path a zombie can still write
    through after a survivor adopted its journal.  The fence epoch makes
    zombie double-writes structurally impossible only if every such
    write path re-validates the epoch first.
    """
    is_fixture = "ct013" in module.name
    out: List[Finding] = []

    # -- (a) no deadline-less outbound connections -------------------------
    for call in calls_in(module.tree):
        seg = last_seg(dotted(call.func))
        if seg not in _CT013_NET_CALLS:
            continue
        names, splat = kw_names(call)
        if "timeout" in names or splat:
            continue
        out.append(Finding(
            "CT013", module.path, call.lineno, call.col_offset,
            f"outbound connection '{seg}' without an explicit timeout "
            "kwarg: a wedged peer (accepted connection that never "
            "answers) blocks this caller forever and no circuit breaker "
            "ever trips — route serve-plane HTTP through "
            "runtime/netio.http_json_call, or pass timeout=",
        ))

    # -- (b) fence-gated acknowledged writes in the member server ----------
    if module.name not in _CT013_FENCE_SCOPE and not is_fixture:
        return out

    def _fence_guarded(call: ast.Call) -> bool:
        """Fencing evidence anywhere in the enclosing function chain: a
        ``*fence*.check()`` call, or an ``except ...Fenced`` handler
        (the append path itself re-validates under the journal lock and
        surfaces the verdict as the exception)."""
        scope: Optional[ast.AST] = module.enclosing_function(call)
        while scope is not None:
            for c in calls_in(scope):
                name = dotted(c.func) or ""
                if last_seg(name) == "check" and "fence" in name.lower():
                    return True
            for node in ast.walk(scope):
                if (isinstance(node, ast.ExceptHandler)
                        and node.type is not None):
                    if any(
                        "Fenced" in (dotted(n) or "")
                        for n in ast.walk(node.type)
                    ):
                        return True
            scope = module.enclosing_function(scope)
        return False

    for call in calls_in(module.tree):
        seg = last_seg(dotted(call.func))
        if seg not in _CT013_FENCED_WRITES:
            continue
        if _fence_guarded(call):
            continue
        out.append(Finding(
            "CT013", module.path, call.lineno, call.col_offset,
            f"acknowledged write '{seg}' with no fencing evidence in "
            "scope (no fence_guard.check() and no Fenced handler): a "
            "zombie server adopted away while wedged can still write "
            "through this path, double-running acknowledged work — "
            "re-validate the fence epoch before bytes move",
        ))
    return out


# =============================================================================
# CT014 - supervisor hygiene
# =============================================================================

#: the supervisor surface: the fleet CLI (now the supervisor process) and
#: the gateway/router module whose failover/scale-down helpers are
#: lifecycle decisions too
_CT014_SCOPE = ("fleet.py",)

#: journal-plane evidence for a lifecycle decision: a typed ledger record
#: or a durable failure-surface record in scope
_CT014_JOURNAL_EVIDENCE = frozenset({"append_transition", "record_failures"})

#: trace-plane evidence: the decision lands on the timeline
_CT014_TRACE_EVIDENCE = frozenset({"instant"})


def ct014_supervisor_hygiene(module: LintModule) -> List[Finding]:
    """Supervisor hygiene for the fleet's control plane (docs/SERVING.md
    "Supervision").

    (a) **Every lifecycle decision is journaled AND traced**: a call
    site that spawns/respawns a process (``*spawn*``, ``Popen``) or
    scales the fleet down (``drain_emptiest``) must show journal-plane
    evidence (``append_transition``/``record_failures`` or a
    ``*journal_decision*`` helper) and trace-plane evidence
    (``trace.instant`` or the same helper) — in the enclosing function
    chain or directly in the same-module definition of the called
    helper.  An unjournaled respawn/scale decision makes a healed fleet
    unauditable: nobody can replay WHY capacity changed, which is the
    difference between a control loop and a haunted house.

    (b) **No process spawn or blocking wait under a lock**: extending
    CT012(a), a ``subprocess.Popen``/``subprocess.*`` call or a blocking
    wait (``sleep``/``wait``/``join``/``result``) while holding any
    ``*lock*``-named context serializes fork+exec (or a child's whole
    lifetime) behind bookkeeping every submit contends for.  The
    supervisor is single-threaded by design; anything lock-shaped in
    this layer must stay pure bookkeeping.
    """
    is_fixture = "ct014" in module.name
    if module.name not in _CT014_SCOPE and not is_fixture:
        return []
    out: List[Finding] = []

    defs_by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)

    def _evidence_in(scope: ast.AST) -> Tuple[bool, bool]:
        journaled = traced = False
        for c in calls_in(scope):
            seg = last_seg(dotted(c.func)) or ""
            if "journal_decision" in seg:
                # the canonical helper writes both planes at once
                journaled = traced = True
            if seg in _CT014_JOURNAL_EVIDENCE:
                journaled = True
            if seg in _CT014_TRACE_EVIDENCE:
                traced = True
        return journaled, traced

    def _decision_evidence(call: ast.Call,
                           callee_seg: str) -> Tuple[bool, bool]:
        journaled = traced = False
        scope: Optional[ast.AST] = module.enclosing_function(call)
        while scope is not None:
            j, t = _evidence_in(scope)
            journaled, traced = journaled or j, traced or t
            scope = module.enclosing_function(scope)
        # one level into the called helper: a spawn wrapper that
        # journals inside its own body covers all its call sites
        target = defs_by_name.get(callee_seg)
        if target is not None:
            j, t = _evidence_in(target)
            journaled, traced = journaled or j, traced or t
        return journaled, traced

    # -- (a) spawn/scale decisions carry journal + trace evidence ----------
    for call in calls_in(module.tree):
        seg = last_seg(dotted(call.func))
        if seg is None:
            continue
        low = seg.lower()
        if "journal_decision" in low:
            continue  # the evidence helper is not itself a decision
        if not (seg == "Popen" or "spawn" in low
                or seg == "drain_emptiest"):
            continue
        journaled, traced = _decision_evidence(call, seg)
        if not journaled:
            out.append(Finding(
                "CT014", module.path, call.lineno, call.col_offset,
                f"lifecycle decision '{seg}' with no journal-plane "
                "evidence in scope (append_transition / record_failures "
                "/ a *journal_decision* helper): an unjournaled "
                "respawn/scale decision cannot be replayed or "
                "attributed after the fleet heals itself",
            ))
        if not traced:
            out.append(Finding(
                "CT014", module.path, call.lineno, call.col_offset,
                f"lifecycle decision '{seg}' with no trace-plane "
                "evidence in scope (trace.instant / a *journal_decision* "
                "helper): supervisor decisions must land on the trace "
                "timeline next to the work they moved",
            ))

    # -- (b) no fork+exec or blocking wait under a lock --------------------
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        keys = [
            k for k in (
                _lock_key(module, item.context_expr) for item in node.items
            ) if k is not None
        ]
        if not keys:
            continue
        held = keys[-1]
        for stmt in node.body:
            for inner in _walk_inline(stmt):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted(inner.func)
                seg = last_seg(name)
                if seg is None:
                    continue
                if seg == "join" and isinstance(
                    inner.func, ast.Attribute
                ) and isinstance(inner.func.value, ast.Constant):
                    continue  # "sep".join(...) is not a thread join
                if (seg == "Popen"
                        or (name or "").startswith("subprocess.")
                        or seg in _BLOCKING_CALLS):
                    out.append(Finding(
                        "CT014", module.path, inner.lineno,
                        inner.col_offset,
                        f"process spawn / blocking wait '{name}' while "
                        f"holding lock '{held}': fork+exec (or a "
                        "child's lifetime) serialized behind supervisor "
                        "bookkeeping — decide under the lock, spawn "
                        "outside it",
                    ))
    return out


# =============================================================================
# CT015 - reduce-plane discipline
# =============================================================================

#: the reduce-plane surface: the tree driver (both planes) and the
#: multihost wiring that probes collective support
_CT015_SCOPE = ("reduce_tree.py", "multihost.py")

#: waits on the reduce plane and the patience evidence each must carry:
#: ``callee -> (min_positional_args_that_satisfy, accepted_kwargs)``.
#: ``_wait_npz(path, wait_s)`` satisfies positionally; the collective
#: level dispatch and the support probe must name their deadline.
_CT015_WAITS: Dict[str, Tuple[Optional[int], frozenset]] = {
    "_wait_npz": (2, frozenset({"wait_s", "deadline"})),
    "solve_level": (None, frozenset({"deadline_s", "hop_deadline_s"})),
    "collectives_supported": (1, frozenset({"deadline_s", "timeout"})),
}


def ct015_reduce_plane_discipline(module: LintModule) -> List[Finding]:
    """Reduce-plane discipline (docs/PERFORMANCE.md "Collective reduce
    plane").

    (a) **No unbounded waits on the reduce plane**: every collective hop
    (``solve_level`` dispatch, ``collectives_supported`` probe) and every
    packet poll (``_wait_npz``) must carry an explicit deadline/patience
    argument.  A deadline-less hop turns one dead worker into a wedged
    worker *group*: siblings block forever on a packet or a collective
    that is never coming, and the driver's own timeout is the only thing
    left to notice — minutes instead of one patience window.

    (b) **Every ``degraded:packet_plane`` fallback site writes a failures
    record**: a function whose body mentions the resolution string must
    show a ``record_failures`` call — in its own body or one level into a
    same-module helper it calls (the CT014 evidence walk).  A silent
    degradation leaves io_metrics claiming collectives ran while every
    level quietly went through the filesystem; the failures record is
    what makes the ladder auditable.
    """
    is_fixture = "ct015" in module.name
    if module.name not in _CT015_SCOPE and not is_fixture:
        return []
    out: List[Finding] = []

    # -- (a) every hop/poll carries patience -------------------------------
    for call in calls_in(module.tree):
        seg = last_seg(dotted(call.func))
        if seg not in _CT015_WAITS:
            continue
        min_pos, accepted = _CT015_WAITS[seg]
        names, splat = kw_names(call)
        if splat or (names & accepted):
            continue
        if min_pos is not None and len(call.args) >= min_pos:
            continue
        out.append(Finding(
            "CT015", module.path, call.lineno, call.col_offset,
            f"reduce-plane wait '{seg}' without an explicit "
            f"deadline/patience argument ({sorted(accepted)}): an "
            "unbounded hop lets one dead worker wedge the whole group — "
            "every packet poll and collective dispatch must be able to "
            "declare the hop lost",
        ))

    # -- (b) degraded:packet_plane sites write a failures record -----------
    defs_by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)

    def _writes_failures(scope: ast.AST, depth: int = 1) -> bool:
        for c in calls_in(scope):
            seg = last_seg(dotted(c.func))
            if seg == "record_failures":
                return True
            if depth and seg in defs_by_name and _writes_failures(
                defs_by_name[seg], depth - 1
            ):
                return True
        return False

    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mentions = any(
            "degraded:packet_plane" in (str_const(n) or "")
            for n in ast.walk(node)
        )
        if not mentions:
            continue
        if _writes_failures(node):
            continue
        out.append(Finding(
            "CT015", module.path, node.lineno, node.col_offset,
            f"'{node.name}' degrades to the packet plane "
            "(degraded:packet_plane) without failures-record evidence "
            "(record_failures in its body or a same-module helper it "
            "calls): silent degradation makes the collective/packet "
            "ladder unauditable",
        ))
    return out


# =============================================================================
# registry
# =============================================================================

RULES = {
    "CT001": ct001_executor_contract,
    "CT002": ct002_atomic_writes,
    "CT003": ct003_lock_discipline,
    "CT004": ct004_fault_site_coverage,
    "CT005": ct005_jit_hygiene,
    "CT006": ct006_drain_safety,
    "CT007": ct007_memory_target_contract,
    "CT008": ct008_trace_hygiene,
    "CT009": ct009_server_hygiene,
    "CT010": ct010_journal_discipline,
    "CT011": ct011_verified_read_discipline,
    "CT012": ct012_fleet_hygiene,
    "CT013": ct013_grayfail_hygiene,
    "CT014": ct014_supervisor_hygiene,
    "CT015": ct015_reduce_plane_discipline,
}
