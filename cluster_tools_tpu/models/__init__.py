"""Flax models for the inference task (boundary/affinity CNNs).

The reference's inference task loaded arbitrary PyTorch models per job
(SURVEY.md §2a "inference"); the rebuild ships a TPU-native model family —
3-D U-Nets in flax, bfloat16 compute — plus a registry so checkpoints can
name their architecture.
"""

from .unet import UNet3D, get_model

__all__ = ["UNet3D", "get_model"]
