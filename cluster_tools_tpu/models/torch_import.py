"""Import PyTorch checkpoints into the flax model zoo.

The reference's inference task executes user-supplied *PyTorch* models
(SURVEY.md §2a "inference", §2b "PyTorch (+CUDA)"); a user switching to this
framework arrives with torch-trained weights.  This module converts a torch
``state_dict`` whose architecture mirrors one of our flax models (same
layers in the same order — the "I trained the same U-Net in torch" case)
into the flax parameter tree, so the TPU inference path runs the trained
network directly.

Matching is positional: both frameworks register parameters in module
application/definition order, so the flattened torch tensors are converted
one-for-one onto the flattened flax leaves, with layout rules per kind:

- ``Conv3d.weight``      (O, I, kD, kH, kW) -> kernel (kD, kH, kW, I, O)
- ``ConvTranspose3d.weight`` (I, O, kD, kH, kW) -> kernel
  (kD, kH, kW, I, O), spatial axes FLIPPED (torch's transposed conv is the
  gradient of a correlation; ``lax.conv_transpose`` does not mirror —
  verified numerically in ``tests/test_inference.py``)
- ``GroupNorm.weight``/``.bias`` -> ``scale``/``bias``
- ``Conv*.bias`` -> ``bias``

A shape/kind mismatch raises with the full remaining-leaf diff rather than
producing silently-wrong weights.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np


def _convert_leaf(path, flax_leaf, torch_key: str, tensor: np.ndarray):
    """Convert one torch tensor to the layout of one flax leaf, or raise."""
    kind = path[-1]
    want = tuple(flax_leaf.shape)
    if kind == "kernel" and tensor.ndim == 5:
        if "ConvTranspose" in path[-2]:
            # (I, O, kD, kH, kW) -> (kD, kH, kW, I, O), mirrored spatially
            conv = np.ascontiguousarray(
                tensor.transpose(2, 3, 4, 0, 1)[::-1, ::-1, ::-1]
            )
        else:
            # (O, I, kD, kH, kW) -> (kD, kH, kW, I, O)
            conv = tensor.transpose(2, 3, 4, 1, 0)
        if conv.shape != want:
            raise ValueError(
                f"flax {'/'.join(path)} wants {want}, torch {torch_key!r} "
                f"converts to {conv.shape}"
            )
        return conv
    if kind in ("scale", "bias") and tensor.ndim == 1:
        if tuple(tensor.shape) != want:
            raise ValueError(
                f"flax {'/'.join(path)} wants {want}, torch {torch_key!r} "
                f"has {tuple(tensor.shape)}"
            )
        return tensor
    raise ValueError(
        f"cannot map torch {torch_key!r} (shape {tuple(tensor.shape)}) onto "
        f"flax {'/'.join(path)} (shape {want})"
    )


def _to_array(v) -> np.ndarray:
    # .detach() first: state_dicts saved with keep_vars=True (or from
    # named_parameters()) hold requires_grad tensors that np.asarray
    # refuses to convert directly
    return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)


def infer_unet_config(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Deduce the UNet3D hyperparameters from a torch ``state_dict`` alone.

    A user arriving with their own torch-trained U-Net should not have to
    reverse-engineer ``base_features``/``depth``/``norm`` by hand
    (SURVEY.md §2a inference: the reference loads an arbitrary user model
    per job).  The U-Net family's tensor census is rigid enough to invert:

    - 5-D conv tensors: per level a 2-conv block + 1 downsample, a 2-conv
      bottom, per level 1 transpose + a 2-conv block, and 1 output head
      = ``6 * depth + 3``  ->  depth.
    - first conv weight ``(O, I, k, k, k)``: O = base_features,
      I = in_channels; last conv weight: O = out_channels.
    - 1-D tensors: one bias per conv without norm (``6 depth + 3``), plus a
      GroupNorm scale+bias pair per block conv (``+ 4 (2 depth + 1)``)
      with norm.

    Returns kwargs for :class:`~.unet.UNet3D` (plus ``in_channels``, which
    flax infers from the input and the caller uses for the sample shape).
    Raises ``ValueError`` naming the offending tensor when the census does
    not fit the family.
    """
    items = [
        (k, a)
        for k, v in state_dict.items()
        if "num_batches_tracked" not in k
        for a in (_to_array(v),)
    ]
    conv5 = [(k, a) for k, a in items if a.ndim == 5]
    one_d = [(k, a) for k, a in items if a.ndim == 1]
    other = [
        (k, a) for k, a in items if a.ndim not in (1, 5) and a.ndim >= 1
    ]
    if other:
        k, a = other[0]
        raise ValueError(
            f"state_dict tensor {k!r} has shape {tuple(a.shape)} — not part "
            "of the 3-D U-Net family (expected 5-D conv kernels and 1-D "
            "bias/norm vectors)"
        )
    if not conv5:
        raise ValueError(
            "state_dict holds no 5-D tensors — not a 3-D conv net"
        )
    n5 = len(conv5)
    if n5 < 3 or (n5 - 3) % 6:
        raise ValueError(
            f"{n5} conv tensors does not fit the U-Net census 6*depth + 3 "
            f"(first conv tensor: {conv5[0][0]!r})"
        )
    depth = (n5 - 3) // 6
    base_features = int(conv5[0][1].shape[0])
    in_channels = int(conv5[0][1].shape[1])
    out_channels = int(conv5[-1][1].shape[0])
    n1 = len(one_d)
    if n1 == n5:
        norm = None
    elif n1 == n5 + 4 * (2 * depth + 1):
        norm = "group"
    else:
        raise ValueError(
            f"{n1} 1-D tensors fits neither norm=None ({n5}) nor "
            f"norm='group' ({n5 + 4 * (2 * depth + 1)}) for depth={depth} "
            f"(first 1-D tensor: {one_d[0][0] if one_d else None!r})"
        )
    return {
        "out_channels": out_channels,
        "base_features": base_features,
        "depth": depth,
        "norm": norm,
        "in_channels": in_channels,
    }


def import_torch_unet(path_or_state_dict, **overrides):
    """Torch U-Net checkpoint -> ``(flax_model, variables)``, config-free.

    Infers the architecture with :func:`infer_unet_config`, instantiates
    the flax :class:`~.unet.UNet3D` twin, and converts the weights.  This
    is the "bring your own trained U-Net" entry point; for a state_dict
    that does NOT mirror the family, the census error (or the first
    unmappable tensor from the positional converter) says which tensor
    broke the match.  ``overrides`` go to the UNet3D constructor (e.g.
    ``dtype=jnp.float32`` for bit-closer parity checks).

    Caveat the shapes cannot encode: GroupNorm *group counts*.  The twin
    uses ``min(8, channels)`` groups; a checkpoint trained with a
    different grouping imports cleanly but normalizes differently —
    validate imported models against a reference forward pass.
    """
    import os

    if isinstance(path_or_state_dict, (str, bytes, os.PathLike)):
        import torch

        obj = torch.load(
            path_or_state_dict, map_location="cpu", weights_only=True
        )
        obj = _unwrap_state_dict(obj, path_or_state_dict)
    else:
        obj = path_or_state_dict
    cfg = infer_unet_config(obj)
    in_channels = cfg.pop("in_channels")
    cfg.update(overrides)
    from .unet import UNet3D

    model = UNet3D(**cfg)
    mult = 2 ** cfg["depth"]
    sample = (1, mult, mult, mult, in_channels)
    return model, torch_state_dict_to_flax(obj, model, sample)


def torch_state_dict_to_flax(
    state_dict: Mapping[str, Any], model, sample_shape
) -> Dict:
    """Convert a torch ``state_dict`` to ``model``'s flax variables.

    ``model`` is a flax module (e.g. :class:`~.unet.UNet3D`); ``sample_shape``
    an input shape used to initialize the parameter template.  The torch
    architecture must mirror the flax one layer-for-layer in order.
    """
    import flax.traverse_util as tu

    template = model.init(
        jax.random.PRNGKey(0), jnp.zeros(sample_shape, jnp.float32)
    )
    # flatten_dict preserves dict insertion order == module-application
    # order, the property positional matching relies on (same machinery as
    # tasks/inference.py's npz checkpoints)
    flax_leaves = list(tu.flatten_dict(template["params"]).items())
    torch_items = [
        (k, arr)
        for k, v in state_dict.items()
        if "num_batches_tracked" not in k
        for arr in (_to_array(v),)
        if arr.ndim >= 1
    ]
    if len(torch_items) != len(flax_leaves):
        # name the FIRST pair that fails to convert — that is where the
        # architectures diverge; the full lists follow for context
        first = None
        for (path, leaf), (tkey, tensor) in zip(flax_leaves, torch_items):
            try:
                _convert_leaf(path, leaf, tkey, tensor)
            except ValueError as e:
                first = str(e)
                break
        fpaths = ["/".join(p) for p, _ in flax_leaves]
        tkeys = [k for k, _ in torch_items]
        raise ValueError(
            f"parameter count mismatch: flax has {len(flax_leaves)} leaves, "
            f"torch has {len(torch_items)} tensors.\nfirst unmappable "
            f"tensor: {first or 'lists agree up to the shorter length'}\n"
            f"flax: {fpaths}\ntorch: {tkeys}"
        )
    flat = {}
    for (path, leaf), (tkey, tensor) in zip(flax_leaves, torch_items):
        flat[("params",) + path] = jnp.asarray(
            _convert_leaf(path, leaf, tkey, tensor), dtype=leaf.dtype
        )
    return tu.unflatten_dict(flat)


def load_torch_checkpoint(path: str, model, sample_shape) -> Dict:
    """Load a ``.pt``/``.pth`` torch checkpoint file into flax variables.

    Accepts a raw ``state_dict`` or the common wrapper dicts
    (``{"state_dict": ...}`` / ``{"model_state_dict": ...}``).
    """
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    obj = _unwrap_state_dict(obj, path)
    return torch_state_dict_to_flax(obj, model, sample_shape)


def _unwrap_state_dict(obj, origin):
    for key in ("state_dict", "model_state_dict", "model"):
        if isinstance(obj, dict) and key in obj and isinstance(obj[key], dict):
            obj = obj[key]
            break
    if not isinstance(obj, dict):
        raise ValueError(
            f"{origin!r} does not contain a state_dict "
            f"(got {type(obj).__name__})"
        )
    return obj
