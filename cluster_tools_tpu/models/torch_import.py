"""Import PyTorch checkpoints into the flax model zoo.

The reference's inference task executes user-supplied *PyTorch* models
(SURVEY.md §2a "inference", §2b "PyTorch (+CUDA)"); a user switching to this
framework arrives with torch-trained weights.  This module converts a torch
``state_dict`` whose architecture mirrors one of our flax models (same
layers in the same order — the "I trained the same U-Net in torch" case)
into the flax parameter tree, so the TPU inference path runs the trained
network directly.

Matching is positional: both frameworks register parameters in module
application/definition order, so the flattened torch tensors are converted
one-for-one onto the flattened flax leaves, with layout rules per kind:

- ``Conv3d.weight``      (O, I, kD, kH, kW) -> kernel (kD, kH, kW, I, O)
- ``ConvTranspose3d.weight`` (I, O, kD, kH, kW) -> kernel
  (kD, kH, kW, I, O), spatial axes FLIPPED (torch's transposed conv is the
  gradient of a correlation; ``lax.conv_transpose`` does not mirror —
  verified numerically in ``tests/test_inference.py``)
- ``GroupNorm.weight``/``.bias`` -> ``scale``/``bias``
- ``Conv*.bias`` -> ``bias``

A shape/kind mismatch raises with the full remaining-leaf diff rather than
producing silently-wrong weights.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np


def _convert_leaf(path, flax_leaf, torch_key: str, tensor: np.ndarray):
    """Convert one torch tensor to the layout of one flax leaf, or raise."""
    kind = path[-1]
    want = tuple(flax_leaf.shape)
    if kind == "kernel" and tensor.ndim == 5:
        if "ConvTranspose" in path[-2]:
            # (I, O, kD, kH, kW) -> (kD, kH, kW, I, O), mirrored spatially
            conv = np.ascontiguousarray(
                tensor.transpose(2, 3, 4, 0, 1)[::-1, ::-1, ::-1]
            )
        else:
            # (O, I, kD, kH, kW) -> (kD, kH, kW, I, O)
            conv = tensor.transpose(2, 3, 4, 1, 0)
        if conv.shape != want:
            raise ValueError(
                f"flax {'/'.join(path)} wants {want}, torch {torch_key!r} "
                f"converts to {conv.shape}"
            )
        return conv
    if kind in ("scale", "bias") and tensor.ndim == 1:
        if tuple(tensor.shape) != want:
            raise ValueError(
                f"flax {'/'.join(path)} wants {want}, torch {torch_key!r} "
                f"has {tuple(tensor.shape)}"
            )
        return tensor
    raise ValueError(
        f"cannot map torch {torch_key!r} (shape {tuple(tensor.shape)}) onto "
        f"flax {'/'.join(path)} (shape {want})"
    )


def torch_state_dict_to_flax(
    state_dict: Mapping[str, Any], model, sample_shape
) -> Dict:
    """Convert a torch ``state_dict`` to ``model``'s flax variables.

    ``model`` is a flax module (e.g. :class:`~.unet.UNet3D`); ``sample_shape``
    an input shape used to initialize the parameter template.  The torch
    architecture must mirror the flax one layer-for-layer in order.
    """
    import flax.traverse_util as tu

    template = model.init(
        jax.random.PRNGKey(0), jnp.zeros(sample_shape, jnp.float32)
    )
    # flatten_dict preserves dict insertion order == module-application
    # order, the property positional matching relies on (same machinery as
    # tasks/inference.py's npz checkpoints)
    flax_leaves = list(tu.flatten_dict(template["params"]).items())
    def to_array(v) -> np.ndarray:
        # .detach() first: state_dicts saved with keep_vars=True (or from
        # named_parameters()) hold requires_grad tensors that np.asarray
        # refuses to convert directly
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    torch_items = [
        (k, arr)
        for k, v in state_dict.items()
        if "num_batches_tracked" not in k
        for arr in (to_array(v),)
        if arr.ndim >= 1
    ]
    if len(torch_items) != len(flax_leaves):
        fpaths = ["/".join(p) for p, _ in flax_leaves]
        tkeys = [k for k, _ in torch_items]
        raise ValueError(
            f"parameter count mismatch: flax has {len(flax_leaves)} leaves, "
            f"torch has {len(torch_items)} tensors.\nflax: {fpaths}\n"
            f"torch: {tkeys}"
        )
    flat = {}
    for (path, leaf), (tkey, tensor) in zip(flax_leaves, torch_items):
        flat[("params",) + path] = jnp.asarray(
            _convert_leaf(path, leaf, tkey, tensor), dtype=leaf.dtype
        )
    return tu.unflatten_dict(flat)


def load_torch_checkpoint(path: str, model, sample_shape) -> Dict:
    """Load a ``.pt``/``.pth`` torch checkpoint file into flax variables.

    Accepts a raw ``state_dict`` or the common wrapper dicts
    (``{"state_dict": ...}`` / ``{"model_state_dict": ...}``).
    """
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    for key in ("state_dict", "model_state_dict", "model"):
        if isinstance(obj, dict) and key in obj and isinstance(obj[key], dict):
            obj = obj[key]
            break
    if not isinstance(obj, dict):
        raise ValueError(
            f"{path!r} does not contain a state_dict (got {type(obj).__name__})"
        )
    return torch_state_dict_to_flax(obj, model, sample_shape)
