"""3-D U-Net in flax, designed for the TPU MXU.

Replaces the capability of the reference's per-job PyTorch CNNs (SURVEY.md
§2a "inference": boundary/affinity prediction over blocks with halo).
TPU-first choices:

- channels-last (NDHWC) layout — the native layout for XLA TPU convolutions,
- bfloat16 compute with float32 params (``dtype``/``param_dtype``),
- GroupNorm (batch-size independent: blocks are the batch),
- strided-conv downsampling and transpose-conv upsampling (keeps everything
  as convolutions on the MXU).

Input/output: ``(batch, z, y, x, c_in) -> (batch, z, y, x, out_channels)``,
logits (callers apply sigmoid/softmax).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class ConvBlock(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16
    norm: Any = "group"

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(
                self.features, (3, 3, 3), padding="SAME", dtype=self.dtype
            )(x)
            if self.norm == "group":
                x = nn.GroupNorm(
                    num_groups=min(8, self.features), dtype=jnp.float32
                )(x)
            x = nn.gelu(x)
        return x


class UNet3D(nn.Module):
    """Symmetric 3-D U-Net.

    ``depth`` pooling levels halve each spatial dim; inputs must be
    divisible by ``2**depth`` per axis (the inference task pads blocks to
    meet this).
    """

    out_channels: int = 1
    base_features: int = 16
    depth: int = 2
    dtype: Any = jnp.bfloat16
    # "group" or None.  GroupNorm statistics span the whole input window, so
    # blockwise-with-halo prediction is only *approximately* equal to a
    # single-shot forward; norm=None makes the network purely convolutional
    # (exactly shift-invariant, blockwise == single-shot inside the
    # receptive field).
    norm: Any = "group"

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        skips = []
        feats = self.base_features
        for _ in range(self.depth):
            x = ConvBlock(feats, self.dtype, self.norm)(x)
            skips.append(x)
            x = nn.Conv(
                feats * 2, (2, 2, 2), strides=(2, 2, 2), dtype=self.dtype
            )(x)
            feats *= 2
        x = ConvBlock(feats, self.dtype, self.norm)(x)
        for skip in reversed(skips):
            feats //= 2
            x = nn.ConvTranspose(
                feats, (2, 2, 2), strides=(2, 2, 2), dtype=self.dtype
            )(x)
            x = jnp.concatenate([x, skip.astype(x.dtype)], axis=-1)
            x = ConvBlock(feats, self.dtype, self.norm)(x)
        x = nn.Conv(self.out_channels, (1, 1, 1), dtype=jnp.float32)(x)
        return x


_MODELS = {"unet3d": UNet3D}


def get_model(name: str, **kwargs) -> nn.Module:
    try:
        cls = _MODELS[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_MODELS)}")
    return cls(**kwargs)
