"""ctypes bindings for the native runtime kernels (``native/ct_native.cpp``).

The reference outsourced host-side merge hot spots to C++ (``nifty.ufd``
union-find, the nifty multicut solvers — SURVEY.md §2b); here the same
stages call a small C++ shared library when available and fall back to the
pure-Python implementations otherwise.  The library is built on first use
(``g++ -O3 -shared``, ~1 s) and cached next to the source.

Public API:

- :func:`available` — True when the library is importable/buildable,
- :func:`union_find` — min-label roots over equivalence pairs,
- :func:`greedy_additive` — GAEC node labels,
- :func:`parallel_contract` — round-based parallel edge contraction
  (ops/contraction.py's host fast path),
- :func:`merge_edge_features` — the count-weighted per-edge feature merge.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libct_native.so"))

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_forced_off = False


@contextlib.contextmanager
def force_python():
    """Temporarily disable every native kernel (each returns None, taking
    its caller down the pure-Python/numpy fallback) — the oracle/baseline
    switch used by the contraction tests and bench's solver-scale record,
    kept here so both disable the ladder the same way."""
    global _forced_off
    _forced_off = True
    try:
        yield
    finally:
        _forced_off = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "ct_native.cpp")
    if not os.path.exists(src):
        return False
    # compile to a process-unique temp path and rename into place: renames
    # are atomic, so concurrent builders can't interleave writes into one
    # corrupt .so (which would permanently disable the native path)
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-std=c++17", "-shared", "-o", tmp, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _forced_off:
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src = os.path.join(_NATIVE_DIR, "ct_native.cpp")
        stale = os.path.exists(_LIB_PATH) and os.path.exists(src) and (
            os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
        )
        if (stale or not os.path.exists(_LIB_PATH)) and not _build():
            return None

        def _open():
            lib = ctypes.CDLL(_LIB_PATH)
            # symbol probe: a library built from older source loads fine but
            # lacks newer kernels — treat it as stale
            for sym in (
                "ct_union_find",
                "ct_greedy_additive",
                "ct_parallel_contract",
                "ct_merge_edge_features",
                "ct_mutex_watershed",
                "ct_kernighan_lin",
                "ct_edt_sq",
                "ct_ws_flood",
            ):
                getattr(lib, sym)
            return lib

        try:
            lib = _open()
        except (OSError, AttributeError):
            # stale/corrupt artifact (interrupted build or older source):
            # rebuild once before giving up
            if not _build():
                return None
            try:
                lib = _open()
            except (OSError, AttributeError):
                return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        lib.ct_union_find.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p]
        lib.ct_union_find.restype = ctypes.c_int
        lib.ct_greedy_additive.argtypes = [
            ctypes.c_int64,
            i64p,
            f64p,
            ctypes.c_int64,
            ctypes.c_double,
            i64p,
        ]
        lib.ct_greedy_additive.restype = ctypes.c_int
        lib.ct_parallel_contract.argtypes = [
            ctypes.c_int64,
            i64p,
            f64p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_double,
            i64p,
        ]
        lib.ct_parallel_contract.restype = ctypes.c_int
        lib.ct_merge_edge_features.argtypes = [
            u64p,
            f64p,
            ctypes.c_int64,
            u64p,
            ctypes.c_int64,
            f64p,
            f64p,
            f64p,
            f64p,
            f64p,
        ]
        lib.ct_merge_edge_features.restype = ctypes.c_int64
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.ct_mutex_watershed.argtypes = [
            ctypes.c_int64,
            i64p,
            i64p,
            u8p,
            i64p,
            ctypes.c_int64,
            i64p,
        ]
        lib.ct_mutex_watershed.restype = ctypes.c_int
        lib.ct_kernighan_lin.argtypes = [
            ctypes.c_int64,
            i64p,
            f64p,
            ctypes.c_int64,
            i64p,
            ctypes.c_int64,
            ctypes.c_double,
        ]
        lib.ct_kernighan_lin.restype = ctypes.c_int
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.ct_edt_sq.argtypes = [
            u8p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            f32p,
        ]
        lib.ct_edt_sq.restype = ctypes.c_int
        lib.ct_ws_flood.argtypes = [
            u8p,
            u8p,
            i32p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.ct_ws_flood.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def union_find(pairs: np.ndarray, n_labels: int) -> Optional[np.ndarray]:
    """Min-label component roots, or None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    pairs = np.ascontiguousarray(np.asarray(pairs).reshape(-1, 2), np.int64)
    out = np.empty(int(n_labels), np.int64)
    lib.ct_union_find(pairs, len(pairs), int(n_labels), out)
    return out


def greedy_additive(
    n_nodes: int, edges: np.ndarray, costs: np.ndarray, stop_cost: float = 0.0
) -> Optional[np.ndarray]:
    """GAEC labels 0..k-1, or None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    edges = np.ascontiguousarray(np.asarray(edges).reshape(-1, 2), np.int64)
    costs = np.ascontiguousarray(np.asarray(costs, np.float64))
    out = np.empty(int(n_nodes), np.int64)
    lib.ct_greedy_additive(
        int(n_nodes), edges, costs, len(edges), float(stop_cost), out
    )
    return out


def parallel_contract(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    mode_max: bool,
    threshold: float,
) -> Optional[np.ndarray]:
    """Round-based parallel edge contraction (ops/contraction.py semantics):
    labels 0..k-1, or None when the library is unavailable.  ``payload`` is
    [m, k] float64 columns summed on merge; priority is column 0 (k == 1)
    or column 0 / column 1 (k == 2)."""
    lib = _load()
    if lib is None:
        return None
    edges = np.ascontiguousarray(np.asarray(edges).reshape(-1, 2), np.int64)
    payload = np.ascontiguousarray(
        np.asarray(payload, np.float64).reshape(len(edges), -1)
    )
    out = np.empty(int(n_nodes), np.int64)
    lib.ct_parallel_contract(
        int(n_nodes), edges, payload, len(edges), payload.shape[1],
        int(bool(mode_max)), float(threshold), out,
    )
    return out


def kernighan_lin(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    init_labels: np.ndarray,
    max_outer: int = 20,
    epsilon: float = 1e-9,
) -> Optional[np.ndarray]:
    """KL refinement of ``init_labels`` (copied), or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    edges = np.ascontiguousarray(np.asarray(edges).reshape(-1, 2), np.int64)
    costs = np.ascontiguousarray(np.asarray(costs, np.float64))
    labels = np.ascontiguousarray(np.asarray(init_labels, np.int64)).copy()
    lib.ct_kernighan_lin(
        int(n_nodes), edges, costs, len(edges), labels, int(max_outer),
        float(epsilon),
    )
    return labels


def mutex_watershed(
    n_nodes: int,
    u: np.ndarray,
    v: np.ndarray,
    is_attractive: np.ndarray,
    order: np.ndarray,
) -> Optional[np.ndarray]:
    """Mutex-watershed component roots per node, or None when unavailable.

    ``order`` is the edge processing order (indices sorted by decreasing
    priority, numpy ``argsort`` on the host); semantics match the Python
    ``_MutexUnionFind`` loop in ``ops/mws.py`` exactly.
    """
    lib = _load()
    if lib is None:
        return None
    u = np.ascontiguousarray(np.asarray(u), np.int64)
    v = np.ascontiguousarray(np.asarray(v), np.int64)
    att = np.ascontiguousarray(np.asarray(is_attractive), np.uint8)
    order = np.ascontiguousarray(np.asarray(order), np.int64)
    out = np.empty(int(n_nodes), np.int64)
    lib.ct_mutex_watershed(int(n_nodes), u, v, att, order, len(order), out)
    return out


def merge_edge_features(parts, table: np.ndarray):
    """Accumulate per-block (uv, feats[m, 5]) parts onto the lexsorted
    ``table``: (running count-weighted mean, running M2 = var * n, min,
    max, count sums) per table row — the streaming Chan combine, stable
    for large-mean data — or None when the library is unavailable.
    ``parts`` iterates (uv, feats)."""
    lib = _load()
    if lib is None:
        return None
    table = np.ascontiguousarray(np.asarray(table).reshape(-1, 2), np.uint64)
    k = len(table)
    means = np.zeros(k, np.float64)
    m2s = np.zeros(k, np.float64)
    mins = np.full(k, np.inf)
    maxs = np.full(k, -np.inf)
    counts = np.zeros(k, np.float64)
    for uv, feats in parts:
        if len(uv) == 0:
            continue
        uv = np.ascontiguousarray(np.asarray(uv).reshape(-1, 2), np.uint64)
        feats = np.asarray(feats, np.float64)
        if feats.ndim != 2 or feats.shape[1] != 5:
            raise ValueError(
                f"edge-feature block has {feats.shape} columns, expected "
                "(m, 5) (mean, min, max, count, variance) — regenerate "
                "per-block features written by an older format"
            )
        feats = np.ascontiguousarray(feats)
        lib.ct_merge_edge_features(
            uv, feats, len(uv), table, k, means, m2s, mins, maxs, counts
        )
    return means, m2s, mins, maxs, counts


def edt_sq(
    fg: np.ndarray,
    sampling=None,
    cap: Optional[float] = None,
) -> Optional[np.ndarray]:
    """Exact squared EDT of a 3-D bool mask (float32), or None when the
    library is unavailable.  ``sampling`` is per-axis voxel size (scipy
    convention); ``cap`` clips the (unsquared) distance like the device
    kernels' ``dt_max_distance``."""
    lib = _load()
    if lib is None:
        return None
    fg = np.ascontiguousarray(np.asarray(fg), np.uint8)
    if fg.ndim != 3:
        raise ValueError("edt_sq expects a 3-D mask")
    nz, ny, nx = fg.shape
    sz, sy, sx = (1.0, 1.0, 1.0) if sampling is None else map(float, sampling)
    out = np.empty(fg.shape, np.float32)
    cap_sq = float(cap) * float(cap) if cap is not None else 0.0
    lib.ct_edt_sq(fg, nz, ny, nx, sz, sy, sx, cap_sq, out)
    return out


def ws_flood(
    hmap: np.ndarray, fg: np.ndarray, seeds: np.ndarray
) -> Optional[np.ndarray]:
    """Seeded watershed by 256-level bucket-queue priority flood
    (6-connectivity) over a uint8 priority map, or None when the library
    is unavailable.  ``seeds``: int32, > 0; returns flooded labels with 0
    outside ``fg``/unreached."""
    lib = _load()
    if lib is None:
        return None
    hmap = np.ascontiguousarray(np.asarray(hmap), np.uint8)
    fg = np.ascontiguousarray(np.asarray(fg), np.uint8)
    if hmap.ndim != 3 or hmap.shape != fg.shape:
        raise ValueError("ws_flood expects matching 3-D hmap/fg")
    labels = np.ascontiguousarray(np.asarray(seeds), np.int32).copy()
    nz, ny, nx = hmap.shape
    lib.ct_ws_flood(hmap, fg, labels, nz, ny, nx)
    return labels
