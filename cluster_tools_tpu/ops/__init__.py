from . import ccl
from . import unionfind
from . import edt
from . import watershed
