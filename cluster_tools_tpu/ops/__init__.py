from . import ccl
from . import unionfind
from . import edt
from . import watershed
from . import rag
from . import multicut
from . import mws
from . import agglomeration
