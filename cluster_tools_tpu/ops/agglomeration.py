"""Average-linkage agglomerative clustering of a region graph (GASP-style).

The reference's ``cluster_tools/agglomerative_clustering/`` ran nifty/elf
agglomeration on the RAG from merged features (SURVEY.md §2a).  This module
implements the host-side core: merge the currently-cheapest edge (lowest
size-weighted mean boundary probability) while it is below ``threshold``;
contractions combine parallel edges by size-weighted averaging — i.e.
average linkage, the GASP default.

Same heap + neighbor-map scheme as :mod:`.multicut`'s GAEC (lazy
invalidation by current-value check), with (weight-sum, size-sum) payloads
instead of additive costs.
"""

from __future__ import annotations

import heapq

import numpy as np


def average_agglomeration(
    n_nodes: int,
    edges: np.ndarray,
    probs: np.ndarray,
    sizes: np.ndarray,
    threshold: float,
) -> np.ndarray:
    """Average-linkage agglomeration.  Returns int64 labels 0..k-1.

    ``probs``: per-edge mean boundary probability (low = merge);
    ``sizes``: per-edge contact areas (the averaging weights).

    Tie-breaking is deterministic and documented: heap entries are
    ``(mean, u, v, size_sum)`` tuples, so among equal-mean edges the
    smallest ``(u, v)`` endpoint pair (cluster representatives at push
    time) merges first — the same ordering contract as
    :func:`..ops.multicut.greedy_additive`, keeping impl-ladder parity
    tests stable across platforms.
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64)
    probs = np.asarray(probs, dtype=np.float64)
    sizes = np.asarray(sizes, dtype=np.float64)

    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    # neighbor maps: nbrs[u][v] = (weight_sum, size_sum); mean = ws / ss
    nbrs: list = [dict() for _ in range(n_nodes)]
    for (u, v), p, s in zip(edges, probs, sizes):
        if u == v:
            continue
        u, v = int(u), int(v)
        s = max(float(s), 1e-12)
        ws, ss = nbrs[u].get(v, (0.0, 0.0))
        nbrs[u][v] = (ws + p * s, ss + s)
        nbrs[v][u] = nbrs[u][v]

    heap = [
        (ws / ss, u, v, ss)
        for u in range(n_nodes)
        for v, (ws, ss) in nbrs[u].items()
        if u < v
    ]
    heapq.heapify(heap)

    while heap:
        mean_p, u, v, ss = heapq.heappop(heap)
        if mean_p >= threshold:
            break
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        cur = nbrs[ru].get(rv)
        # stale unless the entry still matches the popped priority
        if cur is None or abs(cur[0] / cur[1] - mean_p) > 1e-12 or cur[1] != ss:
            continue
        if len(nbrs[ru]) < len(nbrs[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        del nbrs[ru][rv]
        for x, (ws_x, ss_x) in nbrs[rv].items():
            if x == ru:
                continue
            ws0, ss0 = nbrs[ru].get(x, (0.0, 0.0))
            combined = (ws0 + ws_x, ss0 + ss_x)
            nbrs[ru][x] = combined
            nbrs[x][ru] = combined
            del nbrs[x][rv]
            new_mean = combined[0] / combined[1]
            if new_mean < threshold:
                heapq.heappush(heap, (new_mean, ru, x, combined[1]))
        nbrs[rv].clear()

    roots = np.array([find(i) for i in range(n_nodes)], dtype=np.int64)
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int64)
