"""Connected-components labeling as a dense, XLA-friendly device kernel.

The reference delegated per-block CCL to ``vigra.labelVolumeWithBackground``
(C++, serial two-pass union-find; SURVEY.md §2b).  A serial union-find is the
wrong shape for a TPU's dense SIMD model, so this is a ground-up redesign: the
*label-equivalence* algorithm (Playne & Hawick style), which is a fixpoint
iteration of three dense steps —

1. **propagate**: every foreground voxel takes the min label over its
   neighborhood (background holds a +inf sentinel, so no masking logic),
2. **hook**: scatter-min the improved label onto the voxel's current root
   (union-by-min), which lets label information jump across whole trees
   instead of one voxel per step,
3. **compress**: pointer-jumping ``lab = lab[lab]`` to full path compression.

Each step is a dense shift/gather/scatter over the block, so XLA can fuse and
tile it; the data-dependent iteration count lives in ``lax.while_loop``
(compiled once, static shapes).  Convergence is O(log d) hook rounds in
practice.  Labels are ``flat_index(min voxel of component) + 1``; background
is 0 after :func:`finalize_labels`.

The kernel is pure ``(block) -> labels`` and vmap/shard_map-compatible, so a
batch of blocks runs as one device program across the mesh.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _true_like(x: jnp.ndarray) -> jnp.ndarray:
    """Scalar ``True`` carrying ``x``'s varying-manual-axes type.

    Under ``shard_map``, ``lax.while_loop`` requires the initial carry to have
    the same vma (varying-over-mesh-axes) type as the body output; a literal
    ``True`` is unvarying.  Deriving the constant from ``x`` inherits the
    right type in every context (jit, vmap, shard_map) with one fused reduce.
    """
    return jnp.any(x != x) | True


def _match_vma(x: jnp.ndarray, ref: jnp.ndarray) -> jnp.ndarray:
    """Give ``x`` the varying-manual-axes type of ``ref``.

    Adds a ``ref``-derived zero so constants (e.g. ``arange`` parent tables)
    can seed ``while_loop`` carries whose bodies mix in sharded data.  No-op
    outside ``shard_map``.
    """
    z = (ref.ravel()[:1].sum() * 0).astype(x.dtype)
    return x + z


def _shift(x: jnp.ndarray, offset: int, axis: int, fill) -> jnp.ndarray:
    """y[i] = x[i - offset] along ``axis``, with ``fill`` shifted in."""
    n = x.shape[axis]
    pad_shape = list(x.shape)
    pad_shape[axis] = abs(offset)
    pad = jnp.full(pad_shape, fill, dtype=x.dtype)
    if offset > 0:
        body = lax.slice_in_dim(x, 0, n - offset, axis=axis)
        return jnp.concatenate([pad, body], axis=axis)
    else:
        body = lax.slice_in_dim(x, -offset, n, axis=axis)
        return jnp.concatenate([body, pad], axis=axis)


def _neighbor_offsets(ndim: int, connectivity: int) -> Sequence[Tuple[int, ...]]:
    """Half of the symmetric neighborhood (each unordered pair once)."""
    offsets = []
    for off in np.ndindex(*([3] * ndim)):
        off = tuple(o - 1 for o in off)
        if all(o == 0 for o in off):
            continue
        if sum(abs(o) for o in off) > connectivity:
            continue
        # keep only the lexicographically-positive half
        if off > tuple([0] * ndim):
            offsets.append(off)
    return offsets


def _shift_nd(x: jnp.ndarray, offset: Tuple[int, ...], fill) -> jnp.ndarray:
    for axis, o in enumerate(offset):
        if o != 0:
            x = _shift(x, o, axis, fill)
    return x


def _compress(flat: jnp.ndarray, sentinel) -> jnp.ndarray:
    """Pointer-jump ``flat = flat[flat]`` to fixpoint (full path compression)."""
    n = flat.shape[0]

    def gather(f):
        g = f[jnp.clip(f, 0, n - 1)]
        return jnp.where(f == sentinel, sentinel, g)

    def cond(state):
        f, changed = state
        return changed

    def body(state):
        f, _ = state
        f2 = gather(f)
        return f2, jnp.any(f2 != f)

    flat, _ = lax.while_loop(cond, body, (flat, _true_like(flat)))
    return flat


@partial(jax.jit, static_argnames=("connectivity",))
def label_components(mask: jnp.ndarray, connectivity: int = 1) -> jnp.ndarray:
    """Label connected components of a boolean mask (any rank >= 1).

    Returns int32 labels with the same shape as ``mask``: for foreground
    voxels, ``flat_index_of_component_minimum`` (a stable, globally
    offsettable representative); background voxels hold ``N`` (the sentinel).
    Use :func:`finalize_labels` to convert to 1-based labels with 0 background.

    ``connectivity`` as in scipy: 1 = faces only, ``ndim`` = full neighborhood.
    """
    shape = mask.shape
    n = int(np.prod(shape))
    sentinel = jnp.int32(n)
    mask = mask.astype(bool)
    idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)
    lab = jnp.where(mask, idx, sentinel)
    offsets = _neighbor_offsets(len(shape), connectivity)

    def neighbor_min(lab3):
        m = lab3
        for off in offsets:
            m = jnp.minimum(m, _shift_nd(lab3, off, sentinel))
            m = jnp.minimum(m, _shift_nd(lab3, tuple(-o for o in off), sentinel))
        return jnp.where(mask, m, sentinel)

    def cond(state):
        flat, changed = state
        return changed

    def body(state):
        flat, _ = state
        lab3 = flat.reshape(shape)
        nmin = neighbor_min(lab3).ravel()
        improved = nmin < flat
        # hook: push the improved label onto the current root (flat is fully
        # compressed, so flat[i] is i's root)
        root = jnp.clip(flat, 0, n - 1)
        upd = jnp.where(improved, nmin, sentinel)
        hooked = flat.at[root].min(upd, mode="drop")
        hooked = jnp.where(flat == sentinel, sentinel, hooked)
        new = _compress(jnp.minimum(hooked, jnp.minimum(flat, nmin)), sentinel)
        return new, jnp.any(new != flat)

    flat0 = lab.ravel()
    flat, _ = lax.while_loop(cond, body, (flat0, _true_like(flat0)))
    return flat.reshape(shape)


def finalize_labels(raw: jnp.ndarray) -> jnp.ndarray:
    """Convert sentinel/flat-index labels to (flat_index + 1, background=0)."""
    n = int(np.prod(raw.shape))
    return jnp.where(raw == n, 0, raw + 1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("max_labels", "value_bound"))
def relabel_consecutive(
    labels: jnp.ndarray, max_labels: int, value_bound: Optional[int] = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Map non-negative labels (0 = background) to dense 1..K.

    ``max_labels`` is a static upper bound on the number of distinct
    foreground labels.  Returns ``(dense_labels, n_labels)``; ``n_labels >
    max_labels`` means the bound was exceeded (ids are then clamped to
    ``max_labels + 1`` so downstream offset arithmetic stays bounded while
    the overflow flag propagates).

    Fast path (the framework's own labels are flat voxel indices):
    presence bitmap -> prefix-sum ranks -> one gather — ~3 gather-class
    passes instead of a full-volume key-value sort, which at 512³ is
    ~8.5 s on the chip (sort ≈ 10x a gather pass; docs/PERFORMANCE.md).
    ``value_bound`` is the static inclusive upper bound on label VALUES
    and sizes the bitmap — callers whose labels live in a padded/haloed
    index space must pass that span (the cropped ``labels.size`` default
    would silently shunt them to the sort).  A runtime ``lax.cond`` falls
    back to the sort whenever any label exceeds the bound, so the
    contract is unchanged for arbitrary non-negative int32 labels.
    """
    flat = labels.ravel().astype(jnp.int32)
    n = flat.shape[0]
    dom = n if value_bound is None else int(value_bound)

    def _bitmap(flat):
        present = jnp.zeros((dom + 1,), jnp.int8).at[flat].set(1, mode="drop")
        present = present.at[0].set(0)  # background is not a label
        rank = jnp.cumsum(present, dtype=jnp.int32)  # rank[v] = dense id
        n_fg = rank[-1]
        dense = jnp.where(
            flat > 0,
            jnp.minimum(rank[jnp.clip(flat, 0, dom)], max_labels + 1),
            0,
        )
        return dense, n_fg

    def _sort(flat):
        pos = jnp.arange(n, dtype=jnp.int32)
        svals, spos = lax.sort((flat, pos), num_keys=1)
        prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), svals[:-1]])
        is_new_fg = (svals != prev) & (svals > 0)
        rank = jnp.cumsum(is_new_fg.astype(jnp.int32))  # 1-based dense ids
        n_fg = rank[-1]
        rank = jnp.where(svals > 0, jnp.minimum(rank, max_labels + 1), 0)
        dense = jnp.zeros_like(flat).at[spos].set(rank)
        return dense, n_fg

    dense, n_fg = lax.cond(flat.max() <= dom, _bitmap, _sort, flat)
    return dense.reshape(labels.shape), n_fg


def label_components_batch(
    masks: jnp.ndarray, connectivity: int = 1
) -> jnp.ndarray:
    """vmapped :func:`label_components` over a leading block-batch axis."""
    return jax.vmap(partial(label_components, connectivity=connectivity))(masks)


@partial(jax.jit, static_argnames=("connectivity",))
def label_components_keyed(keys: jnp.ndarray, connectivity: int = 1) -> jnp.ndarray:
    """Label connected components of equal-valued regions.

    Like :func:`label_components`, but voxels connect only where their
    ``keys`` are equal and non-zero — the kernel behind
    connected-components-on-a-segmentation (each segment splits into its
    spatially connected parts; reference: the postprocess CC task).

    ``keys`` must be an integer array (map uint64 segment ids to dense
    int32 on host first); 0 is background.  Returns the same flat-index
    representative encoding as :func:`label_components`.
    """
    shape = keys.shape
    n = int(np.prod(shape))
    sentinel = jnp.int32(n)
    mask = keys != 0
    idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)
    lab = jnp.where(mask, idx, sentinel)
    offsets = _neighbor_offsets(len(shape), connectivity)

    def neighbor_min(lab3):
        m = lab3
        for off in offsets:
            for o in (off, tuple(-x for x in off)):
                cand = _shift_nd(lab3, o, sentinel)
                same = _shift_nd(keys, o, 0) == keys
                m = jnp.minimum(m, jnp.where(same, cand, sentinel))
        return jnp.where(mask, m, sentinel)

    def cond(state):
        flat, changed = state
        return changed

    def body(state):
        flat, _ = state
        lab3 = flat.reshape(shape)
        nmin = neighbor_min(lab3).ravel()
        improved = nmin < flat
        root = jnp.clip(flat, 0, n - 1)
        upd = jnp.where(improved, nmin, sentinel)
        hooked = flat.at[root].min(upd, mode="drop")
        hooked = jnp.where(flat == sentinel, sentinel, hooked)
        new = _compress(jnp.minimum(hooked, jnp.minimum(flat, nmin)), sentinel)
        return new, jnp.any(new != flat)

    flat0 = lab.ravel()
    flat, _ = lax.while_loop(cond, body, (flat0, _true_like(flat0)))
    return flat.reshape(shape)
