"""Round-based parallel edge contraction — the vectorized agglomeration core.

The sequential solvers in :mod:`.multicut` (GAEC heap) and
:mod:`.agglomeration` (average-linkage heap) contract ONE edge per step:
O(E log E) pops through a Python heap with dict-of-dict neighbor merges.
That is fine for the reduced subproblems of the hierarchical multicut but
cannot scale to the 512³ headline's ~800k fragments / multi-million-edge
RAGs, and none of it vectorizes.

This module replaces the *mechanism* (one edge at a time) while keeping the
*policy* (contract the most attractive edge first) approximately, via the
classic mutual-best-edge matching (Boruvka-style rounds, the same scheme as
the tile_ws basin-merge rounds):

    repeat until no contractible edge remains:
      1. every node picks its best incident contractible edge
         (max cost for GAEC, min mean-probability for average linkage;
         ties broken toward the smallest edge id — documented, total order)
      2. edges selected by BOTH endpoints contract (the picks form a
         matching, so the union step is a single parent[hi] = lo scatter —
         pointer depth 1, no find loops)
      3. endpoints remap through the new roots; parallel edges merge by
         segment-sum re-aggregation (costs add for GAEC; (weight·size,
         size) sums for average linkage)

    Progress: the globally best contractible edge is mutual-best by
    construction (any competitor at either endpoint would be globally
    better), so every round contracts ≥1 edge and the loop terminates in
    ≤ n rounds; on real RAGs the matching contracts a constant fraction of
    nodes per round, giving O(log n) rounds of O(E) vectorized work.

The result is not always bit-identical to the sequential greedy order (two
simultaneous contractions see each other's pre-merge costs), but on
multicut instances the energy tracks sequential GAEC within a couple of
percent and unambiguous instances produce identical partitions — both
regression-tested against the heap oracle.

Three implementations behind the ``impl="auto"`` ladder, mirroring the
volume kernels' substrate dispatch:

- ``"jax"``    device rounds under one jit: static edge capacity,
               ``lax.while_loop``, scatter-max best-edge selection, one
               2-key ``lax.sort`` + segment-sum per round for the
               re-aggregation (the :func:`..ops.rag.device_edge_aggregate`
               machinery) — for graphs already device-resident (fused
               RAG→costs→solve path).
- ``"native"`` the same rounds in C++ (``native/ct_native.cpp:
               ct_parallel_contract``) — the host fast path.
- ``"numpy"``  the vectorized reference implementation and the parity
               oracle for both of the above.

``impl="auto"`` resolves device-JAX on an accelerator backend, else
native when the library loads, else numpy; the sequential heap solvers
remain available as ``impl="heap"`` (and are the quality oracle in tests).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import numpy as np

_ACCEL_PLATFORMS = ("tpu", "axon")


# -- process-wide solver metrics ---------------------------------------------
# Same snapshot/delta pattern as the executor's dispatch counters and the
# chunk cache: the task runtime snapshots around run_impl and merges the
# delta into io_metrics.json, so every solve stops being a black box next
# to the instrumented I/O and dispatch paths (docs/PERFORMANCE.md
# "Distributed agglomeration").  ``solver_rounds`` is counted by the numpy
# reference rung (the native rung is bit-parity with it but does not
# report its loop count; the jax rung's count lives on device).

_METRICS_LOCK = threading.Lock()
_SOLVER_COUNTERS = {
    "solver_calls": 0,      # parallel_contraction invocations
    "solver_rounds": 0,     # contraction rounds (numpy rung)
    "solver_edges_in": 0,   # edges entering the solves
    "solver_edges_out": 0,  # inter-cluster edges remaining after them
}


def solver_snapshot() -> Dict[str, float]:
    """Current process-wide contraction-solver counters (monotonic; diff
    two snapshots with :func:`solver_delta` to attribute a task's share)."""
    with _METRICS_LOCK:
        return dict(_SOLVER_COUNTERS)


def solver_delta(snapshot: Dict[str, float]) -> Dict[str, float]:
    """Counter movement since ``snapshot`` (same keys)."""
    cur = solver_snapshot()
    return {k: cur[k] - snapshot.get(k, 0) for k in cur}


def _record_solver_metrics(**deltas) -> None:
    with _METRICS_LOCK:
        for k, v in deltas.items():
            _SOLVER_COUNTERS[k] += int(v)


def _resolve_impl(impl: str) -> str:
    if impl != "auto":
        return impl
    try:
        if jax.default_backend() in _ACCEL_PLATFORMS:
            return "jax"
    except Exception:  # pragma: no cover - backend probe only
        pass
    from .. import native

    return "native" if native.available() else "numpy"


def _relabel_consecutive(roots: np.ndarray) -> np.ndarray:
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int64)


def sum_by_key(
    key: np.ndarray, payload: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Group-by-key payload-column sums: ``(unique_keys_sorted, sums)``.

    Stable argsort + bincount instead of ``np.unique(return_inverse)``:
    same groups, same original-order accumulation — THE documented
    summation order of the contraction engine (the native kernel
    reproduces it for bit-parity, and the reduce tree's frontier/merge
    aggregation reuses it so hierarchical solves stay bit-comparable) —
    about 2x faster per round."""
    order = np.argsort(key, kind="stable")
    ks = key[order]
    first = np.ones(len(ks), bool)
    first[1:] = ks[1:] != ks[:-1]
    uniq = ks[first]
    inv = np.empty(len(ks), np.int64)
    inv[order] = np.cumsum(first) - 1
    out = np.empty((len(uniq), payload.shape[1]), np.float64)
    for c in range(payload.shape[1]):
        out[:, c] = np.bincount(inv, weights=payload[:, c], minlength=len(uniq))
    return uniq, out


def _canonical_edges(
    n_nodes: int, edges: np.ndarray, payload: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Canonical (lo < hi) unique edges with payload columns summed over
    parallel edges; rows lexsorted — edge id == row index, the documented
    tie-break order."""
    u = np.minimum(edges[:, 0], edges[:, 1])
    v = np.maximum(edges[:, 0], edges[:, 1])
    keep = u != v
    u, v, payload = u[keep], v[keep], payload[keep]
    if len(u) == 0:
        return (
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            np.zeros((0, payload.shape[1]), np.float64),
        )
    key = u.astype(np.int64) * np.int64(n_nodes) + v.astype(np.int64)
    uniq, out = sum_by_key(key, payload)
    return (uniq // n_nodes).astype(np.int64), (uniq % n_nodes).astype(np.int64), out


def _contract_rounds_numpy(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    mode: str,
    threshold: float,
) -> np.ndarray:
    """Vectorized reference implementation of the round scheme.

    ``payload``: [m, k] float64 columns summed on merge.  Priority is
    ``payload[:, 0]`` for k == 1 (GAEC cost) and
    ``payload[:, 0] / payload[:, 1]`` for k == 2 (size-weighted mean).
    ``mode="max"`` contracts while priority > threshold (GAEC);
    ``mode="min"`` while priority < threshold (average linkage).
    """
    n_nodes = int(n_nodes)
    labels = np.arange(n_nodes, dtype=np.int64)
    u, v, payload = _canonical_edges(n_nodes, edges, payload)
    sign = 1.0 if mode == "max" else -1.0
    thr = sign * float(threshold)
    rounds = 0

    while len(u):
        prio = payload[:, 0] if payload.shape[1] == 1 else (
            payload[:, 0] / np.maximum(payload[:, 1], 1e-300)
        )
        prio = sign * prio  # always maximize
        elig = prio > thr
        if not elig.any():
            break
        eid = np.arange(len(u), dtype=np.int64)
        # step 1: per-node best priority over incident contractible edges
        best_p = np.full(n_nodes, -np.inf)
        np.maximum.at(best_p, u[elig], prio[elig])
        np.maximum.at(best_p, v[elig], prio[elig])
        # among priority-ties, the smallest edge id wins (documented order)
        best_e = np.full(n_nodes, len(u), dtype=np.int64)
        cand_u = elig & (prio == best_p[u])
        cand_v = elig & (prio == best_p[v])
        np.minimum.at(best_e, u[cand_u], eid[cand_u])
        np.minimum.at(best_e, v[cand_v], eid[cand_v])
        # step 2: mutual picks form a matching -> depth-1 union
        mutual = elig & (best_e[u] == eid) & (best_e[v] == eid)
        rounds += 1
        root = np.arange(n_nodes, dtype=np.int64)
        root[v[mutual]] = u[mutual]
        labels = root[labels]
        # step 3: remap + re-aggregate parallel edges
        u, v, payload = _canonical_edges(
            n_nodes, np.stack([root[u], root[v]], axis=1), payload
        )
    _record_solver_metrics(solver_rounds=rounds)
    return _relabel_consecutive(labels)


# ---------------------------------------------------------------------------
# device implementation: the same rounds under one jit
# ---------------------------------------------------------------------------


def _contract_rounds_jax(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    mode: str,
    threshold: float,
) -> np.ndarray:
    import jax.numpy as jnp

    # canonicalize on host first: parallel input edges MUST merge before
    # round 1 (GAEC's additive contract — a [+1, -2] duplicate pair is net
    # repulsive), and self loops drop here, so the device program starts
    # from the same unique edge set as the numpy/native rungs
    eu, ev, payload = _canonical_edges(n_nodes, edges, payload)
    m = len(eu)
    cap = 1 << max(4, int(np.ceil(np.log2(max(m, 1)))))
    # n_nodes is a static jit argument; bucket it to the next power of two
    # so block subproblems of every distinct size share a handful of
    # compiled programs instead of one XLA compile per size
    n_pad = 1 << max(4, int(np.ceil(np.log2(max(n_nodes, 1)))))
    u = np.full(cap, n_pad, np.int32)
    v = np.full(cap, n_pad, np.int32)
    u[:m] = eu
    v[:m] = ev
    pay = np.zeros((cap, payload.shape[1]), np.float32)
    pay[:m] = payload
    labels = _device_contract(
        jnp.asarray(u), jnp.asarray(v), jnp.asarray(pay),
        jnp.float32(threshold), int(n_pad), mode, payload.shape[1],
    )
    labels = np.asarray(labels)[:n_nodes].astype(np.int64)
    return _relabel_consecutive(labels)


@partial(jax.jit, static_argnames=("n_nodes", "mode", "k"))
def _device_contract(u, v, pay, threshold, n_nodes, mode, k):
    """One jitted program: while any node still has a contractible edge,
    scatter-max best-edge selection -> matching -> parent scatter ->
    2-key sort re-aggregation.  Same pointer-jumping/segment-sum idiom as
    ops/unionfind.py and ops/rag.py::device_edge_aggregate."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    cap = u.shape[0]
    n = n_nodes
    sign = jnp.float32(1.0 if mode == "max" else -1.0)
    thr = sign * threshold
    NEG = jnp.float32(-np.inf)
    SENT = jnp.int32(n)  # padding sentinel node id

    def prio_of(pay):
        if k == 1:
            p = pay[:, 0]
        else:
            p = pay[:, 0] / jnp.maximum(pay[:, 1], jnp.float32(1e-30))
        return sign * p

    def cond(state):
        u, v, pay, labels, progressed = state
        return progressed

    def body(state):
        u, v, pay, labels, _ = state
        active = u != SENT
        prio = jnp.where(active, prio_of(pay), NEG)
        elig = active & (prio > thr)
        eid = jnp.arange(cap, dtype=jnp.int32)
        drop_u = jnp.where(elig, u, SENT)
        drop_v = jnp.where(elig, v, SENT)
        best_p = jnp.full((n + 1,), NEG).at[drop_u].max(prio, mode="drop")
        best_p = best_p.at[drop_v].max(prio, mode="drop")
        cand_u = jnp.where(elig & (prio == best_p[u]), u, SENT)
        cand_v = jnp.where(elig & (prio == best_p[v]), v, SENT)
        best_e = jnp.full((n + 1,), cap, jnp.int32).at[cand_u].min(
            eid, mode="drop"
        )
        best_e = best_e.at[cand_v].min(eid, mode="drop")
        mutual = elig & (best_e[u] == eid) & (best_e[v] == eid)
        # matching -> single scatter, depth-1 parents
        root = jnp.arange(n + 1, dtype=jnp.int32).at[
            jnp.where(mutual, v, SENT)
        ].set(jnp.where(mutual, u, SENT), mode="drop")
        labels = root[labels]
        # remap + canonicalize; contracted-away self edges -> sentinel
        ru = root[u]
        rv = root[v]
        lo = jnp.minimum(ru, rv)
        hi = jnp.maximum(ru, rv)
        dead = (lo == hi) | ~active
        lo = jnp.where(dead, SENT, lo)
        hi = jnp.where(dead, SENT, hi)
        # parallel-edge merge: 2-key sort + segment sums (rag.py idiom)
        ops = lax.sort((lo, hi) + tuple(pay[:, c] for c in range(k)), num_keys=2)
        lo, hi = ops[0], ops[1]
        cols = ops[2:]
        valid = lo != SENT
        is_first = valid & (
            (lo != jnp.concatenate([SENT[None], lo[:-1]]))
            | (hi != jnp.concatenate([SENT[None], hi[:-1]]))
        )
        seg = jnp.cumsum(is_first.astype(jnp.int32)) - 1
        sid = jnp.where(valid, seg, cap)
        new_u = jnp.full((cap + 1,), SENT, jnp.int32).at[sid].min(
            jnp.where(valid, lo, SENT), mode="drop"
        )[:cap]
        new_v = jnp.full((cap + 1,), SENT, jnp.int32).at[sid].min(
            jnp.where(valid, hi, SENT), mode="drop"
        )[:cap]
        new_pay = jnp.stack(
            [
                jax.ops.segment_sum(
                    jnp.where(valid, c, 0.0), sid, num_segments=cap + 1
                )[:cap]
                for c in cols
            ],
            axis=1,
        )
        return new_u, new_v, new_pay, labels, jnp.any(mutual)

    labels0 = jnp.arange(n + 1, dtype=jnp.int32)
    u, v, pay, labels, _ = lax.while_loop(
        cond, body, (u, v, pay, labels0, jnp.bool_(True))
    )
    return labels[:n]


# ---------------------------------------------------------------------------
# per-lane frontier rounds: the reduce tree's fused level program
# ---------------------------------------------------------------------------


def lane_frontier_rounds(u, v, pay, f_node, f_ghost, f_pay, threshold,
                         *, n_pad, mode, k):
    """One reduce-tree group as a device computation: canonical
    aggregation + mutual-best contraction rounds with frontier abstention,
    the exact :func:`..parallel.reduce_tree.frontier_contraction` scheme
    in f64/int64 on device.  ``vmap`` this over the padded lanes of a tree
    level and wrap it in a ``shard_map`` + ``all_gather`` to get the
    collective reduce plane's one-dispatch-per-level program
    (docs/PERFORMANCE.md "Collective reduce plane").

    Bit-identity contract (property-tested in tests/test_reduce_plane.py):
    every float op mirrors the numpy reference — f64 payloads (run under
    ``jax.experimental.enable_x64``), stable sorts whose equal-key order
    matches ``sum_by_key``'s stable argsort, and sequential scatter-adds
    whose per-segment accumulation order equals ``np.bincount``'s
    original-index order, so parallel-edge and frontier re-aggregation
    round identically and the mutual-best float comparisons see the same
    bits.  Ties break toward the smallest edge id, where ids are the
    canonical sorted rank — the same documented order as the host rungs.

    Inputs are fixed-capacity lanes (the ragged-pool marshalling idiom):
    ``u``/``v`` ``[We]`` int64 endpoints with ``n_pad`` as the padding
    sentinel, ``pay`` ``[We, k]`` f64, frontier ``f_node``/``f_ghost``/
    ``f_pay`` ``[Wf]``/``[Wf, k]`` with the same sentinel on ``f_node``.
    Static: ``n_pad`` (node capacity), ``mode``, ``k``.  Returns
    ``(labels [n_pad] raw roots, rounds)`` — the caller crops to the real
    member count and applies the consecutive relabel on host.
    """
    import jax.numpy as jnp
    from jax import lax

    We = u.shape[0]
    Wf = f_node.shape[0]
    n = n_pad
    sign = 1.0 if mode == "max" else -1.0
    thr = sign * threshold
    NEG = -jnp.inf
    SENT = jnp.int64(n)
    BIGK = jnp.int64(2 ** 62)

    def prio_of(p):
        if k == 1:
            return sign * p[:, 0]
        return sign * (p[:, 0] / jnp.maximum(p[:, 1], 1e-300))

    def agg_edges(u, v, pay):
        # _canonical_edges on device: lo<hi canonicalization, self/pad
        # edges to the sentinel, stable 2-key sort (== the host's single
        # lo*n+hi key), segment compaction so the surviving edge ids are
        # the sorted ranks, and in-order scatter-adds for the payload sums
        lo = jnp.minimum(u, v)
        hi = jnp.maximum(u, v)
        dead = (lo == hi) | (u == SENT)
        lo = jnp.where(dead, SENT, lo)
        hi = jnp.where(dead, SENT, hi)
        ops = lax.sort((lo, hi) + tuple(pay[:, c] for c in range(k)),
                       num_keys=2, is_stable=True)
        lo, hi = ops[0], ops[1]
        cols = ops[2:]
        valid = lo != SENT
        is_first = valid & (
            (lo != jnp.concatenate([SENT[None], lo[:-1]]))
            | (hi != jnp.concatenate([SENT[None], hi[:-1]]))
        )
        seg = jnp.cumsum(is_first.astype(jnp.int64)) - 1
        sid = jnp.where(valid, seg, We)
        new_u = jnp.full((We + 1,), SENT, jnp.int64).at[sid].min(
            jnp.where(valid, lo, SENT), mode="drop")[:We]
        new_v = jnp.full((We + 1,), SENT, jnp.int64).at[sid].min(
            jnp.where(valid, hi, SENT), mode="drop")[:We]
        new_pay = jnp.stack(
            [jnp.zeros((We + 1,)).at[sid].add(
                jnp.where(valid, c, 0.0), mode="drop")[:We]
             for c in cols], axis=1)
        return new_u, new_v, new_pay

    def agg_frontier(fn, fg, fpay):
        # _aggregate_frontier on device: the same fn*mult+fg key (mult
        # recomputed per call over the live entries, like the host) and
        # the same stable-sort + in-order summation
        valid = fn != SENT
        mult = jnp.maximum(jnp.max(jnp.where(valid, fg, -1)) + 1, 1)
        key = jnp.where(valid, fn * mult + fg, BIGK)
        ops = lax.sort((key,) + tuple(fpay[:, c] for c in range(k)),
                       num_keys=1, is_stable=True)
        key = ops[0]
        cols = ops[1:]
        valid = key != BIGK
        is_first = valid & (key != jnp.concatenate([BIGK[None], key[:-1]]))
        seg = jnp.cumsum(is_first.astype(jnp.int64)) - 1
        sid = jnp.where(valid, seg, Wf)
        key_seg = jnp.full((Wf + 1,), BIGK, jnp.int64).at[sid].min(
            jnp.where(valid, key, BIGK), mode="drop")[:Wf]
        live = key_seg != BIGK
        new_fn = jnp.where(live, key_seg // mult, SENT)
        new_fg = jnp.where(live, key_seg % mult, jnp.int64(0))
        new_fpay = jnp.stack(
            [jnp.zeros((Wf + 1,)).at[sid].add(
                jnp.where(valid, c, 0.0), mode="drop")[:Wf]
             for c in cols], axis=1)
        return new_fn, new_fg, new_fpay

    u, v, pay = agg_edges(u, v, pay)
    f_node, f_ghost, f_pay = agg_frontier(f_node, f_ghost, f_pay)

    def cond(state):
        return state[-1]

    def body(state):
        u, v, pay, fn, fg, fpay, labels, rounds, _ = state
        active = u != SENT
        prio = jnp.where(active, prio_of(pay), NEG)
        elig = active & (prio > thr)
        eid = jnp.arange(We, dtype=jnp.int64)
        best_p = jnp.full((n + 1,), NEG).at[
            jnp.where(elig, u, SENT)].max(prio, mode="drop")
        best_p = best_p.at[jnp.where(elig, v, SENT)].max(prio, mode="drop")
        # external competition: the frontier raises best_p but never
        # places a candidate edge id — the node abstains if it wins
        factive = fn != SENT
        fprio = jnp.where(factive, prio_of(fpay), NEG)
        felig = factive & (fprio > thr)
        best_p = best_p.at[jnp.where(felig, fn, SENT)].max(
            fprio, mode="drop")
        cand_u = jnp.where(elig & (prio == best_p[u]), u, SENT)
        cand_v = jnp.where(elig & (prio == best_p[v]), v, SENT)
        best_e = jnp.full((n + 1,), We, jnp.int64).at[cand_u].min(
            eid, mode="drop")
        best_e = best_e.at[cand_v].min(eid, mode="drop")
        mutual = elig & (best_e[u] == eid) & (best_e[v] == eid)
        progressed = jnp.any(mutual)
        root = jnp.arange(n + 1, dtype=jnp.int64).at[
            jnp.where(mutual, v, SENT)].set(
            jnp.where(mutual, u, SENT), mode="drop")
        labels = root[labels]
        u2, v2, pay2 = agg_edges(root[u], root[v], pay)
        fn2, fg2, fpay2 = agg_frontier(root[fn], fg, fpay)
        return (u2, v2, pay2, fn2, fg2, fpay2, labels,
                rounds + progressed.astype(jnp.int64), progressed)

    labels0 = jnp.arange(n + 1, dtype=jnp.int64)
    state = (u, v, pay, f_node, f_ghost, f_pay, labels0, jnp.int64(0),
             jnp.bool_(True))
    state = lax.while_loop(cond, body, state)
    return state[6][:n], state[7]


# ---------------------------------------------------------------------------
# dispatch + public entry points
# ---------------------------------------------------------------------------


def parallel_contraction(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    mode: str,
    threshold: float,
    impl: str = "auto",
) -> np.ndarray:
    """Run the round engine; returns int64 labels 0..k-1.

    See the module docstring for ``mode``/``payload`` semantics and the
    ``impl`` ladder.  ``impl="heap"`` is rejected here (the heap solvers
    have their own entry points with richer signatures).
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if mode not in ("max", "min"):
        raise ValueError(f"mode must be 'max' or 'min', got {mode!r}")
    if n_nodes == 0 or len(edges) == 0:
        return np.arange(n_nodes, dtype=np.int64)
    payload = np.asarray(payload, dtype=np.float64).reshape(len(edges), -1)

    labels = None
    resolved = _resolve_impl(impl)
    if resolved == "jax":
        labels = _contract_rounds_jax(n_nodes, edges, payload, mode, threshold)
    elif resolved == "native":
        from .. import native

        labels = native.parallel_contract(
            n_nodes, edges, payload, mode == "max", threshold
        )
        if labels is None:
            if impl == "native":
                raise RuntimeError(
                    "native library unavailable for impl='native'"
                )
            resolved = "numpy"
    if labels is None:
        if resolved != "numpy":
            raise ValueError(f"unknown impl {impl!r}")
        labels = _contract_rounds_numpy(n_nodes, edges, payload, mode, threshold)
    # observability (docs/PERFORMANCE.md "Distributed agglomeration"):
    # edges-in vs surviving inter-cluster edges, per solve
    _record_solver_metrics(
        solver_calls=1,
        solver_edges_in=len(edges),
        solver_edges_out=int(
            (labels[edges[:, 0]] != labels[edges[:, 1]]).sum()
        ),
    )
    return labels


def gaec_parallel(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    stop_cost: float = 0.0,
    impl: str = "auto",
) -> np.ndarray:
    """Parallel GAEC: round-based contraction of mutually-best positive
    edges; parallel edges merge additively.  Drop-in for
    :func:`..ops.multicut.greedy_additive` (same contract, approximate
    greedy order — energy within a couple percent on RAG instances)."""
    if impl == "heap":
        from .multicut import greedy_additive

        return greedy_additive(n_nodes, edges, costs, stop_cost)
    costs = np.asarray(costs, dtype=np.float64).reshape(-1, 1)
    return parallel_contraction(
        n_nodes, edges, costs, "max", float(stop_cost), impl=impl
    )


def average_parallel(
    n_nodes: int,
    edges: np.ndarray,
    probs: np.ndarray,
    sizes: Optional[np.ndarray] = None,
    threshold: float = 0.5,
    impl: str = "auto",
) -> np.ndarray:
    """Parallel average-linkage agglomeration: contract mutually-cheapest
    edges while the size-weighted mean boundary probability is below
    ``threshold``.  Drop-in for
    :func:`..ops.agglomeration.average_agglomeration`."""
    if impl == "heap":
        from .agglomeration import average_agglomeration

        return average_agglomeration(
            n_nodes, edges, probs,
            np.ones(len(edges)) if sizes is None else sizes, threshold,
        )
    probs = np.asarray(probs, dtype=np.float64)
    s = (
        np.ones(len(probs), np.float64)
        if sizes is None
        else np.maximum(np.asarray(sizes, np.float64), 1e-12)
    )
    payload = np.stack([probs * s, s], axis=1)
    return parallel_contraction(
        n_nodes, edges, payload, "min", float(threshold), impl=impl
    )
