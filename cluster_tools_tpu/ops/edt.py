"""Euclidean distance transform as a separable, dense device kernel.

The reference used ``vigra.filters.distanceTransform`` (C++ Felzenszwalb-style
lower-envelope scan; SURVEY.md §2b).  The envelope scan is inherently
sequential per line, which is hostile to a vector unit, so this redesign uses
the *brute-force separable* formulation instead: exact squared EDT decomposes
per axis as

    g[i] = min_j ( f[j] + w * (i - j)^2 )

— a min-plus product of each line with a fixed (n, n) parabola matrix.  The
broadcast-add + min-reduce fuses in XLA into a single tiled loop (no (n, n)
intermediate in HBM), and all lines process in parallel on the VPU.  O(n) more
FLOPs than Felzenszwalb per line, but FLOPs are what a TPU has; block
extents are <= a few hundred voxels so n^2 per line is small.

Supports anisotropic ``sampling`` (e.g. CREMI's (40, 4, 4) nm voxels).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# numpy (not jnp) so importing this module never triggers jax backend
# initialization — with the TPU plugin registered that would dial the chip
# at import time
_BIG = np.float32(1e12)


def _edt_1d_axis(f: jnp.ndarray, axis: int, w: float) -> jnp.ndarray:
    """One separable pass: g[..., i] = min_j f[..., j] + w*(i-j)^2 along axis."""
    n = f.shape[axis]
    f = jnp.moveaxis(f, axis, -1)
    i = jnp.arange(n, dtype=jnp.float32)
    dist = (i[:, None] - i[None, :]) ** 2 * jnp.float32(w)  # [j, i]
    g = jnp.min(f[..., :, None] + dist, axis=-2)
    return jnp.moveaxis(g, -1, axis)


@partial(jax.jit, static_argnames=("sampling",))
def _dt_squared_impl(mask: jnp.ndarray, sampling: Tuple[float, ...]) -> jnp.ndarray:
    f = jnp.where(mask, _BIG, jnp.float32(0.0))
    for axis in range(mask.ndim):
        f = _edt_1d_axis(f, axis, float(sampling[axis]) ** 2)
    return jnp.minimum(f, _BIG)


def _norm_sampling(ndim: int, sampling) -> Tuple[float, ...]:
    if sampling is None:
        return (1.0,) * ndim
    sampling = tuple(float(s) for s in np.atleast_1d(sampling))
    if len(sampling) == 1:
        sampling = sampling * ndim
    if len(sampling) != ndim:
        raise ValueError(f"sampling {sampling} has wrong rank for ndim {ndim}")
    return sampling


def distance_transform_squared(
    mask: jnp.ndarray, sampling: Optional[Sequence[float]] = None
) -> jnp.ndarray:
    """Squared EDT of a boolean mask: distance to the nearest background voxel.

    Foreground voxels get the squared distance to the nearest ``False`` voxel;
    background voxels get 0.  If the block contains no background, foreground
    saturates at a large constant (callers clip or don't care — matches the
    halo-read semantics where blocks always see some context).  ``sampling``
    may be a scalar, list, tuple, or array of per-axis voxel sizes.
    """
    return _dt_squared_impl(mask, _norm_sampling(mask.ndim, sampling))


def distance_transform(
    mask: jnp.ndarray, sampling: Optional[Sequence[float]] = None
) -> jnp.ndarray:
    """Exact Euclidean distance transform (sqrt of the squared EDT)."""
    return jnp.sqrt(distance_transform_squared(mask, sampling=sampling))
