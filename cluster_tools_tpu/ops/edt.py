"""Euclidean distance transform as a separable, dense device kernel.

The reference used ``vigra.filters.distanceTransform`` (C++ Felzenszwalb-style
lower-envelope scan; SURVEY.md §2b).  The envelope scan is inherently
sequential per line and hostile to a vector unit, so this redesign uses the
*parabolic erosion cascade* (van den Boomgaard's decomposition of quadratic
structuring functions): the per-axis min-plus transform

    g[i] = min_j ( f[j] + w * (i - j)^2 )

equals ``r`` iterated erosions with the 3-tap kernel ``[c_i, 0, c_i]`` where
``c_i = w * (2i - 1)`` — because the k smallest odd increments sum to
``w * k^2``, a voxel reached over offset ``k`` accumulates exactly the
parabola cost.  Each iteration is an elementwise min of three shifted arrays:
no (n, n) intermediate, pure VPU work, fused by XLA into a few
bandwidth-bound loops.  ``r = n`` gives the exact transform; smaller ``r``
gives the transform capped at radius ``r`` per axis (all values below the cap
are exact) — the natural choice inside blockwise pipelines where distances
beyond the block/halo scale are meaningless.

Supports anisotropic ``sampling`` (e.g. CREMI's (40, 4, 4) nm voxels).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# numpy (not jnp) so importing this module never triggers jax backend
# initialization — with the TPU plugin registered that would dial the chip
# at import time
_BIG = np.float32(1e12)

# cascade iterations are sequential full-volume passes; above this radius the
# one-shot broadcast min-plus (O(n) parallel work per output, fully fusable)
# wins over an O(radius)-deep dependent-kernel chain
_CASCADE_MAX_RADIUS = 160


def _edt_1d_axis_bcast(f: jnp.ndarray, axis: int, w: float) -> jnp.ndarray:
    """One-shot min-plus: g[..., i] = min_j f[..., j] + w*(i-j)^2 along axis."""
    n = f.shape[axis]
    f = jnp.moveaxis(f, axis, -1)
    i = jnp.arange(n, dtype=jnp.float32)
    dist = (i[:, None] - i[None, :]) ** 2 * jnp.float32(w)  # [j, i]
    g = jnp.min(f[..., :, None] + dist, axis=-2)
    return jnp.moveaxis(g, -1, axis)


def _edt_1d_axis(f: jnp.ndarray, axis: int, w: float, radius: int) -> jnp.ndarray:
    """Parabolic erosion along ``axis``: min_j f[j] + w*(i-j)^2, |i-j| <= radius."""
    n = f.shape[axis]
    radius = min(radius, n - 1)
    if radius <= 0:
        return f
    if radius > _CASCADE_MAX_RADIUS:
        return _edt_1d_axis_bcast(f, axis, w)
    pad_shape = list(f.shape)
    pad_shape[axis] = 1
    pad = jnp.full(pad_shape, _BIG, dtype=f.dtype)

    def shift(x, direction):
        if direction > 0:
            body = lax.slice_in_dim(x, 0, n - 1, axis=axis)
            return jnp.concatenate([pad, body], axis=axis)
        body = lax.slice_in_dim(x, 1, n, axis=axis)
        return jnp.concatenate([body, pad], axis=axis)

    def body(i, g):
        c = jnp.float32(w) * (2.0 * i.astype(jnp.float32) + 1.0)
        lo = shift(g, +1) + c
        hi = shift(g, -1) + c
        return jnp.minimum(g, jnp.minimum(lo, hi))

    return lax.fori_loop(0, radius, body, f)


@partial(jax.jit, static_argnames=("sampling", "radii", "impl", "interpret"))
def _dt_squared_impl(
    mask: jnp.ndarray,
    sampling: Tuple[float, ...],
    radii: Tuple[int, ...],
    impl: str = "auto",
    interpret: bool = False,
) -> jnp.ndarray:
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    f = jnp.where(mask, _BIG, jnp.float32(0.0))
    if impl == "pallas" and mask.ndim == 3:
        return _dt_squared_pallas(f, sampling, radii, interpret=interpret)
    for axis in range(mask.ndim):
        f = _edt_1d_axis(f, axis, float(sampling[axis]) ** 2, radii[axis])
    return jnp.minimum(f, _BIG)


def _pad_to_mosaic_tiles(f: jnp.ndarray):
    """Pad a 3-D array up to the Mosaic (8, 8, 128) tile multiples with
    +BIG (pad values never win a min).  Returns (padded, original_shape)."""
    z, y, x = f.shape
    zp = -(-z // 8) * 8
    yp = -(-y // 8) * 8
    xp = -(-x // 128) * 128
    if (zp, yp, xp) != (z, y, x):
        f = jnp.pad(
            f, ((0, zp - z), (0, yp - y), (0, xp - x)), constant_values=_BIG
        )
    return f, (z, y, x)


def _pallas_axis_cascade(
    f: jnp.ndarray, axis: int, w: float, radius: int, interpret: bool = False
) -> jnp.ndarray:
    """One VMEM erosion cascade along ``axis`` (padded lanes cropped after)."""
    from .pallas_kernels import edt_cascade_pallas

    f, (z, y, x) = _pad_to_mosaic_tiles(f)
    f = edt_cascade_pallas(f, axis, radius, w, float(_BIG), interpret=interpret)
    return f[:z, :y, :x]


def edt_axis_pass(
    f: jnp.ndarray, axis: int, w: float, radius: int, impl: str = "auto"
) -> jnp.ndarray:
    """One separable min-plus (parabolic erosion) pass along ``axis``.

    Public building block for composed transforms — in particular the
    mesh-distributed exact EDT, which reshards the volume between per-axis
    passes (:mod:`cluster_tools_tpu.parallel.distributed_edt`).  ``w`` is
    the squared per-axis voxel size; ``radius`` caps the pass (values up to
    the cap exact).
    """
    radius = min(int(radius), f.shape[axis] - 1)
    if radius <= 0:
        return f
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas" and f.ndim == 3:
        return _pallas_axis_cascade(f, axis, float(w), radius)
    return _edt_1d_axis(f, axis, float(w), radius)


def _dt_squared_pallas(
    f: jnp.ndarray,
    sampling: Tuple[float, ...],
    radii: Tuple[int, ...],
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-axis VMEM erosion cascades, one shared pad across all three axes
    (see :func:`_pad_to_mosaic_tiles`)."""
    from .pallas_kernels import edt_cascade_pallas

    f, (z, y, x) = _pad_to_mosaic_tiles(f)
    for axis in range(3):
        r = min(radii[axis], f.shape[axis] - 1)
        if r > 0:
            f = edt_cascade_pallas(
                f, axis, r, float(sampling[axis]) ** 2, float(_BIG),
                interpret=interpret,
            )
    return jnp.minimum(f[:z, :y, :x], _BIG)


def _norm_sampling(ndim: int, sampling) -> Tuple[float, ...]:
    if sampling is None:
        return (1.0,) * ndim
    sampling = tuple(float(s) for s in np.atleast_1d(sampling))
    if len(sampling) == 1:
        sampling = sampling * ndim
    if len(sampling) != ndim:
        raise ValueError(f"sampling {sampling} has wrong rank for ndim {ndim}")
    return sampling


def distance_transform_squared(
    mask: jnp.ndarray,
    sampling: Optional[Sequence[float]] = None,
    max_distance: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Squared EDT of a boolean mask: distance to the nearest background voxel.

    Foreground voxels get the squared distance to the nearest ``False`` voxel;
    background voxels get 0.  If the block contains no background, foreground
    saturates at a large constant (callers clip or don't care — matches the
    halo-read semantics where blocks always see some context).  ``sampling``
    may be a scalar, list, tuple, or array of per-axis voxel sizes.

    ``max_distance`` caps the transform: values up to the cap are exact,
    larger distances saturate (at least ``max_distance**2``).  Inside
    blockwise pipelines pass the halo/seed scale — the cascade cost is linear
    in the per-axis radius, so a cap turns O(n) iterations into O(cap).

    ``impl``: "auto" (VMEM cascade kernel on TPU, XLA elsewhere), "pallas",
    or "xla".
    """
    sampling = _norm_sampling(mask.ndim, sampling)
    if max_distance is None:
        radii = tuple(n - 1 for n in mask.shape)
    else:
        radii = tuple(
            int(np.ceil(float(max_distance) / s)) for s in sampling
        )
    return _dt_squared_impl(mask, sampling, radii, impl=impl)


def distance_transform(
    mask: jnp.ndarray,
    sampling: Optional[Sequence[float]] = None,
    max_distance: Optional[float] = None,
) -> jnp.ndarray:
    """Exact Euclidean distance transform (sqrt of the squared EDT)."""
    return jnp.sqrt(
        distance_transform_squared(mask, sampling=sampling, max_distance=max_distance)
    )
