"""Separable image filters as dense device kernels.

The reference used vigra's C++ filters (gaussian smoothing before seed
detection in the watershed task, hessian/gradient filters in feature
pipelines; SURVEY.md §2b "vigra").  Here filters are separable 1-D
convolutions expressed as weighted shift-sums, which XLA fuses into a single
vectorized loop per axis — no im2col, no explicit conv op needed for the
small radii these pipelines use.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ccl import _shift


def _gaussian_kernel(sigma: float, truncate: float = 3.0) -> np.ndarray:
    radius = max(1, int(truncate * sigma + 0.5))
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


@partial(jax.jit, static_argnames=("sigma", "sampling"))
def gaussian_smooth(
    x: jnp.ndarray,
    sigma: float,
    sampling: Optional[Tuple[float, ...]] = None,
) -> jnp.ndarray:
    """Separable gaussian blur with border renormalization.

    ``sampling`` gives per-axis voxel sizes; the effective per-axis sigma is
    ``sigma / sampling[axis]`` (world-space sigma, as vigra's).  Borders use
    the blur(x)/blur(1) normalization, so edge voxels average only over real
    data rather than zero padding.
    """
    if sigma <= 0:
        return x.astype(jnp.float32)
    if sampling is None:
        sampling = (1.0,) * x.ndim
    xf = x.astype(jnp.float32)
    ones = jnp.ones_like(xf)

    def blur(v):
        for axis in range(v.ndim):
            s_ax = float(sigma) / float(sampling[axis])
            if s_ax <= 1e-3:
                continue
            k = _gaussian_kernel(s_ax)
            radius = len(k) // 2
            acc = jnp.zeros_like(v)
            for j, w in enumerate(k):
                acc = acc + jnp.float32(w) * _shift(v, j - radius, axis, 0.0)
            v = acc
        return v

    return blur(xf) / jnp.maximum(blur(ones), 1e-6)


@partial(jax.jit, static_argnames=("axis",))
def gradient_1d(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Central-difference gradient along one axis (replicated borders)."""
    xf = x.astype(jnp.float32)
    fwd = _shift(xf, -1, axis, 0.0)
    bwd = _shift(xf, 1, axis, 0.0)
    n = x.shape[axis]
    idx = jnp.arange(n)
    shape = [1] * x.ndim
    shape[axis] = n
    interior = ((idx > 0) & (idx < n - 1)).reshape(shape)
    return jnp.where(interior, 0.5 * (fwd - bwd), 0.0)


def gradient_magnitude(
    x: jnp.ndarray, sigma: float = 0.0, sampling: Optional[Tuple[float, ...]] = None
) -> jnp.ndarray:
    """Gaussian gradient magnitude (reference: vigra ``gaussianGradientMagnitude``)."""
    s = gaussian_smooth(x, sigma, sampling) if sigma > 0 else x.astype(jnp.float32)
    if sampling is None:
        sampling = (1.0,) * x.ndim
    g2 = jnp.zeros(x.shape, jnp.float32)
    for axis in range(x.ndim):
        g = gradient_1d(s, axis) / jnp.float32(sampling[axis])
        g2 = g2 + g * g
    return jnp.sqrt(g2)


def _symmetric3_eigenvalues(
    a00, a01, a02, a11, a12, a22
) -> jnp.ndarray:
    """Closed-form eigenvalues of a field of symmetric 3x3 matrices.

    Noble/Smith trigonometric form of Cardano's method — branch-free dense
    arithmetic, exactly what the VPU wants (no per-voxel LAPACK calls).
    Returns (*shape, 3) sorted descending.
    """
    q = (a00 + a11 + a22) / 3.0
    b00, b11, b22 = a00 - q, a11 - q, a22 - q
    p2 = (
        b00 * b00 + b11 * b11 + b22 * b22
        + 2.0 * (a01 * a01 + a02 * a02 + a12 * a12)
    )
    # floor keeps p**3 above float32 underflow (else r = det/p^3 is 0/0 NaN
    # on near-zero matrices); eigenvalues are then ~q to within the floor
    p = jnp.maximum(jnp.sqrt(jnp.maximum(p2 / 6.0, 0.0)), 1e-10)
    # r = det(B / p) / 2, clamped into Cardano's domain
    det = (
        b00 * (b11 * b22 - a12 * a12)
        - a01 * (a01 * b22 - a12 * a02)
        + a02 * (a01 * a12 - b11 * a02)
    )
    r = jnp.clip(det / (2.0 * p * p * p), -1.0, 1.0)
    phi = jnp.arccos(r) / 3.0
    two_pi_3 = jnp.float32(2.0 * np.pi / 3.0)
    # phi in [0, pi/3]: cos(phi) is the max root, cos(phi + 2pi/3) the min
    e1 = q + 2.0 * p * jnp.cos(phi)
    e3 = q + 2.0 * p * jnp.cos(phi + two_pi_3)
    e2 = 3.0 * q - e1 - e3
    return jnp.stack([e1, e2, e3], axis=-1)


@partial(jax.jit, static_argnames=("sigma", "sampling"))
def hessian_eigenvalues(
    x: jnp.ndarray,
    sigma: float,
    sampling: Optional[Tuple[float, ...]] = None,
) -> jnp.ndarray:
    """Eigenvalues of the gaussian Hessian, descending (*shape, 3).

    Reference capability: vigra ``hessianOfGaussianEigenvalues`` — the
    ridge/blob detector ilastik's feature bank exposes.  Second derivatives
    come from central differences of the sigma-smoothed volume; eigenvalues
    from the closed form above.
    """
    if x.ndim != 3:
        raise ValueError("hessian_eigenvalues expects a 3-D volume")
    if sampling is None:
        sampling = (1.0,) * x.ndim
    s = gaussian_smooth(x, sigma, sampling)
    inv = [1.0 / float(sp) for sp in sampling]
    g = [gradient_1d(s, a) * jnp.float32(inv[a]) for a in range(3)]
    h = {}
    for a in range(3):
        for b in range(a, 3):
            h[(a, b)] = gradient_1d(g[a], b) * jnp.float32(inv[b])
    return _symmetric3_eigenvalues(
        h[(0, 0)], h[(0, 1)], h[(0, 2)], h[(1, 1)], h[(1, 2)], h[(2, 2)]
    )


@partial(jax.jit, static_argnames=("sigma", "rho", "sampling"))
def structure_tensor_eigenvalues(
    x: jnp.ndarray,
    sigma: float,
    rho: Optional[float] = None,
    sampling: Optional[Tuple[float, ...]] = None,
) -> jnp.ndarray:
    """Eigenvalues of the gaussian structure tensor, descending (*shape, 3).

    Reference capability: vigra ``structureTensorEigenvalues``.  Gradients
    at inner scale ``sigma``; the outer product is integrated at outer scale
    ``rho`` (vigra/ilastik convention: ``rho = sigma / 2`` when omitted).
    """
    if x.ndim != 3:
        raise ValueError("structure_tensor_eigenvalues expects a 3-D volume")
    if sampling is None:
        sampling = (1.0,) * x.ndim
    if rho is None:
        rho = float(sigma) / 2.0
    s = gaussian_smooth(x, sigma, sampling)
    inv = [1.0 / float(sp) for sp in sampling]
    g = [gradient_1d(s, a) * jnp.float32(inv[a]) for a in range(3)]
    t = {}
    for a in range(3):
        for b in range(a, 3):
            t[(a, b)] = gaussian_smooth(g[a] * g[b], rho, sampling)
    return _symmetric3_eigenvalues(
        t[(0, 0)], t[(0, 1)], t[(0, 2)], t[(1, 1)], t[(1, 2)], t[(2, 2)]
    )
