"""Separable image filters as dense device kernels.

The reference used vigra's C++ filters (gaussian smoothing before seed
detection in the watershed task, hessian/gradient filters in feature
pipelines; SURVEY.md §2b "vigra").  Here filters are separable 1-D
convolutions expressed as weighted shift-sums, which XLA fuses into a single
vectorized loop per axis — no im2col, no explicit conv op needed for the
small radii these pipelines use.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ccl import _shift


def _gaussian_kernel(sigma: float, truncate: float = 3.0) -> np.ndarray:
    radius = max(1, int(truncate * sigma + 0.5))
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


@partial(jax.jit, static_argnames=("sigma", "sampling"))
def gaussian_smooth(
    x: jnp.ndarray,
    sigma: float,
    sampling: Optional[Tuple[float, ...]] = None,
) -> jnp.ndarray:
    """Separable gaussian blur with border renormalization.

    ``sampling`` gives per-axis voxel sizes; the effective per-axis sigma is
    ``sigma / sampling[axis]`` (world-space sigma, as vigra's).  Borders use
    the blur(x)/blur(1) normalization, so edge voxels average only over real
    data rather than zero padding.
    """
    if sigma <= 0:
        return x.astype(jnp.float32)
    if sampling is None:
        sampling = (1.0,) * x.ndim
    xf = x.astype(jnp.float32)
    ones = jnp.ones_like(xf)

    def blur(v):
        for axis in range(v.ndim):
            s_ax = float(sigma) / float(sampling[axis])
            if s_ax <= 1e-3:
                continue
            k = _gaussian_kernel(s_ax)
            radius = len(k) // 2
            acc = jnp.zeros_like(v)
            for j, w in enumerate(k):
                acc = acc + jnp.float32(w) * _shift(v, j - radius, axis, 0.0)
            v = acc
        return v

    return blur(xf) / jnp.maximum(blur(ones), 1e-6)


@partial(jax.jit, static_argnames=("axis",))
def gradient_1d(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Central-difference gradient along one axis (replicated borders)."""
    xf = x.astype(jnp.float32)
    fwd = _shift(xf, -1, axis, 0.0)
    bwd = _shift(xf, 1, axis, 0.0)
    n = x.shape[axis]
    idx = jnp.arange(n)
    shape = [1] * x.ndim
    shape[axis] = n
    interior = ((idx > 0) & (idx < n - 1)).reshape(shape)
    return jnp.where(interior, 0.5 * (fwd - bwd), 0.0)


def gradient_magnitude(
    x: jnp.ndarray, sigma: float = 0.0, sampling: Optional[Tuple[float, ...]] = None
) -> jnp.ndarray:
    """Gaussian gradient magnitude (reference: vigra ``gaussianGradientMagnitude``)."""
    s = gaussian_smooth(x, sigma, sampling) if sigma > 0 else x.astype(jnp.float32)
    if sampling is None:
        sampling = (1.0,) * x.ndim
    g2 = jnp.zeros(x.shape, jnp.float32)
    for axis in range(x.ndim):
        g = gradient_1d(s, axis) / jnp.float32(sampling[axis])
        g2 = g2 + g * g
    return jnp.sqrt(g2)
