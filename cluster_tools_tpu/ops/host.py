"""Host per-block kernels — the reference's per-job compute path, faster.

The reference framework runs its per-block compute as single-core scipy /
vigra calls inside cluster jobs (SURVEY.md §2a watershed +
connected_components per-job kernels).  On a machine without an
accelerator the device-shaped tiled/XLA kernels of this framework pay
virtual-mesh serialization for no benefit, so the same capability is
shipped as host kernels, selectable with ``impl="host"`` in the watershed
task and used by ``bench.py``'s cpu-smoke headline.

The hot stages call the framework's own C++ layer when available
(``native/ct_native.cpp`` via ctypes — the same pattern the reference
used for vigra/nifty): an exact Felzenszwalb-Huttenlocher squared EDT
and a 256-level bucket-queue priority-flood watershed, each roughly an
order of magnitude over the scipy generic equivalents they replace
(``distance_transform_edt`` / ``watershed_ift``).  scipy remains the
always-available fallback.

These functions are the semantic (not bit-exact) host twins of
:func:`..ops.tile_ws.dt_watershed_tiled` /
:func:`..ops.tile_ccl.label_components_tiled`: thresholded foreground,
Euclidean distance transform, EDT-maxima seeds, seeded watershed, and a
connected-components pass.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def host_label_components(mask: np.ndarray) -> np.ndarray:
    """Connected components of a boolean mask (scipy, connectivity 1)."""
    from scipy import ndimage

    lab, _ = ndimage.label(mask)
    return lab.astype(np.int32)


def host_dt_watershed(
    vol: np.ndarray,
    threshold: float,
    dt_max_distance: Optional[float] = None,
    min_seed_distance: float = 0.0,
    mask: Optional[np.ndarray] = None,
    sampling: Optional[Tuple[float, ...]] = None,
    fg: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Distance-transform watershed of a boundary map, scipy single-core.

    ``fg`` lets the caller pass an already-thresholded foreground (the
    fused host pipeline thresholds once for ws + CC + count).

    Foreground is ``vol < threshold`` (low boundary evidence), seeds are
    EDT local maxima at least ``min_seed_distance`` from the boundary;
    fragments grow by :func:`scipy.ndimage.watershed_ift` on the quantized
    boundary map.  ``sampling`` is the per-axis voxel size (anisotropy), as
    scipy's.  ``dt_max_distance`` clips the transform to mirror the device
    kernels' capped EDT — including its trade-off: interiors thicker than
    2x the cap saturate into one plateau whose maxima fuse into a single
    seed (see tasks/watershed._kernel_params), so the cap is NOT
    seed-neutral, it is seed-*consistent* with the device path.
    """
    from scipy import ndimage

    from .. import native

    if fg is None:
        fg = vol < threshold
    if mask is not None:
        fg = fg & mask
    dist_sq = (
        native.edt_sq(fg, sampling=sampling, cap=dt_max_distance)
        if vol.ndim == 3 else None
    )
    if dist_sq is not None:
        # maxima of the squared distance == maxima of the distance
        # (monotone); the cap is applied inside the native kernel
        dist = dist_sq
        min_seed = min_seed_distance * min_seed_distance
    else:
        dist = ndimage.distance_transform_edt(fg, sampling=sampling)
        if dt_max_distance is not None:
            dist = np.minimum(dist, float(dt_max_distance))
        min_seed = min_seed_distance
    maxima = (ndimage.maximum_filter(dist, size=3) == dist) & fg
    if min_seed_distance > 0:
        maxima &= dist >= min_seed
    seeds, _ = ndimage.label(maxima)
    hmap = np.clip(vol * 255, 0, 255).astype(np.uint8)
    ws = (
        native.ws_flood(hmap, fg, seeds.astype(np.int32))
        if vol.ndim == 3 else None
    )
    if ws is None:
        ws = ndimage.watershed_ift(hmap, seeds.astype(np.int32))
        ws[~fg] = 0
    return ws


def host_ws_ccl(
    vol: np.ndarray,
    threshold: float,
    dt_max_distance: Optional[float] = None,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """The fused-step equivalent on host: ``(ws, cc, n_foreground)``."""
    fg = vol < threshold
    ws = host_dt_watershed(
        vol,
        threshold,
        dt_max_distance=dt_max_distance,
        min_seed_distance=min_seed_distance,
        sampling=sampling,
        fg=fg,
    )
    cc = host_label_components(fg)
    return ws, cc, int(fg.sum())
