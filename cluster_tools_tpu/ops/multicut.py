"""Multicut solvers: greedy additive edge contraction + local refinement.

The reference consumed nifty's C++ solver zoo (kernighan-lin,
greedy-additive, fusion-moves) through ``utils/segmentation_utils.py``'s
``key_to_agglomerator`` registry (SURVEY.md §2a "Utils", "multicut").  This
module provides the rebuild's solver core:

- :func:`greedy_additive` — GAEC: contract the currently-most-attractive
  edge until none is positive.  Host implementation (heap + neighbor maps):
  edge contraction is inherently sequential, and solver inputs here are
  *reduced* graphs (per-block subproblems or the hierarchically contracted
  global problem), orders of magnitude smaller than the volume.
- :func:`kernighan_lin` — boundary-node move refinement on top of an
  initial partition (greedy positive-gain passes).
- :func:`multicut_energy` — the objective: sum of costs of cut edges
  (costs > 0 attractive, < 0 repulsive; minimization).

Sign convention matches ``probs_to_costs``: ``w = log((1-p)/p)`` — an edge
with low boundary probability has positive (attractive) cost, and cutting it
is penalized.
"""

from __future__ import annotations

import heapq
from typing import Dict, Tuple

import numpy as np


def multicut_energy(
    edges: np.ndarray, costs: np.ndarray, node_labels: np.ndarray
) -> float:
    """Objective value: sum of costs over cut edges (lower is better)."""
    if len(edges) == 0:
        return 0.0
    cut = node_labels[edges[:, 0]] != node_labels[edges[:, 1]]
    return float(costs[cut].sum())


def _relabel_consecutive(parent: np.ndarray) -> np.ndarray:
    _, labels = np.unique(parent, return_inverse=True)
    return labels.astype(np.int64)


def greedy_additive(
    n_nodes: int, edges: np.ndarray, costs: np.ndarray, stop_cost: float = 0.0
) -> np.ndarray:
    """Greedy additive edge contraction (GAEC, Keuper et al. style).

    Repeatedly contracts the highest-cost edge while it exceeds
    ``stop_cost`` (default 0: only attractive edges merge); parallel edges
    arising from a contraction have their costs *added*.  Returns int64
    node labels 0..k-1.
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)

    from .. import native

    labels = native.greedy_additive(n_nodes, edges, costs, stop_cost)
    if labels is not None:
        return labels

    # union-find
    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    # neighbor cost maps, symmetric
    nbrs: list = [dict() for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        u, v = int(u), int(v)
        nbrs[u][v] = nbrs[u].get(v, 0.0) + w
        nbrs[v][u] = nbrs[v].get(u, 0.0) + w
    heap: list = [
        (-w, u, v) for u in range(n_nodes) for v, w in nbrs[u].items() if u < v
    ]
    heapq.heapify(heap)

    while heap:
        neg_w, u, v = heapq.heappop(heap)
        w = -neg_w
        if w <= stop_cost:
            break
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        # stale entry: the edge's current weight must match
        if nbrs[ru].get(rv) != w:
            continue
        # contract rv into ru (ru keeps the larger neighbor map)
        if len(nbrs[ru]) < len(nbrs[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        del nbrs[ru][rv]
        for x, wx in nbrs[rv].items():
            if x == ru:
                continue
            new_w = nbrs[ru].get(x, 0.0) + wx
            nbrs[ru][x] = new_w
            nbrs[x][ru] = new_w
            del nbrs[x][rv]
            if new_w > stop_cost:
                heapq.heappush(heap, (-new_w, ru, x))
        nbrs[rv].clear()

    roots = np.array([find(i) for i in range(n_nodes)], dtype=np.int64)
    return _relabel_consecutive(roots)


def kernighan_lin(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    init_labels: np.ndarray | None = None,
    max_passes: int = 10,
) -> np.ndarray:
    """Local-move refinement: greedily move boundary nodes between adjacent
    partitions while the objective improves (a practical Kernighan-Lin-style
    heuristic over an initial GAEC partition)."""
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)
    labels = (
        greedy_additive(n_nodes, edges, costs)
        if init_labels is None
        else np.asarray(init_labels, dtype=np.int64).copy()
    )
    if len(edges) == 0:
        return _relabel_consecutive(labels)
    # adjacency with costs
    adj: list = [[] for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        adj[int(u)].append((int(v), w))
        adj[int(v)].append((int(u), w))

    for _ in range(max_passes):
        moved = False
        for u in range(n_nodes):
            if not adj[u]:
                continue
            lu = labels[u]
            # gain of moving u to partition L = sum of edge costs to L
            # minus sum of edge costs to current partition
            gains: Dict[int, float] = {}
            stay = 0.0
            for v, w in adj[u]:
                lv = labels[v]
                if lv == lu:
                    stay += w
                else:
                    gains[lv] = gains.get(lv, 0.0) + w
            if not gains:
                continue
            best_l, best_w = max(gains.items(), key=lambda kv: kv[1])
            if best_w > stay + 1e-12:
                labels[u] = best_l
                moved = True
        if not moved:
            break
    return _relabel_consecutive(labels)


def contract_graph(
    edges: np.ndarray,
    costs: np.ndarray,
    node_labels: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Contract a graph by a node labeling: map endpoints through labels,
    drop self-edges, sum parallel-edge costs.  Returns (new_edges,
    new_costs) on the label id space — the reduce step of the hierarchical
    multicut (reference: ``reduce_problem.py``)."""
    if len(edges) == 0:
        return edges.reshape(0, 2).astype(np.int64), costs.astype(np.float64)
    u = node_labels[edges[:, 0]]
    v = node_labels[edges[:, 1]]
    lo = np.minimum(u, v)
    hi = np.maximum(u, v)
    keep = lo != hi
    pairs = np.stack([lo[keep], hi[keep]], axis=1)
    w = np.asarray(costs, dtype=np.float64)[keep]
    if len(pairs) == 0:
        return np.zeros((0, 2), np.int64), np.zeros(0, np.float64)
    new_edges, inv = np.unique(pairs, axis=0, return_inverse=True)
    new_costs = np.zeros(len(new_edges), np.float64)
    np.add.at(new_costs, inv.ravel(), w)
    return new_edges.astype(np.int64), new_costs


def lifted_multicut_energy(
    edges: np.ndarray,
    costs: np.ndarray,
    lifted_edges: np.ndarray,
    lifted_costs: np.ndarray,
    node_labels: np.ndarray,
) -> float:
    """Lifted objective: local cut costs + lifted cut costs (lower is
    better; a lifted edge is 'cut' when its endpoints are in different
    clusters, regardless of graph connectivity)."""
    e = multicut_energy(edges, costs, node_labels)
    if len(lifted_edges):
        cut = node_labels[lifted_edges[:, 0]] != node_labels[lifted_edges[:, 1]]
        e += float(np.asarray(lifted_costs, np.float64)[cut].sum())
    return e


def lifted_greedy_additive(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    lifted_edges: np.ndarray,
    lifted_costs: np.ndarray,
    stop_cost: float = 0.0,
) -> np.ndarray:
    """GAEC for the lifted multicut (Keuper et al. style).

    Clusters may only contract along *local* edges, but the merge priority
    is the combined local+lifted cost between the two clusters; lifted
    weights merge additively alongside local ones.  Returns int64 labels.
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)
    lifted_edges = np.asarray(lifted_edges, dtype=np.int64).reshape(-1, 2)
    lifted_costs = np.asarray(lifted_costs, dtype=np.float64)
    if len(lifted_edges) == 0:
        # plain multicut: reuse the (native-accelerated) GAEC
        return greedy_additive(n_nodes, edges, costs, stop_cost)

    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    local: list = [dict() for _ in range(n_nodes)]
    lifted: list = [dict() for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        u, v = int(u), int(v)
        local[u][v] = local[u].get(v, 0.0) + w
        local[v][u] = local[u][v]
    for (u, v), w in zip(lifted_edges, lifted_costs):
        if u == v:
            continue
        u, v = int(u), int(v)
        lifted[u][v] = lifted[u].get(v, 0.0) + w
        lifted[v][u] = lifted[u][v]

    def prio(u, v):
        return local[u][v] + lifted[u].get(v, 0.0)

    heap = [
        (-prio(u, v), u, v)
        for u in range(n_nodes)
        for v in local[u]
        if u < v
    ]
    heapq.heapify(heap)

    while heap:
        neg_w, u, v = heapq.heappop(heap)
        w = -neg_w
        if w <= stop_cost:
            break
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        if rv not in local[ru] or abs(prio(ru, rv) - w) > 1e-12:
            continue  # stale
        if len(local[ru]) + len(lifted[ru]) < len(local[rv]) + len(lifted[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        del local[ru][rv]
        lifted[ru].pop(rv, None)
        # merge local neighbor costs
        for x, wx in local[rv].items():
            if x == ru:
                continue
            nw = local[ru].get(x, 0.0) + wx
            local[ru][x] = nw
            local[x][ru] = nw
            del local[x][rv]
        # merge lifted neighbor costs
        for x, wx in lifted[rv].items():
            if x == ru:
                continue
            nw = lifted[ru].get(x, 0.0) + wx
            lifted[ru][x] = nw
            lifted[x][ru] = nw
            del lifted[x][rv]
        # only pairs whose priority changed need re-pushing: local
        # neighbors inherited from rv, and ru-neighbors whose lifted part
        # changed (lifted[rv] also landed on ru)
        changed = set(local[rv]) | (set(lifted[rv]) & set(local[ru]))
        changed.discard(ru)
        local[rv].clear()
        lifted[rv].clear()
        for x in changed:
            if x in local[ru]:
                p = prio(ru, x)
                if p > stop_cost:
                    heapq.heappush(heap, (-p, ru, x))

    roots = np.array([find(i) for i in range(n_nodes)], dtype=np.int64)
    return _relabel_consecutive(roots)
