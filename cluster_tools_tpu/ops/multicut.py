"""Multicut solvers: GAEC, Kernighan-Lin, fusion moves, decomposition.

The reference consumed nifty's C++ solver zoo (kernighan-lin,
greedy-additive, fusion-moves) through ``utils/segmentation_utils.py``'s
``key_to_agglomerator`` registry (SURVEY.md §2a "Utils", "multicut").  This
module provides the rebuild's solver core:

- :func:`greedy_additive` — GAEC: contract the currently-most-attractive
  edge until none is positive.  Host implementation (heap + neighbor maps):
  edge contraction is inherently sequential, and solver inputs here are
  *reduced* graphs (per-block subproblems or the hierarchically contracted
  global problem), orders of magnitude smaller than the volume.
- :func:`kernighan_lin` — faithful KL for multicut (Keuper et al.'s KLj):
  pairwise two-set refinement with *gain sequences* — tentative move chains
  including negative-gain steps, rolled back to the best prefix — plus join
  moves, so it escapes the single-move local minima a greedy pass gets
  stuck in.
- :func:`fusion_moves` — fusion-move solver (Beier et al. style): propose
  partitions from GAEC on perturbed costs, fuse each proposal with the
  incumbent by solving the multicut on the intersection-contracted graph;
  monotonically non-increasing energy.
- :func:`decompose_solve` — pre-decompose over attractive-edge components,
  solve each part independently (nifty's decomposition solver pattern).
- :func:`multicut_energy` — the objective: sum of costs of cut edges
  (costs > 0 attractive, < 0 repulsive; minimization).

Sign convention matches ``probs_to_costs``: ``w = log((1-p)/p)`` — an edge
with low boundary probability has positive (attractive) cost, and cutting it
is penalized.
"""

from __future__ import annotations

import hashlib
import heapq
import os
from typing import Dict, List, Optional, Tuple

import numpy as np
from zipfile import BadZipFile


class SolverCheckpoint:
    """Preemption-safe intermediate state for long solves (SURVEY.md §5.3).

    The reference's resume grain is task/block; a long global solve dying
    mid-run lost everything.  This persists the partition after every KL
    outer sweep (atomic tmp+rename, like the block markers), fingerprinted
    by the problem's (edges, costs) bytes so a stale checkpoint from a
    different reduced problem can never seed a resume.
    """

    def __init__(self, path: str, edges: np.ndarray, costs: np.ndarray):
        self.path = path
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(edges).tobytes())
        h.update(np.ascontiguousarray(costs).tobytes())
        self.problem_key = h.hexdigest()

    def load(self) -> Optional[Tuple[np.ndarray, int]]:
        """(labels, next_sweep) from a matching checkpoint, else None."""
        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as f:
                if str(f["problem_key"]) != self.problem_key:
                    return None
                return f["labels"].astype(np.int64), int(f["sweep"])
        except (OSError, ValueError, KeyError, BadZipFile):
            # torn write from a crash mid-save: ignore, solve from scratch
            return None

    def save(self, labels: np.ndarray, sweep: int, energy: float) -> None:
        self._sweep_temps()  # a kill inside a prior save orphans its temp
        tmp = f"{self.path}.{os.getpid()}.tmp"
        np.savez(
            tmp,
            labels=np.asarray(labels, np.int64),
            sweep=np.int64(sweep),
            energy=np.float64(energy),
            problem_key=self.problem_key,
        )
        # np.savez appends .npz to names without it
        if not tmp.endswith(".npz") and os.path.exists(tmp + ".npz"):
            tmp = tmp + ".npz"
        os.replace(tmp, self.path)

    def clear(self) -> None:
        self._sweep_temps()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _sweep_temps(self) -> None:
        import glob

        for stale in glob.glob(f"{self.path}.*.tmp*"):
            try:
                os.unlink(stale)
            except OSError:
                pass


def multicut_energy(
    edges: np.ndarray, costs: np.ndarray, node_labels: np.ndarray
) -> float:
    """Objective value: sum of costs over cut edges (lower is better)."""
    if len(edges) == 0:
        return 0.0
    cut = node_labels[edges[:, 0]] != node_labels[edges[:, 1]]
    return float(costs[cut].sum())


def _relabel_consecutive(parent: np.ndarray) -> np.ndarray:
    _, labels = np.unique(parent, return_inverse=True)
    return labels.astype(np.int64)


def greedy_additive(
    n_nodes: int, edges: np.ndarray, costs: np.ndarray, stop_cost: float = 0.0
) -> np.ndarray:
    """Greedy additive edge contraction (GAEC, Keuper et al. style).

    Repeatedly contracts the highest-cost edge while it exceeds
    ``stop_cost`` (default 0: only attractive edges merge); parallel edges
    arising from a contraction have their costs *added*.  Returns int64
    node labels 0..k-1.

    Tie-breaking is deterministic and documented: heap entries are
    ``(-cost, u, v)`` tuples, so among equal-cost edges the smallest
    ``(u, v)`` endpoint pair (current cluster representatives at push time)
    contracts first.  The native kernel (``ct_greedy_additive``) orders its
    heap identically, so the two paths agree across platforms and the
    impl-ladder parity tests are stable.
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)

    from .. import native

    labels = native.greedy_additive(n_nodes, edges, costs, stop_cost)
    if labels is not None:
        return labels

    # union-find
    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    # neighbor cost maps, symmetric
    nbrs: list = [dict() for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        u, v = int(u), int(v)
        nbrs[u][v] = nbrs[u].get(v, 0.0) + w
        nbrs[v][u] = nbrs[v].get(u, 0.0) + w
    heap: list = [
        (-w, u, v) for u in range(n_nodes) for v, w in nbrs[u].items() if u < v
    ]
    heapq.heapify(heap)

    while heap:
        neg_w, u, v = heapq.heappop(heap)
        w = -neg_w
        if w <= stop_cost:
            break
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        # stale entry: the edge's current weight must match
        if nbrs[ru].get(rv) != w:
            continue
        # contract rv into ru (ru keeps the larger neighbor map)
        if len(nbrs[ru]) < len(nbrs[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        del nbrs[ru][rv]
        for x, wx in nbrs[rv].items():
            if x == ru:
                continue
            new_w = nbrs[ru].get(x, 0.0) + wx
            nbrs[ru][x] = new_w
            nbrs[x][ru] = new_w
            del nbrs[x][rv]
            if new_w > stop_cost:
                heapq.heappush(heap, (-new_w, ru, x))
        nbrs[rv].clear()

    roots = np.array([find(i) for i in range(n_nodes)], dtype=np.int64)
    return _relabel_consecutive(roots)


def greedy_node_moves(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    init_labels: np.ndarray | None = None,
    max_passes: int = 10,
) -> np.ndarray:
    """Greedy single-node move refinement (hill climbing): move boundary
    nodes to the adjacent partition with the best immediate gain.  Cheaper
    and weaker than :func:`kernighan_lin` — no gain sequences, cannot escape
    single-move local minima."""
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)
    labels = (
        greedy_additive(n_nodes, edges, costs)
        if init_labels is None
        else np.asarray(init_labels, dtype=np.int64).copy()
    )
    if len(edges) == 0:
        return _relabel_consecutive(labels)
    # adjacency with costs
    adj: list = [[] for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        adj[int(u)].append((int(v), w))
        adj[int(v)].append((int(u), w))

    for _ in range(max_passes):
        moved = False
        for u in range(n_nodes):
            if not adj[u]:
                continue
            lu = labels[u]
            # gain of moving u to partition L = sum of edge costs to L
            # minus sum of edge costs to current partition
            gains: Dict[int, float] = {}
            stay = 0.0
            for v, w in adj[u]:
                lv = labels[v]
                if lv == lu:
                    stay += w
                else:
                    gains[lv] = gains.get(lv, 0.0) + w
            if not gains:
                continue
            best_l, best_w = max(gains.items(), key=lambda kv: kv[1])
            if best_w > stay + 1e-12:
                labels[u] = best_l
                moved = True
        if not moved:
            break
    return _relabel_consecutive(labels)


def _kl_refine_pair(
    nodes_a: List[int],
    nodes_b: List[int],
    labels: np.ndarray,
    adj: List[List[Tuple[int, float]]],
    epsilon: float,
) -> float:
    """One KL inner loop on the two partitions holding ``nodes_a/b``.

    Builds the full tentative move sequence (every node of both sets flipped
    exactly once, always the unmoved node with maximal gain next — negative
    gains included), then applies the best positive prefix, or the A|B join
    if that is better.  Returns the realized energy improvement; mutates
    ``labels`` in place.
    """
    la = labels[nodes_a[0]]
    lb = labels[nodes_b[0]]
    members = nodes_a + nodes_b
    in_pair = {u: i for i, u in enumerate(members)}
    side = np.array([0] * len(nodes_a) + [1] * len(nodes_b), dtype=np.int8)

    # D[i] = gain of flipping member i = c(i, other side) - c(i, own side),
    # edges within the pair only (edges to other partitions stay cut either
    # way); cut_ab = total cost currently cut between A and B (join gain)
    d = np.zeros(len(members))
    cut_ab = 0.0
    for i, u in enumerate(members):
        for v, w in adj[u]:
            j = in_pair.get(v)
            if j is None:
                continue
            if side[j] == side[i]:
                d[i] -= w
            else:
                d[i] += w
                if i < j:
                    cut_ab += w
    join_gain = cut_ab

    # tentative sequence with rollback to the best prefix
    moved = np.zeros(len(members), bool)
    order: List[int] = []
    cum = 0.0
    cum_seq: List[float] = []
    for _ in range(len(members)):
        cand = np.where(~moved)[0]
        i = cand[np.argmax(d[cand])]
        moved[i] = True
        order.append(int(i))
        cum += d[i]
        cum_seq.append(cum)
        u = members[i]
        old_side = side[i]
        side[i] = 1 - old_side
        for v, w in adj[u]:
            j = in_pair.get(v)
            if j is None or moved[j]:
                continue
            d[j] += 2.0 * w if side[j] == old_side else -2.0 * w

    best_k = int(np.argmax(cum_seq)) + 1
    best_gain = cum_seq[best_k - 1]

    if join_gain > best_gain and join_gain > epsilon:
        for u in nodes_b:
            labels[u] = la
        return join_gain
    if best_gain > epsilon:
        # flipping ALL nodes is a relabeling no-op (A and B swap names);
        # treat it as no gain to avoid cycling
        if best_k == len(members):
            return 0.0
        for i in order[:best_k]:
            labels[members[i]] = lb if labels[members[i]] == la else la
        return best_gain
    return 0.0


def kernighan_lin(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    init_labels: np.ndarray | None = None,
    max_outer: int = 20,
    epsilon: float = 1e-9,
    checkpoint: Optional[SolverCheckpoint] = None,
) -> np.ndarray:
    """Kernighan-Lin for multicut (Keuper et al.'s KLj scheme).

    Starting from an initial partition (GAEC by default), repeatedly refines
    every pair of adjacent partitions with the classic KL inner loop — a
    *gain sequence* of tentative node flips (negative gains included)
    rolled back to its best prefix — and considers joining the pair
    outright.  Iterates until a full sweep yields no improvement.  Energy is
    monotonically non-increasing from the initial partition.

    With ``checkpoint``, the solve becomes preemption-safe: the partition
    persists after the GAEC init and after EVERY outer sweep (one sweep per
    solver call), and a killed run resumes from the last persisted sweep —
    identical sweep sequence, identical result.  ``checkpoint.clear()`` is
    the caller's responsibility on success (the task layer owns artifact
    lifecycle).
    """
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)
    start_sweep = 0
    resumed = checkpoint.load() if checkpoint is not None else None
    if resumed is not None:
        labels, start_sweep = resumed
        labels = labels.copy()
    else:
        labels = (
            greedy_additive(n_nodes, edges, costs)
            if init_labels is None
            else np.asarray(init_labels, dtype=np.int64).copy()
        )
    if len(edges) == 0:
        return _relabel_consecutive(labels)

    from .. import native

    if checkpoint is None:
        refined = native.kernighan_lin(
            n_nodes, edges, costs, labels, max_outer=max_outer,
            epsilon=epsilon,
        )
        if refined is not None:
            return _relabel_consecutive(refined)
        return _kernighan_lin_python(
            n_nodes, edges, costs, labels, max_outer, epsilon
        )

    # checkpointed mode: one outer sweep per call, persist between sweeps.
    # Each call recomputes partition pairs from the current labels — exactly
    # what the fused outer loop does — so the sweep sequence (and result)
    # matches an uninterrupted checkpointed run after any kill+resume.
    prev_e = multicut_energy(edges, costs, labels)
    if resumed is None:
        checkpoint.save(labels, 0, prev_e)
    for sweep in range(start_sweep, max_outer):
        refined = native.kernighan_lin(
            n_nodes, edges, costs, labels.copy(), max_outer=1,
            epsilon=epsilon,
        )
        if refined is None:
            refined = _kernighan_lin_python(
                n_nodes, edges, costs, labels.copy(), 1, epsilon
            )
        e = multicut_energy(edges, costs, refined)
        labels = np.asarray(refined, np.int64)
        checkpoint.save(labels, sweep + 1, e)
        if prev_e - e <= epsilon:
            break
        prev_e = e
    return _relabel_consecutive(labels)


def _kernighan_lin_python(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    labels: np.ndarray,
    max_outer: int = 20,
    epsilon: float = 1e-9,
) -> np.ndarray:
    """Pure-Python KL sweep — fallback and the native kernel's parity oracle
    (``tests/test_multicut.py::test_kl_native_python_parity``).  Mutates and
    returns a relabeled copy of ``labels``."""
    adj: List[List[Tuple[int, float]]] = [[] for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        adj[int(u)].append((int(v), float(w)))
        adj[int(v)].append((int(u), float(w)))

    for _ in range(max_outer):
        # adjacent pairs from the current cut edges
        pairs = set()
        for (u, v) in edges:
            lu, lv = int(labels[u]), int(labels[v])
            if lu != lv:
                pairs.add((min(lu, lv), max(lu, lv)))

        improved = 0.0
        for la, lb in sorted(pairs):
            # membership MUST be read fresh per pair: earlier refinements in
            # this sweep move/join nodes, and _kl_refine_pair's gain
            # accounting assumes its member lists are exactly the nodes
            # currently labeled la/lb (stale lists once caused energy
            # increases by treating in-pair edges as fixed cut edges)
            a = np.where(labels == la)[0].tolist()
            b = np.where(labels == lb)[0].tolist()
            if not a or not b:
                continue
            improved += _kl_refine_pair(a, b, labels, adj, epsilon)
        if improved <= epsilon:
            break
    return _relabel_consecutive(labels)


def fusion_moves(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    n_iterations: int = 8,
    noise_scale: float = 1.0,
    seed: int = 0,
    refine_with_kl: bool = True,
) -> np.ndarray:
    """Fusion-move multicut solver (Beier et al. style).

    The incumbent starts at GAEC.  Each round draws a proposal partition —
    GAEC on costs perturbed with Gaussian noise (scaled by the cost std and
    annealed over rounds) — and *fuses* it with the incumbent: nodes agreeing
    in both partitions are contracted, the small fused problem is solved with
    GAEC+KL, and the result is accepted iff the energy improves.  Since the
    fused search space contains both inputs, energy never increases; with KL
    refinement the solution matches or beats both GAEC and plain KL in
    practice.
    """
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    costs = np.asarray(costs, dtype=np.float64)
    best = greedy_additive(n_nodes, edges, costs)
    if refine_with_kl:
        best = kernighan_lin(n_nodes, edges, costs, init_labels=best)
    best_e = multicut_energy(edges, costs, best)
    if len(edges) == 0:
        return best
    rng = np.random.default_rng(seed)
    scale0 = float(np.std(costs)) if len(costs) else 1.0

    for it in range(n_iterations):
        sigma = noise_scale * scale0 * (1.0 - it / max(n_iterations, 1) * 0.5)
        proposal = greedy_additive(
            n_nodes, edges, costs + rng.normal(0.0, sigma, len(costs))
        )
        # intersection partition: same cluster iff same in BOTH partitions
        inter = np.unique(
            np.stack([best, proposal], axis=1), axis=0, return_inverse=True
        )[1].astype(np.int64)
        c_edges, c_costs = contract_graph(edges, costs, inter)
        k = int(inter.max()) + 1
        sub = greedy_additive(k, c_edges, c_costs)
        if refine_with_kl:
            sub = kernighan_lin(k, c_edges, c_costs, init_labels=sub)
        cand = sub[inter]
        cand_e = multicut_energy(edges, costs, cand)
        if cand_e < best_e - 1e-12:
            best, best_e = cand, cand_e
    return _relabel_consecutive(best)


def decompose_solve(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    sub_solver=None,
) -> np.ndarray:
    """Decomposition solver: split over attractive-edge components first.

    Components connected only through repulsive (cost <= 0) edges can never
    profitably merge, so the graph decomposes into the connected components
    of the attractive subgraph, each solved independently (nifty's
    decomposition-solver pattern).  ``sub_solver(n, edges, costs)`` defaults
    to :func:`fusion_moves`.
    """
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    costs = np.asarray(costs, dtype=np.float64)
    if sub_solver is None:
        sub_solver = fusion_moves
    if len(edges) == 0:
        return np.arange(int(n_nodes), dtype=np.int64)

    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components as cc

    pos = edges[costs > 0]
    if len(pos) == 0:
        return np.arange(int(n_nodes), dtype=np.int64)
    g = coo_matrix(
        (np.ones(len(pos)), (pos[:, 0], pos[:, 1])), shape=(n_nodes, n_nodes)
    )
    n_comp, comp = cc(g, directed=False)
    # group nodes and intra-component edges per component with one sort each
    # (a per-component remap/scan would be quadratic when the graph shatters)
    node_order = np.argsort(comp, kind="stable")
    node_starts = np.searchsorted(comp[node_order], np.arange(n_comp + 1))
    node_rank = np.empty(n_nodes, dtype=np.int64)
    node_rank[node_order] = np.arange(n_nodes) - node_starts[comp[node_order]]
    ecomp = comp[edges[:, 0]]
    same = ecomp == comp[edges[:, 1]]
    se, sc, ec = edges[same], costs[same], ecomp[same]
    edge_order = np.argsort(ec, kind="stable")
    edge_starts = np.searchsorted(ec[edge_order], np.arange(n_comp + 1))

    labels = np.zeros(n_nodes, dtype=np.int64)
    offset = 0
    for c in range(n_comp):
        nodes = node_order[node_starts[c] : node_starts[c + 1]]
        if len(nodes) == 1:
            labels[nodes] = offset
            offset += 1
            continue
        eidx = edge_order[edge_starts[c] : edge_starts[c + 1]]
        sub_edges = node_rank[se[eidx]]
        sub = sub_solver(len(nodes), sub_edges, sc[eidx])
        labels[nodes] = sub + offset
        offset += int(sub.max()) + 1 if len(sub) else 1
    return _relabel_consecutive(labels)


def contract_graph(
    edges: np.ndarray,
    costs: np.ndarray,
    node_labels: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Contract a graph by a node labeling: map endpoints through labels,
    drop self-edges, sum parallel-edge costs.  Returns (new_edges,
    new_costs) on the label id space — the reduce step of the hierarchical
    multicut (reference: ``reduce_problem.py``)."""
    if len(edges) == 0:
        return edges.reshape(0, 2).astype(np.int64), costs.astype(np.float64)
    u = node_labels[edges[:, 0]]
    v = node_labels[edges[:, 1]]
    lo = np.minimum(u, v)
    hi = np.maximum(u, v)
    keep = lo != hi
    pairs = np.stack([lo[keep], hi[keep]], axis=1)
    w = np.asarray(costs, dtype=np.float64)[keep]
    if len(pairs) == 0:
        return np.zeros((0, 2), np.int64), np.zeros(0, np.float64)
    new_edges, inv = np.unique(pairs, axis=0, return_inverse=True)
    new_costs = np.zeros(len(new_edges), np.float64)
    np.add.at(new_costs, inv.ravel(), w)
    return new_edges.astype(np.int64), new_costs


def lifted_multicut_energy(
    edges: np.ndarray,
    costs: np.ndarray,
    lifted_edges: np.ndarray,
    lifted_costs: np.ndarray,
    node_labels: np.ndarray,
) -> float:
    """Lifted objective: local cut costs + lifted cut costs (lower is
    better; a lifted edge is 'cut' when its endpoints are in different
    clusters, regardless of graph connectivity)."""
    e = multicut_energy(edges, costs, node_labels)
    if len(lifted_edges):
        cut = node_labels[lifted_edges[:, 0]] != node_labels[lifted_edges[:, 1]]
        e += float(np.asarray(lifted_costs, np.float64)[cut].sum())
    return e


def lifted_frontier_capable() -> bool:
    """Whether the lifted objective has a frontier-abstention formulation.

    It does not: a lifted edge contributes to a cluster pair's priority
    only while the pair stays *graph-connected*, a property of the whole
    partition that a shard cannot decide from its boundary frontier alone
    (``lifted_greedy_additive`` re-checks connectivity on every merge).
    The frontier trick — abstain when an unseen cross-shard edge could
    outbid the local best — therefore has no sound lifted analogue, and
    the collective reduce plane (like ``frontier_contraction``) refuses
    lifted problems; they stay on the host GAEC path.
    """
    return False


def lifted_greedy_additive(
    n_nodes: int,
    edges: np.ndarray,
    costs: np.ndarray,
    lifted_edges: np.ndarray,
    lifted_costs: np.ndarray,
    stop_cost: float = 0.0,
) -> np.ndarray:
    """GAEC for the lifted multicut (Keuper et al. style).

    Clusters may only contract along *local* edges, but the merge priority
    is the combined local+lifted cost between the two clusters; lifted
    weights merge additively alongside local ones.  Returns int64 labels.
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64)
    costs = np.asarray(costs, dtype=np.float64)
    lifted_edges = np.asarray(lifted_edges, dtype=np.int64).reshape(-1, 2)
    lifted_costs = np.asarray(lifted_costs, dtype=np.float64)
    if len(lifted_edges) == 0:
        # plain multicut: reuse the (native-accelerated) GAEC
        return greedy_additive(n_nodes, edges, costs, stop_cost)

    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    local: list = [dict() for _ in range(n_nodes)]
    lifted: list = [dict() for _ in range(n_nodes)]
    for (u, v), w in zip(edges, costs):
        if u == v:
            continue
        u, v = int(u), int(v)
        local[u][v] = local[u].get(v, 0.0) + w
        local[v][u] = local[u][v]
    for (u, v), w in zip(lifted_edges, lifted_costs):
        if u == v:
            continue
        u, v = int(u), int(v)
        lifted[u][v] = lifted[u].get(v, 0.0) + w
        lifted[v][u] = lifted[u][v]

    def prio(u, v):
        return local[u][v] + lifted[u].get(v, 0.0)

    heap = [
        (-prio(u, v), u, v)
        for u in range(n_nodes)
        for v in local[u]
        if u < v
    ]
    heapq.heapify(heap)

    while heap:
        neg_w, u, v = heapq.heappop(heap)
        w = -neg_w
        if w <= stop_cost:
            break
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        if rv not in local[ru] or abs(prio(ru, rv) - w) > 1e-12:
            continue  # stale
        if len(local[ru]) + len(lifted[ru]) < len(local[rv]) + len(lifted[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        del local[ru][rv]
        lifted[ru].pop(rv, None)
        # merge local neighbor costs
        for x, wx in local[rv].items():
            if x == ru:
                continue
            nw = local[ru].get(x, 0.0) + wx
            local[ru][x] = nw
            local[x][ru] = nw
            del local[x][rv]
        # merge lifted neighbor costs
        for x, wx in lifted[rv].items():
            if x == ru:
                continue
            nw = lifted[ru].get(x, 0.0) + wx
            lifted[ru][x] = nw
            lifted[x][ru] = nw
            del lifted[x][rv]
        # only pairs whose priority changed need re-pushing: local
        # neighbors inherited from rv, and ru-neighbors whose lifted part
        # changed (lifted[rv] also landed on ru)
        changed = set(local[rv]) | (set(lifted[rv]) & set(local[ru]))
        changed.discard(ru)
        local[rv].clear()
        lifted[rv].clear()
        for x in changed:
            if x in local[ru]:
                p = prio(ru, x)
                if p > stop_cost:
                    heapq.heappush(heap, (-p, ru, x))

    roots = np.array([find(i) for i in range(n_nodes)], dtype=np.int64)
    return _relabel_consecutive(roots)
