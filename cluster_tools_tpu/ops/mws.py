"""Mutex watershed over affinity maps with offset vectors.

The reference's ``cluster_tools/mutex_watershed/`` consumed the ``affogato``
C++ kernels (SURVEY.md §2a "mutex_watershed", §2b).  This module provides the
rebuild's per-block kernel: the Kruskal-style mutex watershed (Wolf et al.) —
process all (attractive and repulsive) edges in order of decreasing priority;
attractive edges union their endpoints unless a mutex constraint forbids it,
repulsive edges install a mutex between their endpoints' clusters.

Edge generation and priority sorting (the bandwidth-heavy, regular parts)
are vectorized; the constraint loop is inherently sequential over the
sorted edge list and runs on host per block — blocks are processed
batch-parallel across the IO pool.  The loop executes in the C++ runtime
extension (``ct_mutex_watershed`` in ``native/ct_native.cpp``, built on
first use) with :func:`python_constraint_loop` as fallback;
``tests/test_mws_stitching.py::test_native_python_constraint_parity`` runs
both on the same sorted edges and asserts identical partitions.

Convention (as in the reference stack): ``offsets[:ndim]`` are the unit
("attractive") offsets; all further offsets are long-range ("repulsive").
Affinity semantics: high affinity = strong attraction for attractive
channels, and for repulsive channels high value = strong repulsion (the
caller converts if its data uses the inverted convention).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def offset_edges(
    shape: Sequence[int], offsets: Sequence[Sequence[int]]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """All (u, v, channel) edges induced by ``offsets`` on a ``shape`` grid.

    Returns flat voxel indices ``u``, ``v`` and the channel index per edge;
    edges whose endpoint falls outside the volume are dropped.
    """
    shape = tuple(shape)
    us, vs, cs = [], [], []
    idx = np.arange(int(np.prod(shape)), dtype=np.int64).reshape(shape)
    for c, off in enumerate(offsets):
        src = tuple(
            slice(max(0, -o), s - max(0, o)) for o, s in zip(off, shape)
        )
        dst = tuple(
            slice(max(0, o), s - max(0, -o)) for o, s in zip(off, shape)
        )
        u = idx[src].ravel()
        v = idx[dst].ravel()
        us.append(u)
        vs.append(v)
        cs.append(np.full(len(u), c, np.int32))
    return np.concatenate(us), np.concatenate(vs), np.concatenate(cs)


def _affinity_values(
    affs: np.ndarray, offsets: Sequence[Sequence[int]]
) -> np.ndarray:
    """Per-edge affinity values matching :func:`offset_edges` order."""
    shape = affs.shape[1:]
    vals = []
    for c, off in enumerate(offsets):
        src = tuple(
            slice(max(0, -o), s - max(0, o)) for o, s in zip(off, shape)
        )
        vals.append(affs[c][src].ravel())
    return np.concatenate(vals)


class _MutexUnionFind:
    """Union-find with per-cluster mutex sets (small-set merging)."""

    def __init__(self, n: int):
        self.parent = np.arange(n, dtype=np.int64)
        self.rank = np.zeros(n, np.int8)
        self.mutexes: dict = {}

    def find(self, x: int) -> int:
        p = self.parent
        root = x
        while p[root] != root:
            root = p[root]
        while p[x] != root:
            p[x], x = root, p[x]
        return root

    def has_mutex(self, ra: int, rb: int) -> bool:
        ma = self.mutexes.get(ra)
        return ma is not None and rb in ma

    def add_mutex(self, ra: int, rb: int):
        self.mutexes.setdefault(ra, set()).add(rb)
        self.mutexes.setdefault(rb, set()).add(ra)

    def merge(self, ra: int, rb: int):
        if self.rank[ra] < self.rank[rb]:
            ra, rb = rb, ra
        elif self.rank[ra] == self.rank[rb]:
            self.rank[ra] += 1
        self.parent[rb] = ra
        mb = self.mutexes.pop(rb, None)
        if mb:
            ma = self.mutexes.setdefault(ra, set())
            for x in mb:
                sx = self.mutexes.get(x)
                if sx is not None:
                    sx.discard(rb)
                    sx.add(ra)
                ma.add(x)


def python_constraint_loop(
    n: int,
    u: np.ndarray,
    v: np.ndarray,
    is_attractive: np.ndarray,
    order: np.ndarray,
) -> np.ndarray:
    """Pure-Python mutex constraint loop — the native kernel's parity oracle.

    Same contract as ``native.mutex_watershed``: process edges in ``order``,
    merging attractive pairs unless a mutex forbids it, installing mutexes
    for repulsive pairs; returns per-voxel int64 roots.
    ``tests/test_mws_stitching.py::test_native_python_constraint_parity``
    asserts both paths produce the same partition and records the speedup.
    """
    uf = _MutexUnionFind(n)
    for i in order:
        ru, rv = uf.find(int(u[i])), uf.find(int(v[i]))
        if ru == rv:
            continue
        if is_attractive[i]:
            if not uf.has_mutex(ru, rv):
                uf.merge(ru, rv)
        else:
            uf.add_mutex(ru, rv)
    return np.array([uf.find(i) for i in range(n)], dtype=np.int64)


def mutex_watershed(
    affs: np.ndarray,
    offsets: Sequence[Sequence[int]],
    mask: Optional[np.ndarray] = None,
    strides: Optional[Sequence[int]] = None,
    randomize_strides: bool = False,
    seed: int = 0,
) -> np.ndarray:
    """Cluster a volume from affinities: returns int64 labels (1-based;
    0 where masked out).

    ``strides`` subsamples repulsive edges: on a regular grid by default, or
    uniformly at random with the same keep fraction when
    ``randomize_strides`` (avoids grid-aligned repulsion artifacts);
    attractive edges are always dense.
    """
    ndim = affs.ndim - 1
    shape = affs.shape[1:]
    n = int(np.prod(shape))
    u, v, c = offset_edges(shape, offsets)
    w = _affinity_values(np.asarray(affs, np.float64), offsets)
    is_attractive = c < ndim

    if strides is not None:
        keep = is_attractive.copy()
        rep = ~is_attractive
        if randomize_strides:
            frac = 1.0 / float(np.prod([int(s) for s in strides]))
            rnd = np.random.default_rng(seed).random(len(u)) < frac
            keep |= rep & rnd
        else:
            # keep repulsive edges only at strided source voxels
            coords = np.unravel_index(u, shape)
            on_grid = np.ones(len(u), bool)
            for d, s in enumerate(strides):
                on_grid &= coords[d] % int(s) == 0
            keep |= rep & on_grid
        u, v, c, w, is_attractive = (
            u[keep],
            v[keep],
            c[keep],
            w[keep],
            is_attractive[keep],
        )

    if mask is not None:
        m = np.asarray(mask).astype(bool).ravel()
        keep = m[u] & m[v]
        u, v, w, is_attractive = u[keep], v[keep], w[keep], is_attractive[keep]

    order = np.argsort(-w, kind="stable")

    from .. import native

    roots = native.mutex_watershed(n, u, v, is_attractive, order)
    if roots is None:
        roots = python_constraint_loop(n, u, v, is_attractive, order)
    _, labels = np.unique(roots, return_inverse=True)
    labels = labels.astype(np.int64).reshape(shape) + 1
    if mask is not None:
        labels[~np.asarray(mask).astype(bool)] = 0
    return labels
