"""Pallas TPU kernels for the tile-level phase of two-level labeling.

Why these exist: profiling the round-2 fused step on a real v5-lite chip
showed the label fixpoints (``ops/ccl.py`` hook+compress, ``ops/watershed.py``
pointer resolve) spending essentially all their time in full-volume random
gathers/scatters, which the TPU executes at ~165M elements/s regardless of
locality or table size — ~70x slower per pass than a dense shift.  A v5-lite
chip measured: 6-neighbor dense min sweep over 512^3 = ~16ms; one random
gather over the same array = ~850ms.  The fix is architectural: do ALL
data-dependent iteration inside VMEM tiles with dense shift/min steps (this
module), and reduce the cross-tile problem to small edge lists handled with
sorts and sub-millisecond scatters (``tile_ccl.py``).

Kernels:

- :func:`tile_ccl_pallas` — exact connected-components labeling *within* each
  (tz, ty, tx) tile: iterated 6-neighbor min-propagation of global flat
  indices in VMEM to a fixpoint (``lax.while_loop`` in-kernel).  No gathers:
  shifts are static slices.  The volume crosses HBM exactly once each way.
- :func:`apply_remap_pallas` — applies a per-tile value remap table
  (old_label -> new_label, <= cap entries per tile) with an unrolled
  compare-select loop in VMEM: the cross-tile merge touches only labels that
  appear on tile faces, so each tile's table is tiny and value-matching
  replaces a full-volume gather.

Tile shape: last dim 128 (TPU lane width), middle dims sized so a tile is a
few vreg rows — (16, 16, 128) by default, 128KB of int32 per tile.

The reference (SURVEY.md §2b) got per-block CCL from vigra's serial C++
union-find; this is the TPU-native replacement, not a translation.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import typeof
from .ccl import _shift

# Sentinel must exceed any global flat index (volumes are int32-bounded
# anyway: > 2**31 voxels per shard is rejected upstream).
BIG = 2**30

# watershed pointer-propagation: value read from outside the tile
WS_MARKER = -(2**30)

# descent-direction codes 1..6 in this order; 0 = self (terminal)
WS_OFFS = ((-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0), (0, 0, -1), (0, 0, 1))


def _out_struct(shape, dtype, *like) -> jax.ShapeDtypeStruct:
    """Output aval for ``pallas_call`` whose varying-manual-axes match ``like``.

    Under ``shard_map(check_vma=True)`` (the default) ``pallas_call`` refuses a
    plain ``ShapeDtypeStruct`` — the output's ``vma`` must be stated.  The
    kernels here are purely per-shard, so the output varies over exactly the
    axes their inputs vary over.
    """
    vma = frozenset()
    for a in like:
        v = getattr(typeof(a), "vma", None)
        if v:
            vma = vma | v
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _ccl_kernel_doubling(tile_shape, mask_ref, out_ref):
    """In-tile CCL via guarded run-doubling propagation.

    Per iteration, every axis propagates the min label along *entire
    foreground runs* with log2(extent) doubling levels: a label may jump
    2^k along an axis iff the whole segment between is foreground
    (``conn_k[i] = conn_{k-1}[i] & conn_{k-1}[i - 2^{k-1}]``).  Convergence
    is O(#direction changes of the component) instead of O(diameter) —
    fewer, fatter iterations than the unit-step kernel; which wins is
    hardware-measured (scripts/tpu_measure.py), selected via
    ``tile_ccl_pallas(..., doubling=True)``.
    """
    tz, ty, tx = tile_shape
    i = pl.program_id(0)
    j = pl.program_id(1)
    k = pl.program_id(2)
    ny = pl.num_programs(1) * ty
    nx = pl.num_programs(2) * tx
    mask = mask_ref[:] > 0
    gz = lax.broadcasted_iota(jnp.int32, tile_shape, 0) + i * tz
    gy = lax.broadcasted_iota(jnp.int32, tile_shape, 1) + j * ty
    gx = lax.broadcasted_iota(jnp.int32, tile_shape, 2) + k * tx
    gidx = (gz * ny + gy) * nx + gx
    lab = jnp.where(mask, gidx, jnp.int32(BIG))

    def axis_sweep(l, ax):
        n = l.shape[ax]
        for direction in (1, -1):
            conn = mask & _shift(mask, direction, ax, False)
            m = l
            step = 1
            while step < n:
                cand = _shift(m, direction * step, ax, jnp.int32(BIG))
                m = jnp.where(conn, jnp.minimum(m, cand), m)
                nxt = step * 2
                if nxt < n:
                    conn = conn & _shift(conn, direction * step, ax, False)
                step = nxt
            l = jnp.minimum(l, jnp.where(mask, m, jnp.int32(BIG)))
        return l

    def cond(s):
        return s[1]

    def body(s):
        l, _ = s
        l2 = l
        for ax in range(3):
            l2 = axis_sweep(l2, ax)
        return l2, jnp.any(l2 != l)

    lab, _ = lax.while_loop(cond, body, (lab, True))
    out_ref[:] = lab


def _ccl_kernel(tile_shape, mask_ref, out_ref):
    tz, ty, tx = tile_shape
    i = pl.program_id(0)
    j = pl.program_id(1)
    k = pl.program_id(2)
    ny = pl.num_programs(1) * ty
    nx = pl.num_programs(2) * tx
    mask = mask_ref[:] > 0
    gz = lax.broadcasted_iota(jnp.int32, tile_shape, 0) + i * tz
    gy = lax.broadcasted_iota(jnp.int32, tile_shape, 1) + j * ty
    gx = lax.broadcasted_iota(jnp.int32, tile_shape, 2) + k * tx
    gidx = (gz * ny + gy) * nx + gx
    lab = jnp.where(mask, gidx, jnp.int32(BIG))

    def nmin(l):
        m = l
        for ax in range(3):
            m = jnp.minimum(m, _shift(l, 1, ax, jnp.int32(BIG)))
            m = jnp.minimum(m, _shift(l, -1, ax, jnp.int32(BIG)))
        return m

    def cond(s):
        return s[1]

    def body(s):
        l, _ = s
        # two propagation steps per convergence check: halves the number of
        # full-tile reductions on the critical path
        l1 = jnp.minimum(l, jnp.where(mask, nmin(l), jnp.int32(BIG)))
        l2 = jnp.minimum(l1, jnp.where(mask, nmin(l1), jnp.int32(BIG)))
        return l2, jnp.any(l2 != l)

    lab, _ = lax.while_loop(cond, body, (lab, True))
    out_ref[:] = lab


@partial(jax.jit, static_argnames=("tile", "interpret", "doubling"))
def tile_ccl_pallas(
    mask: jnp.ndarray,
    tile: Tuple[int, int, int] = (16, 16, 128),
    interpret: bool = False,
    doubling: bool = False,
) -> jnp.ndarray:
    """Exact per-tile CCL of a 3-D bool mask; labels are global flat indices.

    Shape must be divisible by ``tile`` (callers pad).  Foreground voxels get
    the minimum global flat index of their *within-tile* component;
    background gets ``BIG``.  Cross-tile merging is ``tile_ccl.py``'s job.
    ``doubling`` selects the run-doubling propagation variant.
    """
    z, y, x = mask.shape
    tz, ty, tx = tile
    assert z % tz == 0 and y % ty == 0 and x % tx == 0, (mask.shape, tile)
    kernel = _ccl_kernel_doubling if doubling else _ccl_kernel
    return pl.pallas_call(
        partial(kernel, tile),
        out_shape=_out_struct((z, y, x), jnp.int32, mask),
        grid=(z // tz, y // ty, x // tx),
        in_specs=[
            pl.BlockSpec(tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(mask.astype(jnp.int32))


def ws_propagate_step(value, dirs, gidx, axes, ny, nx):
    """One step of label flow along descent pointers (shared kernel/XLA math).

    Every voxel whose direction code is ``d`` copies the value of its descent
    target (the neighbor at ``WS_OFFS[d-1]``); terminals (code 0) keep their
    value.  A copy that would read outside the tile (the shifted-in
    ``WS_MARKER``) resolves to the *exit code* ``-(target_gidx + 2)`` instead,
    freezing the fragment until the cross-tile chase resolves it.

    ``axes`` maps the three spatial offsets onto array axes (kernel: (0,1,2);
    XLA tiled fallback: trailing axes of a batched array); ``ny``/``nx`` are
    the *global* volume dims for flat-index arithmetic.
    """
    new = value
    for code, off in enumerate(WS_OFFS, start=1):
        foff = (off[0] * ny + off[1]) * nx + off[2]
        v_t = value
        for ax, s in zip(axes, off):
            if s:
                v_t = _shift(v_t, -s, ax, jnp.int32(WS_MARKER))
        sel = dirs == code
        exit_code = -(gidx + jnp.int32(foff)) - 2
        new = jnp.where(
            sel,
            jnp.where(v_t == jnp.int32(WS_MARKER), exit_code, v_t),
            new,
        )
    return new


def _ws_kernel(tile_shape, dir_ref, seed_ref, out_ref):
    tz, ty, tx = tile_shape
    i = pl.program_id(0)
    j = pl.program_id(1)
    k = pl.program_id(2)
    ny = pl.num_programs(1) * ty
    nx = pl.num_programs(2) * tx
    gz = lax.broadcasted_iota(jnp.int32, tile_shape, 0) + i * tz
    gy = lax.broadcasted_iota(jnp.int32, tile_shape, 1) + j * ty
    gx = lax.broadcasted_iota(jnp.int32, tile_shape, 2) + k * tx
    gidx = (gz * ny + gy) * nx + gx
    dirs = dir_ref[:]
    sv = seed_ref[:]  # -1 invalid, 0 unseeded, >0 seed label
    terminal = dirs == 0
    value = jnp.where(
        sv > 0, sv, jnp.where(terminal & (sv == 0), -gidx - 2, jnp.int32(0))
    )

    def cond(s):
        return s[1]

    def body(s):
        v, _ = s
        v2 = ws_propagate_step(v, dirs, gidx, (0, 1, 2), ny, nx)
        return v2, jnp.any(v2 != v)

    value, _ = lax.while_loop(cond, body, (value, True))
    out_ref[:] = value


@partial(jax.jit, static_argnames=("tile", "interpret"))
def tile_ws_propagate_pallas(
    dirs: jnp.ndarray,
    seeds_or_invalid: jnp.ndarray,
    tile: Tuple[int, int, int] = (16, 16, 128),
    interpret: bool = False,
) -> jnp.ndarray:
    """In-tile watershed label flow along a descent-direction field.

    ``dirs``: int32 codes (0 = terminal/self, 1..6 = ``WS_OFFS``).
    ``seeds_or_invalid``: int32, -1 = masked out, 0 = no seed, >0 = seed id.
    Output per voxel: seed label (>0), 0 (invalid), ``-(t + 2)`` (drains to
    the unseeded in-tile terminal ``t``), or ``-(g + 2)`` for an exit whose
    target voxel ``g`` lies in another tile (resolved by ``tile_ws``).
    """
    z, y, x = dirs.shape
    tz, ty, tx = tile
    assert z % tz == 0 and y % ty == 0 and x % tx == 0
    return pl.pallas_call(
        partial(_ws_kernel, tile),
        out_shape=_out_struct((z, y, x), jnp.int32, dirs, seeds_or_invalid),
        grid=(z // tz, y // ty, x // tx),
        in_specs=[
            pl.BlockSpec(tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM),
            pl.BlockSpec(tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(dirs.astype(jnp.int32), seeds_or_invalid.astype(jnp.int32))


def _edt_kernel(axis, radius, w, big, x_ref, out_ref):
    g = x_ref[:]
    n = g.shape[axis]

    def body(i, g):
        c = jnp.float32(w) * (2.0 * i.astype(jnp.float32) + 1.0)
        lo = _shift(g, 1, axis, jnp.float32(big)) + c
        hi = _shift(g, -1, axis, jnp.float32(big)) + c
        return jnp.minimum(g, jnp.minimum(lo, hi))

    out_ref[:] = lax.fori_loop(0, min(radius, n - 1), body, g)


@partial(jax.jit, static_argnames=("axis", "radius", "w", "big", "interpret"))
def edt_cascade_pallas(
    f: jnp.ndarray,
    axis: int,
    radius: int,
    w: float,
    big: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Parabolic erosion cascade along one axis, iterated in VMEM.

    The XLA formulation runs ``radius`` dependent full-volume passes through
    HBM (~5ms each at 512^3 — an EDT capped at halo=32 costs ~0.5s);
    keeping each line's whole extent in VMEM makes the cascade compute-bound
    instead.  Blocks span the full processed axis, so no cross-block halo
    exists.  Shapes must divide the tile; callers pad (values ``big`` pad
    correctly: they never win a ``min``).
    """
    z, y, x = f.shape
    if axis == 0:
        tile = (z, 8, 128)
    elif axis == 1:
        tile = (8, y, 128)
    else:
        tile = (8, 8, x)
    tz, ty, tx = tile
    assert z % tz == 0 and y % ty == 0 and x % tx == 0, (f.shape, tile)
    return pl.pallas_call(
        partial(_edt_kernel, axis, radius, w, big),
        out_shape=_out_struct((z, y, x), jnp.float32, f),
        grid=(z // tz, y // ty, x // tx),
        in_specs=[
            pl.BlockSpec(tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(f.astype(jnp.float32))


def _apply_kernel(cap, old_ref, new_ref, lab_ref, out_ref):
    lab = lab_ref[:]
    # unrolled compare-select over the tile's remap entries; slots beyond the
    # tile's fragment count hold old = -1 which never matches a label
    for c in range(cap):
        o = old_ref[0, 0, c]
        nw = new_ref[0, 0, c]
        lab = jnp.where(lab == o, nw, lab)
    out_ref[:] = lab


@partial(jax.jit, static_argnames=("tile", "cap", "interpret"))
def apply_remap_pallas(
    labels: jnp.ndarray,
    old_tbl: jnp.ndarray,
    new_tbl: jnp.ndarray,
    tile: Tuple[int, int, int] = (16, 16, 128),
    cap: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-tile value remap: labels equal to old_tbl[t, c] become new_tbl[t, c].

    ``old_tbl``/``new_tbl`` are (n_tiles, cap) int32, tiles in z-major grid
    order; unused slots must hold -1.  Labels not present in the tile's table
    pass through unchanged.
    """
    z, y, x = labels.shape
    tz, ty, tx = tile
    gz, gy, gx = z // tz, y // ty, x // tx
    assert old_tbl.shape == (gz * gy * gx, cap), (old_tbl.shape, (gz * gy * gx, cap))
    # (n_tiles, 1, cap) so the block's trailing dims equal the array's —
    # the Mosaic block-shape divisibility rule for non-(8,128) tails
    old3 = old_tbl.reshape(-1, 1, cap)
    new3 = new_tbl.reshape(-1, 1, cap)

    def tbl_map(i, j, k):
        return ((i * gy + j) * gx + k, 0, 0)

    return pl.pallas_call(
        partial(_apply_kernel, cap),
        out_shape=_out_struct((z, y, x), jnp.int32, old_tbl, new_tbl, labels),
        grid=(gz, gy, gx),
        in_specs=[
            pl.BlockSpec((1, 1, cap), tbl_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cap), tbl_map, memory_space=pltpu.VMEM),
            pl.BlockSpec(tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            tile, lambda i, j, k: (i, j, k), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(old3, new3, labels)
