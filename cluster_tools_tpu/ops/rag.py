"""Region-adjacency-graph extraction kernels.

TPU-native replacement for the capability the reference got from the
``nifty.distributed`` C++ layer (SURVEY.md §2a "graph", §2b): per-block RAG
extraction from a label volume, plus per-edge accumulation of boundary-map
statistics.

Design: the bandwidth-heavy part — scanning every axis-adjacent voxel pair of
a block and emitting (min-label, max-label, boundary-value) triples — is a
jitted, static-shape device kernel (:func:`axis_edge_scan`).  The
variable-size part — deduplicating pairs into an edge list and accumulating
per-edge statistics — runs on host with vectorized numpy (:func:`block_rag`),
because per-block edge counts are data-dependent and small (≲ 3·|block|)
while the scan touches every voxel.  This mirrors the reference's split, where
C++ did the scan and serialized small per-block graphs to N5.

Halo convention for blockwise extraction: each block is read with a +1 voxel
halo on its *upper* faces only.  For the scan along axis ``a`` the input is
sliced to the inner extent along every other axis and inner+1 along ``a`` —
so every voxel-face pair of the volume is owned by exactly one block and
per-edge counts add up correctly across blocks.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# per-edge accumulated statistics, in column order
FEATURE_NAMES = ("mean", "min", "max", "count", "variance")


@partial(jax.jit, static_argnames=("axis", "with_values"))
def axis_edge_scan(
    seg: jnp.ndarray,
    values: Optional[jnp.ndarray],
    axis: int,
    with_values: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scan adjacent voxel pairs along one axis.

    For every pair ``(x, x+e_axis)`` with two *different, non-zero* labels,
    emits the pair (as min/max) and, if ``with_values``, the boundary value
    ``max(values[x], values[x+e_axis])`` (the boundary-map accumulation
    convention).  Returns flat ``(lo, hi, val, valid)`` of static length
    ``prod(shape)/shape[axis]*(shape[axis]-1)``; invalid slots have
    ``lo == hi == 0``.
    """
    ndim = seg.ndim
    sl_a = tuple(slice(0, -1) if d == axis else slice(None) for d in range(ndim))
    sl_b = tuple(slice(1, None) if d == axis else slice(None) for d in range(ndim))
    u = seg[sl_a].ravel()
    v = seg[sl_b].ravel()
    valid = (u != v) & (u != 0) & (v != 0)
    lo = jnp.where(valid, jnp.minimum(u, v), 0)
    hi = jnp.where(valid, jnp.maximum(u, v), 0)
    if with_values:
        va = values[sl_a].ravel()
        vb = values[sl_b].ravel()
        val = jnp.where(valid, jnp.maximum(va, vb), 0)
    else:
        val = jnp.zeros_like(lo, dtype=jnp.float32)
    return lo, hi, val, valid


@partial(jax.jit, static_argnames=("edge_cap", "with_values", "inner_shape"))
def device_edge_aggregate(
    seg: jnp.ndarray,
    values: Optional[jnp.ndarray],
    edge_cap: int,
    with_values: bool = True,
    inner_shape: Optional[Tuple[int, ...]] = None,
):
    """Sorted, deduplicated RAG edges + per-edge stats, entirely on device.

    Replaces the host-side ``np.unique(pairs, axis=0)`` in :func:`block_rag`
    (1-2s per 128^3 block, after a device->host transfer of every adjacent
    pair) with one multi-operand device sort + segmented reductions — the
    same sort-compact machinery as ops/tile_ccl.

    ``seg``: int32 labels (0 = background) — callers with uint64 global ids
    densify first.  Returns ``(lo, hi, count, vsum, vsumsq, vmin, vmax,
    shift, n_edges)`` — ``vsumsq`` is the second moment about ``shift``
    (the global value mean; see the in-body comment)
    with static length ``edge_cap`` (slots past ``n_edges`` hold lo=hi=0);
    ``n_edges > edge_cap`` means overflow (results truncated).
    """
    from jax import lax

    INT_MAX = jnp.int32(np.iinfo(np.int32).max)
    inner = tuple(inner_shape) if inner_shape is not None else seg.shape
    los, his, vals = [], [], []
    for axis in range(seg.ndim):
        # the block-ownership halo convention (module docstring): inner+1
        # along the scan axis, inner along the others
        bb = tuple(
            slice(0, min(inner[d] + 1, seg.shape[d]))
            if d == axis
            else slice(0, inner[d])
            for d in range(seg.ndim)
        )
        lo, hi, val, valid = axis_edge_scan(
            seg[bb], None if values is None else values[bb], axis,
            with_values=with_values,
        )
        los.append(jnp.where(valid, lo, INT_MAX))
        his.append(jnp.where(valid, hi, INT_MAX))
        vals.append(val)
    lo = jnp.concatenate(los).astype(jnp.int32)
    hi = jnp.concatenate(his).astype(jnp.int32)
    val = jnp.concatenate(vals).astype(jnp.float32)
    lo, hi, val = lax.sort((lo, hi, val), num_keys=2)
    valid = lo != INT_MAX
    is_first = valid & (
        (lo != jnp.concatenate([INT_MAX[None], lo[:-1]]))
        | (hi != jnp.concatenate([INT_MAX[None], hi[:-1]]))
    )
    seg_id = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    n_edges = jnp.where(valid.any(), seg_id[-1] + 1, 0)
    sid = jnp.where(valid, jnp.minimum(seg_id, edge_cap), edge_cap)
    ones = valid.astype(jnp.int32)
    count = jax.ops.segment_sum(ones, sid, num_segments=edge_cap + 1)[:-1]
    out_lo = jnp.zeros((edge_cap + 1,), jnp.int32).at[sid].max(
        jnp.where(valid, lo, 0), mode="drop"
    )[:-1]
    out_hi = jnp.zeros((edge_cap + 1,), jnp.int32).at[sid].max(
        jnp.where(valid, hi, 0), mode="drop"
    )[:-1]
    if with_values:
        vsum = jax.ops.segment_sum(
            jnp.where(valid, val, 0.0), sid, num_segments=edge_cap + 1
        )[:-1]
        # second moment about the GLOBAL value mean, not zero: for values
        # clustered away from 0 (8-bit intensities, probabilities near 1)
        # E[x^2] - mean^2 in float32 is catastrophic cancellation — shifting
        # makes both accumulated terms proportional to the spread instead
        shift = jnp.sum(jnp.where(valid, val, 0.0)) / jnp.maximum(
            jnp.sum(valid.astype(jnp.float32)), 1.0
        )
        d = val - shift
        vsumsq = jax.ops.segment_sum(
            jnp.where(valid, d * d, 0.0), sid, num_segments=edge_cap + 1
        )[:-1]
        vmin = jax.ops.segment_min(
            jnp.where(valid, val, jnp.float32(np.inf)), sid,
            num_segments=edge_cap + 1,
        )[:-1]
        vmax = jax.ops.segment_max(
            jnp.where(valid, val, jnp.float32(-np.inf)), sid,
            num_segments=edge_cap + 1,
        )[:-1]
    else:
        shift = jnp.float32(0.0)
        vsum = vsumsq = vmin = vmax = jnp.zeros((edge_cap,), jnp.float32)
    return out_lo, out_hi, count, vsum, vsumsq, vmin, vmax, shift, n_edges


def _densify_labels(seg: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-densify a label block to int32 ids: returns ``(dense, table)``
    with ``table[dense] == seg`` and ``table[0] == 0`` (background keeps
    slot 0).  Shared by every device RAG path so the int32 guard and the
    dtype-preserving zero-prepend stay in one place."""
    uniq = np.unique(seg)
    if uniq[0] != 0:
        # dtype-preserving prepend: a bare [0] would promote uint64
        # labels to float64 and corrupt ids above 2**53
        uniq = np.concatenate([np.zeros(1, uniq.dtype), uniq])
    if len(uniq) >= 2**31:
        raise ValueError("block has too many labels for int32 densification")
    return np.searchsorted(uniq, seg).astype(np.int32), uniq


@partial(jax.jit, static_argnames=("edge_cap", "inner_shape"))
def device_rag_costs(
    seg: jnp.ndarray,
    values: jnp.ndarray,
    edge_cap: int,
    beta,
    inner_shape: Optional[Tuple[int, ...]] = None,
):
    """Fused RAG -> costs -> dense remap, one jitted program.

    Extends :func:`device_edge_aggregate` with the two host stages every
    graph workflow used to run between extraction and solve:

    - the ``probs_to_costs`` transform (tasks/costs.py) on the per-edge mean
      boundary value, computed in-program from the segment sums,
    - dense node remapping: the unique edge-endpoint labels are compacted on
      device (one more sort over the 2*edge_cap endpoint slots — edge-scale,
      not voxel-scale) and the edge list is rewritten in dense node indices,
      eliminating the host ``np.unique(uv)`` + remap round-trip.

    Returns ``(node_table, n_nodes, lo_dense, hi_dense, costs, count,
    mean, n_edges)``; ``node_table`` has static length ``2 * edge_cap``
    (slots past ``n_nodes`` hold int32 max) and carries the dense->seg-label
    mapping.  ``beta`` is a traced scalar (no recompile per value).
    """
    from jax import lax

    INT_MAX = jnp.int32(np.iinfo(np.int32).max)
    (lo, hi, count, vsum, _vsumsq, _vmin, _vmax, _shift,
     n_edges) = device_edge_aggregate(
        seg, values, edge_cap, with_values=True, inner_shape=inner_shape
    )
    valid = jnp.arange(edge_cap) < n_edges
    mean = jnp.where(valid, vsum / jnp.maximum(count, 1), 0.0)
    eps = jnp.float32(1e-5)
    p = jnp.clip(mean, eps, 1.0 - eps)
    beta = jnp.clip(jnp.asarray(beta, jnp.float32), eps, 1.0 - eps)
    costs = jnp.where(
        valid, jnp.log((1.0 - p) / p) + jnp.log((1.0 - beta) / beta), 0.0
    )
    # dense node compaction over the endpoint slots (sort-compact idiom)
    lab = jnp.concatenate(
        [jnp.where(valid, lo, INT_MAX), jnp.where(valid, hi, INT_MAX)]
    )
    lab = lax.sort(lab)
    lvalid = lab != INT_MAX
    is_first = lvalid & (lab != jnp.concatenate([INT_MAX[None], lab[:-1]]))
    nid = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    n_nodes = jnp.where(lvalid.any(), nid[-1] + 1, 0)
    node_table = jnp.full((2 * edge_cap,), INT_MAX, jnp.int32).at[
        jnp.where(is_first, nid, 2 * edge_cap - 1)
    ].min(jnp.where(is_first, lab, INT_MAX))
    lo_dense = jnp.where(
        valid, jnp.searchsorted(node_table, lo).astype(jnp.int32), 0
    )
    hi_dense = jnp.where(
        valid, jnp.searchsorted(node_table, hi).astype(jnp.int32), 0
    )
    return node_table, n_nodes, lo_dense, hi_dense, costs, count, mean, n_edges


def block_rag_fused(
    seg: np.ndarray,
    values: np.ndarray,
    beta: float = 0.5,
    inner_shape: Optional[Sequence[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Solver-ready block problem straight from the label volume.

    One device program (:func:`device_rag_costs`) extracts the RAG,
    deduplicates edges, turns mean boundary values into signed multicut
    costs, and compacts node ids — the host sees only edge-scale arrays.
    ``seg`` may be any integer dtype; labels that do not fit int32 take the
    densify-first path of :func:`block_rag` internally.

    Returns ``(nodes, edges, costs, sizes, mean)``: ``nodes`` the original
    labels (dense index -> label, sorted ascending), ``edges`` int64 [m, 2]
    in dense indices, ``costs`` float32 (``probs_to_costs`` with ``beta``),
    ``sizes`` int64 contact counts, ``mean`` float32 mean boundary value.
    """
    if seg.ndim != 3:
        raise ValueError("block_rag_fused expects a 3-D block")
    inner = tuple(inner_shape) if inner_shape is not None else seg.shape
    orig_table = None
    # dtype bound first: skips the O(voxels) host max() scan entirely for
    # label dtypes that cannot trip the int32 guard
    if seg.dtype.kind not in "iu" or (
        np.iinfo(seg.dtype).max >= np.iinfo(np.int32).max
        and seg.size
        and int(seg.max()) >= np.iinfo(np.int32).max
    ):
        # uint64 global ids: densify on host first (the _block_rag_device
        # path), then map the node table back at the end
        seg, orig_table = _densify_labels(seg)
    seg_j = jnp.asarray(np.ascontiguousarray(seg).astype(np.int32, copy=False))
    vals_j = jnp.asarray(values, jnp.float32)

    cap = 1 << 14
    while True:
        (node_table, n_nodes, lo, hi, costs, count, mean,
         n_edges) = device_rag_costs(
            seg_j, vals_j, cap, float(beta), inner_shape=inner
        )
        n = int(n_edges)
        if n <= cap:
            break
        while cap < n:
            cap *= 2
    k = int(n_nodes)
    nodes = np.asarray(node_table[:k]).astype(np.int64)
    if orig_table is not None:
        nodes = orig_table[nodes]
    edges = np.stack(
        [np.asarray(lo[:n]), np.asarray(hi[:n])], axis=1
    ).astype(np.int64)
    return (
        nodes,
        edges,
        np.asarray(costs[:n], np.float32),
        np.asarray(count[:n]).astype(np.int64),
        np.asarray(mean[:n], np.float32),
    )


def block_rag(
    seg: np.ndarray,
    values: Optional[np.ndarray] = None,
    inner_shape: Optional[Sequence[int]] = None,
    return_nodes: bool = False,
):
    """Extract the RAG of one block: unique undirected edges + edge sizes
    (+ per-edge boundary statistics if ``values`` given).

    ``seg`` may include a +1 upper-face halo; pass the halo-free extent as
    ``inner_shape`` and each axis scan is restricted per the module halo
    convention (each voxel pair owned by exactly one block).

    Returns ``(uv, sizes, feats)``:

    - ``uv``     uint64 [m, 2], lexsorted, ``uv[:, 0] < uv[:, 1]``, label 0
      (background / ignore) excluded,
    - ``sizes``  int64 [m], number of voxel-face contacts per edge,
    - ``feats``  float32 [m, 5] per-edge (mean, min, max, count, variance) of the
      boundary values, or None.

    With ``return_nodes`` a fourth element is appended: the sorted unique
    non-zero labels of the *inner* (halo-free) region — the block's node
    set, computed from the extraction's own label pass instead of a second
    host ``np.unique`` over the voxels (the graph task used to re-scan).

    3-D blocks dedup on device (:func:`device_edge_aggregate` — one sort +
    segmented reductions instead of shipping every adjacent pair to the host
    for ``np.unique``); other ranks use the host path
    (:func:`_block_rag_host`, also the device path's parity oracle).
    """
    inner = tuple(inner_shape) if inner_shape is not None else seg.shape
    if seg.ndim == 3:
        out = _block_rag_device(seg, values, inner, return_nodes=return_nodes)
    else:
        out = _block_rag_host(seg, values, inner)
        if return_nodes:
            inner_bb = tuple(slice(0, s) for s in inner)
            nodes = np.unique(np.asarray(seg[inner_bb]))
            out = out + (nodes[nodes != 0],)
    return out


def _block_rag_host(
    seg: np.ndarray, values: Optional[np.ndarray], inner: Tuple[int, ...]
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Host-dedup RAG extraction (np.unique over all adjacent pairs)."""
    with_values = values is not None
    seg_j = jnp.asarray(seg)
    val_j = jnp.asarray(values, dtype=jnp.float32) if with_values else None
    los, his, vals = [], [], []
    for axis in range(seg.ndim):
        bb = tuple(
            slice(0, min(inner[d] + 1, seg.shape[d]))
            if d == axis
            else slice(0, inner[d])
            for d in range(seg.ndim)
        )
        lo, hi, val, valid = axis_edge_scan(
            seg_j[bb], None if val_j is None else val_j[bb], axis, with_values
        )
        valid = np.asarray(valid)
        los.append(np.asarray(lo)[valid])
        his.append(np.asarray(hi)[valid])
        if with_values:
            vals.append(np.asarray(val)[valid])
    lo = np.concatenate(los)
    hi = np.concatenate(his)
    if len(lo) == 0:
        uv = np.zeros((0, 2), np.uint64)
        feats = np.zeros((0, len(FEATURE_NAMES)), np.float32) if with_values else None
        return uv, np.zeros(0, np.int64), feats
    pairs = np.stack([lo, hi], axis=1).astype(np.uint64)
    uv, inv, sizes = np.unique(
        pairs, axis=0, return_inverse=True, return_counts=True
    )
    inv = inv.ravel()
    if not with_values:
        return uv, sizes.astype(np.int64), None
    v = np.concatenate(vals).astype(np.float64)
    m = len(uv)
    s = np.zeros(m, np.float64)
    np.add.at(s, inv, v)
    sq = np.zeros(m, np.float64)
    np.add.at(sq, inv, v * v)
    mn = np.full(m, np.inf)
    np.minimum.at(mn, inv, v)
    mx = np.full(m, -np.inf)
    np.maximum.at(mx, inv, v)
    mean = s / sizes
    var = np.maximum(sq / sizes - mean * mean, 0.0)
    feats = np.stack(
        [mean, mn, mx, sizes.astype(np.float64), var], axis=1
    ).astype(np.float32)
    return uv, sizes.astype(np.int64), feats


def _block_rag_device(
    seg: np.ndarray,
    values: Optional[np.ndarray],
    inner: Tuple[int, ...],
    return_nodes: bool = False,
):
    """Device-dedup path of :func:`block_rag` (3-D blocks).

    Labels are densified on host (one unique over the block's voxels — tiny
    next to a unique over every adjacent *pair*), aggregated on device, and
    mapped back to the original uint64 ids.  The static edge capacity starts
    at a power-of-two estimate and doubles on overflow, so each capacity
    bucket compiles once per process.
    """
    with_values = values is not None
    dense, uniq = _densify_labels(seg)
    vals_j = None if values is None else jnp.asarray(values, jnp.float32)

    cap = 1 << 14
    while True:
        (lo, hi, count, vsum, vsumsq, vmin, vmax, shift,
         n_edges) = device_edge_aggregate(
            jnp.asarray(dense), vals_j, cap, with_values=with_values,
            inner_shape=tuple(inner),
        )
        n = int(n_edges)
        if n <= cap:
            break
        while cap < n:
            cap *= 2
    lo = np.asarray(lo[:n]).astype(np.int64)
    hi = np.asarray(hi[:n]).astype(np.int64)
    sizes = np.asarray(count[:n]).astype(np.int64)
    uv = np.stack([uniq[lo], uniq[hi]], axis=1).astype(np.uint64)
    nodes: Tuple = ()
    if return_nodes:
        # inner node set from the dense table (int32 pass over the inner
        # region, cheaper than re-uniquing the original-dtype labels)
        inner_bb = tuple(slice(0, s) for s in inner)
        inner_ids = np.unique(dense[inner_bb])
        inner_lab = uniq[inner_ids]
        nodes = (inner_lab[inner_lab != 0],)
    if not with_values:
        return (uv, sizes, None) + nodes
    s = np.asarray(vsum[:n], np.float64)
    sq = np.asarray(vsumsq[:n], np.float64)
    mean = s / np.maximum(sizes, 1)
    # sq is the second moment about the global shift c:
    # var = E[(x-c)^2] - (mean-c)^2
    c = float(shift)
    var = np.maximum(sq / np.maximum(sizes, 1) - (mean - c) ** 2, 0.0)
    feats = np.stack(
        [
            mean,
            np.asarray(vmin[:n], np.float64),
            np.asarray(vmax[:n], np.float64),
            sizes.astype(np.float64),
            var,
        ],
        axis=1,
    ).astype(np.float32)
    return (uv, sizes, feats) + nodes


def merge_edge_lists(edge_lists) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-block ``(uv, sizes)`` lists into one global edge list.

    Returns ``(uv, sizes)`` with unique lexsorted rows; sizes summed across
    blocks (each voxel-face contact is counted by exactly one block, per the
    module halo convention).
    """
    uvs = [uv for uv, _ in edge_lists if len(uv)]
    if not uvs:
        return np.zeros((0, 2), np.uint64), np.zeros(0, np.int64)
    all_uv = np.concatenate(uvs)
    all_sz = np.concatenate([sz for _, sz in edge_lists if len(sz)])
    uv, inv = np.unique(all_uv, axis=0, return_inverse=True)
    sizes = np.zeros(len(uv), np.int64)
    np.add.at(sizes, inv.ravel(), all_sz)
    return uv, sizes


def merge_feature_lists(uv_global: np.ndarray, parts) -> np.ndarray:
    """Weighted merge of per-block edge features onto the global edge list.

    ``parts`` iterates ``(uv, feats)`` with feats columns
    :data:`FEATURE_NAMES`.  Mean is count-weighted; min/max are reduced;
    counts are summed; variance merges through the streaming (Chan)
    parallel combine — running mean + second moment about it — which stays
    accurate for large-mean data where the naive E[x^2] - mean^2
    reconstruction cancels catastrophically.  Edges absent from all parts
    get zeros.
    """
    m = len(uv_global)

    from .. import native

    merged = native.merge_edge_features(parts, uv_global)
    if merged is not None:
        mean, m2, mn, mx, cnt = merged
    else:
        mean = np.zeros(m, np.float64)
        m2 = np.zeros(m, np.float64)
        mn = np.full(m, np.inf)
        mx = np.full(m, -np.inf)
        cnt = np.zeros(m, np.float64)
        for uv, feats in parts:
            if len(uv) == 0:
                continue
            feats = np.asarray(feats)
            if feats.ndim != 2 or feats.shape[1] != len(FEATURE_NAMES):
                raise ValueError(
                    f"edge-feature block has shape {feats.shape}, expected "
                    f"(m, {len(FEATURE_NAMES)}) {FEATURE_NAMES} — regenerate "
                    "per-block features written by an older format"
                )
            ids = find_edge_ids(uv_global, uv)
            ok = ids >= 0
            ids = ids[ok]
            f = feats[ok].astype(np.float64)
            nb = f[:, 3]
            pos = nb > 0
            ids, f, nb = ids[pos], f[pos], nb[pos]
            # the streaming combine below uses fancy-index updates, which
            # are last-write-wins on duplicate ids — enforce the per-part
            # uniqueness every producer (np.unique output) guarantees
            # rather than corrupt counts silently
            if len(ids) != len(np.unique(ids)):
                raise ValueError(
                    "edge-feature part contains duplicate edge rows — "
                    "merge duplicates (np.unique per block) before "
                    "merge_feature_lists"
                )
            na = cnt[ids]
            ntot = na + nb
            delta = f[:, 0] - mean[ids]
            mean[ids] += delta * nb / ntot
            m2[ids] += f[:, 4] * nb + delta * delta * na * nb / ntot
            np.minimum.at(mn, ids, f[:, 1])
            np.maximum.at(mx, ids, f[:, 2])
            cnt[ids] = ntot
    has = cnt > 0
    var = np.zeros(m, np.float64)
    var[has] = np.maximum(m2[has] / cnt[has], 0.0)
    mean = np.where(has, mean, 0.0)
    mn[~has] = 0.0
    mx[~has] = 0.0
    return np.stack([mean, mn, mx, cnt, var], axis=1).astype(np.float32)


def find_edge_ids(uv_sorted: np.ndarray, uv_query: np.ndarray) -> np.ndarray:
    """Row-index of each query edge in a lexsorted unique edge array.

    Works on original (uint64) or dense labels; missing edges map to -1.
    Implemented via a structured-view searchsorted, avoiding overflow of
    packed keys for large label spaces.
    """
    if len(uv_query) == 0:
        return np.zeros(0, np.int64)
    if len(uv_sorted) == 0:
        return np.full(len(uv_query), -1, np.int64)
    # structured dtype: field-wise *numeric* comparison (a raw-bytes void
    # view would compare little-endian integers in byte order and silently
    # mis-sort any label >= 256)
    dt = uv_sorted.dtype
    struct_dt = np.dtype([("u", dt), ("v", dt)])

    def as_struct(arr):
        s = np.empty(len(arr), dtype=struct_dt)
        s["u"] = arr[:, 0]
        s["v"] = arr[:, 1]
        return s

    av = as_struct(uv_sorted)
    qv = as_struct(uv_query.astype(dt, copy=False))
    idx = np.searchsorted(av, qv)
    idx_c = np.clip(idx, 0, len(av) - 1)
    found = av[idx_c] == qv
    return np.where(found, idx_c, -1).astype(np.int64)
