"""Two-level connected-components labeling: VMEM tiles + small edge lists.

Round-2's ``label_components`` (ops/ccl.py) is a single-level label-equivalence
fixpoint whose hook/compress steps are full-volume random gathers and
scatters.  Measured on a TPU v5-lite chip those run at ~165M elements/s —
~70x slower than a dense shift pass — making CCL the dominant cost of the
north-star fused step.  This module is the TPU-native redesign:

1. **Tile phase** (``pallas_kernels.tile_ccl_pallas``): exact CCL *within*
   (16, 16, 128) VMEM tiles by dense 6-neighbor min-propagation of global
   flat indices — zero gathers, one HBM round trip for the whole volume.
2. **Face phase** (this module, pure XLA): equivalences can only cross tile
   faces.  Face voxel pairs are extracted with strided slices, de-duplicated
   first along runs (dense compare), then by value (one small 2-key sort),
   and compacted with cumsum+scatter into fixed-size edge arrays (the data-
   dependent edge count lives in *capacity* parameters with overflow flags,
   keeping shapes static for XLA).
3. **Union-find** on the deduped edge list: pointer-jump + hook-min rounds on
   arrays of ``edge_cap`` elements — thousands of times smaller than the
   volume.
4. **Resolve**: roots are scattered into a parent table at endpoint positions
   only, and the final per-voxel relabel is either a per-tile value-remap in
   VMEM (``apply_remap_pallas`` — face-touching fragments per tile are few)
   or a single full gather on the XLA fallback path.

The reference delegated this to vigra's serial two-pass union-find per block
plus ``nifty.ufd`` merges over a filesystem (SURVEY.md §2a
connected_components, §2b); here the same two-level idea (local labeling +
boundary merge) is mapped onto the TPU memory hierarchy instead of a cluster.

All steps run under ``jit``/``shard_map`` (vma-safe carries via the ccl
helpers).  Overflow of any capacity is reported, never silently wrong.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ccl import _match_vma, _shift, _true_like, label_components

BIG = 2**30  # background sentinel during the padded/tiled phase

DEFAULT_TILE = (16, 16, 128)
DEFAULT_PAIR_CAP = 1 << 21
# ceiling for unique merged face edges.  Was 1<<19: the measured pair load
# on bench-like volumes is ~0.6% of voxels and size-constant, which
# projects to ~1M at 512³ — over the old ceiling with no margin.  n//128
# still rules below ~250M voxels, so behavior only changes at very large
# single-shard volumes (docs/PERFORMANCE.md "512³ capacity audit").
DEFAULT_EDGE_CAP = 1 << 21
DEFAULT_TABLE_CAP = 64


def _round_up(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _auto_cap(n_voxels: int, default: int, divisor: int) -> int:
    """Volume-scaled capacity: static (shape-derived), bounded by ``default``.

    Tiny volumes (tests, the driver dry-run) would otherwise pay the full
    multi-million-element sort/compact overhead of benchmark-scale caps.
    The 16384 floor keeps adversarially dense small volumes (sparse seeds in
    pure noise: most strip voxels carry basin codes) inside capacity while
    still costing microseconds.
    """
    return max(16384, min(default, _round_up(n_voxels // divisor, 1024)))


def _tile_for(shape: Tuple[int, int, int]) -> Tuple[int, int, int]:
    """Pick a lane-aligned tile; tiny axes get padded up to one tile."""
    z, y, x = shape
    return (min(16, _round_up(z, 8)), min(16, _round_up(y, 8)), 128)


def tile_local_labels_xla(
    mask: jnp.ndarray, tile: Tuple[int, int, int]
) -> jnp.ndarray:
    """Per-tile CCL via the legacy kernel, vmapped — CPU/fallback path.

    Same contract as ``tile_ccl_pallas``: global flat indices, ``BIG``
    background.
    """
    z, y, x = mask.shape
    tz, ty, tx = tile
    gz, gy, gx = z // tz, y // ty, x // tx
    tiles = (
        mask.reshape(gz, tz, gy, ty, gx, tx)
        .transpose(0, 2, 4, 1, 3, 5)
        .reshape(gz * gy * gx, tz, ty, tx)
    )
    local = jax.vmap(lambda m: label_components(m, connectivity=1))(tiles)
    nloc = tz * ty * tx
    # local rep -> global flat index, elementwise
    tid = jnp.arange(gz * gy * gx, dtype=jnp.int32).reshape(-1, 1, 1, 1)
    ti = tid // (gy * gx)
    tj = (tid // gx) % gy
    tk = tid % gx
    lz = local // (ty * tx)
    ly = (local // tx) % ty
    lx = local % tx
    glob = ((ti * tz + lz) * y + tj * ty + ly) * x + tk * tx + lx
    glob = jnp.where(local == nloc, jnp.int32(BIG), glob.astype(jnp.int32))
    return (
        glob.reshape(gz, gy, gx, tz, ty, tx)
        .transpose(0, 3, 1, 4, 2, 5)
        .reshape(z, y, x)
    )


def _compact(
    flags: jnp.ndarray, values: Tuple[jnp.ndarray, ...], cap: int, fill: int
):
    """Pack ``values[i][flags]`` into ``cap``-sized arrays (cumsum+scatter).

    Returns (packed_values, n_kept).  Entries beyond ``cap`` are dropped —
    callers must check ``n_kept > cap`` for overflow.  This replaces
    ``jnp.nonzero(size=...)``, whose sort-based lowering measured ~10x
    slower on TPU.
    """
    flat = flags.ravel()
    pos = jnp.cumsum(flat.astype(jnp.int32)) - 1
    dest = jnp.where(flat, pos, cap)
    dest = jnp.where(dest >= cap, cap, dest)
    out = []
    for v in values:
        buf = jnp.full((cap + 1,), fill, dtype=v.dtype)
        buf = buf.at[dest].set(v.ravel(), mode="drop")
        out.append(buf[:cap])
    n_kept = jnp.where(flat.size > 0, pos[-1] + 1, 0).astype(jnp.int32)
    return tuple(out), n_kept


def tier_mode() -> str:
    """Capacity-tier compile mode, from ``CT_TIER_MODE``.

    - ``cond`` (default): both tiers compiled, selected at runtime by
      ``lax.cond`` — exact for any input.
    - ``big``: only the full-capacity tier is compiled.  Exact for any
      input; gives up the small tier's runtime win.
    - ``small``: only the 1/16 tier is compiled.  Exact whenever the live
      count fits the small tier (the common case the tier exists for);
      inputs that don't fit are truncated and reported through the site's
      overflow channel, never silently.

    ``big``/``small`` exist to shrink the compiled program: every tiered
    site otherwise duplicates a sort-heavy merge core into both branches
    of its cond (~24% of the fused step's HLO), which matters on backends
    where compile time, not runtime, is the binding constraint.
    """
    mode = os.environ.get("CT_TIER_MODE", "cond")
    if mode not in ("cond", "big", "small"):
        raise ValueError(
            f"CT_TIER_MODE must be cond/big/small, got {mode!r}"
        )
    return mode


def run_capacity_tiered(arrays, n_total, big_cap, core, n_padded,
                        max_rounds, vma_like, trunc_fold=None):
    """Run ``core(*arrays, cap, max_rounds, vma_like)`` at 1/16 capacity
    when the runtime entry count allows.

    Every sort inside a merge core runs at its STATIC buffer size, so a
    typical volume (real entries ≪ capacity) would sort ~all padding.
    When ``n_total`` fits the small tier, the real entries are compacted
    (``BIG`` marks padding) and the ENTIRE core runs at that size; its
    capacity-proportional outputs (the first ``n_padded`` of the returned
    tuple) are padded back to the big-tier sizes with ``BIG``.  The small
    tier cannot overflow: its capacity equals its input capacity and
    dedup only shrinks.  Used by :func:`merge_face_pairs` and
    ``tile_ws``'s :func:`~cluster_tools_tpu.ops.tile_ws.fill_unseeded_basins`
    and :func:`~cluster_tools_tpu.ops.tile_ws.collect_negative_values`.
    Inline variants of the same 1/16 tier (they need slot-aligned
    scatter-back or shape-independent outputs rather than tail-padding)
    live in :func:`build_remap_tables` (this module),
    ``tile_ws.chase_exits``, and ``tile_ws.value_join`` — retune the
    ratio in ALL of these together.

    :func:`tier_mode` selects which tiers are compiled.  In ``small``
    mode an input that doesn't fit is truncated and the truncation is
    folded into the output's LAST element (``max`` against an int32 flag
    by default; pass ``trunc_fold(last, trunc_int32)`` when the last
    element is a count rather than a flag).
    """
    small_n = min(big_cap, max(3 * 16384, arrays[0].shape[0] // 16))
    mode = tier_mode()

    def _small(args):
        compacted, _ = _compact(args[0] < BIG, args, small_n, BIG)
        out = core(*compacted, small_n, max_rounds, vma_like)
        padded = tuple(
            jnp.pad(
                x, (0, (x.shape[0] // small_n) * big_cap - x.shape[0]),
                constant_values=BIG,
            )
            for x in out[:n_padded]
        )
        return padded + out[n_padded:]

    def _big(args):
        return core(*args, big_cap, max_rounds, vma_like)

    if mode == "big" or small_n >= big_cap:
        return _big(tuple(arrays))
    if mode == "small":
        out = _small(tuple(arrays))
        trunc = (n_total > small_n).astype(jnp.int32)
        last = (
            trunc_fold(out[-1], trunc) if trunc_fold is not None
            else jnp.maximum(out[-1], trunc)
        )
        return out[:-1] + (last,)
    return lax.cond(n_total <= small_n, _small, _big, tuple(arrays))


def _face_pairs_axis(
    labels: jnp.ndarray, tile: Tuple[int, int, int], axis: int, pair_cap: int
):
    """Label pairs across tile boundaries along ``axis``, run-deduped."""
    t = tile[axis]
    n = labels.shape[axis]
    g = n // t
    if g <= 1:
        empty = jnp.full((pair_cap,), jnp.int32(BIG))
        return (empty, empty), jnp.int32(0)
    a = lax.slice_in_dim(labels, t - 1, n - 1, stride=t, axis=axis)
    b = lax.slice_in_dim(labels, t, n, stride=t, axis=axis)
    valid = (a < BIG) & (b < BIG)
    # run-dedup along the largest non-sliced axis: consecutive identical
    # (a, b) pairs come from the same fragment adjacency
    dedup_axis = 2 if axis != 2 else 1
    a_prev = _shift1(a, dedup_axis, -1)
    b_prev = _shift1(b, dedup_axis, -1)
    keep = valid & ((a != a_prev) | (b != b_prev))
    (pa, pb), n_kept = _compact(keep, (a, b), pair_cap, BIG)
    return (pa, pb), n_kept


def _shift1(x: jnp.ndarray, axis: int, fill: int) -> jnp.ndarray:
    """Shift by +1 along ``axis`` with ``fill`` shifted in (ccl._shift alias)."""
    return _shift(x, 1, axis, jnp.int32(fill))


def merge_face_pairs(
    labels: jnp.ndarray,
    tile: Tuple[int, int, int],
    pair_cap: int = DEFAULT_PAIR_CAP,
    edge_cap: int = DEFAULT_EDGE_CAP,
    max_rounds: int = 64,
):
    """Union-find closure over tile-face equivalences.

    ``labels``: per-tile global-flat-index labels (``BIG`` background).
    Returns ``(ea, eb, root_a, root_b, n_edges, overflow)`` where ``ea/eb``
    are the deduped edge endpoints (label values, ``BIG``-padded) and
    ``root_a/root_b`` their final merged roots.  ``overflow`` is True when a
    capacity was exceeded or the union-find hit ``max_rounds`` unconverged
    (labels would be under-merged — callers re-run with bigger caps or fall
    back).
    """
    pair_lists = []
    overflow = _match_vma(jnp.zeros((), jnp.int32), labels)
    n_total = _match_vma(jnp.zeros((), jnp.int32), labels)
    for axis in range(3):
        (pa, pb), kept = _face_pairs_axis(labels, tile, axis, pair_cap)
        pair_lists.append((pa, pb))
        overflow = jnp.maximum(overflow, (kept > pair_cap).astype(jnp.int32))
        n_total = n_total + jnp.minimum(kept, pair_cap)
    # the concat inherits the labels' varying-manual-axes type even when every
    # axis had a single tile (all-constant empty pair lists) — required for
    # the while_loop carries below under shard_map
    a = _match_vma(jnp.concatenate([p[0] for p in pair_lists]), labels)
    b = _match_vma(jnp.concatenate([p[1] for p in pair_lists]), labels)

    ea, eb, root_a, root_b, n_edges, core_ovf = run_capacity_tiered(
        (a, b), n_total, edge_cap, _merge_core, 4, max_rounds, labels
    )
    overflow = jnp.maximum(overflow, core_ovf)
    return ea, eb, root_a, root_b, n_edges, overflow > 0


def _merge_core(a, b, edge_cap, max_rounds, vma_like):
    """Dedup + dense-id union-find over one capacity tier; outputs sized
    ``edge_cap`` (``BIG``-padded), overflow as int32."""
    overflow = _match_vma(jnp.zeros((), jnp.int32), vma_like)
    # value-dedup: one small sort, duplicates & padding end up adjacent/last
    a, b = lax.sort((a, b), num_keys=2)
    dup = (a == _shift1(a, 0, -1)) & (b == _shift1(b, 0, -1))
    keep = (~dup) & (a < BIG)
    (ea, eb), n_edges = _compact(keep, (a, b), edge_cap, BIG)
    overflow = jnp.maximum(overflow, (n_edges > edge_cap).astype(jnp.int32))

    # compact endpoint labels to dense ids so the union-find's parent table
    # is edge-sized, not volume-sized: full pointer-doubling per round then
    # costs a couple of tiny gathers instead of touching a 500MB table
    m2 = 2 * edge_cap
    vals = jnp.concatenate([ea, eb])
    slots = jnp.arange(m2, dtype=jnp.int32)
    svals, sslots = lax.sort((vals, slots), num_keys=1)
    is_new = svals != _shift1(svals, 0, -1)
    rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    uniq = jnp.full((m2,), jnp.int32(BIG)).at[rank].set(svals)
    dense = jnp.zeros((m2,), jnp.int32).at[sslots].set(rank)
    da, db = dense[:edge_cap], dense[edge_cap:]

    parent = _match_vma(jnp.arange(m2, dtype=jnp.int32), vma_like)

    def cond(s):
        _, changed, it = s
        return changed & (it < max_rounds)

    def body(s):
        P, _, it = s
        ra = P[da]
        rb = P[db]
        lo = jnp.minimum(ra, rb)
        hi = jnp.maximum(ra, rb)
        P = P.at[hi].min(lo)
        P = P.at[da].min(lo)
        P = P.at[db].min(lo)
        # full path compression: the table is small, so doubling is cheap
        P = P[P]
        P = P[P]
        return P, jnp.any(ra != rb), it + 1

    parent, unconverged, _ = lax.while_loop(
        cond, body, (parent, _true_like(da), jnp.int32(0))
    )
    # a max_rounds exit leaves edges with differing roots: report, never hide
    overflow = jnp.maximum(overflow, unconverged.astype(jnp.int32))
    # map dense roots back to label values
    root_a = uniq[parent[da]]
    root_b = uniq[parent[db]]
    root_a = jnp.where(ea < BIG, root_a, jnp.int32(BIG))
    root_b = jnp.where(eb < BIG, root_b, jnp.int32(BIG))
    return ea, eb, root_a, root_b, n_edges, overflow


def _tile_id_of(v: jnp.ndarray, shape, tile) -> jnp.ndarray:
    z, y, x = shape
    tz, ty, tx = tile
    gy, gx = y // ty, x // tx
    vz = v // (y * x)
    vy = (v // x) % y
    vx = v % x
    return ((vz // tz) * gy + (vy // ty)) * gx + (vx // tx)


def build_remap_tables(
    tile_ids: jnp.ndarray,
    old_vals: jnp.ndarray,
    new_vals: jnp.ndarray,
    n_tiles: int,
    table_cap: int = DEFAULT_TABLE_CAP,
):
    """Per-tile (old_label -> new_label) tables for the VMEM apply kernel.

    ``tile_ids``: which tile each entry belongs to (``BIG`` = drop the
    entry); duplicates of (tile, old) collapse to one slot.  Returns
    ``(old_tbl, new_tbl, overflow)`` with tables shaped
    ``(n_tiles, table_cap)``; unused slots hold -1.

    The sort runs at the static input size; table shapes don't depend on
    it, so the usual 1/16 capacity tier applies with no scatter-back —
    entries are just compacted first when the live count fits.
    """
    n_in = tile_ids.shape[0]
    small_n = max(16384, n_in // 16)
    mode = tier_mode()
    if small_n < n_in and mode != "big":
        n_live = (tile_ids < BIG).sum()

        def _small(args):
            compacted, _ = _compact(args[0] < BIG, args, small_n, BIG)
            return _remap_tables_core(*compacted, n_tiles, table_cap)

        def _big(args):
            return _remap_tables_core(*args, n_tiles, table_cap)

        if mode == "small":
            old_tbl, new_tbl, overflow = _small(
                (tile_ids, old_vals, new_vals)
            )
            return old_tbl, new_tbl, overflow | (n_live > small_n)

        return lax.cond(
            n_live <= small_n, _small, _big, (tile_ids, old_vals, new_vals)
        )
    return _remap_tables_core(tile_ids, old_vals, new_vals, n_tiles, table_cap)


def _remap_tables_core(tile_ids, old_vals, new_vals, n_tiles, table_cap):
    tid, v, r = lax.sort((tile_ids, old_vals, new_vals), num_keys=2)
    dup = (tid == _shift1(tid, 0, -1)) & (v == _shift1(v, 0, -1))
    valid = (tid < BIG) & (~dup)
    # within-tile slot rank counting only valid entries
    cnt = jnp.cumsum(valid.astype(jnp.int32))
    is_first = (tid != _shift1(tid, 0, -1)) & (tid < BIG)
    base = lax.cummax(jnp.where(is_first, cnt - valid.astype(jnp.int32), -1))
    slot = jnp.where(valid, cnt - 1 - base, table_cap)
    overflow = jnp.any(valid & (slot >= table_cap))
    dest = jnp.where(valid & (slot < table_cap), tid * table_cap + slot,
                     n_tiles * table_cap)
    old_tbl = jnp.full((n_tiles * table_cap + 1,), jnp.int32(-1))
    new_tbl = jnp.full((n_tiles * table_cap + 1,), jnp.int32(-1))
    old_tbl = old_tbl.at[dest].set(v, mode="drop")
    new_tbl = new_tbl.at[dest].set(r, mode="drop")
    return (
        old_tbl[:-1].reshape(n_tiles, table_cap),
        new_tbl[:-1].reshape(n_tiles, table_cap),
        overflow,
    )


def resolve_labels_gather(
    labels: jnp.ndarray,
    ea: jnp.ndarray,
    eb: jnp.ndarray,
    root_a: jnp.ndarray,
    root_b: jnp.ndarray,
) -> jnp.ndarray:
    """Fallback resolve: scatter roots into a parent table, one full gather."""
    n = int(np.prod(labels.shape))
    P = _match_vma(jnp.arange(n + 1, dtype=jnp.int32), labels)
    P = P.at[jnp.minimum(ea, n)].set(jnp.minimum(root_a, n), mode="drop")
    P = P.at[jnp.minimum(eb, n)].set(jnp.minimum(root_b, n), mode="drop")
    flat = labels.ravel()
    out = P[jnp.minimum(flat, n)]
    return jnp.where(flat >= BIG, jnp.int32(BIG), out).reshape(labels.shape)


@partial(jax.jit, static_argnames=("cap",))
def label_components_sparse(mask: jnp.ndarray, cap: Optional[int] = None):
    """Connected components (connectivity 1) of a SPARSE 3-D mask.

    Output shape of :func:`label_components_tiled` — int32 labels holding
    a per-component representative flat index, ``mask.size`` for
    background — but the representative is the component's minimum flat
    index in ARRAY order, where the tiled labeler picks the minimum in
    its padded/tiled order: the two agree for components contained in one
    tile and may differ (same partition, different id) for tile-spanning
    components.  Callers treat these ids as opaque distinct tokens
    (relabel/offset downstream), so the modes are interchangeable as
    segmentations, not as raw id values.

    Cost scales with the POPCOUNT capacity ``cap`` (default
    ``max(3*16384, size/16)``), not with the tile grid: set voxels are
    compacted, a 3-axis adjacency is built in compacted-slot space via
    the dense rank array (one gather per axis — no sorts anywhere), and
    the slot-space union-find resolves in one
    :func:`~cluster_tools_tpu.ops.unionfind.union_find` while-loop.

    Built for the watershed's seed-plateau labeling (maxima measure ~1.4%
    of the bench volume at ``min_seed_distance=2``): the full tiled CCL
    machinery is ~1.4k HLO lines and was the largest single contributor
    to the fused step's remote-compile cost; this is ~1/10 the program.
    Returns ``(labels, overflow)`` — overflow True when set voxels exceed
    ``cap`` (labels then unreliable; raise ``cap``).
    """
    if mask.ndim != 3:
        raise ValueError("label_components_sparse expects a 3-D mask")
    from .unionfind import union_find

    z, y, x = mask.shape
    n = z * y * x
    if n >= BIG:
        raise ValueError(f"volume {mask.shape} has >= 2**30 voxels; shard it")
    if cap is None:
        cap = min(n, max(3 * 16384, n // 16))
    flat = mask.ravel()
    idx = _match_vma(jnp.arange(n, dtype=jnp.int32), mask)
    (cidx,), n_live = _compact(flat, (idx,), cap, n)
    overflow = n_live > cap
    # dense rank: slot of any set voxel (the same cumsum _compact used)
    rank = jnp.cumsum(flat.astype(jnp.int32)) - 1
    pair_lists = []
    slot_ids = _match_vma(jnp.arange(cap, dtype=jnp.int32), mask)
    live = cidx < n
    for step, bound_ok in (
        (y * x, (cidx // (y * x)) + 1 < z),
        (x, (cidx // x) % y + 1 < y),
        (1, cidx % x + 1 < x),
    ):
        nb = jnp.clip(cidx + step, 0, n - 1)
        ok = live & bound_ok & flat[nb]
        # (slot, neighbor slot); invalid pairs become self-loop no-ops
        pair_lists.append(
            jnp.stack(
                [
                    jnp.where(ok, slot_ids, 0),
                    jnp.where(ok, rank[nb], 0),
                ],
                axis=1,
            )
        )
    parent = union_find(jnp.concatenate(pair_lists, axis=0), cap)
    # representative flat index per slot; ascending compaction makes the
    # min slot the min flat index
    rep = cidx[parent]
    out = jnp.full((n + 1,), jnp.int32(n))
    out = _match_vma(out, mask)
    out = out.at[jnp.where(live, cidx, n)].set(
        jnp.where(live, rep, n), mode="drop"
    )
    return out[:n].reshape(mask.shape), overflow


def label_components_tiled(
    mask: jnp.ndarray,
    connectivity: int = 1,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    pair_cap: Optional[int] = None,
    edge_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-level CCL of a 3-D bool mask.

    Same output contract as :func:`~cluster_tools_tpu.ops.ccl.label_components`
    — int32, foreground = flat index (in ``mask``'s own shape) of a canonical
    component representative, background = ``mask.size`` — plus an
    ``overflow`` bool: True when an internal capacity was exceeded and labels
    may be under-merged (raise the caps; results are otherwise still
    per-tile-consistent).  Unlike the legacy kernel the representative is the
    component's minimum index in the *padded, tiled* order, which is a
    canonical choice but not necessarily the minimum in array order.

    ``impl``: "pallas" (TPU VMEM kernels), "xla" (portable), or "auto"
    (pallas exactly when the default backend is TPU).  ``connectivity`` must
    be 1 (face connectivity) — callers needing the full neighborhood use the
    legacy kernel.  Capacities default to volume-scaled values (static,
    shape-derived); pass explicit caps for workloads with unusually many
    fragments per tile face.

    ``CT_TIER_MODE`` is resolved here, OUTSIDE the jit boundary, and passed
    down as a static argument — flipping the env var mid-process correctly
    retraces (no stale-cache surprise).  Callers that wrap this function in
    their own ``jax.jit`` capture the mode at their own trace time, the
    usual closure semantics.
    """
    return _label_components_tiled_jit(
        mask, connectivity=connectivity, impl=impl, tile=tile,
        pair_cap=pair_cap, edge_cap=edge_cap, table_cap=table_cap,
        interpret=interpret, _tier=tier_mode(),
    )


@partial(
    jax.jit,
    static_argnames=(
        "connectivity", "impl", "tile", "pair_cap", "edge_cap", "table_cap",
        "interpret", "_tier",
    ),
)
def _label_components_tiled_jit(
    mask: jnp.ndarray,
    connectivity: int = 1,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    pair_cap: Optional[int] = None,
    edge_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    _tier: str = "cond",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    # _tier is keying-only: the tiered sites below read tier_mode() at trace
    # time, and including the resolved value in the static key guarantees
    # that read always matches the cache entry being built.
    if mask.ndim != 3:
        raise ValueError("label_components_tiled expects a 3-D mask")
    if connectivity != 1:
        raise ValueError("tiled CCL supports connectivity=1 only")
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"

    z, y, x = mask.shape
    tile = _tile_for(mask.shape) if tile is None else tile
    tz, ty, tx = tile
    zp, yp, xp = _round_up(z, tz), _round_up(y, ty), _round_up(x, tx)
    if zp * yp * xp >= BIG:
        raise ValueError(
            f"padded volume {(zp, yp, xp)} has >= 2**30 voxels; flat-index "
            "labels would collide with the background sentinel — shard the "
            "volume (parallel.distributed_ccl) instead"
        )
    padded = (zp != z) or (yp != y) or (xp != x)
    if pair_cap is None:
        pair_cap = _auto_cap(zp * yp * xp, DEFAULT_PAIR_CAP, 32)
    if edge_cap is None:
        edge_cap = _auto_cap(zp * yp * xp, DEFAULT_EDGE_CAP, 128)
    m = mask.astype(bool)
    if padded:
        m = jnp.pad(m, ((0, zp - z), (0, yp - y), (0, xp - x)))

    if impl == "pallas":
        from .pallas_kernels import apply_remap_pallas, tile_ccl_pallas

        labels = tile_ccl_pallas(m, tile=tile, interpret=interpret)
    else:
        labels = tile_local_labels_xla(m, tile)

    ea, eb, root_a, root_b, n_edges, overflow = merge_face_pairs(
        labels, tile, pair_cap=pair_cap, edge_cap=edge_cap
    )

    if impl == "pallas":
        n_tiles = (zp // tz) * (yp // ty) * (xp // tx)
        v = jnp.concatenate([ea, eb])
        r = jnp.concatenate([root_a, root_b])
        changed = (v < BIG) & (r != v)
        tids = jnp.where(
            changed, _tile_id_of(v, (zp, yp, xp), tile), jnp.int32(BIG)
        )
        old_tbl, new_tbl, tbl_overflow = build_remap_tables(
            tids, v, r, n_tiles, table_cap=table_cap
        )

        def fast(args):
            labels, old_tbl, new_tbl = args
            return apply_remap_pallas(
                labels, old_tbl, new_tbl, tile=tile, cap=table_cap,
                interpret=interpret,
            )

        def slow(args):
            labels, _, _ = args
            return resolve_labels_gather(labels, ea, eb, root_a, root_b)

        resolved = lax.cond(tbl_overflow, slow, fast, (labels, old_tbl, new_tbl))
    else:
        resolved = resolve_labels_gather(labels, ea, eb, root_a, root_b)

    n_orig = z * y * x
    if padded:
        resolved = resolved[:z, :y, :x]
        # padded-flat representative -> original-flat representative
        vz = resolved // (yp * xp)
        vy = (resolved // xp) % yp
        vx = resolved % xp
        orig = ((vz * y + vy) * x + vx).astype(jnp.int32)
        out = jnp.where(resolved >= BIG, jnp.int32(n_orig), orig)
    else:
        out = jnp.where(resolved >= BIG, jnp.int32(n_orig), resolved)
    return out, overflow
