"""Two-level seeded watershed: in-tile pointer flow + small basin graphs.

Round-2's ``seeded_watershed`` (ops/watershed.py) resolves the steepest-
descent pointer forest with full-volume pointer jumping and grows labels into
unseeded basins one voxel ring per iteration — both dominated by the TPU's
~165M elem/s random-gather rate (see ops/tile_ccl.py for the measurements).
This module keeps the exact same *descent semantics* (lex-min ``(height,
flat_index)`` over the closed neighborhood — the reference's
``vigra.watershedsNew`` per-block behavior, SURVEY.md §2a "watershed") but
restructures the resolution:

1. **Descent directions** (dense XLA): each voxel stores a 3-bit code for
   which neighbor it drains to — no pointer table, no gathers.
2. **In-tile flow** (``pallas_kernels.tile_ws_propagate_pallas``): labels
   flow along the pointer forest *inside* (16, 16, 128) VMEM tiles as dense
   select/shift steps to a fixpoint.  Each voxel ends with its basin's seed
   label, the code of its unseeded in-tile terminal, or an *exit code*
   naming the voxel its path leaves the tile through.
3. **Exit chase** (XLA, small): unique exit codes are collected from tile
   boundary strips (capacity-compacted), then chased across tiles by
   pointer-jumping on arrays of edge size — basins are object-scale, so
   chains are a few hops.
4. **Apply**: per-tile value-remap tables (the ops/tile_ccl machinery) or a
   gather fallback.
5. **Unseeded-basin fill**: instead of ring-growing, basins without seeds
   merge into their neighbor across the *lowest saddle* (Boruvka rounds) —
   minimum-spanning-forest watershed semantics, strictly closer to
   priority-flood than the old relaxation.  Two machines compute it
   (``CT_FILL_MODE``, default ``auto`` = substrate-aware): ``dense``
   (auto on cpu only) runs sort-free scatter-min rounds over the full
   face grids with exact per-pair min saddles
   (:func:`fill_unseeded_basins_dense`); ``capacity`` (auto on tpu AND
   gpu — volume-scale random access is the chip bottleneck, and the
   host-cache rationale doesn't transfer to gpu) runs the rounds on a
   compacted basin-boundary edge list with run-start saddle sampling
   (~1/18 the transient memory).  Basins with no seeded reachable
   neighbor keep label 0 (legacy behavior).  All mode env vars
   (``CT_FILL_MODE``/``CT_SEED_CCL``/``CT_TIER_MODE``) are resolved at
   the public entry points, OUTSIDE jit, and folded into the compile
   key — flipping one mid-process retraces, no ``jax.clear_caches()``
   needed.

When every basin is seeded (e.g. the oracle test's fully-seeded minima) the
result is bit-identical to the legacy kernel; only unseeded-basin fill order
differs.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ccl import _match_vma, _shift, _true_like
from .pallas_kernels import WS_OFFS
from .tile_ccl import (
    BIG,
    DEFAULT_TABLE_CAP,
    _auto_cap,
    _compact,
    _round_up,
    _shift1,
    _tile_for,
    _tile_id_of,
    build_remap_tables,
    run_capacity_tiered,
    tier_mode,
)

_BIGF = np.float32(3e38)

DEFAULT_EXIT_CAP = 1 << 21
DEFAULT_FILL_CAP = 1 << 21
# unique unseeded-basin adjacencies (deduped (a, b) pairs), not face
# voxels — object-scale, so orders of magnitude below FILL_CAP
DEFAULT_ADJ_CAP = 1 << 18


def _auto_fill_rounds(n_pad: int) -> int:
    """Default Boruvka round bound for the unseeded-basin fill.

    A round at least halves the unseeded component count, so
    ``ceil(log2(n)) + 1`` rounds suffice for ANY input (components can
    never exceed voxels).  The bound is a while-loop max trip count —
    generous values cost nothing at runtime (the loop exits on
    convergence) and nothing in program size.  The old fixed 16 silently
    under-covered volumes with more than 2^16 unseeded basins: the 512³
    host-substrate rehearsal measured 80,902 distinct basins and the fill
    correctly raised its overflow flag at exactly this bound —
    caught before any chip window paid for it (r5).
    """
    return max(16, int(np.ceil(np.log2(max(2, n_pad)))) + 1)


def _resolve_fill_mode(fill_mode: Optional[str]) -> str:
    """Resolve the unseeded-basin fill machinery to ``dense``/``capacity``.

    ``None`` reads ``CT_FILL_MODE`` (default ``auto``).  ``auto`` is
    substrate-aware because the two machines' cost models invert across
    backends:

    - ``dense`` on the **cpu** backend only: sort-free scatter-min Boruvka
      over the full face grids — exact min saddles, no caps, 3.8x faster
      end-to-end at 128^3 on the host, where gathers are cache-friendly.
    - ``capacity`` everywhere else (tpu/axon AND gpu): compacted lists +
      dedup sorts.  On the chip, random gather/scatter runs ~165M elem/s
      regardless of locality (docs/PERFORMANCE.md "Where the time goes"),
      so the dense rounds' ~15 volume-scale passes per round project to
      ~13s/round at 512^3; on gpu the host-cache rationale simply doesn't
      transfer and the dense path's ~1.8GB transient at 512^3 is a real
      risk (advisor r4) — capacity until a measured A/B says otherwise.

    Resolved OUTSIDE the jit boundary so the value is part of the compile
    key: flipping the env var mid-process retraces instead of silently
    reusing the previously compiled mode.
    """
    if fill_mode is None:
        fill_mode = os.environ.get("CT_FILL_MODE", "auto")
    if fill_mode == "auto":
        fill_mode = "dense" if jax.default_backend() == "cpu" else "capacity"
    if fill_mode not in ("dense", "capacity"):
        raise ValueError(
            f"CT_FILL_MODE must be auto/capacity/dense, got {fill_mode!r}"
        )
    return fill_mode


def _resolve_seed_mode(seed_mode: Optional[str]) -> str:
    """Resolve the seed-plateau CCL program (``None`` -> ``CT_SEED_CCL``).

    Like :func:`_resolve_fill_mode`, resolved pre-jit so the env var is
    folded into the compile key.
    """
    if seed_mode is None:
        seed_mode = os.environ.get("CT_SEED_CCL", "tiled")
    if seed_mode not in ("tiled", "sparse"):
        raise ValueError(f"CT_SEED_CCL must be tiled/sparse, got {seed_mode!r}")
    return seed_mode


def _sortable_float_key(f: jnp.ndarray) -> jnp.ndarray:
    """Monotone float32 -> int32 key (total order, NaN-free inputs)."""
    u = lax.bitcast_convert_type(f.astype(jnp.float32), jnp.int32)
    return u ^ ((u >> 31) & jnp.int32(0x7FFFFFFF))


def descent_directions(
    height: jnp.ndarray,
    is_seed: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Code 0..6 of each voxel's steepest-descent target (0 = self).

    Identical tiebreak to ``watershed._descent_pointers``: lexicographic min
    of ``(height, flat_index)`` over the closed 6-neighborhood; seeds and
    invalid voxels are terminals.  Dense shifts only.
    """
    shape = height.shape
    n = int(np.prod(shape))
    z, y, x = shape
    idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)
    h = jnp.where(valid, height.astype(jnp.float32), _BIGF)

    best_h = h
    best_i = idx
    best_d = jnp.zeros(shape, jnp.int32)
    for code, off in enumerate(WS_OFFS, start=1):
        nh = h
        ni = idx
        for ax, s in enumerate(off):
            if s:
                nh = _shift(nh, -s, ax, _BIGF)
                ni = _shift(ni, -s, ax, jnp.int32(n))
        better = (nh < best_h) | ((nh == best_h) & (ni < best_i))
        best_h = jnp.where(better, nh, best_h)
        best_i = jnp.where(better, ni, best_i)
        best_d = jnp.where(better, jnp.int32(code), best_d)
    return jnp.where(is_seed | ~valid, 0, best_d)


def tile_ws_propagate_xla(
    dirs: jnp.ndarray, sv: jnp.ndarray, tile: Tuple[int, int, int]
) -> jnp.ndarray:
    """Portable in-tile pointer flow; the formulation is substrate-aware.

    Output contract (both formulations, bit-identical — oracle-locked in
    tests/test_tile_ws.py): each voxel ends with its in-tile path
    terminal's value — seed label, unseeded-terminal code ``-gidx-2``, or
    the exit code of the FIRST out-of-tile hop.

    - off-TPU (cpu and anything else): **pointer jumping** — the in-tile
      successor table composed to closure in O(log path) rounds of
      gathers over L1/L2-resident ``tz*ty*tx`` tables; 5.4× the stepping
      recurrence on the host (docs/PERFORMANCE.md r5).
    - on TPU (``tpu``/``axon``): the **per-hop dense stepping** recurrence
      (same math as the Mosaic kernel) — dense shifts ride full VPU/HBM
      bandwidth while random gathers run ~165M elem/s regardless of
      locality, so O(path) vectorized rounds beat O(log path) gather
      rounds there.  This path only matters when the portable kernels run
      on-chip (the impl="xla" fallback rung); impl="auto" uses the Mosaic
      kernel.

    The choice is made at trace time from ``jax.default_backend()`` —
    part of program identity per backend, like every other
    substrate-aware selection in this module.
    """
    if jax.default_backend() in ("tpu", "axon"):
        return _tile_ws_propagate_stepping(dirs, sv, tile)
    return _tile_ws_propagate_jump(dirs, sv, tile)


def _flow_tile_setup(dirs: jnp.ndarray, sv: jnp.ndarray, tile):
    """Shared tile scatter/gather plumbing for both flow formulations:
    returns ``(gidx, dirs_t, sv_t, from_tiles)`` — the tiled global flat
    indices, tiled inputs, and the inverse layout transform.  One home so
    a layout change cannot drift the oracle-locked formulations apart."""
    z, y, x = dirs.shape
    tz, ty, tx = tile
    gz, gy, gx = z // tz, y // ty, x // tx

    def to_tiles(a):
        return (
            a.reshape(gz, tz, gy, ty, gx, tx)
            .transpose(0, 2, 4, 1, 3, 5)
            .reshape(gz * gy * gx, tz, ty, tx)
        )

    def from_tiles(a):
        return (
            a.reshape(gz, gy, gx, tz, ty, tx)
            .transpose(0, 3, 1, 4, 2, 5)
            .reshape(z, y, x)
        )

    idx = jnp.arange(z * y * x, dtype=jnp.int32).reshape(z, y, x)
    return to_tiles(idx), to_tiles(dirs), to_tiles(sv), from_tiles


def _tile_ws_propagate_stepping(
    dirs: jnp.ndarray, sv: jnp.ndarray, tile: Tuple[int, int, int]
) -> jnp.ndarray:
    """Per-hop dense stepping recurrence (the Mosaic kernel's math)."""
    from .pallas_kernels import ws_propagate_step

    _, y, x = dirs.shape
    gidx, dirs_t, sv_t, from_tiles = _flow_tile_setup(dirs, sv, tile)
    terminal = dirs_t == 0
    value = jnp.where(
        sv_t > 0, sv_t, jnp.where(terminal & (sv_t == 0), -gidx - 2, 0)
    ).astype(jnp.int32)

    def cond(s):
        return s[1]

    def body(s):
        v, _ = s
        v2 = ws_propagate_step(v, dirs_t, gidx, (1, 2, 3), y, x)
        return v2, jnp.any(v2 != v)

    value, _ = lax.while_loop(cond, body, (value, _true_like(value)))
    return from_tiles(value)


def _tile_ws_propagate_jump(
    dirs: jnp.ndarray, sv: jnp.ndarray, tile: Tuple[int, int, int]
) -> jnp.ndarray:
    """Pointer-jumping formulation: successor table composed to closure.

    Voxels whose descent target leaves the tile become pseudo-terminals
    carrying their exit code, so closure over ``nxt`` reaches exactly the
    same fixpoint the stepping recurrence does.
    """
    z, y, x = dirs.shape
    tz, ty, tx = tile
    gz, gy, gx = z // tz, y // ty, x // tx
    gidx, dirs_t, sv_t, from_tiles = _flow_tile_setup(dirs, sv, tile)

    # per-code offsets as lookup tables indexed by the direction code
    offs = np.concatenate([[[0, 0, 0]], np.asarray(WS_OFFS)]).astype(np.int32)
    oz = jnp.asarray(offs[:, 0])[dirs_t]
    oy = jnp.asarray(offs[:, 1])[dirs_t]
    ox = jnp.asarray(offs[:, 2])[dirs_t]
    cz = lax.broadcasted_iota(jnp.int32, dirs_t.shape, 1)
    cy = lax.broadcasted_iota(jnp.int32, dirs_t.shape, 2)
    cx = lax.broadcasted_iota(jnp.int32, dirs_t.shape, 3)
    tzc, tyc, txc = cz + oz, cy + oy, cx + ox
    inb = (
        (tzc >= 0) & (tzc < tz) & (tyc >= 0) & (tyc < ty)
        & (txc >= 0) & (txc < tx)
    )
    self_flat = (cz * ty + cy) * tx + cx
    tgt_flat = (tzc * ty + tyc) * tx + txc
    terminal = dirs_t == 0
    # exit code: -(global flat index of the out-of-tile target) - 2
    foff = (oz * y + oy) * x + ox
    exit_code = -(gidx + foff) - 2
    pseudo_term = terminal | ~inb
    nxt = jnp.where(pseudo_term, self_flat, tgt_flat)
    val = jnp.where(
        sv_t > 0,
        sv_t,
        jnp.where(
            terminal & (sv_t == 0),
            -gidx - 2,
            jnp.where(~inb & ~terminal, exit_code, 0),
        ),
    ).astype(jnp.int32)

    nt = gz * gy * gx
    nxt = nxt.reshape(nt, tz * ty * tx)
    val = val.reshape(nt, tz * ty * tx)

    def cond(s):
        return s[1]

    def body(s):
        p, _ = s
        p2 = jnp.take_along_axis(p, p, axis=1)
        return p2, jnp.any(p2 != p)

    nxt, _ = lax.while_loop(cond, body, (nxt, _true_like(nxt)))
    out = jnp.where(val != 0, val, jnp.take_along_axis(val, nxt, axis=1))
    return from_tiles(out.reshape(nt, tz, ty, tx))


def _strip_entries(values: jnp.ndarray, tile, axis: int, side: int):
    """(value, tile_id) arrays for one family of tile-boundary slabs."""
    t = tile[axis]
    n = values.shape[axis]
    start = 0 if side == 0 else t - 1
    sl = lax.slice_in_dim(values, start, n, stride=t, axis=axis)
    shape = sl.shape
    tz, ty, tx = tile
    div = [tz, ty, tx]
    ids = []
    for ax in range(3):
        io = lax.broadcasted_iota(jnp.int32, shape, ax)
        if ax == axis:
            ids.append(io)  # slab index == tile index along the sliced axis
        else:
            ids.append(io // div[ax])
    z, y, x = values.shape
    gy, gx = y // ty, x // tx
    tid = (ids[0] * gy + ids[1]) * gx + ids[2]
    return sl, tid


def collect_negative_values(
    values: jnp.ndarray, tile: Tuple[int, int, int], cap: int
):
    """Deduped (value, tile_id) pairs for negative labels on tile boundaries.

    Every cross-tile fragment touches a boundary strip of each tile it
    occupies, so this covers all (tile, value) incidences needed for exits
    and fill remaps.  Returns ``(vals, tids, overflow)``.
    """
    vs, ts = [], []
    overflow = _match_vma(jnp.zeros((), jnp.int32), values)
    n_total = overflow
    for axis in range(3):
        for side in (0, 1):
            sl, tid = _strip_entries(values, tile, axis, side)
            # a family can never hold more entries than its strip has
            # voxels, so capping at the strip size is FREE headroom-wise
            # and stops thin families (x strips are volume/128) from
            # being padded to the full exit capacity — at 512^3 this
            # nearly halves the concat the dedup sort below runs over
            fam_cap = max(1024, min(cap, int(np.prod(sl.shape))))
            neg = sl <= -2
            dedup_axis = 2 if axis != 2 else 1
            prev = _shift1(sl, dedup_axis, -1)
            prev_t = _shift1(tid, dedup_axis, -1)
            keep = neg & ((sl != prev) | (tid != prev_t))
            (v, t_), kept = _compact(keep, (sl, tid), fam_cap, BIG)
            overflow = jnp.maximum(
                overflow, (kept > fam_cap).astype(jnp.int32)
            )
            n_total = n_total + jnp.minimum(kept, fam_cap)
            vs.append(v)
            ts.append(t_)
    v = jnp.concatenate(vs)
    t_ = jnp.concatenate(ts)
    # the value-dedup sort runs at the static sum-of-family-caps concat
    # size (≤ 6*cap; ~half of it at 512³ thanks to the strip-size bounds
    # above) — tier it like the merge cores (shared rationale in
    # run_capacity_tiered).  Note the 1/16 small tier's exact envelope
    # scales with this concat, so CT_TIER_MODE=small covers ~half the
    # live-entry range it did with untrimmed buffers — cond mode (the
    # default) is unaffected
    cv, ct, n_kept = run_capacity_tiered(
        (v, t_), n_total, cap, _collect_core, 2, 0, values,
        # last output is a COUNT checked against ``cap`` by the caller:
        # in small tier_mode a truncated input must read as overflowing
        trunc_fold=lambda n, trunc: jnp.where(trunc > 0, cap + 1, n),
    )
    overflow = jnp.maximum(overflow, (n_kept > cap).astype(jnp.int32))
    return cv, ct, overflow > 0


def _collect_core(v, t_, cap, _max_rounds, _vma_like):
    """Sort-dedup one (value, tile) tier; outputs sized ``cap``."""
    v, t_ = lax.sort((v, t_), num_keys=2)
    dup = (v == _shift1(v, 0, BIG)) & (t_ == _shift1(t_, 0, BIG))
    keep = (~dup) & (v < BIG)
    (cv, ct), n_kept = _compact(keep, (v, t_), cap, BIG)
    return cv, ct, n_kept


def value_join(
    query_vals: jnp.ndarray, table_vals: jnp.ndarray, table_finals: jnp.ndarray
) -> jnp.ndarray:
    """For each query value, the table's final (or the query itself if absent).

    Sort-based join — ``searchsorted`` lowers to a binary-search gather chain
    that measured ~50x slower than a sort at these sizes on TPU.

    Both operands are static-capacity buffers (``BIG``-padded), so the
    usual 1/16 tier applies, slot-aligned like ``chase_exits``: when the
    live counts fit, both sides compact, the join runs small, and results
    scatter back to their query slots (absent/padded queries keep their
    identity mapping either way).
    """
    nq = query_vals.shape[0]
    nt = table_vals.shape[0]
    small_q = max(16384, nq // 16)
    small_t = max(16384, nt // 16)
    # tier_mode "small" keeps the cond here: value_join returns no
    # overflow channel, so a truncated table would lose mappings silently
    # — the cond's big branch is the only safe fallback
    if tier_mode() == "big":
        return _value_join_core(query_vals, table_vals, table_finals)
    if small_q < nq and small_t < nt:
        n_q = (query_vals < BIG).sum()
        n_t = (table_vals < BIG).sum()

        def _small(args):
            qv, tv, tf = args
            (cq, slots), _ = _compact(
                qv < BIG, (qv, jnp.arange(nq, dtype=jnp.int32)), small_q, BIG
            )
            (ctv, ctf), _ = _compact(tv < BIG, (tv, tf), small_t, BIG)
            res = _value_join_core(cq, ctv, ctf)
            return qv.at[slots].set(res, mode="drop")

        def _big(args):
            return _value_join_core(*args)

        return lax.cond(
            (n_q <= small_q) & (n_t <= small_t), _small, _big,
            (query_vals, table_vals, table_finals),
        )
    return _value_join_core(query_vals, table_vals, table_finals)


def _value_join_core(query_vals, table_vals, table_finals):
    nq = query_vals.shape[0]
    nt = table_vals.shape[0]
    keys = jnp.concatenate([table_vals, query_vals])
    is_query = jnp.concatenate(
        [jnp.zeros((nt,), jnp.int32), jnp.ones((nq,), jnp.int32)]
    )
    payload = jnp.concatenate([table_finals, query_vals])
    slot = jnp.concatenate(
        [jnp.full((nt,), -1, jnp.int32), jnp.arange(nq, dtype=jnp.int32)]
    )
    keys, is_query, payload, slot = lax.sort(
        (keys, is_query, payload, slot), num_keys=2
    )
    pos = jnp.arange(nt + nq, dtype=jnp.int32)
    last_tbl = lax.cummax(jnp.where(is_query == 0, pos, -1))
    tbl_key = keys[jnp.clip(last_tbl, 0, nt + nq - 1)]
    tbl_fin = payload[jnp.clip(last_tbl, 0, nt + nq - 1)]
    res = jnp.where((last_tbl >= 0) & (tbl_key == keys), tbl_fin, keys)
    out = jnp.zeros((nq,), jnp.int32)
    out = out.at[jnp.where(is_query == 1, slot, nq)].set(res, mode="drop")
    return out


def chase_exits(values: jnp.ndarray, codes: jnp.ndarray, max_hops: int = 256):
    """Resolve exit codes by following values across tiles.

    ``codes``: negative codes (``BIG``-padded).  Returns ``(finals,
    unconverged)``: the final value each code's chain reaches (a seed label
    (>0), 0, or the unseeded terminal code of its basin), and a flag that is
    True when a chain exceeded ``max_hops`` (finals then hold intermediate
    codes — callers must fold this into their overflow report).

    Per hop the chase gathers ``codes``-many volume entries, and ``codes``
    is a STATIC capacity buffer — so like the merge cores this tiers: when
    the runtime active-code count fits 1/16 of the buffer, the chain loop
    runs on the compacted codes and the finals scatter back to their
    original slots (identical results — each chain is chased
    independently).
    """
    n = values.size
    flat = values.ravel()

    def _core(c):
        active0 = c <= -2
        g = jnp.where(active0, -c - 2, 0)
        val = jnp.where(active0, flat[jnp.clip(g, 0, n - 1)], c)

        def cond(s):
            _, _, moved, hops = s
            return moved & (hops < max_hops)

        def body(s):
            g, val, _, hops = s
            active = (val <= -2) & (val != -g - 2)
            g2 = jnp.where(active, -val - 2, g)
            val2 = jnp.where(active, flat[jnp.clip(g2, 0, n - 1)], val)
            return g2, val2, jnp.any(active), hops + 1

        g, val, moved, _ = lax.while_loop(
            cond, body, (g, val, _true_like(g), jnp.int32(0))
        )
        return jnp.where(active0, val, c), moved

    # tier selection mirrors tile_ccl.run_capacity_tiered (same 1/16
    # ratio — retune together) but needs a slot-aligned scatter-back
    # instead of the helper's tail-padding, and a 1x floor (the input is
    # one buffer, not a 3-axis concat)
    cap = codes.shape[0]
    small_n = max(16384, cap // 16)
    mode = tier_mode()
    if small_n >= cap or mode == "big":
        return _core(codes)

    def _small(c):
        (pc, slots), _ = _compact(
            c <= -2, (c, jnp.arange(cap, dtype=jnp.int32)), small_n, BIG
        )
        fin_s, moved = _core(pc)
        # non-active codes map to themselves; padded slots (BIG) drop
        out = c.at[slots].set(fin_s, mode="drop")
        return out, moved

    n_active = (codes <= -2).sum()
    if mode == "small":
        fin, moved = _small(codes)
        # truncated chains were never chased: report through the
        # documented unconverged channel (callers fold into overflow)
        return fin, moved | (n_active > small_n)
    return lax.cond(n_active <= small_n, _small, _core, codes)


def _resolve_codes_gather(values: jnp.ndarray, codes, finals) -> jnp.ndarray:
    """Fallback apply: scatter code resolutions into a voxel-indexed table."""
    n = values.size
    table = _match_vma(-jnp.arange(n, dtype=jnp.int32) - 2, values)
    pos = jnp.where(codes <= -2, -codes - 2, n)
    table = table.at[pos].set(finals, mode="drop")
    flat = values.ravel()
    looked = table[jnp.clip(-flat - 2, 0, n - 1)]
    return jnp.where(flat <= -2, looked, flat).reshape(values.shape)


def fill_unseeded_basins(
    labels: jnp.ndarray,
    height: jnp.ndarray,
    fill_cap: int = DEFAULT_FILL_CAP,
    max_rounds: Optional[int] = None,
    adj_cap: Optional[int] = None,
):
    """Merge unseeded basins across their lowest saddles (Boruvka rounds).

    ``labels``: >0 seeded basin label, <= -2 unseeded basin code, 0 invalid.
    Returns ``(edge_vals, edge_finals, overflow)`` — the remap (old basin
    code -> final label, 0 if unreachable) for every unseeded basin seen on
    a boundary, for the caller to apply.

    Cost structure (r4, full story in docs/PERFORMANCE.md): face-voxel
    collection keeps the generous ``fill_cap`` (noise robustness); the
    Boruvka rounds run on the *deduplicated basin adjacency list*
    (``adj_cap``, object-scale) with each round's min-edge selection as
    two int32 scatter-mins rather than a sort; and the whole
    dedup+rounds machine is capacity-tiered (``run_capacity_tiered``) so
    the common few-unseeded-basins case executes at 1/16 size.
    Overflowing ``adj_cap`` raises the overflow flag like every other
    capacity.  ``max_rounds=None`` resolves to the always-sufficient
    volume-scaled bound (:func:`_auto_fill_rounds`).
    """
    if max_rounds is None:
        max_rounds = _auto_fill_rounds(labels.size)
    h = height.astype(jnp.float32)
    evs_a, evs_b, evs_h = [], [], []
    overflow = _match_vma(jnp.zeros((), jnp.int32), labels)
    n_total = _match_vma(jnp.zeros((), jnp.int32), labels)
    for axis in range(3):
        na = labels.shape[axis]
        a = lax.slice_in_dim(labels, 0, na - 1, axis=axis)
        b = lax.slice_in_dim(labels, 1, na, axis=axis)
        ha = lax.slice_in_dim(h, 0, na - 1, axis=axis)
        hb = lax.slice_in_dim(h, 1, na, axis=axis)
        saddle = _sortable_float_key(jnp.maximum(ha, hb))
        flag = (a != b) & (a != 0) & (b != 0) & ((a < 0) | (b < 0))
        dedup_axis = 2 if axis != 2 else 1
        keep = flag & (
            (a != _shift1(a, dedup_axis, 0)) | (b != _shift1(b, dedup_axis, 0))
        )
        (pa, pb, ph), kept = _compact(keep, (a, b, saddle), fill_cap, BIG)
        overflow = jnp.maximum(overflow, (kept > fill_cap).astype(jnp.int32))
        n_total = n_total + jnp.minimum(kept, fill_cap)
        evs_a.append(pa)
        evs_b.append(pb)
        evs_h.append(ph)
    a = jnp.concatenate(evs_a)
    b = jnp.concatenate(evs_b)
    hk = jnp.concatenate(evs_h)

    # Default adjacency capacity must stay well below the raw 3*fill_cap
    # candidate buffer or the dedup buys nothing, but "object-scale"
    # undershoots: the r5 512³ host rehearsal MEASURED 1.77M unique
    # adjacencies on the bench synthetic (n/85 — 80,902 unseeded basins
    # averaging ~22 distinct neighbors each, dense seeding makes small
    # basins touch many seeded labels), so the old n/128 truncated and
    # flagged the whole headline run.  n/32 gives ~2.7x headroom over
    # that measurement while staying ~11x under the raw buffer at 512³
    # (3 * fill_cap = 3 * 2^24 ≈ 50.3M vs n/32 ≈ 4.7M); the
    # DEFAULT_ADJ_CAP floor covers pure-noise small volumes.  Overflow is
    # flagged; adversarial regimes should raise adj_cap explicitly.
    if adj_cap is None:
        adj_cap = min(
            3 * fill_cap, max(DEFAULT_ADJ_CAP, labels.size // 32)
        )

    # Capacity tiering: a realistic seeded volume (few unseeded basins)
    # would pay the full 3*fill_cap dedup sort on ~all padding — the
    # common case runs the whole dedup+Boruvka machine at 1/16 size
    # (rationale + the shared threshold live in
    # tile_ccl.run_capacity_tiered).
    edge_vals, edge_finals, core_overflow = run_capacity_tiered(
        (a, b, hk), n_total, adj_cap, _fill_core, 2, max_rounds, labels
    )
    overflow = jnp.maximum(overflow, core_overflow)
    return edge_vals, edge_finals, overflow > 0


def fill_unseeded_basins_dense(
    values: jnp.ndarray,
    height: jnp.ndarray,
    max_rounds: Optional[int] = None,
    face_cap: Optional[int] = None,
):
    """Sort-free unseeded-basin fill: face-list scatter-min Boruvka rounds.

    Same MSF semantics as :func:`fill_unseeded_basins` with the saddle per
    basin pair the exact minimum over every shared face voxel (the
    capacity fill samples run-start saddles — see the ``keep`` flags
    there), and still NO SORTS anywhere.  r5 restructure: the per-axis
    basin-face candidate set is harvested ONCE into compacted lists (an
    O(n) cumsum compact, not a sort) — sound because a face can only
    LEAVE the edge set as basins merge, never join it — and every Boruvka
    round then runs face-sized gathers/scatters (~9% of voxels per axis
    on bench-like data, docs/PERFORMANCE.md "512³ capacity audit")
    instead of ~18 full-volume passes.  ``face_cap`` (default
    ``max(2^16, n/6)`` with a 2^24 ceiling) bounds each list: ≥1.8× the
    measured ~9%/axis load while n/6 governs (n ≲ 100M), narrowing to
    ~1.4× at 512³ where the int32-memory ceiling binds; regimes that
    exceed it are truncated and REPORTED through the overflow flag,
    never silent.  NOTE the
    round passes are random-access gathers/scatters, which the chip runs
    at ~165M elem/s regardless of locality — on TPU the capacity sorts
    are the predicted-fast path and the auto default picks them; the
    on-chip A/B lives in scripts/tpu_measure.py.
    Memory: three per-axis lists of five ``face_cap`` arrays plus the
    ``P``/``best`` tables — ~1.1GB transient at 512³ (below the old
    full-grid formulation's ~1.8GB).

    ``values``: >0 seeded label, <= -2 unseeded terminal code
    (``-flat_index - 2``), 0 invalid, and **-1 for masked/padded voxels**
    (what :func:`seeded_watershed_tiled` actually passes by fill time).
    -1 voxels are hookable neighbors: the edge predicate admits
    (unseeded, -1) faces and an unseeded basin whose lowest saddle
    touches one adopts -1, which the caller's final ``values > 0`` squash
    maps to background 0 — the same adopt-to-0 semantics as the capacity
    path.  Callers must NOT assume invalid voxels sit out of saddle
    competition.  Returns ``(resolved_values, overflow_int32)`` —
    per-voxel labels with every reachable unseeded basin resolved to its
    adopted seed label (unreachable basins keep their codes; callers zero
    them), overflow set when ``max_rounds`` rounds did not converge OR a
    face list truncated.

    Selected by ``fill_mode="dense"`` (``CT_FILL_MODE``), or by the
    substrate-aware ``auto`` default on the cpu backend — resolution
    happens pre-jit in :func:`_resolve_fill_mode`.
    """
    shape = values.shape
    n = int(np.prod(shape))
    v = values.ravel()
    h = _sortable_float_key(height.astype(jnp.float32)).ravel()
    i32max = jnp.iinfo(jnp.int32).max
    if face_cap is None:
        face_cap = min(1 << 24, max(1 << 16, n // 6))
    if max_rounds is None:
        max_rounds = _auto_fill_rounds(n)

    # P[g] = current label of the basin whose terminal voxel is g; codes
    # resolve through it, seeds are terminal by value
    P0 = _match_vma(-jnp.arange(n, dtype=jnp.int32) - 2, values)

    def resolve_flat(P, x):
        return jnp.where(x <= -2, P[jnp.clip(-x - 2, 0, n - 1)], x)

    # ---- one-time face harvest (round-invariant superset) ----
    # a face is a candidate edge iff the ORIGINAL codes differ, both are
    # nonzero, and at least one side is an unseeded basin; merging only
    # shrinks this set (equal-resolved faces drop out via the per-round
    # predicate), so harvesting once is exact.  eid = axis * n + voxel
    # index is globally distinct and seen identically from both sides, so
    # the min-edge graph is a forest plus 2-cycles (the classic
    # distinct-weight Boruvka argument, as in _fill_core).
    flat_idx = _match_vma(jnp.arange(n, dtype=jnp.int32), values)
    trunc = _match_vma(jnp.zeros((), jnp.int32), values)
    faces = []
    for axis in range(3):
        nb = _shift(values, -1, axis, jnp.int32(0)).ravel()
        ok0 = (
            (v != nb) & (v != 0) & (nb != 0)
            & ((v <= -2) | (nb <= -2))
        )
        (idx_c,), n_faces = _compact(ok0, (flat_idx,), face_cap, n)
        trunc = jnp.maximum(trunc, (n_faces > face_cap).astype(jnp.int32))
        stride = int(np.prod(shape[axis + 1:], dtype=np.int64))
        pad = idx_c >= n
        ia = jnp.clip(idx_c, 0, n - 1)
        ib = jnp.clip(idx_c + stride, 0, n - 1)
        va = jnp.where(pad, 0, v[ia])
        vb = jnp.where(pad, 0, v[ib])
        sad = jnp.maximum(h[ia], h[ib])
        eid = jnp.where(
            pad, i32max, jnp.int32(axis) * jnp.int32(n) + idx_c
        )
        faces.append((va, vb, sad, eid, pad))
    me_idx = _match_vma(jnp.arange(n, dtype=jnp.int32), values)

    def round_cond(s):
        _, changed, it = s
        return changed & (it < max_rounds)

    def round_body(s):
        P, _, it = s
        best_h = _match_vma(jnp.full((n,), i32max, jnp.int32), values)
        best_e = _match_vma(jnp.full((n,), i32max, jnp.int32), values)
        sides = []
        for va, vb, sad, eid, pad in faces:
            ra = resolve_flat(P, va)
            rb = resolve_flat(P, vb)
            live = ~pad & (ra != rb)
            sides.append((ra, rb, sad, live, eid))
            sides.append((rb, ra, sad, live, eid))
        for src, dst, sad, live, eid in sides:
            m = live & (src <= -2)
            g = jnp.where(m, -src - 2, n)
            best_h = best_h.at[g].min(
                jnp.where(m, sad, i32max), mode="drop"
            )
        for src, dst, sad, live, eid in sides:
            m = live & (src <= -2)
            tie = m & (best_h[jnp.clip(-src - 2, 0, n - 1)] == sad)
            gt = jnp.where(tie, -src - 2, n)
            best_e = best_e.at[gt].min(
                jnp.where(tie, eid, i32max), mode="drop"
            )
        P2 = P
        for src, dst, sad, live, eid in sides:
            m = live & (src <= -2)
            gsafe = jnp.clip(-src - 2, 0, n - 1)
            win = m & (best_h[gsafe] == sad) & (best_e[gsafe] == eid)
            gw = jnp.where(win, -src - 2, n)
            P2 = P2.at[gw].set(jnp.where(win, dst, 0), mode="drop")
        # break 2-cycles (two roots that picked the same edge from both
        # sides): the smaller terminal index stays a root
        me = me_idx
        tgt = jnp.clip(-P2 - 2, 0, n - 1)
        mutual = (P2 <= -2) & (P2[tgt] == (-me - 2)) & (me < tgt)
        P2 = jnp.where(mutual, -me - 2, P2)
        # pointer-jump to CLOSURE, not a fixed count: a partially
        # compressed table would let the next round's resolution expose
        # intermediate codes, and a non-root's re-hook would then
        # overwrite (sever) an already-contracted MSF union — the exact-
        # semantics claim depends on every round starting from true roots
        def comp_cond(t):
            _, ch = t
            return ch

        def comp_body(t):
            p, _ = t
            p2 = resolve_flat(p, p)
            return p2, jnp.any(p2 != p)

        P2, _ = lax.while_loop(comp_cond, comp_body, (P2, _true_like(P2)))
        changed = jnp.any(P2 != P)
        return P2, changed, it + 1

    P, unconverged, _ = lax.while_loop(
        round_cond, round_body, (P0, _true_like(v), jnp.int32(0))
    )
    resolved = resolve_flat(P, v).reshape(shape)
    return resolved, jnp.maximum(unconverged.astype(jnp.int32), trunc)


def _fill_core(a, b, hk, adj_cap, max_rounds, vma_like):
    """Dedup + dense ids + Boruvka rounds over one capacity tier.

    Returns ``(edge_vals, edge_finals, overflow_int32)`` with outputs
    sized ``2 * adj_cap``; ``vma_like`` carries the shard_map varying-axes
    signature for freshly created arrays.
    """
    overflow = _match_vma(jnp.zeros((), jnp.int32), vma_like)
    # dedup to unique (a, b) adjacencies with their min saddle: ascending
    # sort puts each pair's lowest saddle first and the BIG padding last
    sa, sb, sh = lax.sort((a, b, hk), num_keys=3)
    first = (sa != _shift1(sa, 0, BIG)) | (sb != _shift1(sb, 0, BIG))
    keep_adj = first & (sa < BIG)
    (a, b, hk), n_adj = _compact(keep_adj, (sa, sb, sh), adj_cap, BIG)
    overflow = jnp.maximum(overflow, (n_adj > adj_cap).astype(jnp.int32))

    # dense ids over all endpoint values
    m2 = a.shape[0] * 2
    vals = jnp.concatenate([a, b])
    slots = jnp.arange(m2, dtype=jnp.int32)
    sv, ss = lax.sort((vals, slots), num_keys=1)
    is_new = sv != _shift1(sv, 0, -BIG)
    rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    uniq = jnp.full((m2,), jnp.int32(BIG)).at[rank].set(sv)
    dense = jnp.zeros((m2,), jnp.int32).at[ss].set(rank)
    da, db = dense[: a.shape[0]], dense[a.shape[0]:]
    edge_pad = a >= BIG

    parent = _match_vma(jnp.arange(m2, dtype=jnp.int32), vma_like)

    def round_cond(s):
        _, changed, it = s
        return changed & (it < max_rounds)

    eid = jnp.arange(a.shape[0], dtype=jnp.int32)
    # composite weight (saddle, edge_id): globally distinct and seen
    # identically from both endpoints, so the min-edge graph is a forest
    # plus 2-cycles only (the classic Boruvka distinct-weight argument) —
    # ties on raw saddle height cannot form longer hook cycles.  The
    # lexicographic min per root is computed as TWO int32 scatter-mins
    # (saddle, then edge-id among saddle ties) instead of a 4-array sort:
    # a full sort is ~10x the cost of a gather/scatter pass on the TPU
    # (docs/PERFORMANCE.md "Where the time goes"), so each Boruvka round
    # drops from sort-bound to a handful of gather-class passes.

    def round_body(s):
        P, _, it = s
        ra = P[da]
        rb = P[db]
        alive = (ra != rb) & (~edge_pad)
        # orient every edge both ways; only negative-valued roots hook
        live_a = alive & (uniq[ra] <= -2)
        live_b = alive & (uniq[rb] <= -2)
        np_ = P.shape[0]
        # init with int32 max, NOT BIG: sortable keys of saddles >= 2.0
        # exceed 2^30 and must still win the scatter-min
        i32max = jnp.iinfo(jnp.int32).max
        best_h = jnp.full((np_,), jnp.int32(i32max))
        best_h = best_h.at[jnp.where(live_a, ra, np_)].min(hk, mode="drop")
        best_h = best_h.at[jnp.where(live_b, rb, np_)].min(hk, mode="drop")
        tie_a = live_a & (best_h[ra] == hk)
        tie_b = live_b & (best_h[rb] == hk)
        best_e = jnp.full((np_,), jnp.int32(i32max))
        best_e = best_e.at[jnp.where(tie_a, ra, np_)].min(eid, mode="drop")
        best_e = best_e.at[jnp.where(tie_b, rb, np_)].min(eid, mode="drop")
        # per root exactly one (edge, side) attains the lexicographic min —
        # except the two sides of ONE edge when both its roots pick it,
        # which is precisely the 2-cycle the break below resolves
        win_a = tie_a & (best_e[ra] == eid)
        win_b = tie_b & (best_e[rb] == eid)
        parent2 = jnp.arange(np_, dtype=jnp.int32)
        parent2 = parent2.at[jnp.where(win_a, ra, np_)].set(
            jnp.where(win_a, rb, 0), mode="drop"
        )
        parent2 = parent2.at[jnp.where(win_b, rb, np_)].set(
            jnp.where(win_b, ra, 0), mode="drop"
        )
        # break 2-cycles: the lower id stays a root
        pp = parent2[parent2]
        me = jnp.arange(np_, dtype=jnp.int32)
        parent2 = jnp.where((pp == me) & (me < parent2), me, parent2)
        # jump to CLOSURE, not a fixed count: a round's hook forest can
        # chain arbitrarily many roots (monotone saddle runs), and a
        # partially-composed P would let the next round hook from
        # intermediate nodes — splitting one component's members across
        # different final seeds.  P stays closed inductively: P0 is the
        # identity, and composing a closed P through a closed parent2
        # yields true roots only.
        def comp_cond(t):
            _, ch = t
            return ch

        def comp_body(t):
            p, _ = t
            p2 = p[p]
            return p2, jnp.any(p2 != p)

        parent2, _ = lax.while_loop(
            comp_cond, comp_body, (parent2, _true_like(parent2))
        )
        newP = parent2[P]
        return newP, jnp.any(newP != P), it + 1

    parent, unconverged, _ = lax.while_loop(
        round_cond, round_body, (parent, _true_like(da), jnp.int32(0))
    )
    # a max_rounds exit leaves basins mid-chain: report, never hide
    overflow = jnp.maximum(overflow, unconverged.astype(jnp.int32))

    root_val = uniq[parent]
    final_of = jnp.where(root_val > 0, root_val, 0)
    # remap for every unseeded endpoint value
    edge_vals = uniq
    edge_finals = jnp.where(uniq <= -2, final_of, uniq)
    return edge_vals, edge_finals, overflow


def seeded_watershed_tiled(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    exit_cap: Optional[int] = None,
    fill_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    adj_cap: Optional[int] = None,
    fill_rounds: Optional[int] = None,
    fill_mode: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Seeded watershed with the two-level tile machinery.

    Contract matches :func:`~cluster_tools_tpu.ops.watershed.seeded_watershed`
    (labels int32, 0 outside mask / unreachable) up to unseeded-basin fill
    order: unseeded basins take the label across their lowest saddle
    (minimum-spanning-forest watershed) rather than ring-growing.  Returns
    ``(labels, overflow)``.

    Sparse-seed / noise-heavy regimes (many unseeded basins) may overflow
    the fill capacities or need more than ``fill_rounds`` Boruvka rounds
    (a round at least halves the unseeded component count; the ``None``
    default resolves to ``max(16, ceil(log2(n)) + 1)`` — sufficient for
    ANY basin count, see :func:`_auto_fill_rounds`); the overflow flag
    reports capacity truncation and ``adj_cap`` is the knob to raise.

    ``fill_mode``: ``dense``/``capacity``/``None`` (= ``CT_FILL_MODE``,
    default substrate-aware ``auto`` — see :func:`_resolve_fill_mode`).
    Mode env vars are resolved HERE, outside jit, so flipping one
    mid-process retraces instead of reusing a stale cache entry.
    """
    return _seeded_watershed_tiled_jit(
        height, seeds, mask, impl=impl, tile=tile, exit_cap=exit_cap,
        fill_cap=fill_cap, table_cap=table_cap, interpret=interpret,
        adj_cap=adj_cap, fill_rounds=fill_rounds,
        fill_mode=_resolve_fill_mode(fill_mode), _tier=tier_mode(),
    )


@partial(
    jax.jit,
    static_argnames=(
        "impl", "tile", "exit_cap", "fill_cap", "table_cap", "interpret",
        "adj_cap", "fill_rounds", "fill_mode", "_tier",
    ),
)
def _seeded_watershed_tiled_jit(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    exit_cap: Optional[int] = None,
    fill_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    adj_cap: Optional[int] = None,
    fill_rounds: Optional[int] = None,
    fill_mode: str = "capacity",
    _tier: str = "cond",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    # _tier is keying-only (the tiered sites read tier_mode() at trace time;
    # the static arg pins the cache entry to the resolved value).
    # The body is flow-phase + fill-phase cores so the split execution mode
    # (parallel/split_pipeline.py) can jit each phase as its OWN program —
    # composing them here compiles the identical fused program.
    values, h, flow_overflow = _ws_flow_core(
        height, seeds, mask, impl=impl, tile=tile, exit_cap=exit_cap,
        table_cap=table_cap, interpret=interpret,
    )
    out, fill_overflow = _ws_fill_core(
        values, h, height.shape, impl=impl, tile=tile, exit_cap=exit_cap,
        fill_cap=fill_cap, table_cap=table_cap, interpret=interpret,
        adj_cap=adj_cap, fill_rounds=fill_rounds, fill_mode=fill_mode,
    )
    return out, flow_overflow | fill_overflow


def _resolve_impl(impl: str) -> str:
    return ("pallas" if jax.default_backend() == "tpu" else "xla") \
        if impl == "auto" else impl


def _ws_static_plan(shape, tile, exit_cap, fill_cap):
    """Tile/padded geometry + capacity defaults, shared by the fused program
    and the split-phase programs so both compile identical caps."""
    z, y, x = shape
    tile = _tile_for(shape) if tile is None else tile
    tz, ty, tx = tile
    zp, yp, xp = _round_up(z, tz), _round_up(y, ty), _round_up(x, tx)
    if zp * yp * xp >= BIG:
        raise ValueError(
            f"padded volume {(zp, yp, xp)} has >= 2**30 voxels; shard it"
        )
    n_pad = zp * yp * xp
    if exit_cap is None:
        # n/3 >= the total strip voxel count for the default tile, so exits
        # can never overflow below ~6M voxels.  ABOVE that the loads keep
        # scaling with the volume (measured on bench-like box-filtered
        # noise, fractions size-constant 96³→160³ and smoothing-
        # insensitive: exit candidates ~8% of voxels SUMMED over the six
        # strip families — docs/PERFORMANCE.md "512³ capacity audit"), so
        # the old 2^21 ceiling would truncate a 512³ run by ~6x.  The
        # overflow check is PER FAMILY (each compact is capped separately);
        # the largest family carries ~2.5% of voxels, so n/12 leaves ~3x
        # per-family headroom up to the 2^24 ceiling (int32 buffers,
        # ~600MB transient at 512³).  The ~8% total only picks the
        # capacity TIER, never the flag.
        exit_cap = min(
            1 << 24, max(_auto_cap(n_pad, DEFAULT_EXIT_CAP, 3), n_pad // 12)
        )
    if fill_cap is None:
        # fill edges can reach ~n/2 per axis in pure-noise/sparse-seed
        # regimes (overflow-flagged); the proportional floor covers the
        # measured ~9%-per-axis bench-like load with ~2.5x margin
        fill_cap = min(
            1 << 24, max(_auto_cap(n_pad, DEFAULT_FILL_CAP, 1), n_pad // 8)
        )
    return tile, (zp, yp, xp), exit_cap, fill_cap


def _ws_flow_core(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    *,
    impl: str,
    tile: Optional[Tuple[int, int, int]],
    exit_cap: Optional[int],
    table_cap: int,
    interpret: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Flow phase: tile-pad, descent directions, in-tile flow, exit chase +
    remap.  Returns ``(values, h, overflow)`` at TILE-PADDED shape: >0
    seeded label, <= -2 unseeded terminal code, -1 masked/padded, plus the
    padded float32 heights the fill phase needs."""
    if height.ndim != 3:
        raise ValueError("seeded_watershed_tiled expects a 3-D volume")
    impl = _resolve_impl(impl)
    z, y, x = height.shape
    tile, (zp, yp, xp), exit_cap, _ = _ws_static_plan(
        height.shape, tile, exit_cap, 0
    )
    tz, ty, tx = tile
    padded = (zp != z) or (yp != y) or (xp != x)
    valid = jnp.ones(height.shape, bool) if mask is None else mask.astype(bool)
    h = height.astype(jnp.float32)
    s = seeds.astype(jnp.int32)
    if padded:
        pads = ((0, zp - z), (0, yp - y), (0, xp - x))
        h = jnp.pad(h, pads, constant_values=_BIGF)
        s = jnp.pad(s, pads)
        valid = jnp.pad(valid, pads)

    dirs = descent_directions(h, s > 0, valid)
    sv = jnp.where(valid, s, -1)

    if impl == "pallas":
        from .pallas_kernels import apply_remap_pallas, tile_ws_propagate_pallas

        values = tile_ws_propagate_pallas(dirs, sv, tile=tile, interpret=interpret)
    else:
        values = tile_ws_propagate_xla(dirs, sv, tile)

    # cross-tile exits: collect, chase, remap
    codes, code_tiles, overflow = collect_negative_values(values, tile, exit_cap)
    finals, chase_unconverged = chase_exits(values, codes)
    overflow = overflow | chase_unconverged
    n_tiles = (zp // tz) * (yp // ty) * (xp // tx)

    if impl == "pallas":
        changed = (codes <= -2) & (finals != codes)
        tids = jnp.where(changed, code_tiles, jnp.int32(BIG))
        old_tbl, new_tbl, tbl_overflow = build_remap_tables(
            tids, codes, finals, n_tiles, table_cap=table_cap
        )

        def fast(args):
            v, o, nw = args
            return apply_remap_pallas(
                v, o, nw, tile=tile, cap=table_cap, interpret=interpret
            )

        def slow(args):
            v, _, _ = args
            return _resolve_codes_gather(v, codes, finals)

        values = lax.cond(tbl_overflow, slow, fast, (values, old_tbl, new_tbl))
    else:
        values = _resolve_codes_gather(values, codes, finals)
    return values, h, overflow


def _ws_fill_core(
    values: jnp.ndarray,
    h: jnp.ndarray,
    orig_shape: Tuple[int, int, int],
    *,
    impl: str,
    tile: Optional[Tuple[int, int, int]],
    exit_cap: Optional[int],
    fill_cap: Optional[int],
    table_cap: int,
    interpret: bool,
    adj_cap: Optional[int],
    fill_rounds: Optional[int],
    fill_mode: str,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fill phase: unseeded-basin fill across lowest saddles (fill_mode
    selects the machinery — see :func:`_resolve_fill_mode`), remap, squash
    leftovers to 0, crop the tile padding back to ``orig_shape``."""
    impl = _resolve_impl(impl)
    z, y, x = orig_shape
    tile, (zp, yp, xp), exit_cap, fill_cap = _ws_static_plan(
        orig_shape, tile, exit_cap, fill_cap
    )
    tz, ty, tx = tile
    padded = (zp != z) or (yp != y) or (xp != x)
    if values.shape != (zp, yp, xp):
        raise ValueError(
            f"fill phase expects tile-padded values {(zp, yp, xp)}, "
            f"got {values.shape}"
        )
    if fill_rounds is None:
        fill_rounds = _auto_fill_rounds(zp * yp * xp)
    if fill_mode == "dense":
        values, fill_unconv = fill_unseeded_basins_dense(
            values, h, max_rounds=fill_rounds
        )
        overflow = fill_unconv > 0
        out = jnp.where(values > 0, values, 0).astype(jnp.int32)
        if padded:
            out = out[:z, :y, :x]
        return out, overflow
    fill_vals, fill_finals, overflow = fill_unseeded_basins(
        values, h, fill_cap=fill_cap, max_rounds=fill_rounds, adj_cap=adj_cap
    )
    n_tiles = (zp // tz) * (yp // ty) * (xp // tx)

    if impl == "pallas":
        from .pallas_kernels import apply_remap_pallas

        # tiles needing a basin's entry: strip incidences + the terminal's tile
        bvals, btiles, b_overflow = collect_negative_values(values, tile, exit_cap)
        overflow = overflow | b_overflow
        # map each (value, tile) incidence to its fill final
        bfin = value_join(bvals, fill_vals, fill_finals)
        # terminal-tile incidences for interior basins
        tvals = fill_vals
        t_of = _tile_id_of(jnp.where(tvals <= -2, -tvals - 2, 0), (zp, yp, xp), tile)
        ttiles = jnp.where(tvals <= -2, t_of, jnp.int32(BIG))
        all_vals = jnp.concatenate([bvals, tvals])
        all_fin = jnp.concatenate([bfin, jnp.where(tvals <= -2, fill_finals, tvals)])
        all_tiles = jnp.concatenate(
            [jnp.where((bvals <= -2) & (bfin != bvals), btiles, jnp.int32(BIG)),
             jnp.where((tvals <= -2) & (fill_finals != tvals), ttiles, jnp.int32(BIG))]
        )
        old2, new2, tbl_overflow2 = build_remap_tables(
            all_tiles, all_vals, all_fin, n_tiles, table_cap=table_cap
        )

        def fast2(args):
            v, o, nw = args
            return apply_remap_pallas(
                v, o, nw, tile=tile, cap=table_cap, interpret=interpret
            )

        def slow2(args):
            v, _, _ = args
            return _resolve_codes_gather(v, fill_vals, fill_finals)

        values = lax.cond(tbl_overflow2, slow2, fast2, (values, old2, new2))
    else:
        values = _resolve_codes_gather(values, fill_vals, fill_finals)

    # leftover negatives (basins with no seeded reachable neighbor) -> 0
    out = jnp.where(values > 0, values, 0).astype(jnp.int32)
    if padded:
        out = out[:z, :y, :x]
    return out, overflow


def _dt_seeds_core(
    boundaries: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    dist: Optional[jnp.ndarray],
    *,
    threshold: float,
    sigma_seeds: float,
    min_seed_distance: float,
    sampling,
    dt_max_distance: Optional[float],
    impl: str,
    tile,
    pair_cap: Optional[int],
    edge_cap: Optional[int],
    table_cap: int,
    interpret: bool,
    seed_cap: Optional[int],
    seed_mode: str,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Seed phase of the DT watershed: threshold -> (capped) EDT -> optional
    smoothing -> maxima plateaus -> seed CCL.  Returns ``(seeds, valid,
    overflow)`` at the input shape — the split execution mode
    (parallel/split_pipeline.py) jits this as its own program; the fused
    ``dt_watershed_tiled`` inlines it."""
    from .edt import distance_transform_squared
    from .filters import gaussian_smooth
    from .watershed import local_maxima

    valid = jnp.ones(boundaries.shape, bool) if mask is None else mask.astype(bool)
    fg = (boundaries < threshold) & valid
    if dist is None:
        # "xla" must stay Mosaic-free end-to-end; other modes let the EDT
        # pick its own fast path ("pallas" lacks an interpret plumb, so not
        # forwarded)
        dist = distance_transform_squared(
            fg, sampling=sampling, max_distance=dt_max_distance,
            impl="xla" if impl == "xla" else "auto",
        )
    else:
        # caller-supplied squared distances (e.g. the mesh-exact transform
        # from parallel.distributed_edt); zero them outside the foreground
        # so seed maxima stay inside basins
        dist = jnp.where(fg, dist.astype(jnp.float32), 0.0)
    if sigma_seeds > 0:
        dist = gaussian_smooth(dist, sigma_seeds, sampling=sampling)
    maxima = (
        local_maxima(dist, 1)
        & fg
        & (dist >= min_seed_distance * min_seed_distance)
    )
    raw, seed_overflow = _seed_ccl(
        maxima, seed_cap, mode=seed_mode, impl=impl, tile=tile,
        pair_cap=pair_cap, edge_cap=edge_cap, table_cap=table_cap,
        interpret=interpret,
    )
    n = int(np.prod(boundaries.shape))
    seeds = jnp.where(raw == n, 0, raw + 1).astype(jnp.int32)
    return seeds, valid, seed_overflow


def _seed_ccl(maxima, seed_cap, *, mode, impl, tile, pair_cap, edge_cap,
              table_cap, interpret):
    """Label seed plateaus: ``mode`` picks the program.

    - ``tiled`` (the API default): the full two-level CCL machinery —
      exact for any maxima density.
    - ``sparse``: :func:`~.tile_ccl.label_components_sparse` — ~1/10 the
      compiled program (the single biggest compile-size lever in the
      fused step, see docs/PERFORMANCE.md "program-size analysis");
      exact while maxima fit ``seed_cap`` (default volume/16 — bench-like
      volumes measure ~1.4% at ``min_seed_distance=2``), overflow-flagged
      beyond.

    ``mode`` is a static argument resolved from ``CT_SEED_CCL`` by the
    public entry points (:func:`_resolve_seed_mode`), never read from the
    environment here.
    """
    if mode == "sparse":
        from .tile_ccl import label_components_sparse

        return label_components_sparse(maxima, cap=seed_cap)
    from .tile_ccl import label_components_tiled

    return label_components_tiled(
        maxima, impl=impl, tile=tile, pair_cap=pair_cap, edge_cap=edge_cap,
        table_cap=table_cap, interpret=interpret,
    )


def dt_watershed_tiled(
    boundaries: jnp.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 0.0,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
    mask: Optional[jnp.ndarray] = None,
    dist: Optional[jnp.ndarray] = None,
    dt_max_distance: Optional[float] = None,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    pair_cap: Optional[int] = None,
    edge_cap: Optional[int] = None,
    exit_cap: Optional[int] = None,
    fill_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    seed_cap: Optional[int] = None,
    adj_cap: Optional[int] = None,
    fill_rounds: Optional[int] = None,
    fill_mode: Optional[str] = None,
    seed_mode: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused distance-transform watershed on the two-level machinery.

    The same pipeline as
    :func:`~cluster_tools_tpu.ops.watershed.distance_transform_watershed`
    (threshold -> capped EDT -> seeds = CCL of DT maxima plateaus -> seeded
    watershed; reference ``_ws_block``, SURVEY.md §2a "watershed") with the
    seed CCL and the flood running on the tiled kernels.  3-D only,
    connectivity 1.  Returns ``(labels, overflow)``; labels are
    ``seed_rep + 1`` flat-index based, 0 outside mask/unreached.

    ``dist``: optional precomputed *squared* distances (e.g. the mesh-exact
    transform from :mod:`cluster_tools_tpu.parallel.distributed_edt`); when
    given, the internal EDT (and ``dt_max_distance``) is skipped.

    ``fill_mode`` / ``seed_mode``: explicit machinery selection; ``None``
    resolves ``CT_FILL_MODE`` / ``CT_SEED_CCL`` here, OUTSIDE jit, so the
    env values are part of the compile key (see :func:`_resolve_fill_mode`).
    """
    return _dt_watershed_tiled_jit(
        boundaries, threshold=threshold, sigma_seeds=sigma_seeds,
        min_seed_distance=min_seed_distance, sampling=sampling, mask=mask,
        dist=dist, dt_max_distance=dt_max_distance, impl=impl, tile=tile,
        pair_cap=pair_cap, edge_cap=edge_cap, exit_cap=exit_cap,
        fill_cap=fill_cap, table_cap=table_cap, interpret=interpret,
        seed_cap=seed_cap, adj_cap=adj_cap, fill_rounds=fill_rounds,
        fill_mode=_resolve_fill_mode(fill_mode),
        seed_mode=_resolve_seed_mode(seed_mode), _tier=tier_mode(),
    )


@partial(
    jax.jit,
    static_argnames=(
        "threshold", "sigma_seeds", "min_seed_distance", "sampling",
        "dt_max_distance", "impl", "tile", "pair_cap", "edge_cap",
        "exit_cap", "fill_cap", "table_cap", "interpret", "seed_cap",
        "adj_cap", "fill_rounds", "fill_mode", "seed_mode", "_tier",
    ),
)
def _dt_watershed_tiled_jit(
    boundaries: jnp.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 0.0,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
    mask: Optional[jnp.ndarray] = None,
    dist: Optional[jnp.ndarray] = None,
    dt_max_distance: Optional[float] = None,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    pair_cap: Optional[int] = None,
    edge_cap: Optional[int] = None,
    exit_cap: Optional[int] = None,
    fill_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    seed_cap: Optional[int] = None,
    adj_cap: Optional[int] = None,
    fill_rounds: Optional[int] = None,
    fill_mode: str = "capacity",
    seed_mode: str = "tiled",
    _tier: str = "cond",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    seeds, valid, seed_overflow = _dt_seeds_core(
        boundaries, mask, dist, threshold=threshold, sigma_seeds=sigma_seeds,
        min_seed_distance=min_seed_distance, sampling=sampling,
        dt_max_distance=dt_max_distance, impl=impl, tile=tile,
        pair_cap=pair_cap, edge_cap=edge_cap, table_cap=table_cap,
        interpret=interpret, seed_cap=seed_cap, seed_mode=seed_mode,
    )
    labels, ws_overflow = _seeded_watershed_tiled_jit(
        boundaries, seeds, mask=valid, impl=impl, tile=tile,
        exit_cap=exit_cap, fill_cap=fill_cap, table_cap=table_cap,
        interpret=interpret, adj_cap=adj_cap, fill_rounds=fill_rounds,
        fill_mode=fill_mode, _tier=_tier,
    )
    return labels, seed_overflow | ws_overflow


def dt_watershed_seeded_tiled(
    boundaries: jnp.ndarray,
    ext_seeds: jnp.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 0.0,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
    mask: Optional[jnp.ndarray] = None,
    dt_max_distance: Optional[float] = None,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    pair_cap: Optional[int] = None,
    edge_cap: Optional[int] = None,
    exit_cap: Optional[int] = None,
    fill_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    seed_cap: Optional[int] = None,
    adj_cap: Optional[int] = None,
    fill_rounds: Optional[int] = None,
    fill_mode: Optional[str] = None,
    seed_mode: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-pass-mode DT watershed on the tiled machinery.

    Same contract as
    :func:`~cluster_tools_tpu.ops.watershed.dt_watershed_seeded`
    (checkerboard pass two, SURVEY.md §3.5): ``ext_seeds`` (int32, dense
    1..K, 0 = none) are neighbor labels from pass one; internal DT seeds are
    planted where no external seed sits.  Output values > N are external
    (+N offset, N = voxel count); 1..N are new internal fragments.  Returns
    ``(labels, overflow)``.

    ``fill_mode`` / ``seed_mode`` as in :func:`dt_watershed_tiled` —
    resolved pre-jit so the env values join the compile key.
    """
    return _dt_watershed_seeded_tiled_jit(
        boundaries, ext_seeds, threshold=threshold, sigma_seeds=sigma_seeds,
        min_seed_distance=min_seed_distance, sampling=sampling, mask=mask,
        dt_max_distance=dt_max_distance, impl=impl, tile=tile,
        pair_cap=pair_cap, edge_cap=edge_cap, exit_cap=exit_cap,
        fill_cap=fill_cap, table_cap=table_cap, interpret=interpret,
        seed_cap=seed_cap, adj_cap=adj_cap, fill_rounds=fill_rounds,
        fill_mode=_resolve_fill_mode(fill_mode),
        seed_mode=_resolve_seed_mode(seed_mode), _tier=tier_mode(),
    )


@partial(
    jax.jit,
    static_argnames=(
        "threshold", "sigma_seeds", "min_seed_distance", "sampling",
        "dt_max_distance", "impl", "tile", "pair_cap", "edge_cap",
        "exit_cap", "fill_cap", "table_cap", "interpret", "seed_cap",
        "adj_cap", "fill_rounds", "fill_mode", "seed_mode", "_tier",
    ),
)
def _dt_watershed_seeded_tiled_jit(
    boundaries: jnp.ndarray,
    ext_seeds: jnp.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 0.0,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
    mask: Optional[jnp.ndarray] = None,
    dt_max_distance: Optional[float] = None,
    impl: str = "auto",
    tile: Optional[Tuple[int, int, int]] = None,
    pair_cap: Optional[int] = None,
    edge_cap: Optional[int] = None,
    exit_cap: Optional[int] = None,
    fill_cap: Optional[int] = None,
    table_cap: int = DEFAULT_TABLE_CAP,
    interpret: bool = False,
    seed_cap: Optional[int] = None,
    adj_cap: Optional[int] = None,
    fill_rounds: Optional[int] = None,
    fill_mode: str = "capacity",
    seed_mode: str = "tiled",
    _tier: str = "cond",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    n = int(np.prod(boundaries.shape))
    internal, valid, seed_overflow = _dt_seeds_core(
        boundaries, mask, None, threshold=threshold, sigma_seeds=sigma_seeds,
        min_seed_distance=min_seed_distance, sampling=sampling,
        dt_max_distance=dt_max_distance, impl=impl, tile=tile,
        pair_cap=pair_cap, edge_cap=edge_cap, table_cap=table_cap,
        interpret=interpret, seed_cap=seed_cap, seed_mode=seed_mode,
    )
    ext = ext_seeds.astype(jnp.int32)
    # external seeds dominate; internal ids live in 1..N, external in N+1..
    seeds = jnp.where(ext > 0, ext + jnp.int32(n), internal)
    labels, ws_overflow = _seeded_watershed_tiled_jit(
        boundaries, seeds, mask=valid, impl=impl, tile=tile,
        exit_cap=exit_cap, fill_cap=fill_cap, table_cap=table_cap,
        interpret=interpret, adj_cap=adj_cap, fill_rounds=fill_rounds,
        fill_mode=fill_mode, _tier=_tier,
    )
    return labels, seed_overflow | ws_overflow
