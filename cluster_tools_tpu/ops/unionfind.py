"""Union-find over label-equivalence pairs, as a device fixpoint iteration.

The reference's global label merge ran ``nifty.ufd`` (serial C++ union-find)
in a single merge job — its named scalability cliff (SURVEY.md §3.2).  On TPU
the same merge is a dense pointer-jumping iteration over the whole label
table, so it parallelizes over the vector unit and, across hosts, the
equivalence pairs are all-gathered over ICI before one replicated solve:

  repeat until stable:
    parent <- path-compress(parent)              (pointer jumping)
    for each pair (u, v): parent[max-root] min= min-root   (scatter-min hook)

Everything is static-shape; the data-dependent iteration count lives in
``lax.while_loop``.  A numpy/scipy host implementation is provided for the
driver path and as the test oracle.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ccl import _match_vma, _true_like


@partial(jax.jit, static_argnames=("n_labels",))
def union_find(pairs: jnp.ndarray, n_labels: int) -> jnp.ndarray:
    """Resolve equivalence ``pairs`` (int32 [m, 2]) over labels [0, n_labels).

    Returns ``parent`` of shape [n_labels] mapping every label to its
    component representative (the component's minimum label).  Invalid pairs
    may be encoded as ``(i, i)`` self-loops (no-ops) — useful for padding to
    static shapes.
    """
    n = int(n_labels)
    parent = _match_vma(jnp.arange(n, dtype=jnp.int32), pairs)
    # out-of-range endpoints (e.g. -1 padding) turn the whole pair into a
    # (0, 0) self-loop no-op rather than being clipped into a real label
    u, v = pairs[:, 0], pairs[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n)
    u = jnp.where(valid, u, 0)
    v = jnp.where(valid, v, 0)

    def compress(p):
        def cond(s):
            f, changed = s
            return changed

        def body(s):
            f, _ = s
            f2 = f[f]
            return f2, jnp.any(f2 != f)

        p, _ = lax.while_loop(cond, body, (p, _true_like(p)))
        return p

    def cond(state):
        p, changed = state
        return changed

    def body(state):
        p, _ = state
        ru = p[u]
        rv = p[v]
        lo = jnp.minimum(ru, rv)
        hi = jnp.maximum(ru, rv)
        p2 = p.at[hi].min(lo)
        p2 = compress(p2)
        return p2, jnp.any(p2 != p)

    parent, _ = lax.while_loop(cond, body, (parent, _true_like(parent)))
    return parent


def union_find_host(pairs: np.ndarray, n_labels: int) -> np.ndarray:
    """Host-side driver path: the native C++ union-find when built
    (cluster_tools_tpu/native.py), else scipy sparse connected components.

    Returns the same contract as :func:`union_find`: each label mapped to the
    minimum label of its component.
    """
    pairs = np.asarray(pairs)
    if pairs.size == 0:
        return np.arange(n_labels, dtype=np.int64)

    from .. import native

    roots = native.union_find(pairs.astype(np.int64, copy=False), n_labels)
    if roots is not None:
        return roots

    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components
    data = np.ones(len(pairs), dtype=np.uint8)
    g = coo_matrix(
        (data, (pairs[:, 0], pairs[:, 1])), shape=(n_labels, n_labels)
    )
    _, comp = connected_components(g, directed=False)
    # map each component id -> min label in it
    order = np.argsort(comp, kind="stable")
    comp_sorted = comp[order]
    first = np.ones(len(order), dtype=bool)
    first[1:] = comp_sorted[1:] != comp_sorted[:-1]
    comp_min = np.zeros(comp.max() + 1, dtype=np.int64)
    comp_min[comp_sorted[first]] = order[first]
    # order is sorted by comp then label index ascending, so first occurrence
    # per component is its minimum label
    return comp_min[comp]


@partial(jax.jit, static_argnames=("n_labels",))
def apply_assignment(labels: jnp.ndarray, assignment: jnp.ndarray, n_labels: int):
    """Relabel a block through an assignment table (reference: ``write`` task)."""
    flat = jnp.clip(labels, 0, n_labels - 1)
    return assignment[flat].astype(labels.dtype)
