"""Seeded watershed as a dense steepest-descent + pointer-jumping kernel.

The reference ran ``vigra.analysis.watershedsNew`` (C++; its default "turbo"
algorithm is a union-find/steepest-descent watershed) per block with halo
(SURVEY.md §2a "watershed", §3.1).  The TPU redesign computes the same basin
decomposition with dense, fixed-shape steps:

1. **descent pointers**: every voxel points at the lexicographic minimum of
   ``(height, flat_index)`` over its closed neighborhood — the index tiebreak
   makes the pointer graph acyclic on plateaus; seeds and masked-out voxels
   point at themselves,
2. **resolve**: pointer-jumping ``ptr = ptr[ptr]`` to fixpoint — every voxel
   reaches the self-loop (seed or basin minimum) its steepest path drains to,
3. **fill**: basins whose minimum is not a seed (shallow minima that didn't
   clear the seed threshold) are absorbed by iteratively letting unlabeled
   voxels adopt the label of their lowest labeled neighbor (region growing
   ordered by height, a dense relaxation of priority-flood).

All three are shift/gather iterations in ``lax.while_loop`` — one compiled
program, vmappable over a block batch.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ccl import _shift_nd, _neighbor_offsets, _compress, _true_like, label_components, finalize_labels

_BIG = np.float32(3e38)  # numpy: no backend init at import


def _descent_pointers(
    height: jnp.ndarray,
    is_seed: jnp.ndarray,
    valid: jnp.ndarray,
    connectivity: int,
) -> jnp.ndarray:
    """Flat index of the lex-min (height, index) closed-neighborhood element."""
    shape = height.shape
    n = int(np.prod(shape))
    idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)
    h = jnp.where(valid, height, _BIG)

    best_h = h
    best_i = idx
    for off in _neighbor_offsets(len(shape), connectivity):
        for o in (off, tuple(-x for x in off)):
            nh = _shift_nd(h, o, _BIG)
            ni = _shift_nd(idx, o, jnp.int32(n))
            better = (nh < best_h) | ((nh == best_h) & (ni < best_i))
            best_h = jnp.where(better, nh, best_h)
            best_i = jnp.where(better, ni, best_i)
    ptr = jnp.where(is_seed | ~valid, idx, best_i)
    return ptr.ravel()


@partial(jax.jit, static_argnames=("connectivity",))
def seeded_watershed(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
) -> jnp.ndarray:
    """Grow ``seeds`` (int32, 0 = unlabeled) over ``height`` basins.

    Returns int32 labels, 0 only outside ``mask`` (if given) or in regions
    unreachable from any seed.  Matches steepest-descent watershed semantics
    (vigra's default) up to the deterministic (height, index) plateau
    tiebreak.

    Caveat: the unseeded-basin fill below is an unordered relaxation — an
    unseeded basin adopts whatever labeled neighbor reaches it first, which
    can cross a *higher* ridge than the basin's true lowest saddle (measured
    on synthetic EM: ~35% fragment impurity vs ~6.5% for the saddle-ordered
    fill).  :func:`cluster_tools_tpu.ops.tile_ws.seeded_watershed_tiled`
    implements the height-ordered (minimum-spanning-forest) fill and is the
    default task/pipeline kernel; this function remains for 2-D mode,
    connectivity > 1, and as the fully-seeded oracle.
    """
    shape = height.shape
    n = int(np.prod(shape))
    valid = (
        jnp.ones(shape, bool) if mask is None else mask.astype(bool)
    )
    is_seed = (seeds > 0) & valid
    ptr = _descent_pointers(height.astype(jnp.float32), is_seed, valid, connectivity)
    ptr = _compress(ptr, jnp.int32(n))
    lab = seeds.ravel()[jnp.clip(ptr, 0, n - 1)].astype(jnp.int32)
    lab = jnp.where(valid.ravel(), lab, 0)

    # fill unseeded basins: unlabeled voxels adopt the label of their lowest
    # labeled neighbor, iterated to fixpoint
    h = jnp.where(valid, height.astype(jnp.float32), _BIG)
    offsets = []
    for off in _neighbor_offsets(len(shape), connectivity):
        offsets.append(off)
        offsets.append(tuple(-x for x in off))

    def fill_cond(state):
        lab, changed = state
        return changed

    def fill_body(state):
        lab, _ = state
        lab3 = lab.reshape(shape)
        best_h = jnp.full(shape, _BIG)
        best_l = jnp.zeros(shape, jnp.int32)
        for off in offsets:
            nh = _shift_nd(h, off, _BIG)
            nl = _shift_nd(lab3, off, jnp.int32(0))
            cand = nl > 0
            better = cand & (nh < best_h)
            best_h = jnp.where(better, nh, best_h)
            best_l = jnp.where(better, nl, best_l)
        take = (lab3 == 0) & valid & (best_l > 0)
        new = jnp.where(take, best_l, lab3).ravel()
        return new, jnp.any(new != lab)

    lab, _ = lax.while_loop(fill_cond, fill_body, (lab, _true_like(lab)))
    return lab.reshape(shape)


@partial(jax.jit, static_argnames=("connectivity",))
def local_maxima(x: jnp.ndarray, connectivity: int = 1) -> jnp.ndarray:
    """Boolean mask of (plateau) local maxima: x >= all neighbors."""
    shape = x.shape
    m = jnp.ones(shape, bool)
    neg_big = jnp.float32(-3e38)
    xf = x.astype(jnp.float32)
    for off in _neighbor_offsets(len(shape), connectivity):
        for o in (off, tuple(-x_ for x_ in off)):
            m &= xf >= _shift_nd(xf, o, neg_big)
    return m


@partial(
    jax.jit,
    static_argnames=(
        "sigma_seeds", "connectivity", "sampling", "two_d", "dt_max_distance"
    ),
)
def distance_transform_watershed(
    boundaries: jnp.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 0.0,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    two_d: bool = False,
    dt_max_distance: Optional[float] = None,
) -> jnp.ndarray:
    """Fused per-block distance-transform watershed (the flagship kernel).

    One compiled program reproducing the reference's ``_ws_block`` pipeline
    (SURVEY.md §2a "watershed": threshold -> vigra DT -> seeds = labeled DT
    maxima -> ``vigra.watershedsNew`` on the boundary map), redesigned as
    dense XLA steps:

        fg    = boundaries < threshold          (non-boundary region)
        dist  = separable squared EDT of fg     (anisotropic ``sampling``)
        seeds = CCL of DT local-maxima plateaus
        out   = steepest-descent watershed of ``boundaries`` from seeds

    ``two_d=True`` runs the whole pipeline independently per z-slice (the
    reference's 2-D mode for anisotropic EM volumes), with per-slice label
    offsets keeping labels unique across the block.  Labels are block-local
    (min-voxel flat index based); callers globalize by block offset.  vmap
    over a leading batch axis for mesh-wide execution.

    ``dt_max_distance`` caps the EDT at that physical distance (values below
    the cap stay exact; the cascade cost drops from O(extent) to O(cap) per
    axis).  Seeds beyond the cap merge into plateau components — pass a cap
    comfortably above the expected object radius (e.g. the halo).
    """
    from .edt import distance_transform_squared
    from .filters import gaussian_smooth

    valid = jnp.ones(boundaries.shape, bool) if mask is None else mask.astype(bool)
    if two_d:
        samp2 = None if sampling is None else tuple(sampling[1:])
        lab = jax.vmap(
            lambda b2, m2: distance_transform_watershed(
                b2,
                threshold,
                sigma_seeds,
                min_seed_distance,
                sampling=samp2,
                mask=m2,
                connectivity=connectivity,
                two_d=False,
                dt_max_distance=dt_max_distance,
            )
        )(boundaries, valid)
        per_slice = int(np.prod(boundaries.shape[1:]))
        offs = (
            jnp.arange(boundaries.shape[0], dtype=jnp.int32) * per_slice
        ).reshape((-1,) + (1,) * (boundaries.ndim - 1))
        return jnp.where(lab > 0, lab + offs, 0)

    fg = (boundaries < threshold) & valid
    # impl="xla": the legacy kernel is the predictable fallback and runs
    # under vmap (entry(), executor batches) where the Mosaic EDT lifting
    # is untested on this hardware; the tiled pipeline uses the VMEM EDT
    dist = distance_transform_squared(
        fg, sampling=sampling, max_distance=dt_max_distance, impl="xla"
    )
    if sigma_seeds > 0:
        dist = gaussian_smooth(dist, sigma_seeds, sampling=sampling)
    # dist is the *squared* EDT, so the seed floor compares squared
    seeds = dt_seeds(
        dist,
        fg,
        min_distance=min_seed_distance * min_seed_distance,
        connectivity=connectivity,
    )
    return seeded_watershed(
        boundaries, seeds, mask=valid, connectivity=connectivity
    )


@partial(jax.jit, static_argnames=("connectivity", "max_label"))
def filter_small_segments(
    labels: jnp.ndarray,
    height: jnp.ndarray,
    min_size: jnp.ndarray,
    connectivity: int = 1,
    max_label: Optional[int] = None,
) -> jnp.ndarray:
    """Remove segments below ``min_size`` voxels and grow survivors into the
    freed space (reference: vigra ``sizeFilterSegInplace`` inside
    ``_ws_block``, SURVEY.md §2a "watershed").

    ``labels`` must be flat-index-based, values in [0, max_label] (default
    ``max_label`` = block voxel count; pass ``2 * N`` for the two-pass
    external-id encoding of :func:`dt_watershed_seeded`); sizes are counted
    with a dense ``segment_sum`` over the block, small segments are cleared,
    and the watershed fill relaxation re-grows the remaining labels.
    """
    n = int(np.prod(labels.shape)) if max_label is None else int(max_label)
    flat = labels.ravel().astype(jnp.int32)
    sizes = jax.ops.segment_sum(
        jnp.ones_like(flat), jnp.clip(flat, 0, n), num_segments=n + 1
    )
    small = (sizes[jnp.clip(flat, 0, n)] < min_size) & (flat > 0)
    kept = jnp.where(small, 0, flat).reshape(labels.shape)
    # regrow: freed voxels adopt the label of their lowest labeled neighbor
    grown = seeded_watershed(
        height, kept, mask=labels > 0, connectivity=connectivity
    )
    return grown


@partial(
    jax.jit,
    static_argnames=("sigma_seeds", "connectivity", "sampling", "dt_max_distance"),
)
def dt_watershed_seeded(
    boundaries: jnp.ndarray,
    ext_seeds: jnp.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 0.0,
    min_seed_distance: float = 0.0,
    sampling: Optional[Tuple[float, ...]] = None,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    dt_max_distance: Optional[float] = None,
) -> jnp.ndarray:
    """DT watershed honoring pre-existing external seeds (two-pass mode).

    The reference's ``two_pass_watershed.py`` runs a checkerboard: pass-two
    blocks seed from already-labeled pass-one neighbors so labels agree
    across block faces without a stitching task (SURVEY.md §3.5).  Here
    ``ext_seeds`` (int32, 0 = none, values 1..K dense) are the neighbor
    labels visible in this block's halo; internal DT seeds are planted where
    no external seed sits, and basins drain to whichever seed their steepest
    path reaches.

    Returns int32 labels: values > N are external ids (+N offset, N = block
    voxel count); values in 1..N are new internal fragments (flat-index
    based).  Callers split on N to map back.
    """
    from .edt import distance_transform_squared
    from .filters import gaussian_smooth

    n = int(np.prod(boundaries.shape))
    valid = jnp.ones(boundaries.shape, bool) if mask is None else mask.astype(bool)
    fg = (boundaries < threshold) & valid
    dist = distance_transform_squared(
        fg, sampling=sampling, max_distance=dt_max_distance, impl="xla"
    )
    if sigma_seeds > 0:
        dist = gaussian_smooth(dist, sigma_seeds, sampling=sampling)
    internal = dt_seeds(
        dist,
        fg,
        min_distance=min_seed_distance * min_seed_distance,
        connectivity=connectivity,
    )
    ext = ext_seeds.astype(jnp.int32)
    # external seeds dominate; internal ids live in 1..N, external in N+1..
    seeds = jnp.where(ext > 0, ext + jnp.int32(n), internal)
    return seeded_watershed(boundaries, seeds, mask=valid, connectivity=connectivity)


@partial(jax.jit, static_argnames=("connectivity",))
def dt_seeds(
    dist: jnp.ndarray,
    mask: jnp.ndarray,
    min_distance: float = 0.0,
    connectivity: int = 1,
) -> jnp.ndarray:
    """Watershed seeds: connected components of DT local-maxima plateaus.

    Mirrors the reference's ``_ws_block`` seed construction (maxima of the
    distance transform, labeled; SURVEY.md §2a "watershed").
    """
    maxima = local_maxima(dist, connectivity) & mask & (dist >= min_distance)
    raw = label_components(maxima, connectivity=connectivity)
    return finalize_labels(raw)
