"""Mesh-level parallelism: spatial sharding, halo exchange, distributed merges.

This package is the TPU-native replacement for the reference's *distribution
machinery* — the slurm/LSF job fan-out plus shared-filesystem data plane
(SURVEY.md §2c/§2d).  The reference's one first-class parallelism strategy is
spatial data parallelism (block decomposition with read-side halos); here the
same decomposition is expressed as sharded axes of a ``jax.sharding.Mesh``:

- :mod:`mesh`      — mesh construction over CPU/TPU devices (dp x sp axes),
- :mod:`halo`      — device-side ghost-zone exchange via ``lax.ppermute``
                     over ICI (replaces overlapping filesystem reads),
- :mod:`distributed_ccl` — globally consistent connected components over a
  sharded volume: per-shard CCL, boundary-face equivalences, an
  ``all_gather`` of the equivalence pairs over ICI, and a replicated
  pointer-jumping union-find (replaces the reference's serial ``nifty.ufd``
  merge job — its named scalability cliff, SURVEY.md §3.2),
- :mod:`multihost` — the DCN layer: ``jax.distributed`` wiring, pod-spanning
  meshes, and a local multi-process launcher (the fake-pod test backend).
"""

from .mesh import make_mesh, mesh_axis_sizes
from .reshard import reshard_axis, transpose_sharding
from .distributed_edt import (
    distributed_distance_transform,
    sharded_distance_transform_squared,
)
from .halo import exchange_halo, crop_halo, neighbor_face
from .distributed_ccl import (
    sharded_label_components,
    distributed_connected_components,
)
from .pipeline import make_ws_ccl_step
from .split_pipeline import make_ws_ccl_split
from .multihost import initialize as initialize_distributed, pod_mesh
