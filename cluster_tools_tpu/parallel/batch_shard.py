"""Batch-sharded sweep execution: one compiled program per block batch.

``BlockwiseExecutor.map_blocks`` historically compiled ``jit(vmap(kernel))``
at width ``n_devices * device_batch`` — on a single-device host that is one
compiled dispatch *per block*, serialized behind the XLA dispatch lock, so
dispatch + host-sync overhead caps sweep throughput far below memory
bandwidth (ROADMAP item 2).  This module supplies the sharded alternative,
the standard TPU-native shape (the fluid-flow TPU framework of
arXiv:2108.11076 runs its whole grid as one sharded program per step):

- :func:`batched_shard_map` — a whole Morton batch of blocks becomes ONE
  compiled program over the named device mesh: ``shard_map`` (through the
  version compat shim) splits the stacked batch axis across devices and
  ``vmap`` runs the per-block kernel over each device's sub-batch.  The
  dispatch lock is held once per batch instead of once per block.
- :func:`exchange_batch_halo` — device-side halo exchange along the batch
  axis for batches whose blocks form a contiguous run along one spatial
  axis (slab sweeps): each block's halo is reconstructed from its batch
  neighbor's resident data (local slicing inside a device's sub-batch, one
  ``ppermute`` across device boundaries — the :mod:`.halo` pattern applied
  to the batch axis), so interior halos never touch storage at all.
- :func:`sharded_slab_sweep` — a reference driver for the slab-run case:
  host reads load each slab ONCE (no overlapping reads); the sharded
  program rebuilds every interior halo on device, bit-identical to
  per-block overlapped reads.

The generic executor path stacks halo'd outer regions host-side (the
decompressed-chunk cache already dedups the overlapping halo reads, see
docs/PERFORMANCE.md "Chunk-aware I/O"); the device-side exchange is the
further step for contiguous-run sweeps where even the cache lookup can be
skipped.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from . import device_pool as device_pool_mod


def mesh_n_devices(mesh: Mesh) -> int:
    return int(np.prod(mesh.devices.shape))


def slab_sweep_device_feed_ok(
    shape: Sequence[int], extent: int, halo: int
) -> bool:
    """True when the batch geometry allows the inner-only-load device feed:
    axis-0 decomposes into whole slabs of ``extent`` (no ragged tail — tails
    would need per-block host reads anyway) and the halo fits inside one
    slab so :func:`exchange_batch_halo` can rebuild every interior halo from
    batch-neighbor data alone."""
    size = int(shape[0])
    return (
        extent > 0
        and 0 <= halo <= extent
        and size >= extent
        and size % extent == 0
    )


def resolve_sharded_batch(
    n_devices: int,
    base_batch: int,
    sharded_batch: Optional[int] = None,
) -> int:
    """The sharded batch width: ``sharded_batch`` (rounded up to a device
    multiple), or a default of ``max(2 * base_batch, 8)`` — big enough that
    dispatch overhead amortizes, always divisible by the mesh size so every
    device holds an equal sub-batch."""
    if sharded_batch is not None:
        b = max(1, int(sharded_batch))
    else:
        b = max(2 * int(base_batch), 8)
    b = max(b, n_devices)
    return ((b + n_devices - 1) // n_devices) * n_devices


def use_sharded_sweep(
    sweep_mode: str, n_devices: int, n_blocks: int, batch: int
) -> bool:
    """Resolve the ``sweep_mode`` knob: ``"sharded"`` / ``"per_block"``
    force a path; ``"auto"`` picks sharded when the mesh has >= 2 devices
    (per-block dispatch would leave all but one idle behind the dispatch
    lock) or the sweep has at least one full sharded batch of blocks (the
    dispatch-amortization regime) — single-block sweeps stay per-block."""
    if sweep_mode == "per_block":
        return False
    if sweep_mode == "sharded":
        return True
    if sweep_mode == "auto":
        return n_blocks > 1 and (n_devices >= 2 or n_blocks >= batch)
    raise ValueError(
        f"unknown sweep_mode {sweep_mode!r} "
        "(expected 'auto', 'sharded' or 'per_block')"
    )


def batched_shard_map(
    kernel: Callable,
    mesh: Mesh,
    batch: int,
    axis_name: str = "blocks",
    check_vma: bool = False,
):
    """One compiled dispatch for a stacked batch of blocks, sharded over
    ``mesh``.

    ``kernel`` is the per-block function; the returned callable takes the
    same arguments stacked to ``[batch, ...]`` and runs ``vmap(kernel)``
    over each device's ``batch / n_devices`` sub-batch inside one
    ``shard_map`` program — the whole batch is a single XLA execution, so
    the executor's dispatch lock is held once per batch instead of once per
    block.  Per-lane numerics are those of ``vmap``, independent of the
    batch width, which is what makes the sharded sweep bit-identical to the
    per-block path (asserted by tests/test_sharded.py and ``bench.py
    --sweep``).

    ``check_vma=False`` for the same reason as ``parallel/pipeline.py``:
    kernels carrying ``while_loop``/pallas bodies trip the static
    replication checker on the jax versions the compat shim supports; only
    the advisory check is off, the collectives (none here unless the kernel
    adds them) are unaffected.
    """
    n = mesh_n_devices(mesh)
    batch = int(batch)
    if batch % n:
        raise ValueError(
            f"sharded batch {batch} is not divisible by the {n}-device mesh"
        )

    def _sharded_batch_body(*args):
        return jax.vmap(kernel)(*args)

    spec = P(axis_name)
    return jax.jit(
        shard_map(
            _sharded_batch_body,
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
            check_vma=check_vma,
        )
    )


def ragged_shard_map(
    kernel: Callable,
    mesh: Mesh,
    batch: int,
    specs: Sequence,
    axis_name: str = "blocks",
    check_vma: bool = False,
):
    """One compiled dispatch for a *ragged* (mixed-shape) batch of blocks,
    driven by the paged block pool's descriptors (:mod:`.block_pool`,
    docs/PERFORMANCE.md "Ragged sweeps").

    ``specs`` is one :class:`~cluster_tools_tpu.parallel.block_pool.
    RaggedArgSpec` per kernel argument.  The returned callable takes, in
    order: one page pool ``[pool_pages, *page_shape]`` per arg (replicated
    to every device), then per arg a page table ``[batch, pages_per_lane]``
    and a valid-extent array ``[batch, ndim]`` (both sharded over the
    batch axis).  Inside one ``shard_map`` program each device vmaps over
    its lanes: a lane gathers its pages from the pool, reassembles the
    dense page-aligned array, masks everything beyond its valid extent
    with the spec's fill value, and runs the kernel — so the Ragged Paged
    Attention shape (fixed pages + ragged metadata, arXiv:2604.15464)
    executes variable-shape block work as ONE XLA execution.

    The reconstruction is pure value movement (gather / reshape /
    transpose / select — no arithmetic), so a lane's kernel input is
    bit-equal to the host-padded array the dense path would have built at
    the same padded shape; per-lane numerics are ``vmap``'s, independent
    of the batch width, which is what keeps the ragged path bit-identical
    to per-block execution on the lanes' stored regions
    (tests/test_ragged.py).  ``check_vma=False`` for the same reason as
    :func:`batched_shard_map`.
    """
    n = mesh_n_devices(mesh)
    batch = int(batch)
    if batch % n:
        raise ValueError(
            f"ragged batch {batch} is not divisible by the {n}-device mesh"
        )
    specs = tuple(specs)

    def _reassemble(pool, table, valid, spec):
        nd = len(spec.grid)
        pages = pool[table]  # [pages_per_lane, *page_shape]
        # grid-major tiles -> dense: (g0..gd, p0..pd) interleaved to
        # (g0, p0, g1, p1, ...) then flattened per axis
        x = pages.reshape(spec.grid + spec.page_shape)
        perm = []
        for ax in range(nd):
            perm.extend((ax, nd + ax))
        x = x.transpose(perm).reshape(spec.padded_shape)
        mask = None
        for ax in range(nd):
            m = lax.broadcasted_iota(
                jnp.int32, spec.padded_shape, ax
            ) < valid[ax]
            mask = m if mask is None else (mask & m)
        fill = jnp.asarray(spec.fill, x.dtype)
        return jnp.where(mask, x, fill)

    def _sharded_body(*flat):
        pools = flat[: len(specs)]
        lanes = flat[len(specs):]  # (table, valid) per arg

        def _lane(*lane_flat):
            args = []
            for i, spec in enumerate(specs):
                table, valid = lane_flat[2 * i], lane_flat[2 * i + 1]
                args.append(_reassemble(pools[i], table, valid, spec))
            return kernel(*args)

        # pools are closed over (vmap broadcasts them across lanes)
        return jax.vmap(_lane)(*lanes)

    spec_in = (
        tuple(P() for _ in specs)
        + tuple(P(axis_name) for _ in specs for _ in range(2))
    )
    return jax.jit(
        shard_map(
            _sharded_body,
            mesh=mesh,
            in_specs=spec_in,
            out_specs=P(axis_name),
            check_vma=check_vma,
        )
    )


def exchange_batch_halo(
    x: jnp.ndarray,
    halo: int,
    axis: int,
    axis_name: str,
    axis_size: int,
    lo_edge: Optional[jnp.ndarray] = None,
    hi_edge: Optional[jnp.ndarray] = None,
    fill=0,
) -> jnp.ndarray:
    """Device-side halo reconstruction along the *batch* axis.

    ``x`` is the local sub-batch ``[b, *spatial]`` of a stacked batch whose
    blocks form a contiguous run along spatial ``axis`` (block ``i+1``
    starts where block ``i`` ends).  Each block's missing halo along that
    axis is its batch neighbor's edge slab: for blocks interior to the
    sub-batch a local slice, across device boundaries one nearest-neighbor
    ``ppermute`` (the :func:`..halo.exchange_halo` pattern applied to the
    batch axis).  ``lo_edge`` / ``hi_edge`` are the run-end slabs (shape =
    one block's halo slab) the host supplies for the globally first / last
    block — read from storage when the run borders more volume, or the
    task's border fill at the volume edge; without them the ends are filled
    with ``fill`` (matching :func:`..halo.exchange_halo` border semantics).

    Returns ``[b, ...]`` with the extent along ``axis`` grown by
    ``2 * halo`` — exactly the stack of halo'd outer regions per-block
    overlapped reads would have produced, without re-reading any interior
    halo from storage.  Must be called inside ``shard_map``.
    """
    if halo <= 0:
        return x
    ax = axis + 1  # x carries the batch axis in front
    extent = x.shape[ax]
    if extent < halo:
        raise ValueError(
            f"block extent {extent} along axis {axis} smaller than halo {halo}"
        )
    n = int(axis_size)
    idx = lax.axis_index(axis_name)
    lo_slabs = lax.slice_in_dim(x, 0, halo, axis=ax)
    hi_slabs = lax.slice_in_dim(x, extent - halo, extent, axis=ax)
    # device-boundary slabs: my first block's low slab -> previous device
    # (as its succ), my last block's high slab -> next device (as its pred);
    # ppermute zero-fills the mesh ends
    first_lo = lax.slice_in_dim(lo_slabs, 0, 1, axis=0)
    last_hi = lax.slice_in_dim(hi_slabs, x.shape[0] - 1, x.shape[0], axis=0)
    from_prev = lax.ppermute(
        last_hi, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    from_next = lax.ppermute(
        first_lo, axis_name, [(i, i - 1) for i in range(1, n)]
    )

    def _edge(slab, edge_val, is_edge):
        if edge_val is None:
            if isinstance(fill, (int, float)) and fill == 0:
                return slab  # ppermute already zero-filled the mesh end
            edge_val = jnp.full(slab.shape[1:], fill, x.dtype)
        return jnp.where(is_edge, edge_val[None].astype(x.dtype), slab)

    from_prev = _edge(from_prev, lo_edge, idx == 0)
    from_next = _edge(from_next, hi_edge, idx == n - 1)
    # per-block pred/succ: neighbors inside the sub-batch are local slices
    pred = jnp.concatenate(
        [from_prev, lax.slice_in_dim(hi_slabs, 0, x.shape[0] - 1, axis=0)],
        axis=0,
    )
    succ = jnp.concatenate(
        [lax.slice_in_dim(lo_slabs, 1, x.shape[0], axis=0), from_next],
        axis=0,
    )
    return jnp.concatenate([pred, x, succ], axis=ax)


def sharded_slab_sweep(
    vol,
    kernel: Callable,
    mesh: Mesh,
    extent: int,
    halo: int,
    batch: Optional[int] = None,
    fill=0.0,
    axis_name: str = "blocks",
    keep_on_device: bool = False,
):
    """Sweep ``vol`` decomposed into axis-0 slabs of ``extent`` as
    batch-sharded programs with device-side halo exchange.

    Each batch of consecutive slabs is loaded WITHOUT its axis-0 halos
    (every voxel is read exactly once); the sharded program reconstructs
    all interior halos on device via :func:`exchange_batch_halo` and runs
    ``vmap(kernel)`` over the halo'd slabs — ``kernel`` receives
    ``[extent + 2*halo, ...]`` exactly as per-slab overlapped reads would
    have produced it (volume ends padded with ``fill``), so the result is
    bit-identical to the per-block path.  Ragged final batches are padded
    with synthetic slabs whose leading rows carry the true ``hi_edge`` (so
    the last real slab still sees its correct halo) and the padded outputs
    are dropped.  Returns the per-slab kernel outputs stacked along axis 0.

    ``vol`` may be a host :class:`numpy.ndarray` (each batch's stack is
    uploaded, counted as ``h2d_bytes``) or an already device-resident
    :class:`jax.Array` — e.g. the payload of a device handoff
    (:func:`~cluster_tools_tpu.runtime.handoff.resolve_device_arrays`) — in
    which case batches are sliced and stacked on device and the skipped
    upload is counted as ``bytes_not_staged``.  With ``keep_on_device=True``
    the result stays a :class:`jax.Array` (no device-to-host copy), ready
    to feed the next device consumer or a device handoff publish; the
    default materializes the host array and counts ``d2h_bytes``.
    """
    n_dev = mesh_n_devices(mesh)
    size = int(vol.shape[0])
    if size % extent:
        raise ValueError(
            f"volume extent {size} is not a multiple of the slab extent "
            f"{extent} (run the ragged tail per-block)"
        )
    if halo > extent:
        raise ValueError(f"halo {halo} exceeds the slab extent {extent}")
    n_slabs = size // extent
    if batch is None:
        batch = min(n_slabs, max(n_dev, 8))
    batch = ((int(batch) + n_dev - 1) // n_dev) * n_dev

    on_device = isinstance(vol, jax.Array)
    xp = jnp if on_device else np
    slab_shape = (extent,) + tuple(vol.shape[1:])
    edge_shape = (halo,) + tuple(vol.shape[1:])
    itemsize = np.dtype(vol.dtype).itemsize

    def _body(stack, lo, hi):
        halod = exchange_batch_halo(
            stack, halo, 0, axis_name, n_dev,
            lo_edge=lo, hi_edge=hi, fill=fill,
        )
        return jax.vmap(kernel)(halod)

    spec = P(axis_name)
    prog = jax.jit(
        shard_map(
            _body,
            mesh=mesh,
            in_specs=(spec, P(), P()),
            out_specs=spec,
            check_vma=False,
        )
    )

    from ..runtime import trace as trace_mod

    fill_edge = xp.full(edge_shape, fill, vol.dtype)
    outs = []
    for start in range(0, n_slabs, batch):
        idxs = list(range(start, min(start + batch, n_slabs)))
        stack = xp.stack([vol[i * extent:(i + 1) * extent] for i in idxs])
        lo = (
            vol[start * extent - halo:start * extent]
            if start > 0 else fill_edge
        )
        end = idxs[-1] + 1
        hi = (
            vol[end * extent:end * extent + halo]
            if end < n_slabs else fill_edge
        )
        n_pad = batch - len(idxs)
        if n_pad:
            # padding slabs lead with the real hi edge so the last REAL
            # slab's device-side succ halo is still its true neighbor data;
            # the rest of the pad (and its outputs) are discarded
            if on_device:
                tail = jnp.full(
                    (extent - halo,) + slab_shape[1:], 0, vol.dtype
                )
                pad = jnp.concatenate([hi, tail], axis=0)
            else:
                pad = np.zeros(slab_shape, vol.dtype)
                pad[:halo] = hi
            stack = xp.concatenate(
                [stack, xp.stack([pad] * n_pad)], axis=0
            )
        feed_bytes = int(np.prod(stack.shape)) * itemsize
        if on_device:
            device_pool_mod.bump("bytes_not_staged", feed_bytes)
        else:
            device_pool_mod.record_h2d(feed_bytes)
        # one span per sharded slab program — the device-halo twin of the
        # executor's dispatch spans (docs/OBSERVABILITY.md)
        with trace_mod.span(
            "shard.slab_batch", start=start, n_slabs=len(idxs),
            feed="device" if on_device else "host",
        ):
            out = prog(stack, lo, hi)
            if not keep_on_device:
                out = np.asarray(out)
                device_pool_mod.record_d2h(int(out.nbytes))
        outs.append(out[: len(idxs)])
    if keep_on_device:
        return jnp.concatenate(outs, axis=0)
    return np.concatenate(outs, axis=0)
