"""Ragged paged block pool: mixed-shape block batches as ONE program.

The sharded sweep (``parallel/batch_shard.py``, docs/PERFORMANCE.md
"Sharded sweeps") wants uniform full-size blocks: every lane of a stacked
batch must share one static shape, so clipped volume-edge blocks, PR-4
degrade-split sub-blocks, and ragged final batches historically fell back
to one compiled dispatch per block — exactly the regime real (non-pow2)
volumes and mixed-tenant serving live in.  This module applies the Ragged
Paged Attention design (PAPERS.md, arXiv:2604.15464) to block sweeps:
**fixed-size pages plus explicit ragged metadata driving one kernel over
variable-length work.**

- a **page** is a fixed-shape tile (chunk-scale; ``DEFAULT_PAGE_EXTENT``
  per axis, or the caller's ``page_shape`` — set it to the dataset chunk
  shape for chunk-aligned pooling),
- the **pool** is one ``[n_pages, *page_shape]`` buffer per kernel arg;
  page 0 is the shared *fill page* (a constant), so table slots that no
  real data backs cost nothing,
- each **lane** (one block of the batch) owns a *page table* row — the
  indices of its pages in grid-row-major order — and a *valid extent*
  descriptor (its true array shape).  Lanes smaller than the batch's
  padded shape reference the fill page for the tiles they don't cover;
  fully synthetic *padding lanes* (the ragged tail of a sweep) reference
  nothing but the fill page and are discarded on d2h,
- the device program (:func:`~cluster_tools_tpu.parallel.batch_shard.
  ragged_shard_map`) gathers each lane's pages back into a dense
  page-aligned array, masks everything beyond the valid extent with the
  fill value, and vmaps the per-block kernel over the lanes — one XLA
  execution for the whole mixed-shape batch.

Page-table indirection is what keeps the compiled-program population
small: the program's shape signature is ``(page grid, page shape, batch
width, dtypes)``, not the per-lane shapes — every mixed-shape batch whose
lanes fit the same page grid reuses one program, where per-shape ``jit``
compilation would build one executable per distinct block shape.

Ragged-safety contract (the executor enforces *where* this path is used,
docs/PERFORMANCE.md "Ragged sweeps"): a lane's kernel runs at the batch's
page-aligned shape, not the lane's own shape, so results are only
guaranteed for the lane's *stored* region when the kernel is shape-local
(receptive field bounded by the halo — the same contract as
``splittable=True`` block splitting).  Uniform-shape batches that are
merely *partial* (the ragged tail) use the lane shape itself as the page,
so every real lane sees exactly the bytes per-block dispatch would have
seen and ANY kernel stays bit-identical.

Host-side buffers are pooled per ``(page_shape, dtype)`` and reused
across batches (checkout at :meth:`PagedBlockPool.pack`, checkin at
:meth:`RaggedBatch.release` once the bytes are on device).  Reuse means a
pool buffer can carry a previous batch's bytes in unused slots — the
device-side valid-extent mask (not just host fill) is what makes stale
pages harmless, and the property tests poison reused buffers to prove it.
"""

from __future__ import annotations

import threading
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

#: default per-axis page extent — a chunk-scale tile.  Small enough that a
#: degrade-split sub-block (half a block per axis) occupies a fraction of
#: a full lane's pages, big enough that page tables stay tiny.
DEFAULT_PAGE_EXTENT = 8

#: pool capacities are rounded up to a power of two so the compiled
#: program population stays bounded: the pool's leading dim is part of the
#: program's shape signature, and without quantization every batch's page
#: count would compile its own executable.
_MIN_POOL_PAGES = 16

#: free-list bound per (page_shape, dtype) buffer class — a sweep has at
#: most prefetch-depth batches packing concurrently.
_MAX_FREE_BUFFERS = 4


class RaggedArgSpec(NamedTuple):
    """Static (compile-key) description of one ragged kernel argument."""

    grid: Tuple[int, ...]        # pages per axis of the padded lane
    page_shape: Tuple[int, ...]  # fixed page tile shape
    dtype: str                   # numpy dtype name (hashable on purpose)
    fill: Any                    # mask fill value (python scalar)
    pool_pages: int              # quantized pool capacity (leading dim)

    @property
    def padded_shape(self) -> Tuple[int, ...]:
        """The dense per-lane shape the kernel runs at: grid * page."""
        return tuple(g * p for g, p in zip(self.grid, self.page_shape))

    @property
    def pages_per_lane(self) -> int:
        return int(np.prod(self.grid))


def default_page_shape(
    max_shape: Sequence[int], uniform: bool
) -> Tuple[int, ...]:
    """Page policy: uniform-shape lanes use the lane shape itself as the
    page (every real lane reconstructs to exactly its own bytes — ANY
    kernel stays bit-identical, the padding lanes being pure fill), while
    mixed-shape lanes use the chunk-scale ``DEFAULT_PAGE_EXTENT`` tile so
    small lanes occupy few pages and different batches' page grids
    coincide (one compiled program instead of one per shape mix)."""
    if uniform:
        return tuple(int(s) for s in max_shape)
    return tuple(min(int(s), DEFAULT_PAGE_EXTENT) for s in max_shape)


def _quantize_pages(n: int) -> int:
    cap = _MIN_POOL_PAGES
    while cap < n:
        cap *= 2
    return cap


class RaggedBatch:
    """One packed mixed-shape batch: per-arg pools + page tables + valid
    extents, plus the lane -> block attribution the executor carries
    through d2h cropping and the dispatch counters."""

    def __init__(self, specs, pools, tables, valids, n_lanes, width,
                 pages_in_use, owner=None, buffers=None):
        self.specs: Tuple[RaggedArgSpec, ...] = tuple(specs)
        self.pools: List[np.ndarray] = pools
        self.tables: List[np.ndarray] = tables
        self.valids: List[np.ndarray] = valids
        self.n_lanes = int(n_lanes)          # real lanes; the rest is padding
        self.width = int(width)
        self.pages_in_use = int(pages_in_use)  # real pages, fill page excluded
        self._owner = owner
        self._buffers = buffers or []

    @property
    def lanes_padded(self) -> int:
        return self.width - self.n_lanes

    @property
    def nbytes(self) -> int:
        return int(
            sum(p.nbytes for p in self.pools)
            + sum(t.nbytes for t in self.tables)
            + sum(v.nbytes for v in self.valids)
        )

    def key(self) -> tuple:
        """Compile-key fragment: everything that shapes the program."""
        return (self.width, self.specs)

    def flat_inputs(self) -> Tuple[List[np.ndarray], List[np.ndarray]]:
        """``(replicated, sharded)`` program inputs: the pools broadcast to
        every device, the per-lane tables + valid extents sharded over the
        batch axis."""
        sharded: List[np.ndarray] = []
        for t, v in zip(self.tables, self.valids):
            sharded.extend((t, v))
        return list(self.pools), sharded

    def lane_valid_shape(self, lane: int) -> Tuple[int, ...]:
        return tuple(int(v) for v in self.valids[0][lane])

    def crop(self, lane: int, leaf: np.ndarray) -> np.ndarray:
        """Crop one output leaf of ``lane`` back to the lane's valid shape.
        A leaf matching an arg's padded spatial shape is cropped to that
        arg's valid extent (arg 0 wins ties — the canonical spatial shape
        of the block); other leaves (scalars, reductions) pass through."""
        leaf = np.asarray(leaf)
        for spec, valid in zip(self.specs, self.valids):
            if tuple(leaf.shape) == spec.padded_shape:
                return leaf[
                    tuple(slice(0, int(v)) for v in valid[lane])
                ]
        return leaf

    def release(self) -> None:
        """Return the pool buffers to the owning :class:`PagedBlockPool`
        for reuse — call once the bytes are on device.  Safe to skip (the
        buffers are then simply garbage-collected with this batch)."""
        if self._owner is not None and self._buffers:
            self._owner._checkin(self._buffers)
        self._buffers = []
        self._owner = None


class PagedBlockPool:
    """Reusable host-side staging pool for ragged batches (one per sweep).

    Thread-safe: ``pack`` is called from the executor's prefetching IO
    threads, so buffer checkout/checkin is under a lock while the actual
    packing works on privately-owned buffers.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._free = {}  # (pages, page_shape, dtype) -> [ndarray, ...]
        self.packs = 0
        self.buffer_reuses = 0

    # -- buffer lifecycle --------------------------------------------------
    def _checkout(self, pages: int, page_shape: Tuple[int, ...],
                  dtype: np.dtype) -> np.ndarray:
        key = (pages, page_shape, str(dtype))
        with self._lock:
            free = self._free.get(key)
            if free:
                self.buffer_reuses += 1
                return free.pop()
        return np.empty((pages,) + page_shape, dtype)

    def _checkin(self, buffers: List[np.ndarray]) -> None:
        with self._lock:
            for buf in buffers:
                key = (buf.shape[0], tuple(buf.shape[1:]), str(buf.dtype))
                free = self._free.setdefault(key, [])
                if len(free) < _MAX_FREE_BUFFERS:
                    free.append(buf)

    # -- packing -----------------------------------------------------------
    def pack(
        self,
        lane_args: Sequence[Tuple[np.ndarray, ...]],
        width: int,
        page_shape: Optional[Sequence[int]] = None,
        fills: Optional[Sequence[Any]] = None,
    ) -> RaggedBatch:
        """Pack ``lane_args`` (one tuple of arrays per real lane; shapes
        may differ between lanes) into a ragged batch of ``width`` lanes.
        Lanes beyond ``len(lane_args)`` are synthetic padding lanes (all
        fill page, valid extent 0 — their outputs are discarded on d2h).

        Raises ValueError when the lanes cannot pack (mismatched arg
        count / rank / dtype across lanes) — the executor treats that as
        "fall back to per-block execution", never as a sweep failure.
        """
        if not lane_args:
            raise ValueError("cannot pack an empty batch")
        n_lanes = len(lane_args)
        width = int(width)
        if width < n_lanes:
            raise ValueError(f"width {width} < {n_lanes} lanes")
        n_args = len(lane_args[0])
        if any(len(la) != n_args for la in lane_args):
            raise ValueError("lanes disagree on the kernel arg count")
        lane_args = [
            tuple(np.asarray(x) for x in la) for la in lane_args
        ]
        if fills is None:
            fills = (0,) * n_args
        if len(fills) != n_args:
            raise ValueError(f"{len(fills)} fills for {n_args} args")

        specs: List[RaggedArgSpec] = []
        pools: List[np.ndarray] = []
        tables: List[np.ndarray] = []
        valids: List[np.ndarray] = []
        buffers: List[np.ndarray] = []
        pages_in_use = 0
        for a in range(n_args):
            arrs = [la[a] for la in lane_args]
            dtype = arrs[0].dtype
            if any(x.dtype != dtype for x in arrs):
                raise ValueError(
                    f"lanes disagree on the dtype of kernel arg {a}"
                )
            # rank consistency is per ARG: args may have different ranks
            # (a 3-d mask next to a 4-d affinity map) — each gets its own
            # page grid and valid-extent descriptor
            nd = arrs[0].ndim
            if any(x.ndim != nd for x in arrs):
                raise ValueError(
                    f"lanes disagree on the rank of kernel arg {a}"
                )
            shapes = [tuple(int(s) for s in x.shape) for x in arrs]
            max_shape = tuple(int(m) for m in np.max(shapes, axis=0))
            uniform = len(set(shapes)) == 1
            # uniform lanes ALWAYS use the lane shape as the page — the
            # any-kernel bit-identity guarantee for partial uniform
            # batches must hold even when the caller tuned ``page_shape``
            # for its mixed-shape batches (chunk alignment only matters
            # there); a caller page tile also only fits same-rank args
            arg_page = page_shape if (
                not uniform
                and page_shape is not None and len(page_shape) == nd
            ) else None
            page = tuple(
                int(p) for p in (arg_page or
                                 default_page_shape(max_shape, uniform))
            )
            if any(p <= 0 for p in page):
                raise ValueError(f"bad page shape {page} for rank {nd}")
            grid = tuple(
                max(1, -(-m // p)) for m, p in zip(max_shape, page)
            )
            # real pages: the tiles each lane's valid extent overlaps
            n_real = sum(
                int(np.prod([-(-s // p) for s, p in zip(shape, page)]))
                for shape in shapes
            )
            cap = _quantize_pages(1 + n_real)
            spec = RaggedArgSpec(grid, page, dtype.name, fills[a], cap)
            pool = self._checkout(cap, page, dtype)
            pool[0] = fills[a]  # the shared fill page (slot 0)
            table = np.zeros((width, spec.pages_per_lane), np.int32)
            valid = np.zeros((width, nd), np.int32)
            slot = 1
            for lane, x in enumerate(arrs):
                shape = shapes[lane]
                valid[lane] = shape
                lane_grid = [-(-s // p) for s, p in zip(shape, page)]
                for coord in np.ndindex(*lane_grid):
                    lo = tuple(c * p for c, p in zip(coord, page))
                    hi = tuple(
                        min(c + p, s) for c, p, s in zip(lo, page, shape)
                    )
                    sub = x[tuple(slice(b, e) for b, e in zip(lo, hi))]
                    if sub.shape == page:
                        pool[slot] = sub
                    else:
                        # partial page: host fill beyond the valid extent
                        # (the device mask re-asserts this — see module
                        # docstring on buffer reuse)
                        pool[slot] = fills[a]
                        pool[slot][
                            tuple(slice(0, e - b) for b, e in zip(lo, hi))
                        ] = sub
                    flat = 0
                    for c, g in zip(coord, grid):
                        flat = flat * g + c
                    table[lane, flat] = slot
                    slot += 1
            pages_in_use += slot - 1
            specs.append(spec)
            pools.append(pool)
            tables.append(table)
            valids.append(valid)
            buffers.append(pool)
        with self._lock:
            self.packs += 1
        return RaggedBatch(
            specs, pools, tables, valids, n_lanes, width,
            pages_in_use, owner=self, buffers=buffers,
        )
