"""HBM-resident page pool: upload pages once, re-address them per batch.

The ragged paged pool (:mod:`.block_pool`, docs/PERFORMANCE.md "Ragged
sweeps") made mixed-shape batches ONE program, but its pools are host
arrays re-staged with ``device_put`` every batch — the h2d copy is paid
for every page of every batch even when consecutive batches (or warm
re-sweeps of the same data) carry identical bytes.  This module is the
device rung of that design (ROADMAP item 2, the "communicate on the
accelerator" thesis of arXiv:2112.09017): a **persistent device
allocation** per ``(page_shape, dtype)`` class, with pages addressed by
*content* so the page-table indirection that already bounds the compiled
program population now also bounds the h2d traffic —

- a :class:`_DeviceArena` is one resident ``[capacity, *page_shape]``
  buffer (replicated over the mesh, exactly like the host pools were),
  its slots assigned to page *contents* (crc32 of the bytes) under an
  LRU.  The fill page, every repeated page, and every page of a warm
  re-sweep hit the resident slot and cost zero h2d bytes,
- :meth:`DevicePagePool.stage` rewrites a packed
  :class:`~cluster_tools_tpu.parallel.block_pool.RaggedBatch`'s page
  tables against the arena slots, uploads ONLY the missing pages (one
  ``device_put`` + one jitted scatter per batch, miss counts quantized
  to powers of two so the scatter's compile population stays bounded),
  and returns a :class:`StagedBatch` whose specs carry the arena
  capacity — the same descriptor-driven program shape, fed from HBM,
- arena capacities are quantized powers of two under a byte budget
  (``device_pool_bytes`` task knob / ``CTT_DEVICE_POOL_BYTES``, kill
  switch ``CTT_DEVICE_POOL=0``).  RESOURCE_EXHAUSTED while uploading
  rides the PR-4 degrade ladder: evict everything, retry once, then
  raise :class:`DevicePoolExhausted` — the executor falls that batch
  back to per-batch host staging, attributed ``degraded:host_staged``
  in failures.json (tests/test_device_plane.py).

Counters follow the chunk-cache snapshot/delta pattern (``h2d_bytes`` /
``d2h_bytes`` / ``device_pool_hits`` / ``device_pool_misses`` /
``device_pool_evictions`` / ``bytes_not_staged`` /
``device_handoffs_served`` / ``host_staged_fallbacks``): the task
runtime snapshots around each task and merges the delta into
``io_metrics.json``, so the avoided h2d traffic is observable per task
(docs/PERFORMANCE.md "Device-resident data plane").  ``d2h_bytes`` and
``device_handoffs_served`` are *recorded* here but *bumped* by the
executor's d2h copies and the handoff registry's device rung
(:mod:`~cluster_tools_tpu.runtime.handoff`) — one counter plane for the
whole device-resident data path.

The collective reduce plane
(:class:`~cluster_tools_tpu.parallel.reduce_tree.CollectiveReducePlane`,
docs/PERFORMANCE.md "Collective reduce plane") is a second consumer of
this pool: each tree level's boundary-edge lanes marshal as one-page
``RaggedBatch`` pools and stage through :meth:`DevicePagePool.stage`, so
a warm re-solve of the same problem (same edge bytes → same crc32 slots)
pays zero h2d before its per-level dispatch.
"""

from __future__ import annotations

import os
import threading
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .block_pool import RaggedArgSpec, RaggedBatch, _quantize_pages

#: default resident-pool byte budget per process when neither the task
#: knob nor ``CTT_DEVICE_POOL_BYTES`` says otherwise: big enough for the
#: chunk-scale page working set of a sweep, small next to device memory.
DEFAULT_POOL_BYTES = 256 << 20

#: counter names, fixed so snapshots/deltas stay schema-stable
STAT_KEYS = (
    "h2d_bytes",
    "d2h_bytes",
    "device_pool_hits",
    "device_pool_misses",
    "device_pool_evictions",
    "device_batches_staged",
    "host_staged_fallbacks",
    "bytes_not_staged",
    "device_handoffs_served",
)

_METRICS_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {k: 0 for k in STAT_KEYS}


def snapshot() -> Dict[str, float]:
    """Current process-wide device-plane counters (monotonic; diff two
    snapshots with :func:`delta` to attribute a task's share)."""
    with _METRICS_LOCK:
        return dict(_COUNTERS)


def delta(snap: Dict[str, float]) -> Dict[str, float]:
    cur = snapshot()
    return {k: cur[k] - snap.get(k, 0) for k in cur}


def bump(key: str, n: float = 1) -> None:
    with _METRICS_LOCK:
        _COUNTERS[key] += n


def record_h2d(nbytes: int) -> None:
    """Attribute ``nbytes`` of host->device traffic (every ``device_put``
    on the executor's dispatch paths reports here)."""
    bump("h2d_bytes", int(nbytes))


def record_d2h(nbytes: int) -> None:
    """Attribute ``nbytes`` of device->host traffic (the executor's
    output copies and the handoff registry's device->memory demotions)."""
    bump("d2h_bytes", int(nbytes))


def device_pool_enabled() -> bool:
    """Process-level kill switch for the WHOLE device-resident data plane
    (resident page pool AND device handoffs): ``CTT_DEVICE_POOL=0``.
    Tasks additionally gate on their ``device_pool`` /
    ``device_handoffs`` config knobs."""
    return os.environ.get("CTT_DEVICE_POOL", "1").lower() not in (
        "0", "false", "off",
    )


def device_pool_budget(explicit: Optional[int] = None) -> int:
    """Byte budget for resident device allocations: the task's
    ``device_pool_bytes`` knob when given, else ``CTT_DEVICE_POOL_BYTES``,
    else :data:`DEFAULT_POOL_BYTES`."""
    if explicit is not None:
        return max(0, int(explicit))
    env = os.environ.get("CTT_DEVICE_POOL_BYTES")
    if env:
        return max(0, int(env))
    return DEFAULT_POOL_BYTES


class DevicePoolExhausted(Exception):
    """The resident pool cannot hold a batch even after evicting
    everything (budget too small, or device RESOURCE_EXHAUSTED persisted
    through the evict+retry rung).  Deliberately NOT a MemoryError: the
    executor must catch it as the typed "fall back to host staging"
    signal, never quarantine blocks over it."""


def _content_key(page: np.ndarray) -> int:
    # content addressing: identical bytes share one resident slot, which
    # is what makes the fill page, repeated pages, and warm re-sweeps
    # free.  crc32 collisions would alias two pages; at chunk-scale page
    # counts (thousands per sweep) the 2^-32 rate is accepted — the same
    # digest the PR-3 integrity sidecars stand on.
    return zlib.crc32(np.ascontiguousarray(page).tobytes())


def _quantize_count(n: int) -> int:
    """Round an upload width up to a power of two (>= 1): the scatter
    update is jitted per width, so unquantized widths would compile one
    executable per distinct miss count."""
    cap = 1
    while cap < n:
        cap *= 2
    return cap


class StagedBatch:
    """A ragged batch staged against the resident device pools: the same
    descriptor surface as :class:`~cluster_tools_tpu.parallel.block_pool.
    RaggedBatch` (specs / width / tables / valids / ``key()``), but the
    pools are live jax arrays in HBM and the specs carry the arena
    capacities — the compiled program gathers straight from the resident
    allocation."""

    def __init__(self, specs, pools, tables, valids, width, staged_bytes,
                 reused_bytes):
        self.specs: Tuple[RaggedArgSpec, ...] = tuple(specs)
        self.pools = pools            # jax arrays, device-resident
        self.tables: List[np.ndarray] = tables
        self.valids: List[np.ndarray] = valids
        self.width = int(width)
        self.staged_bytes = int(staged_bytes)   # h2d paid for this batch
        self.reused_bytes = int(reused_bytes)   # h2d avoided (hits)

    def key(self) -> tuple:
        return (self.width, self.specs)

    def flat_inputs(self):
        """``(replicated, sharded)`` like RaggedBatch.flat_inputs, except
        the replicated pools are ALREADY on device — the caller only
        device_puts the (tiny) tables and valid extents."""
        sharded: List[np.ndarray] = []
        for t, v in zip(self.tables, self.valids):
            sharded.extend((t, v))
        return list(self.pools), sharded


class _DeviceArena:
    """One persistent device buffer per ``(page_shape, dtype)`` class:
    ``[capacity, *page_shape]`` replicated over the mesh, slots assigned
    to page contents under an LRU.  Updates are functional
    (``pool.at[slots].set(staged)``) — a previously staged batch keeps
    its own (immutable) pool version, so eviction can never corrupt an
    in-flight dispatch.  Staging is serialized per arena: the slot table
    and the current pool version must advance atomically, or a second
    thread could observe its content registered as a hit before the
    first thread's scatter produced the version holding those bytes
    (the slot would read as zeros in the version it captured)."""

    def __init__(self, page_shape, dtype, capacity, replicated):
        import jax
        import jax.numpy as jnp

        self.page_shape = tuple(int(p) for p in page_shape)
        self.dtype = np.dtype(dtype)
        self.capacity = int(capacity)
        self.page_nbytes = int(
            np.prod(self.page_shape, dtype=np.int64)
        ) * self.dtype.itemsize
        self.pool = jax.device_put(
            jnp.zeros((self.capacity,) + self.page_shape, self.dtype),
            replicated,
        )
        self._replicated = replicated
        self._lock = threading.Lock()
        # content crc -> slot, in LRU order (oldest first)
        self.slots: "OrderedDict[int, int]" = OrderedDict()
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._update = jax.jit(
            lambda pool, idx, pages: pool.at[idx].set(pages),
            donate_argnums=(),
        )

    @property
    def nbytes(self) -> int:
        return self.capacity * self.page_nbytes

    def _take_slot(self) -> int:
        if self._free:
            return self._free.pop()
        # LRU eviction: the oldest content loses its slot.  Purely a
        # mapping change — the resident bytes are overwritten by the next
        # scatter, and older pool versions held by in-flight batches are
        # immutable.
        _, slot = self.slots.popitem(last=False)
        bump("device_pool_evictions")
        return slot

    def stage_pages(self, host_pool: np.ndarray, n_used: int):
        """Map host slots ``[0, n_used)`` to resident slots, uploading
        only contents the arena does not hold.  Returns the
        ``host_slot -> device_slot`` mapping array and the pool version
        that holds every mapped slot's bytes (the pair is atomic — a
        caller must dispatch against exactly this version)."""
        import jax

        with self._lock:
            return self._stage_pages_locked(host_pool, n_used, jax)

    def _stage_pages_locked(self, host_pool: np.ndarray, n_used: int, jax):
        mapping = np.zeros(n_used, np.int32)
        miss_slots: List[int] = []
        miss_pages: List[np.ndarray] = []
        for s in range(n_used):
            key = _content_key(host_pool[s])
            slot = self.slots.get(key)
            if slot is not None:
                self.slots.move_to_end(key)
                bump("device_pool_hits")
                bump("bytes_not_staged", self.page_nbytes)
            else:
                slot = self._take_slot()
                self.slots[key] = slot
                bump("device_pool_misses")
                miss_slots.append(slot)
                miss_pages.append(host_pool[s])
            mapping[s] = slot
        if miss_slots:
            # quantize the upload width (compile-population bound): the
            # pad repeats the last (slot, page) pair — same slot, same
            # bytes, a benign duplicate write
            width = _quantize_count(len(miss_slots))
            while len(miss_slots) < width:
                miss_slots.append(miss_slots[-1])
                miss_pages.append(miss_pages[-1])
            stacked = np.stack(miss_pages)
            record_h2d(stacked.nbytes)
            staged = jax.device_put(stacked, self._replicated)
            idx = jax.device_put(
                np.asarray(miss_slots, np.int32), self._replicated
            )
            self.pool = self._update(self.pool, idx, staged)
        return mapping, self.pool


class DevicePagePool:
    """Process-wide manager of the resident arenas, one per ``(device
    set, page_shape, dtype)`` class, under one byte budget.  Thread-safe
    end to end: arena lookup/growth serializes here, page staging
    serializes per arena — concurrent executors (the server's worker
    pool) share the resident pages safely."""

    def __init__(self, budget: Optional[int] = None):
        self._lock = threading.Lock()
        self._arenas: "OrderedDict[tuple, _DeviceArena]" = OrderedDict()
        self._budget = device_pool_budget(budget)

    def evict_all(self) -> None:
        """Drop every arena (the degrade ladder's evict rung, and the
        test hook): resident bytes are released as soon as no in-flight
        batch references the pool versions."""
        with self._lock:
            self._arenas.clear()

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(a.nbytes for a in self._arenas.values())

    def _arena_for(self, spec: RaggedArgSpec, need_pages: int,
                   dev_key, replicated) -> _DeviceArena:
        akey = (dev_key, spec.page_shape, spec.dtype)
        page_nbytes = int(
            np.prod(spec.page_shape, dtype=np.int64)
        ) * np.dtype(spec.dtype).itemsize
        cap_budget = self._budget // max(1, page_nbytes)
        if need_pages > cap_budget:
            raise DevicePoolExhausted(
                f"batch needs {need_pages} pages of {page_nbytes} B but "
                f"the device pool budget ({self._budget} B) holds at most "
                f"{cap_budget}"
            )
        with self._lock:
            arena = self._arenas.get(akey)
            if arena is not None and arena.capacity >= need_pages:
                self._arenas.move_to_end(akey)
                return arena
            # grow = a fresh arena at the next quantized capacity (the
            # pool's leading dim is a compile key, so growth is a planned
            # recompile, not a per-batch one); old mappings die with it
            capacity = min(_quantize_pages(need_pages), cap_budget)
            arena = _DeviceArena(
                spec.page_shape, spec.dtype, capacity, replicated
            )
            self._arenas[akey] = arena
            # budget across arenas: evict oldest whole arenas until the
            # resident total fits (never the one just built)
            while (
                sum(a.nbytes for a in self._arenas.values()) > self._budget
                and len(self._arenas) > 1
            ):
                self._arenas.popitem(last=False)
                bump("device_pool_evictions")
            return arena

    def _stage(self, rb: RaggedBatch, dev_key, replicated) -> StagedBatch:
        specs: List[RaggedArgSpec] = []
        pools = []
        tables: List[np.ndarray] = []
        staged0 = snapshot()
        for spec, pool, table in zip(rb.specs, rb.pools, rb.tables):
            n_used = int(table.max()) + 1
            arena = self._arena_for(spec, n_used, dev_key, replicated)
            mapping, pool_version = arena.stage_pages(pool, n_used)
            specs.append(spec._replace(pool_pages=arena.capacity))
            pools.append(pool_version)
            tables.append(mapping[table])
        moved = delta(staged0)
        bump("device_batches_staged")
        return StagedBatch(
            specs, pools, tables, list(rb.valids), rb.width,
            staged_bytes=int(moved["h2d_bytes"]),
            reused_bytes=int(moved["bytes_not_staged"]),
        )

    def stage(self, rb: RaggedBatch, dev_key, replicated,
              block_id: Optional[int] = None) -> StagedBatch:
        """Stage ``rb`` against the resident arenas; the PR-4 ladder on
        RESOURCE_EXHAUSTED: evict every arena, retry once at full size,
        then raise :class:`DevicePoolExhausted` so the executor falls
        back to per-batch host staging (``degraded:host_staged``)."""
        from ..runtime import faults as faults_mod
        from ..runtime.executor import classify_resource_error

        injector = faults_mod.get_injector()
        for attempt in (0, 1):
            try:
                # "h2d" fault site: an injected RESOURCE_EXHAUSTED at
                # page upload models the resident allocation not fitting
                injector.maybe_fail("h2d", block_id, voxels=rb.nbytes)
                return self._stage(rb, dev_key, replicated)
            except DevicePoolExhausted:
                raise
            except Exception as e:
                if classify_resource_error(e) is None:
                    raise
                self.evict_all()
                if attempt:
                    raise DevicePoolExhausted(
                        f"device pool RESOURCE_EXHAUSTED persisted after "
                        f"evicting all resident arenas: {e}"
                    ) from e


_pool: Optional[DevicePagePool] = None
_pool_lock = threading.Lock()


def get_device_pool(budget: Optional[int] = None) -> DevicePagePool:
    """The process-wide resident pool (created on first use).  An
    explicit ``budget`` (the task's ``device_pool_bytes`` knob) re-scopes
    the budget for subsequent staging — the arenas themselves persist,
    which is the point."""
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = DevicePagePool(budget)
    if budget is not None:
        _pool._budget = device_pool_budget(budget)
    return _pool


def reset() -> None:
    """Drop the resident pool and its arenas (tests)."""
    global _pool
    with _pool_lock:
        _pool = None
