"""Globally consistent connected components over a mesh-sharded volume.

This is the fully device-resident form of the reference's two-pass CCL
(SURVEY.md §3.2): there, per-block CCL jobs wrote partial labels to N5, a
face-scan task emitted equivalence pairs to npy files, and one *serial*
``nifty.ufd`` job merged them.  Here the volume lives sharded across the mesh
(one contiguous slab per device along the ``sp`` axis) and the whole merge is
three collectives:

1. per-shard CCL (:func:`~cluster_tools_tpu.ops.ccl.label_components`) with
   labels globalized by shard rank — no offset prefix-sum needed,
2. cross-shard face equivalences via a nearest-neighbor ``ppermute``,
3. ``all_gather`` of the (fixed-capacity) pair lists over ICI, then a
   *replicated* pointer-jumping union-find over the compressed boundary-label
   table, and a local relabel through it.

The union-find domain is only the labels that touch a shard boundary (at most
``2 * S * face_area``), never the full label space — so the replicated solve
stays small regardless of volume size.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.ccl import label_components
from ..ops.unionfind import union_find
from .halo import neighbor_face

_INT32_MAX = np.int32(np.iinfo(np.int32).max)  # numpy: no backend init at import


def _boundary_pairs(
    glob: jnp.ndarray, axis: int, axis_name: str, axis_size: int
) -> jnp.ndarray:
    """Label-equivalence pairs across the low boundary of this shard.

    Pairs up this shard's first slab along ``axis`` with the previous rank's
    last slab (face connectivity, as the reference's ``block_faces`` task).
    Invalid slots are (-1, -1), which the union-find treats as no-ops — the
    pair list has static shape ``(face_area, 2)``.
    """
    mine = lax.slice_in_dim(glob, 0, 1, axis=axis).ravel()
    theirs = neighbor_face(glob, axis, axis_name, axis_size, direction=-1).ravel()
    valid = (mine > 0) & (theirs > 0)
    return jnp.stack(
        [
            jnp.where(valid, theirs, jnp.int32(-1)),
            jnp.where(valid, mine, jnp.int32(-1)),
        ],
        axis=1,
    )


def sharded_label_components(
    mask: jnp.ndarray,
    *,
    axis_name: str,
    axis_size: int,
    connectivity: int = 1,
    shard_axis: int = 0,
) -> jnp.ndarray:
    """Connected components of a volume sharded in slabs along ``shard_axis``.

    Must run inside ``jax.shard_map``; ``mask`` is the local boolean slab.
    Returns int32 labels that are **globally consistent across all shards**:
    every component gets the (globalized) flat index + 1 of its minimum voxel
    in the *first* shard it touches; background is 0.

    Cross-shard stitching uses face connectivity, so ``connectivity`` must be
    1 (same restriction as the blockwise ``block_faces`` task).
    """
    if connectivity != 1:
        raise NotImplementedError(
            "cross-shard stitching supports connectivity=1 only"
        )
    shape = mask.shape
    n_slab = int(np.prod(shape))
    if axis_size * n_slab >= 2**31:
        raise ValueError(
            f"{axis_size} shards of {n_slab} voxels overflow int32 labels; "
            "use more/smaller shards per program or process in block batches"
        )
    rank = lax.axis_index(axis_name)

    # 1. per-shard CCL; globalize by rank so labels are unique across shards
    raw = label_components(mask, connectivity=connectivity)
    glob = jnp.where(
        raw == n_slab, 0, raw + 1 + rank.astype(jnp.int32) * jnp.int32(n_slab)
    ).astype(jnp.int32)

    # 2. cross-shard equivalences + 3. all_gather and replicated union-find
    pairs = _boundary_pairs(glob, shard_axis, axis_name, axis_size)
    all_pairs = lax.all_gather(pairs, axis_name).reshape(-1, 2)

    # compress the (sparse) boundary labels into a dense table
    cap = int(all_pairs.shape[0]) * 2
    flat = all_pairs.ravel()
    flat = jnp.where(flat < 0, _INT32_MAX, flat)
    keys = jnp.unique(flat, size=cap, fill_value=_INT32_MAX)
    dense = jnp.searchsorted(keys, jnp.maximum(all_pairs, 0)).astype(jnp.int32)
    dense = jnp.where(all_pairs < 0, jnp.int32(-1), dense)
    parent = union_find(dense, cap)
    # keys are sorted ascending, so the min dense root is the min label
    rep = keys[parent]

    # 4. local relabel through the boundary table
    pos = jnp.clip(jnp.searchsorted(keys, glob), 0, cap - 1)
    hit = (keys[pos] == glob) & (glob > 0)
    return jnp.where(hit, rep[pos], glob)


def distributed_connected_components(
    mask,
    mesh: Mesh,
    sp_axis: str = "sp",
    connectivity: int = 1,
):
    """shard_map wrapper: CCL of a full volume sharded in slabs over ``sp_axis``.

    ``mask``'s leading dimension is sharded over ``sp_axis``; remaining axes
    are replicated.  Returns globally consistent int32 labels with the same
    sharding.
    """
    from .mesh import mesh_axis_sizes

    size = mesh_axis_sizes(mesh)[sp_axis]
    fn = jax.shard_map(
        partial(
            sharded_label_components,
            axis_name=sp_axis,
            axis_size=size,
            connectivity=connectivity,
        ),
        mesh=mesh,
        in_specs=P(sp_axis),
        out_specs=P(sp_axis),
    )
    return fn(mask)
