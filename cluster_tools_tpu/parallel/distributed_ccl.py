"""Globally consistent connected components over a mesh-sharded volume.

This is the fully device-resident form of the reference's two-pass CCL
(SURVEY.md §3.2): there, per-block CCL jobs wrote partial labels to N5, a
face-scan task emitted equivalence pairs to npy files, and one *serial*
``nifty.ufd`` job merged them.  Here the volume lives sharded across the mesh
— contiguous slabs along one axis, or a full 2-D/3-D spatial decomposition
over several mesh axes — and the whole merge is three collectives:

1. per-shard CCL (:func:`~cluster_tools_tpu.ops.ccl.label_components`) with
   labels globalized by linearized shard rank — no offset prefix-sum needed,
2. cross-shard face equivalences via a nearest-neighbor ``ppermute`` per
   sharded axis,
3. ``all_gather`` of the (fixed-capacity) pair lists over every sharded mesh
   axis, then a *replicated* pointer-jumping union-find over the compressed
   boundary-label table, and a local relabel through it.

The union-find domain is only the labels that touch a shard boundary
(O(shard-boundary area), times the small shifted-view multiplicity at
connectivity>1), never the full label space — so the replicated solve stays
small regardless of volume size.

Label-space ceilings: by default a shard's labels are globalized as
``flat_index + rank * n_slab`` (int32), which overflows once
``n_shards * n_slab >= 2**31``.  Passing ``max_labels_per_shard=C`` compacts
each shard's labels to dense ``1..K`` first (``K <= C``) and globalizes as
``rank * (C + 1) + k`` — the ceiling becomes ``n_shards * (C + 1)``, letting
teravoxel volumes run in int32 as long as no single shard holds more than
``C`` components.  A shard exceeding ``C`` produces aliased labels; every
public entry point therefore computes a mesh-wide overflow flag
(``return_overflow=True`` here and on
:func:`distributed_connected_components`; the fused pipeline returns it
unconditionally) so callers can detect the condition and re-run with a
bigger cap or more shards.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from ..ops.ccl import _match_vma, label_components, relabel_consecutive
from ..ops.tile_ccl import _compact, _shift1
from ..ops.unionfind import union_find
from .halo import neighbor_face

_INT32_MAX = np.int32(np.iinfo(np.int32).max)  # numpy: no backend init at import

# (array_axis, mesh_axis_name, mesh_axis_size)
ShardAxis = Tuple[int, str, int]


def linearized_shard_rank(axes: Sequence[ShardAxis]) -> jnp.ndarray:
    """This device's rank over the sharded axes, first listed axis slowest.

    THE label-globalization convention: every site that builds or merges
    ``rank * span + local`` labels (sharded_label_components, the fused
    pipeline's watershed globalization and stitch) must use this one
    function, or label bases silently drift apart.  Inside ``shard_map``
    only.
    """
    rank = jnp.int32(0)
    for _, name, size in axes:
        rank = rank * jnp.int32(size) + lax.axis_index(name).astype(jnp.int32)
    return rank


def sp_axes_for_mesh(mesh: Mesh, sp_axis) -> Tuple[ShardAxis, ...]:
    """Normalize a mesh-axis name or sequence of names to ``ShardAxis``
    triples over the leading array axes (the whole-volume-wrapper calling
    convention shared by the distributed CCL, EDT, and fused pipeline)."""
    from .mesh import mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    names = (sp_axis,) if isinstance(sp_axis, str) else tuple(sp_axis)
    return tuple((i, name, sizes[name]) for i, name in enumerate(names))


def _boundary_pairs(
    glob: jnp.ndarray, axes: Sequence[ShardAxis], connectivity: int
) -> jnp.ndarray:
    """Label-equivalence pairs across every shard boundary of this shard.

    Generalizes the reference's ``block_faces`` scan to the mesh: for each
    unordered neighbor-shard direction over the sharded axes (first nonzero
    -1, so every shard pair is emitted exactly once; faces at connectivity
    1, plus edge-/corner-adjacent shards at higher connectivity), the
    neighbor's boundary slab arrives by composing one ``ppermute`` per
    crossed axis, and in-slab diagonal adjacency is enumerated as shifted
    views with at most ``connectivity`` total differing coordinates (scipy
    semantics).  Invalid slots are (-1, -1), which the union-find treats as
    no-ops — the pair list has a static shape.
    """
    from itertools import product as iproduct

    from ..ops.ccl import _neighbor_offsets, _shift

    shard_ax = [a for a, _, _ in axes]
    meta = {a: (name, size) for a, name, size in axes}
    out = []
    # the kernel's half-neighborhood, negated: directions whose first nonzero
    # is -1, i.e. each shard receives from its lower-ranked neighbors so every
    # unordered shard pair is emitted exactly once
    for d_combo in (
        tuple(-v for v in d) for d in _neighbor_offsets(len(shard_ax), connectivity)
    ):
        theirs = glob
        mine = glob
        for a, dv in zip(shard_ax, d_combo):
            if dv == 0:
                continue
            name, size = meta[a]
            # ppermute composes: after the first crossing the slab is
            # 1-thick along that axis and the next crossing slices it along
            # its own axis — shards beyond the grid edge contribute 0s
            theirs = neighbor_face(theirs, a, name, size, direction=dv)
            if dv == -1:
                mine = lax.slice_in_dim(mine, 0, 1, axis=a)
            else:
                mine = lax.slice_in_dim(
                    mine, mine.shape[a] - 1, mine.shape[a], axis=a
                )
        crossing = set(a for a, dv in zip(shard_ax, d_combo) if dv)
        budget = connectivity - len(crossing)
        free = [a for a in range(glob.ndim) if a not in crossing]
        for s_combo in iproduct((-1, 0, 1), repeat=len(free)):
            if sum(1 for v in s_combo if v) > budget:
                continue
            th = theirs
            for a, sv in zip(free, s_combo):
                if sv:
                    # th[p] = theirs[p + sv] along axis a; voxels shifted in
                    # from outside the slab are 0 (background, never pair)
                    th = _shift(th, -sv, a, 0)
            m = mine.ravel()
            t = th.ravel()
            valid = (m > 0) & (t > 0)
            out.append(
                jnp.stack(
                    [
                        jnp.where(valid, t, jnp.int32(-1)),
                        jnp.where(valid, m, jnp.int32(-1)),
                    ],
                    axis=1,
                )
            )
    return jnp.concatenate(out, axis=0)


def _norm_shard_axes(
    axis_name: Optional[str],
    axis_size: Optional[int],
    shard_axis: int,
    shard_axes: Optional[Sequence[ShardAxis]],
) -> Tuple[ShardAxis, ...]:
    if shard_axes is not None:
        if axis_name is not None:
            raise ValueError("pass either axis_name/axis_size or shard_axes, not both")
        return tuple((int(a), str(n), int(s)) for a, n, s in shard_axes)
    if axis_name is None or axis_size is None:
        raise ValueError("axis_name and axis_size required without shard_axes")
    return ((int(shard_axis), axis_name, int(axis_size)),)


def sharded_label_components(
    mask: jnp.ndarray,
    *,
    axis_name: Optional[str] = None,
    axis_size: Optional[int] = None,
    connectivity: int = 1,
    shard_axis: int = 0,
    shard_axes: Optional[Sequence[ShardAxis]] = None,
    max_labels_per_shard: Optional[int] = None,
    return_overflow: bool = False,
    impl: str = "legacy",
):
    """Connected components of a volume sharded over one or more mesh axes.

    Must run inside ``jax.shard_map``; ``mask`` is the local boolean shard.
    Single-axis (slab) sharding: pass ``axis_name``/``axis_size`` (+
    ``shard_axis``).  Multi-axis decomposition: pass ``shard_axes`` as a
    sequence of ``(array_axis, mesh_axis_name, mesh_axis_size)`` — e.g. a
    (2, 2, 2) spatial grid shards z, y and x each over its own mesh axis,
    with face equivalences exchanged per axis.

    Returns int32 labels that are **globally consistent across all shards**;
    background is 0.  With ``max_labels_per_shard`` set, per-shard labels are
    compacted before globalization (see module docstring); with
    ``return_overflow`` also returns a replicated bool that is True when any
    shard exceeded the compaction capacity (labels are then unreliable).

    Cross-shard stitching matches the in-shard neighborhood at any
    ``connectivity`` (scipy semantics): faces at 1, plus diagonal adjacency
    across face-, edge- and corner-adjacent shards at 2/3.

    ``impl``: per-shard CCL kernel — "legacy" (ops.ccl hook/compress),
    "tiled"/"pallas"/"xla"/"auto" (the two-level ops.tile_ccl machinery; on
    3-D shards with connectivity 1 this is the TPU fast path, and its
    capacity overflow is folded into the returned overflow flag).
    """
    if not 1 <= connectivity <= mask.ndim:
        raise ValueError(f"connectivity must be in [1, {mask.ndim}]")
    axes = _norm_shard_axes(axis_name, axis_size, shard_axis, shard_axes)
    shape = mask.shape
    n_slab = int(np.prod(shape))
    n_shards = int(np.prod([s for _, _, s in axes]))

    rank = linearized_shard_rank(axes)

    # 1. per-shard CCL; globalize so labels are unique across shards
    use_tiled = impl != "legacy" and mask.ndim == 3 and connectivity == 1
    if use_tiled:
        from ..ops.tile_ccl import label_components_tiled

        tiled_impl = "xla" if impl == "tiled" else impl
        raw, tiled_overflow = label_components_tiled(
            mask, connectivity=connectivity, impl=tiled_impl
        )
    else:
        raw = label_components(mask, connectivity=connectivity)
        tiled_overflow = None
    # constant-False flag carrying the shard data's vma type, so the pmax
    # reduction below is legal with or without compaction
    overflow = raw.ravel()[0] * 0 > 0
    if tiled_overflow is not None:
        overflow = overflow | tiled_overflow
    if max_labels_per_shard is None:
        if n_shards * n_slab >= 2**31:
            raise ValueError(
                f"{n_shards} shards of {n_slab} voxels overflow int32 labels; "
                "pass max_labels_per_shard to compact per-shard label spaces"
            )
        local = jnp.where(raw == n_slab, 0, raw + 1).astype(jnp.int32)
        glob = jnp.where(local > 0, local + rank * jnp.int32(n_slab), 0)
    else:
        cap = int(max_labels_per_shard)
        if n_shards * (cap + 1) >= 2**31:
            raise ValueError(
                f"{n_shards} shards x {cap} labels still overflow int32"
            )
        local = jnp.where(raw == n_slab, 0, raw + 1).astype(jnp.int32)
        # labels are slab flat indices + 1: pass the true value span so the
        # bitmap fast path engages (the default infers from labels.size)
        dense, n_fg = relabel_consecutive(
            local, max_labels=cap, value_bound=n_slab
        )
        overflow = overflow | (n_fg > cap)
        glob = jnp.where(dense > 0, dense + rank * jnp.int32(cap + 1), 0)

    if n_shards == 1:
        # no cross-shard faces exist: per-shard labels are already global.
        # This also keeps the single-chip benchmark free of the (empty)
        # pair/merge machinery.  The overflow flag still needs its pmax over
        # the (size-1) sharded axes: the flag is promised replicated, and
        # shard_map's vma check rejects an sp-varying scalar against P().
        if return_overflow:
            ov = overflow.astype(jnp.int32)
            for _, name, _ in axes:
                ov = lax.pmax(ov, name)
            return glob, ov > 0
        return glob

    # 2. cross-shard equivalences (faces; diagonals too at connectivity>1)
    pairs = _boundary_pairs(glob, axes, connectivity)
    if return_overflow:
        ov = overflow.astype(jnp.int32)
        for _, name, _ in axes:
            ov = lax.pmax(ov, name)
        overflow = ov > 0

    # 3+4. gathered replicated solve + local relabel
    span = (n_slab if max_labels_per_shard is None
            else int(max_labels_per_shard) + 1)
    labels = merge_labels_by_pairs(glob, pairs, axes, rank, span)
    if return_overflow:
        return labels, overflow
    return labels


def merge_labels_by_pairs(
    glob: jnp.ndarray,
    pairs: jnp.ndarray,
    axes: Sequence[ShardAxis],
    rank: jnp.ndarray,
    span: int,
    pair_cap: Optional[int] = None,
) -> jnp.ndarray:
    """Merge globalized per-shard labels through cross-shard equivalences.

    The replicated tail of the two-pass merge, shared by the distributed CCL
    and the fused pipeline's watershed-fragment stitch: dedup the pair list,
    ``all_gather`` it over every sharded mesh axis, compress the (sparse)
    boundary labels into a dense table, pointer-jump the union-find, and
    relabel the local shard through it.

    ``pairs`` arrives FACE-sized — one row per contact voxel, invalid slots
    (-1, -1) — but unique label equivalences are object-scale, so each
    shard sorts and dedups to ``pair_cap`` (default
    ``max(16384, rows/8)`` — below the floor the dedup is skipped
    entirely) BEFORE the collective: the ICI payload and the replicated unique/union-find tail
    shrink by the dedup factor.  Correctness never depends on the cap: a
    ``pmax``-replicated unique count selects a full-size fallback branch
    when ANY shard's dedup would not fit (the predicate must agree across
    shards — both branches contain the ``all_gather``).

    ``glob`` must be globalized as ``rank * span + local`` with local labels
    in ``1..span``.  The final gather is one direct table lookup per voxel —
    a ``searchsorted`` over the full shard would binary-search-gather per
    element (measured ~50x slower on TPU).
    """
    n_in = int(pairs.shape[0])
    if pair_cap is None:
        pair_cap = max(16384, n_in // 8)

    def _tail(shard_pairs):
        all_pairs = shard_pairs
        for _, name, _ in axes:
            all_pairs = lax.all_gather(all_pairs, name).reshape(-1, 2)
        # compress the (sparse) boundary labels into a dense table
        cap = int(all_pairs.shape[0]) * 2
        flat = all_pairs.ravel()
        flat = jnp.where(flat < 0, _INT32_MAX, flat)
        keys = jnp.unique(flat, size=cap, fill_value=_INT32_MAX)
        dense = jnp.searchsorted(
            keys, jnp.maximum(all_pairs, 0)
        ).astype(jnp.int32)
        dense = jnp.where(all_pairs < 0, jnp.int32(-1), dense)
        parent = union_find(dense, cap)
        # keys are sorted ascending, so the min dense root is the min label
        rep = keys[parent]

        base = rank * jnp.int32(span)
        table = _match_vma(jnp.arange(span + 1, dtype=jnp.int32), glob) + base
        loc = keys - base  # position of each boundary label if it is ours
        mine = (keys != _INT32_MAX) & (loc >= 1) & (loc <= span)
        table = table.at[jnp.where(mine, loc, span + 1)].set(
            rep, mode="drop"
        )
        idx = jnp.clip(glob - base, 0, span)
        return jnp.where(glob > 0, table[idx], 0)

    if pair_cap >= n_in:
        return _tail(pairs)

    # per-shard dedup: sort, keep first of each run, compact to pair_cap
    a = jnp.where(pairs[:, 0] < 0, _INT32_MAX, pairs[:, 0])
    b = jnp.where(pairs[:, 0] < 0, _INT32_MAX, pairs[:, 1])
    a, b = lax.sort((a, b), num_keys=2)
    keep = (
        (a != _shift1(a, 0, -1)) | (b != _shift1(b, 0, -1))
    ) & (a != _INT32_MAX)
    (ca, cb), n_kept = _compact(keep, (a, b), pair_cap, -1)
    deduped = jnp.stack([ca, cb], axis=1)
    # the branch predicate must agree on EVERY shard (both branches carry
    # the all_gather): replicate the worst-case unique count first
    n_max = n_kept
    for _, name, _ in axes:
        n_max = lax.pmax(n_max, name)
    return lax.cond(
        n_max <= pair_cap,
        lambda _: _tail(deduped),
        lambda _: _tail(pairs),
        operand=None,
    )


def distributed_connected_components(
    mask,
    mesh: Mesh,
    sp_axis: Union[str, Sequence[str]] = "sp",
    connectivity: int = 1,
    max_labels_per_shard: Optional[int] = None,
    return_overflow: bool = False,
    impl: str = "legacy",
):
    """shard_map wrapper: CCL of a full volume sharded over ``sp_axis``.

    ``sp_axis`` may be one mesh axis name (volume sharded in slabs along its
    leading dimension) or a sequence of names (leading dimensions sharded
    over the respective axes — a 2-D/3-D spatial decomposition).  Returns
    globally consistent int32 labels with the same sharding; with
    ``return_overflow`` also a replicated bool that is True when any shard
    exceeded ``max_labels_per_shard`` (labels are then unreliable — re-run
    with a bigger cap or more shards).
    """
    names = [sp_axis] if isinstance(sp_axis, str) else list(sp_axis)
    shard_axes = sp_axes_for_mesh(mesh, sp_axis)
    fn = shard_map(
        partial(
            sharded_label_components,
            shard_axes=shard_axes,
            connectivity=connectivity,
            max_labels_per_shard=max_labels_per_shard,
            return_overflow=return_overflow,
            impl=impl,
        ),
        mesh=mesh,
        in_specs=P(*names),
        out_specs=(P(*names), P()) if return_overflow else P(*names),
        # see make_ws_ccl_step: Pallas in-kernel vma propagation is broken on
        # this JAX version; only the static replication check is disabled
        check_vma=False,
    )
    return fn(mask)
