"""Exact Euclidean distance transform of a mesh-sharded volume.

The reference's EDT was strictly per-block (vigra inside ``_ws_block``,
SURVEY.md §2a "watershed"): distances saturate at the halo scale, because a
block cannot see background beyond its own read window.  On a mesh the
limitation disappears: the separable min-plus passes commute, so the sharded
axis's pass simply runs *after* an ICI all-to-all that makes that axis fully
resident (:mod:`.reshard` — the sequence-parallel layout-flip pattern), and
every pass operates at full global extent.  Two all-to-alls total; every
pass is the same dense erosion cascade the single-device transform uses
(``ops/edt.py``), Mosaic-accelerated on TPU.

This gives the *exact* global EDT — something the reference could not
compute blockwise at all — while keeping per-device memory at one shard.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.edt import _BIG, _norm_sampling, edt_axis_pass
from .reshard import reshard_axis


def sharded_distance_transform_squared(
    mask: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    sharded_axis: int = 0,
    sampling: Optional[Sequence[float]] = None,
    max_distance: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Squared EDT inside ``shard_map``; ``mask`` is the local shard.

    The volume is globally sharded along ``sharded_axis``; the result has
    the same sharding.  All distances are globally exact (up to
    ``max_distance``, if given).  The reshard target is the last axis other
    than ``sharded_axis``, whose local extent must be divisible by
    ``axis_size``.
    """
    ndim = mask.ndim
    sampling = _norm_sampling(ndim, sampling)
    shard = int(sharded_axis) % ndim
    resident = max(a for a in range(ndim) if a != shard)
    if mask.shape[resident] % axis_size:
        raise ValueError(
            f"reshard axis {resident} extent {mask.shape[resident]} not "
            f"divisible by mesh axis size {axis_size}"
        )
    global_extent = {
        a: mask.shape[a] * (axis_size if a == shard else 1) for a in range(ndim)
    }
    if max_distance is None:
        radii = {a: global_extent[a] - 1 for a in range(ndim)}
    else:
        radii = {
            a: int(np.ceil(float(max_distance) / sampling[a])) for a in range(ndim)
        }

    f = jnp.where(mask, _BIG, jnp.float32(0.0))
    # passes along the already-resident axes
    for a in range(ndim):
        if a != shard:
            f = edt_axis_pass(f, a, sampling[a] ** 2, radii[a], impl=impl)
    # flip the sharded axis resident (one ICI all-to-all), run its pass at
    # full global extent, flip back
    f = reshard_axis(f, axis_name, from_axis=shard, to_axis=resident)
    f = edt_axis_pass(f, shard, sampling[shard] ** 2, radii[shard], impl=impl)
    f = reshard_axis(f, axis_name, from_axis=resident, to_axis=shard)
    return jnp.minimum(f, _BIG)


def distributed_distance_transform(
    mask,
    mesh: Mesh,
    sp_axis: str = "sp",
    sharded_axis: int = 0,
    sampling: Optional[Sequence[float]] = None,
    max_distance: Optional[float] = None,
    impl: str = "auto",
):
    """Whole-volume wrapper: exact EDT of a volume sharded over ``sp_axis``.

    Returns the (non-squared) distance with the input's sharding.  Unlike
    the per-block transform, distances do NOT saturate at any halo — the
    sharded axis's pass runs at full extent after an all-to-all reshard.
    ``sampling`` may be a scalar, list, tuple, or array (normalized here,
    BEFORE the jit boundary — it is a static argument underneath).
    """
    if sampling is not None:
        sampling = tuple(float(s) for s in np.atleast_1d(sampling))
    return _distributed_distance_transform(
        mask, mesh, sp_axis, sharded_axis, sampling,
        None if max_distance is None else float(max_distance), impl,
    )


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "sp_axis", "sharded_axis", "sampling", "max_distance", "impl",
    ),
)
def _distributed_distance_transform(
    mask,
    mesh: Mesh,
    sp_axis: str,
    sharded_axis: int,
    sampling: Optional[Tuple[float, ...]],
    max_distance: Optional[float],
    impl: str,
):
    from .mesh import mesh_axis_sizes

    n = mesh_axis_sizes(mesh)[sp_axis]
    spec = [None] * mask.ndim
    spec[int(sharded_axis) % mask.ndim] = sp_axis

    fn = jax.shard_map(
        partial(
            sharded_distance_transform_squared,
            axis_name=sp_axis,
            axis_size=n,
            sharded_axis=sharded_axis,
            sampling=sampling,
            max_distance=max_distance,
            impl=impl,
        ),
        mesh=mesh,
        in_specs=P(*spec),
        out_specs=P(*spec),
        # Pallas EDT cascades may run inside (see make_ws_ccl_step: in-kernel
        # vma propagation is broken on this JAX version; check only)
        check_vma=False,
    )
    return jnp.sqrt(fn(mask))
