"""Exact Euclidean distance transform of a mesh-sharded volume.

The reference's EDT was strictly per-block (vigra inside ``_ws_block``,
SURVEY.md §2a "watershed"): distances saturate at the halo scale, because a
block cannot see background beyond its own read window.  On a mesh the
limitation disappears: the separable min-plus passes commute, so the sharded
axis's pass simply runs *after* an ICI all-to-all that makes that axis fully
resident (:mod:`.reshard` — the sequence-parallel layout-flip pattern), and
every pass operates at full global extent.  Two all-to-alls total; every
pass is the same dense erosion cascade the single-device transform uses
(``ops/edt.py``), Mosaic-accelerated on TPU.

This gives the *exact* global EDT — something the reference could not
compute blockwise at all — while keeping per-device memory at one shard.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from ..ops.edt import _BIG, _norm_sampling, edt_axis_pass
from .reshard import reshard_axis


def sharded_distance_transform_squared(
    mask: jnp.ndarray,
    *,
    axis_name: Optional[str] = None,
    axis_size: Optional[int] = None,
    sharded_axis: int = 0,
    shard_axes: Optional[Sequence[Tuple[int, str, int]]] = None,
    sampling: Optional[Sequence[float]] = None,
    max_distance: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Squared EDT inside ``shard_map``; ``mask`` is the local shard.

    Single-axis (slab) sharding: pass ``axis_name``/``axis_size``
    (+ ``sharded_axis``).  Multi-axis decomposition: pass ``shard_axes`` as
    a sequence of ``(array_axis, mesh_axis_name, mesh_axis_size)``, as in
    :func:`~.distributed_ccl.sharded_label_components`.  The result keeps
    the input sharding, and all distances are globally exact (up to
    ``max_distance``, if given): each sharded axis's pass runs at full
    extent after an all-to-all flips its sharding onto the reshard target —
    the last non-sharded array axis, or the last *other* sharded axis in a
    fully decomposed volume.  The target's local extent must be divisible by
    every flipped mesh-axis size.
    """
    from .distributed_ccl import _norm_shard_axes

    axes = _norm_shard_axes(axis_name, axis_size, sharded_axis, shard_axes)
    ndim = mask.ndim
    sampling = _norm_sampling(ndim, sampling)
    sharded = {a: (name, n) for a, name, n in axes}
    global_extent = {
        a: mask.shape[a] * sharded.get(a, (None, 1))[1] for a in range(ndim)
    }
    if max_distance is None:
        radii = {a: global_extent[a] - 1 for a in range(ndim)}
    else:
        radii = {
            a: int(np.ceil(float(max_distance) / sampling[a])) for a in range(ndim)
        }

    f = jnp.where(mask, _BIG, jnp.float32(0.0))
    # passes along the already-resident axes (no communication)
    for a in range(ndim):
        if a not in sharded:
            f = edt_axis_pass(f, a, sampling[a] ** 2, radii[a], impl=impl)
    # each sharded axis: flip it resident (one ICI all-to-all), run its pass
    # at full global extent, flip back.  The flip target may itself be
    # sharded by ANOTHER mesh axis — the all_to_all then just splits the
    # target's local extent further, which stays correct as long as it
    # divides evenly.
    for a, name, n in axes:
        # prefer an UNSHARDED flip target (no extra divisibility constraint);
        # only a fully decomposed volume falls back to another sharded axis
        free = [x for x in range(ndim) if x != a and x not in sharded]
        resident = max(free) if free else max(x for x in range(ndim) if x != a)
        if f.shape[resident] % n:
            raise ValueError(
                f"reshard axis {resident} local extent {f.shape[resident]} "
                f"not divisible by mesh axis {name!r} size {n}"
            )
        f = reshard_axis(f, name, from_axis=a, to_axis=resident)
        f = edt_axis_pass(f, a, sampling[a] ** 2, radii[a], impl=impl)
        f = reshard_axis(f, name, from_axis=resident, to_axis=a)
    return jnp.minimum(f, _BIG)


def distributed_distance_transform(
    mask,
    mesh: Mesh,
    sp_axis: Union[str, Sequence[str]] = "sp",
    sharded_axis: int = 0,
    sampling: Optional[Sequence[float]] = None,
    max_distance: Optional[float] = None,
    impl: str = "auto",
):
    """Whole-volume wrapper: exact EDT of a volume sharded over ``sp_axis``.

    ``sp_axis`` may be one mesh axis name (volume sharded along
    ``sharded_axis``) or a sequence of names (leading array axes sharded
    over the respective mesh axes — a 2-D/3-D spatial decomposition, as in
    :func:`~.distributed_ccl.distributed_connected_components`).  Returns
    the (non-squared) distance with the input's sharding.  Unlike the
    per-block transform, distances do NOT saturate at any halo — every
    sharded axis's pass runs at full extent after an all-to-all reshard.
    ``sampling`` may be a scalar, list, tuple, or array (normalized here,
    BEFORE the jit boundary — it is a static argument underneath).
    """
    if sampling is not None:
        sampling = tuple(float(s) for s in np.atleast_1d(sampling))
    names = (sp_axis,) if isinstance(sp_axis, str) else tuple(sp_axis)
    if isinstance(sp_axis, str):
        array_axes = (int(sharded_axis) % mask.ndim,)
    else:
        if sharded_axis != 0:
            raise ValueError(
                "sharded_axis only applies to single-axis sharding; a "
                "sequence sp_axis shards the leading array axes"
            )
        array_axes = tuple(range(len(names)))
    return _distributed_distance_transform(
        mask, mesh, names, array_axes, sampling,
        None if max_distance is None else float(max_distance), impl,
    )


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "names", "array_axes", "sampling", "max_distance", "impl",
    ),
)
def _distributed_distance_transform(
    mask,
    mesh: Mesh,
    names: Tuple[str, ...],
    array_axes: Tuple[int, ...],
    sampling: Optional[Tuple[float, ...]],
    max_distance: Optional[float],
    impl: str,
):
    from .mesh import mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    shard_axes = tuple(
        (a, name, sizes[name]) for a, name in zip(array_axes, names)
    )
    spec = [None] * mask.ndim
    for a, name in zip(array_axes, names):
        spec[a] = name

    fn = shard_map(
        partial(
            sharded_distance_transform_squared,
            shard_axes=shard_axes,
            sampling=sampling,
            max_distance=max_distance,
            impl=impl,
        ),
        mesh=mesh,
        in_specs=P(*spec),
        out_specs=P(*spec),
        # Pallas EDT cascades may run inside (see make_ws_ccl_step: in-kernel
        # vma propagation is broken on this JAX version; check only)
        check_vma=False,
    )
    return jnp.sqrt(fn(mask))
