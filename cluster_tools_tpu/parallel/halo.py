"""Device-side halo (ghost-zone) exchange over a sharded spatial axis.

The reference implemented halos as *overlapping filesystem reads*: every
block job independently re-read up to ``halo`` voxels of its neighbors' data
from the shared N5 store (SURVEY.md §2c "Halo/ghost-zone exchange").  On a
mesh the neighbor data already sits in the neighbor device's HBM, so the halo
is a nearest-neighbor ``lax.ppermute`` over ICI — the same communication
pattern as ring/context-parallel attention, applied to a spatial axis
(SURVEY.md §5.7).

All functions here must be called *inside* ``jax.shard_map`` with ``x`` being
the local shard.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax


def exchange_halo(
    x: jnp.ndarray,
    halo: int,
    axis: int,
    axis_name: str,
    axis_size: int,
    fill=0,
) -> jnp.ndarray:
    """Pad the local shard with ``halo`` slabs from its mesh neighbors.

    Returns an array whose extent along ``axis`` is ``x.shape[axis] + 2*halo``.
    At the mesh ends (rank 0 low side, rank S-1 high side) the halo is filled
    with ``fill`` — matching the reference's border-clipped halo semantics
    where kernels receive a validity mask / padded border instead.

    ``axis_size`` is the static size of the mesh axis (shard_map callers know
    it from the mesh).
    """
    if halo <= 0:
        return x
    if x.shape[axis] < halo:
        raise ValueError(
            f"shard extent {x.shape[axis]} along axis {axis} smaller than halo {halo}"
        )
    n = int(axis_size)
    idx = lax.axis_index(axis_name)
    lo_slab = lax.slice_in_dim(x, 0, halo, axis=axis)
    hi_slab = lax.slice_in_dim(x, x.shape[axis] - halo, x.shape[axis], axis=axis)
    # my low rows -> previous rank's high halo; my high rows -> next rank's low
    halo_hi = lax.ppermute(
        lo_slab, axis_name, [(i, i - 1) for i in range(1, n)]
    )
    halo_lo = lax.ppermute(
        hi_slab, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    # ppermute zero-fills ranks that receive nothing; rewrite with `fill` when
    # a non-zero border fill is requested (e.g. +inf heights, True masks)
    if not (isinstance(fill, (int, float)) and fill == 0):
        halo_hi = jnp.where(idx == n - 1, jnp.full_like(halo_hi, fill), halo_hi)
        halo_lo = jnp.where(idx == 0, jnp.full_like(halo_lo, fill), halo_lo)
    return jnp.concatenate([halo_lo, x, halo_hi], axis=axis)


def crop_halo(x: jnp.ndarray, halo: int, axis: int) -> jnp.ndarray:
    """Inverse of :func:`exchange_halo`: drop ``halo`` slabs from both ends."""
    if halo <= 0:
        return x
    return lax.slice_in_dim(x, halo, x.shape[axis] - halo, axis=axis)


def neighbor_face(
    x: jnp.ndarray,
    axis: int,
    axis_name: str,
    axis_size: int,
    direction: int = -1,
    fill=0,
) -> jnp.ndarray:
    """The 1-voxel face of the neighboring shard adjacent to this shard.

    ``direction=-1`` returns the *previous* rank's last slab (the face just
    below this shard's first voxel); ``direction=+1`` the next rank's first
    slab.  Used by the distributed label merge to emit cross-shard
    equivalences without a full halo exchange.
    """
    n = int(axis_size)
    idx = lax.axis_index(axis_name)
    if direction == -1:
        slab = lax.slice_in_dim(x, x.shape[axis] - 1, x.shape[axis], axis=axis)
        out = lax.ppermute(slab, axis_name, [(i, i + 1) for i in range(n - 1)])
        edge = idx == 0
    elif direction == 1:
        slab = lax.slice_in_dim(x, 0, 1, axis=axis)
        out = lax.ppermute(slab, axis_name, [(i, i - 1) for i in range(1, n)])
        edge = idx == n - 1
    else:
        raise ValueError(f"direction must be +/-1, got {direction}")
    if not (isinstance(fill, (int, float)) and fill == 0):
        out = jnp.where(edge, jnp.full_like(out, fill), out)
    return out
