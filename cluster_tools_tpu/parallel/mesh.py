"""Device-mesh construction.

The reference mapped blocks to slurm array jobs (``BaseClusterTask.
prepare_jobs``, SURVEY.md §2a); here the "cluster" is a ``jax.sharding.Mesh``.
Two axes cover this framework's parallelism:

- ``dp`` — data parallel over independent volumes / block batches,
- ``sp`` — spatial parallel: contiguous slabs of one volume, with halo
  exchange and label-merge collectives over ICI (the analogue of sequence /
  context parallelism for 3-D space, SURVEY.md §5.7).

Multi-host pods extend the same mesh over DCN via ``jax.distributed`` — the
mesh abstraction is identical, only the device list grows.  See
:mod:`~cluster_tools_tpu.parallel.multihost` for the wiring
(``initialize`` + ``pod_mesh``) and the local fake-pod launcher the tests
use.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _pick_grid(n: int, n_axes: int) -> Tuple[int, ...]:
    """Factor ``n`` devices into a mesh grid, favoring the last (sp) axis."""
    if n_axes == 1:
        return (n,)
    # give sp (last axis) the largest power-of-two factor, dp the rest
    sp = 1
    m = n
    while m % 2 == 0 and sp < n // 2:
        sp *= 2
        m //= 2
    if sp == 1:
        sp = n  # odd n: everything on sp, dp=1
    dp = n // sp
    grid = [1] * n_axes
    grid[-1] = sp
    grid[0] = dp
    return tuple(grid)


def backend_devices(target: str = "local", n_devices: Optional[int] = None):
    """Devices for a mesh: ``local`` = CPU (the fake-cluster test backend,
    honoring ``xla_force_host_platform_device_count``), ``tpu`` = TPU chips."""
    if target == "tpu":
        devs = [d for d in jax.devices() if d.platform == "tpu"]
        if not devs:
            raise RuntimeError("target='tpu' but no TPU devices are visible")
    elif target == "local":
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            devs = jax.devices()
    else:
        raise ValueError(f"unknown target {target!r}")
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return devs


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = ("dp", "sp"),
    grid: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    ``grid`` pins the per-axis sizes; otherwise devices are factored so the
    spatial axis gets the largest power-of-two share (halo exchange and the
    label-merge all_gather ride the densest axis).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    n = len(devices)
    if grid is None:
        grid = _pick_grid(n, len(axis_names))
    if int(np.prod(grid)) != n:
        raise ValueError(f"grid {grid} does not cover {n} devices")
    dev_array = np.array(devices).reshape(grid)
    return Mesh(dev_array, tuple(axis_names))


def mesh_axis_sizes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


#: the reduce tree's sibling axis: tree groups of one level are dealt over
#: this 1-D mesh and their labels exchanged with an in-program all_gather
#: (docs/PERFORMANCE.md "Collective reduce plane")
SIBLING_AXIS = "sib"


def sibling_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over every visible device, axis :data:`SIBLING_AXIS` — the
    collective reduce plane's hop fabric.  In-process this spans the local
    (possibly ``xla_force_host_platform_device_count`` virtual) devices; in
    a ``jax.distributed`` pod it spans the global device list, so the same
    level program moves the boundary packets over ICI/DCN instead of the
    filesystem."""
    return make_mesh(n_devices=n_devices, axis_names=(SIBLING_AXIS,))
