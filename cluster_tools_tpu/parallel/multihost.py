"""Multi-host (DCN) execution: ``jax.distributed`` wiring + process launcher.

The reference scaled past one machine by submitting slurm/LSF array jobs that
only ever talked through the shared filesystem (SURVEY.md §2d).  The
TPU-native equivalent is a **multi-process JAX program**: every host runs the
same SPMD program, ``jax.distributed.initialize`` wires the processes into
one runtime over DCN, and the global ``Mesh`` simply spans all hosts'
devices — collectives ride ICI within a slice and DCN across hosts, with no
code change in the ops (the same ``shard_map`` programs run unmodified).

Three pieces live here:

- :func:`initialize` — ``jax.distributed.initialize`` wrapper with the
  session-specific CPU-platform pinning (the PJRT sitecustomize would
  otherwise dial the TPU tunnel in every worker, see ``tests/conftest.py``),
- :func:`pod_mesh` — a mesh over **all** processes' devices (the multi-host
  form of :func:`~cluster_tools_tpu.parallel.mesh.make_mesh`),
- :func:`launch_workers` / :func:`worker_main` — a subprocess launcher that
  runs an N-process CPU pod on one machine, used by the multi-process test
  (the CI stand-in for a real v5p pod, mirroring how the reference's
  ``target='local'`` stood in for slurm, SURVEY.md §4) and by
  ``__graft_entry__.dryrun_multiprocess``.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

_ENV_COORD = "CT_MP_COORDINATOR"
_ENV_NPROC = "CT_MP_NUM_PROCESSES"
_ENV_PID = "CT_MP_PROCESS_ID"
_ENV_TARGET = "CT_MP_TARGET"


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    platform: Optional[str] = None,
) -> None:
    """Join this process into the distributed JAX runtime.

    On a real pod (GKE/TPU VM) all arguments are discovered from the
    environment and may be omitted.  ``platform='cpu'`` pins the CPU backend
    *before* initialization — required for the local fake-pod tests, where
    the PJRT plugin on PYTHONPATH would otherwise dial TPU hardware from
    every worker.
    """
    import jax

    if platform is not None:
        os.environ["JAX_PLATFORMS"] = platform
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def pod_mesh(
    axis_names: Sequence[str] = ("dp", "sp"),
    grid: Optional[Sequence[int]] = None,
):
    """Mesh spanning every device of every process in the distributed job.

    Identical in shape-semantics to :func:`make_mesh`, but always over the
    *global* device list — after :func:`initialize`, ``jax.devices()``
    contains all hosts' devices and the returned mesh crosses DCN.
    Collective layout: keep the ``sp`` (spatial/halo) axis within a host
    where possible; ``jax.devices()`` orders devices process-major, so the
    default factoring puts the fastest-varying (last) mesh axis across
    devices of the same process.
    """
    import jax

    from .mesh import make_mesh

    return make_mesh(
        len(jax.devices()), axis_names=axis_names, grid=grid, devices=jax.devices()
    )


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def collectives_supported(deadline_s: float = 30.0) -> Tuple[bool, str]:
    """Probe whether this runtime can execute a cross-process collective.

    The collective reduce plane must know *before* committing to device
    hops: old jaxlib CPU backends accept ``jax.distributed.initialize``
    but abort the first multi-process computation with "Multiprocess
    computations aren't implemented on the CPU backend" (the env the
    test_multihost skips document).  The probe runs one tiny jitted
    ``psum`` over a 1-D pod mesh — the exact op class the reduce plane
    dispatches — with ``deadline_s`` of patience (a deadline, per ctlint
    CT015: a wedged probe must degrade, not hang the solve).  Returns
    ``(supported, reason)``; single-process runtimes are trivially
    supported (in-process collectives over the local mesh always work).

    Deterministic across the worker group: every process probes the same
    op on the same backend, so all workers pick the same reduce plane.
    """
    import jax

    if jax.process_count() <= 1:
        return True, "single-process runtime"
    import threading

    import numpy as np

    out: Dict[str, object] = {}

    def _probe():
        try:
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .mesh import SIBLING_AXIS, sibling_mesh

            mesh = sibling_mesh()
            sharding = NamedSharding(mesh, P(SIBLING_AXIS))
            n = int(mesh.devices.size)
            x = jax.make_array_from_callback(
                (n,), sharding,
                lambda idx: jnp.ones(np.zeros(n)[idx].shape, jnp.float32),
            )
            total = jax.jit(
                lambda a: a.sum(),
                out_shardings=NamedSharding(mesh, P()),
            )(x)
            ok = float(np.asarray(total)) == float(n)
            out["result"] = (ok, "ok" if ok else "probe sum mismatch")
        except Exception as e:  # the documented old-jaxlib abort lands here
            out["result"] = (False, f"{type(e).__name__}: {e}"[:200])

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(timeout=max(1.0, float(deadline_s)))
    if t.is_alive():
        return False, f"collective probe exceeded {deadline_s:g}s deadline"
    return out.get("result", (False, "probe thread died"))


def launch_workers(
    num_processes: int,
    target: str,
    devices_per_process: int = 1,
    timeout: float = 600.0,
    extra_env: Optional[Dict[str, str]] = None,
) -> List[Tuple[int, str, str]]:
    """Run ``target`` (``"module:function"``) in an N-process local CPU pod.

    Spawns ``num_processes`` Python subprocesses, each pinned to the CPU
    platform with ``devices_per_process`` virtual devices, joined through a
    ``jax.distributed`` coordinator on a free localhost port.  The target
    function runs in every process after initialization (classic SPMD).

    Returns ``[(returncode, stdout, stderr), ...]`` per process; on timeout
    every worker's process group is killed and a ``TimeoutError`` carrying
    the partial per-worker output is raised (:func:`collect_workers`).
    This is the DCN analogue of the reference's LocalTask fake-cluster:
    real multi-process collectives, one machine.
    """
    coord = f"127.0.0.1:{free_port()}"
    # workers must be able to import this package regardless of their cwd
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    procs = []
    for pid in range(num_processes):
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update(
            {
                _ENV_COORD: coord,
                _ENV_NPROC: str(num_processes),
                _ENV_PID: str(pid),
                _ENV_TARGET: target,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (
                    env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={devices_per_process}"
                ).strip(),
            }
        )
        if extra_env:
            env.update(extra_env)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    "from cluster_tools_tpu.parallel.multihost import worker_main; "
                    "worker_main()",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                start_new_session=True,
            )
        )
    return collect_workers(procs, timeout)


#: grace between SIGTERM and SIGKILL when tearing down timed-out workers:
#: long enough to flush logs/heartbeats, short enough not to stall teardown
TERM_GRACE_S = 5.0


def _signal_process_group(p: subprocess.Popen, sig: int) -> None:
    """Deliver ``sig`` to the worker's whole process group (workers are
    session leaders via ``start_new_session=True``, so pgid == pid) —
    signalling only the leader would orphan grandchildren as zombies."""
    try:
        os.killpg(p.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            p.send_signal(sig)
        except OSError:
            pass


def _kill_process_group(p: subprocess.Popen) -> None:
    _signal_process_group(p, signal.SIGKILL)


def _terminate_process_groups(
    procs: List[subprocess.Popen], grace_s: float = TERM_GRACE_S
) -> None:
    """SIGTERM -> grace -> SIGKILL escalation for every live worker group:
    workers get ``grace_s`` (collectively, not per worker) to flush logs
    and heartbeats — a drain-aware worker exits cleanly here — before the
    groups are killed hard.  The final SIGKILL goes to EVERY group, even
    ones whose leader already exited: a grandchild that survived the
    SIGTERM would otherwise keep the output pipes open forever (the
    zombie-with-no-logs failure the escalation must not reintroduce)."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        _signal_process_group(p, signal.SIGTERM)
    deadline = time.monotonic() + max(0.0, grace_s)
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in live):
            break
        time.sleep(0.05)
    for p in live:
        _kill_process_group(p)


def collect_workers(
    procs: List[subprocess.Popen], timeout: float,
    term_grace_s: float = TERM_GRACE_S,
) -> List[Tuple[int, str, str]]:
    """Wait for every worker, returning ``(returncode, stdout, stderr)``
    per process.  On timeout, every worker's *process group* is terminated
    with a SIGTERM -> ``term_grace_s`` -> SIGKILL escalation (workers get a
    chance to flush logs and heartbeats; no zombie grandchildren keep the
    pipes open) and whatever partial stdout/stderr the workers produced is
    collected and surfaced in the raised ``TimeoutError`` — a hung pod must
    leave its logs behind, not vanish into a bare ``TimeoutExpired``."""
    results = []
    try:
        for i, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                _terminate_process_groups(procs, term_grace_s)
                tails = []
                for j, q in enumerate(procs):
                    try:
                        qo, qe = q.communicate(timeout=10.0)
                    except Exception:
                        qo, qe = "", ""
                    tails.append(
                        f"-- worker {j} (rc={q.returncode}) --\n"
                        f"stdout tail:\n{(qo or '')[-800:]}\n"
                        f"stderr tail:\n{(qe or '')[-800:]}"
                    )
                raise TimeoutError(
                    f"multihost worker {i} exceeded timeout={timeout:g}s; "
                    f"killed all {len(procs)} worker process group(s).  "
                    "Partial output:\n" + "\n".join(tails)
                )
            results.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                _kill_process_group(p)
    return results


def worker_main() -> None:
    """Entry point of a :func:`launch_workers` subprocess.

    Reads the coordinator/process config from the environment, pins the CPU
    platform (beating the sitecustomize's own config write), joins the
    distributed runtime, and calls the target function.
    """
    import importlib

    coord = os.environ[_ENV_COORD]
    nproc = int(os.environ[_ENV_NPROC])
    pid = int(os.environ[_ENV_PID])
    target = os.environ[_ENV_TARGET]

    initialize(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
        platform="cpu",
    )
    mod_name, fn_name = target.split(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    # worker-lifetime span on the unified trace timeline (docs/
    # OBSERVABILITY.md): active only when the launcher exported
    # CTT_TRACE=<dir>; the flush is best-effort (targets that flush
    # themselves — the reduce-tree worker — just rewrite the same shard)
    from ..runtime import trace as trace_mod

    try:
        with trace_mod.span("worker.main", worker=pid, target=target):
            fn()
    finally:
        # flush on the failure path too — the shard of the worker that
        # DIED is the one the post-mortem timeline needs most
        try:
            trace_mod.flush()
        except Exception:
            pass


def cc_pod_demo() -> None:
    """SPMD demo/test body: distributed CC + exact EDT across process cuts.

    Every process holds a z-slab of one volume; connected components are
    merged across the process (DCN) cuts by the same
    :func:`~cluster_tools_tpu.parallel.distributed_ccl.
    distributed_connected_components` program that runs single-host — only
    the mesh spans further.  The mesh-exact EDT
    (:mod:`~cluster_tools_tpu.parallel.distributed_edt`) then proves the
    all-to-all reshard rides DCN too.  Each process validates both results
    against scipy oracles and prints ``CC_POD_OK``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from scipy import ndimage

    from .distributed_ccl import distributed_connected_components

    mesh = pod_mesh(axis_names=("sp",))
    sp = int(mesh.devices.size)
    pid = jax.process_index()

    # deterministic volume, generated identically in every process
    rng = np.random.default_rng(7)
    mask_np = rng.random((sp * 8, 24, 24)) > 0.35  # dense: components span cuts
    sharding = NamedSharding(mesh, P("sp"))
    mask = jax.make_array_from_callback(
        mask_np.shape, sharding, lambda idx: jnp.asarray(mask_np[idx])
    )

    labels = distributed_connected_components(mask, mesh, sp_axis="sp")
    # replicate so every process can fetch the full result
    replicated = jax.jit(
        lambda x: x, out_shardings=NamedSharding(mesh, P(None))
    )(labels)
    ours = np.asarray(replicated)

    ref, nref = ndimage.label(mask_np)
    assert (ours > 0).sum() == (ref > 0).sum()
    fwd: dict = {}
    for o, r in zip(ours.ravel().tolist(), ref.ravel().tolist()):
        if o > 0:
            assert fwd.setdefault(o, r) == r, "label split across components"
    assert len(fwd) == nref, (len(fwd), nref)
    # prove the merge crossed a process boundary: some component must span
    # the cut between the first and second process's slabs
    slab = mask_np.shape[0] // sp
    cut_lo, cut_hi = ours[slab - 1], ours[slab]
    spans = set(cut_lo[cut_lo > 0].ravel()) & set(cut_hi[cut_hi > 0].ravel())
    assert spans, "no component spans the process-boundary cut"

    # the all-to-all reshard rides DCN too: the mesh-exact EDT must match
    # scipy across every process cut (x extent divisible by sp for the flip)
    from .distributed_edt import distributed_distance_transform

    emask_np = rng.random((sp * 4, 12, 8 * sp)) > 0.05
    emask_np[0, 0, 0] = False
    emask = jax.make_array_from_callback(
        emask_np.shape, sharding, lambda idx: jnp.asarray(emask_np[idx])
    )
    dist = jax.jit(
        lambda m: distributed_distance_transform(m, mesh, sp_axis="sp"),
        out_shardings=NamedSharding(mesh, P(None)),
    )(emask)
    want = ndimage.distance_transform_edt(emask_np)
    assert np.allclose(np.asarray(dist), want, rtol=1e-5, atol=1e-3), (
        "pod EDT deviates from the scipy oracle"
    )
    print(
        f"CC_POD_OK pid={pid} processes={jax.process_count()} "
        f"devices={sp} components={nref} spanning={len(spans)} edt_ok=1",
        flush=True,
    )
