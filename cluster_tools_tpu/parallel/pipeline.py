"""The fused, mesh-sharded watershed+CCL step — the framework's "train step".

The reference's north-star workload (BASELINE.json) is: blockwise
distance-transform watershed + connected components, with the two-pass
union-find label merge, end-to-end to globally merged labels.  In the
reference that was five luigi tasks and thousands of filesystem round-trips;
here it is **one compiled SPMD program** over a ``(dp, sp...)`` mesh:

- ``dp`` shards a batch of independent volumes (block batches),
- one or more spatial axes shard each volume into slabs (z) or a full
  2-D/3-D spatial decomposition (z × y × x) — the teravoxel layout,
- halo exchange (``ppermute`` over ICI, one per sharded axis — corners fill
  correctly because each exchange forwards the previously received halo),
- the fused DT-watershed kernel runs per shard,
- watershed fragments stitch across every cut by face consensus, and the
  thresholded foreground is labeled with globally consistent components via
  the distributed union-find merge (``all_gather`` + pointer jumping),
- a ``psum`` over the whole mesh yields global statistics.

This module is what ``__graft_entry__.dryrun_multichip`` compiles and runs.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from ..ops.ccl import _match_vma, relabel_consecutive
from ..ops.watershed import distance_transform_watershed
from .distributed_ccl import (
    ShardAxis,
    linearized_shard_rank,
    merge_labels_by_pairs,
    sharded_label_components,
    sp_axes_for_mesh,
)
from .halo import crop_halo, exchange_halo, neighbor_face
from .mesh import mesh_axis_sizes


def _stitch_ws_fragments(
    ws: jnp.ndarray,
    vol: jnp.ndarray,
    axes: Sequence[ShardAxis],
    rank: jnp.ndarray,
    span: int,
    threshold: float,
) -> jnp.ndarray:
    """Merge watershed fragments across every sharded cut by face consensus.

    The device-resident form of the reference's two-pass/stitching semantics
    (SURVEY.md §3.5, ``stitching``): two fragments facing each other across
    a shard boundary merge when the boundary evidence at their contact is
    weak — ``max`` of the two sides' boundary values below ``threshold``.
    The equivalences ride the same gather + union-find + remap tail as the
    distributed CCL merge.
    """
    pairs = []
    for a, name, size in axes:
        mine_l = lax.slice_in_dim(ws, 0, 1, axis=a).ravel()
        theirs_l = neighbor_face(ws, a, name, size, direction=-1).ravel()
        mine_b = lax.slice_in_dim(vol, 0, 1, axis=a).ravel()
        theirs_b = neighbor_face(
            vol, a, name, size, direction=-1, fill=1.0
        ).ravel()
        val = jnp.maximum(mine_b, theirs_b)
        ok = (mine_l > 0) & (theirs_l > 0) & (val < threshold)
        pairs.append(
            jnp.stack(
                [
                    jnp.where(ok, theirs_l, jnp.int32(-1)),
                    jnp.where(ok, mine_l, jnp.int32(-1)),
                ],
                axis=1,
            )
        )
    return merge_labels_by_pairs(
        ws, jnp.concatenate(pairs, axis=0), axes, rank, span
    )


def _ws_ccl_shard(
    boundaries: jnp.ndarray,
    *,
    sp_axes: Tuple[ShardAxis, ...],
    dp_axis: str,
    halo: int,
    threshold: float,
    connectivity: int,
    dt_max_distance: Optional[float],
    min_seed_distance: float,
    max_labels_per_shard: Optional[int],
    impl: str,
    exact_edt: bool,
    stitch_ws_threshold: Optional[float],
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-device body: local shard is ``(local_batch,) + local_volume``.

    ``sp_axes`` holds ``(volume_axis, mesh_axis_name, mesh_axis_size)`` per
    sharded spatial axis (volume axes count WITHOUT the batch axis).
    """
    local_b = boundaries.shape[0]
    n_shards = int(np.prod([s for _, _, s in sp_axes]))
    rank = linearized_shard_rank(sp_axes)
    # the tiled (two-level VMEM) kernels are 3-D/connectivity-1 only; the
    # legacy dense fixpoint covers the rest (2-D volumes included)
    tiled_ok = (
        impl != "legacy" and connectivity == 1 and boundaries.ndim - 1 == 3
    )
    if exact_edt and not tiled_ok:
        # make_ws_ccl_step rejects legacy/connectivity mismatches up front,
        # but the volume rank is only known here — refuse rather than hand
        # back halo-capped seeds the caller opted out of
        raise ValueError(
            "exact_edt requires the tiled kernels, which are 3-D only "
            f"(got a {boundaries.ndim - 1}-D volume)"
        )

    def exchange_all(x, fill):
        # one ppermute per sharded axis; later exchanges forward the halos
        # received by earlier ones, so diagonal (corner) regions arrive with
        # the correct neighbor-of-neighbor data
        for a, name, size in sp_axes:
            x = exchange_halo(x, halo, a, name, size, fill=fill)
        return x

    ws_out = []
    cc_out = []
    # per-shard ws-compaction overflow (varies over the mesh); cc overflow
    # arrives already sp-reduced from sharded_label_components
    ws_overflow = _match_vma(jnp.zeros((), jnp.int32), boundaries)
    cc_overflow = None
    # static Python loop over the (small) local batch: collectives inside the
    # body run once per volume on every rank in lockstep
    for b in range(local_b):
        vol = boundaries[b]
        # border fill = 1.0 (pure boundary) so basins never leak out of the
        # volume
        padded = exchange_all(vol, fill=1.0)
        if tiled_ok:
            from ..ops.tile_ws import dt_watershed_tiled

            tiled_impl = "xla" if impl == "tiled" else impl
            dist_pad = None
            if exact_edt:
                # globally exact squared EDT (all-to-all reshard per axis
                # pass, distributed_edt) instead of the halo-capped
                # per-shard transform; halo-exchange the distances so the
                # padded watershed window sees them too (fill 0 = the
                # outside-volume border is background, matching the
                # boundary fill of 1.0 above)
                from .distributed_edt import sharded_distance_transform_squared

                dist_sq = sharded_distance_transform_squared(
                    vol < threshold,
                    shard_axes=sp_axes,
                    # keep the documented dt_max_distance contract: caps
                    # stay capped (exactness here means exact ACROSS shard
                    # cuts, not uncapped); None = truly global radii
                    max_distance=dt_max_distance,
                    impl="xla" if impl in ("xla", "tiled") else "auto",
                )
                dist_pad = exchange_all(dist_sq, fill=0.0)
            ws, ws_over = dt_watershed_tiled(
                padded,
                threshold=threshold,
                dist=dist_pad,
                dt_max_distance=dt_max_distance,
                min_seed_distance=min_seed_distance,
                impl=tiled_impl,
            )
            ws_overflow = jnp.maximum(ws_overflow, ws_over.astype(jnp.int32))
        else:
            ws = distance_transform_watershed(
                padded,
                threshold=threshold,
                min_seed_distance=min_seed_distance,
                connectivity=connectivity,
                dt_max_distance=dt_max_distance,
            )
        for a, _, _ in sp_axes:
            ws = crop_halo(ws, halo, a)
        # globalize watershed fragment ids by shard rank; with a compaction
        # cap, fragment ids are densified first so the label space is
        # n_shards * cap instead of n_shards * padded_voxels (the int32
        # ceiling that blocked teravoxel volumes)
        n_pad = int(np.prod(padded.shape))
        if max_labels_per_shard is not None:
            cap = int(max_labels_per_shard)
            if n_shards * (cap + 1) >= 2**31:
                raise ValueError(
                    f"{n_shards} shards x {cap} ws fragments overflow int32"
                )
            # ws fragment ids are PADDED-volume flat indices (+1), which
            # exceed the halo-cropped labels.size — pass the padded span
            # or the bitmap fast path silently never engages here
            ws, n_frag = relabel_consecutive(
                ws, max_labels=cap, value_bound=n_pad + 1
            )
            ws_overflow = jnp.maximum(
                ws_overflow, (n_frag > cap).astype(jnp.int32)
            )
            ws = jnp.where(ws > 0, ws + rank * jnp.int32(cap + 1), 0)
            ws_span = cap + 1
        else:
            if n_shards * n_pad >= 2**31:
                raise ValueError(
                    f"{n_shards} shards of {n_pad} padded voxels overflow "
                    "int32 labels; pass max_labels_per_shard"
                )
            ws = jnp.where(ws > 0, ws + rank * jnp.int32(n_pad), 0)
            ws_span = n_pad
        if stitch_ws_threshold is not None and n_shards > 1:
            # cross-shard fragment merge: the "stitch" of BASELINE config 3,
            # device-resident (skipped at 1 shard — no cuts exist, and the
            # relabel table would be pure overhead)
            ws = _stitch_ws_fragments(
                ws, vol, sp_axes, rank, ws_span, float(stitch_ws_threshold)
            )
        ws_out.append(ws)

        # globally merged connected components of the foreground mask — the
        # two-pass union-find merge as ICI collectives
        cc, cc_over = sharded_label_components(
            vol < threshold,
            shard_axes=sp_axes,
            connectivity=connectivity,
            max_labels_per_shard=max_labels_per_shard,
            return_overflow=True,
            impl=impl,
        )
        cc_over = cc_over.astype(jnp.int32)
        cc_overflow = (
            cc_over if cc_overflow is None else jnp.maximum(cc_overflow, cc_over)
        )
        cc_out.append(cc)

    ws_lab = jnp.stack(ws_out)
    cc_lab = jnp.stack(cc_out)
    # global foreground voxel count over the full mesh (dp and all sp axes).
    # Summed in float32: an int32 psum would wrap past 2**31 global
    # foreground voxels (the teravoxel layouts this step supports); f32 is
    # exact below 2**24 per shard and ~1e-7 relative beyond
    n_fg = jnp.sum(cc_lab > 0).astype(jnp.float32)
    for _, name, _ in sp_axes:
        n_fg = lax.psum(n_fg, name)
    n_fg = lax.psum(n_fg, dp_axis)
    # mesh-wide label-compaction overflow flag (always False w/o compaction)
    for _, name, _ in sp_axes:
        ws_overflow = lax.pmax(ws_overflow, name)
    overflow = jnp.maximum(ws_overflow, cc_overflow)
    overflow = lax.pmax(overflow, dp_axis) > 0
    return ws_lab, cc_lab, n_fg, overflow


def make_ws_ccl_step(
    mesh: Mesh,
    halo: int = 4,
    threshold: float = 0.3,
    connectivity: int = 1,
    dp_axis: str = "dp",
    sp_axis: Union[str, Sequence[str]] = "sp",
    dt_max_distance: Optional[float] = None,
    min_seed_distance: float = 0.0,
    max_labels_per_shard: Optional[int] = None,
    impl: str = "auto",
    exact_edt: bool = False,
    stitch_ws_threshold: Optional[float] = None,
):
    """Compile the fused step for ``mesh``.

    Returns a jitted function ``step(boundaries)`` taking a float32 batch of
    volumes ``(B,) + volume`` with ``B % dp == 0``; the batch axis is
    sharded over ``dp``.  ``sp_axis`` may be one mesh axis name (the
    volume's z axis sharded in slabs) or a sequence of names (the leading
    volume axes sharded over the respective mesh axes — a full 2-D/3-D
    spatial decomposition; each sharded extent must divide).  Output:
    ``(ws_labels, cc_labels, n_foreground, overflow)`` with labels sharded
    like the input and the scalars replicated; ``n_foreground`` is float32
    (exact below 2**24 per shard; an int32 count would wrap past 2**31
    global foreground voxels); ``overflow`` is True when any shard exceeded
    ``max_labels_per_shard``, a tiled-kernel capacity, or a compaction cap
    (labels unreliable — raise the cap or add shards).

    ``impl`` selects the per-shard kernels: "auto" (two-level VMEM tile
    machinery, Mosaic on TPU / portable XLA elsewhere — the fast path),
    "pallas"/"xla"/"tiled" to force a tiled variant, or "legacy" (round-2
    dense fixpoint kernels).

    ``exact_edt``: seed the watershed from the *globally exact* EDT
    (mesh-distributed, all-to-all reshard per axis pass) instead of the
    halo-capped per-shard transform — no halo saturation artifacts in the
    seeds.  Requires the tiled kernels (not "legacy") and connectivity=1;
    the reshard target's local extent must divide by each sharded mesh-axis
    size.

    ``stitch_ws_threshold``: when set, watershed fragments facing each other
    across the spatial cuts merge where the boundary evidence at the
    contact is below the threshold (face consensus — the device-resident
    form of the reference's two-pass/stitching step), so the returned
    ``ws_labels`` are globally merged rather than per-shard.
    """
    if exact_edt and (impl == "legacy" or connectivity != 1):
        # the legacy dense-fixpoint branch never reads the flag — refuse
        # rather than silently hand back the halo-capped seeds the caller
        # opted out of
        raise ValueError(
            "exact_edt requires the tiled kernels (impl != 'legacy') and "
            "connectivity=1"
        )
    names = (sp_axis,) if isinstance(sp_axis, str) else tuple(sp_axis)
    sp_axes = sp_axes_for_mesh(mesh, sp_axis)
    body = partial(
        _ws_ccl_shard,
        sp_axes=sp_axes,
        dp_axis=dp_axis,
        halo=halo,
        threshold=threshold,
        connectivity=connectivity,
        dt_max_distance=dt_max_distance,
        min_seed_distance=min_seed_distance,
        max_labels_per_shard=max_labels_per_shard,
        impl=impl,
        exact_edt=exact_edt,
        stitch_ws_threshold=stitch_ws_threshold,
    )
    # check_vma=False: the per-shard body runs Pallas kernels whose in-kernel
    # loop carries mix ref loads (vma-tagged) with constants (untagged), and
    # this JAX version's vma propagation drops the tag across concatenate
    # inside pallas tracing — the static check then rejects a correct
    # program ("carry input {V:sp} vs output" on the EDT cascade).  The
    # collectives (ppermute halo, all_gather merge, psum stats) are
    # unaffected; only the static replication *check* is off.
    spec = P(dp_axis, *names)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=spec,
        out_specs=(spec, spec, P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)
