"""Distributed agglomeration: shard the global solve over an octant reduce tree.

The hierarchical multicut (tasks/multicut.py) funnels every boundary edge of
the reduced RAG into ONE process for the final ``SolveGlobal`` — the last
stage that cannot scale past a single host (ROADMAP item 3).  This module
shards that solve:

1. **Partition** the graph's nodes into ``solver_shards`` spatially
   contiguous shards — Morton order over the owning blocks' grid positions,
   so each shard is an octant-shaped run of the block grid and the edges
   crossing shards are (near-)minimal boundary faces.
2. **Solve locally per shard** with *frontier-aware* contraction rounds
   (:func:`frontier_contraction`, the same mutual-best-edge rounds as
   :mod:`..ops.contraction`): the shard's still-external boundary edges
   compete in every node's best-pick but can never match, so a node whose
   strongest affinity crosses the shard boundary ABSTAINS — its merge is
   deferred to the tree level where that edge becomes internal and is
   decided with fully aggregated context — instead of being absorbed into
   an interior cluster the global solver would have cut.  This is what
   keeps the sharded energy within 0.1% of the single-host solve
   (boundary-blind leaf solves lose 1-3% on the solver-scale bench
   instances; measured in ``make bench-solve``).  Contraction can merge
   but never split, so a leaf that under-merges is always repairable
   higher up; edges a level leaves cut stay in the problem as
   (net-repulsive) context for its ancestors.
3. **Merge up a reduce tree** of configurable ``fanout`` ("Near-Optimal
   Wafer-Scale Reduce", PAPERS.md): at each level, groups of ``fanout``
   children fuse — only the edges between their spans become internal and
   are solved, everything still crossing a group boundary relabels through
   the children's contractions and moves up.  The root sees the fully
   contracted global graph, exactly like the single-host hierarchical
   scheme — composed with the per-shard contraction rounds the way
   "Composing Distributed Computations Through Task and Kernel Fusion"
   (PAPERS.md) argues fused pipelines should: no materialized global
   problem between the stages.

Every step is deterministic: shards and groups are processed in index
order, member supernodes ascend, parallel-edge accumulation reuses the
documented tie-break order of :func:`..ops.contraction._canonical_edges`,
and label offsets are assigned in group order *after* all of a level's
solves finish — thread scheduling cannot reorder anything observable, so
the merged labeling is reproducible across reruns and across the
in-process vs worker-group drivers.

Two drivers share the exact same level steps:

- :func:`sharded_solve` — in-process, group solves fanned out on a thread
  pool (the contraction engine releases the GIL in its native/jax rungs);
- :func:`solve_over_workers` — the inter-host form: a
  :func:`~cluster_tools_tpu.parallel.multihost.launch_workers` worker
  group (each worker joins the ``jax.distributed`` runtime, the same
  wiring as a real pod), leaf shards and merge groups dealt round-robin
  over workers, boundary-edge packets exchanged through the run's scratch
  directory (atomic ``os.replace`` publishes — the DCN-analogue data
  plane this runtime inherits from the reference's shared-filesystem
  cluster heritage).  The merge bookkeeping (cheap, O(E)) is replicated
  on every worker from the same packets, so all workers advance through
  bit-identical level states.

:func:`solve_with_reduce_tree` is the attributed entry point tasks call
(``SolveGlobal``, ``SolveLiftedGlobal``, agglomerative clustering, the
stitching ``merge_mode='multicut'`` seam): ``solver_shards=1`` is the
degenerate single-host path, and ANY sharded failure — a killed worker, a
timed-out reduce hop, an injected ``solve`` fault — degrades to the
single-host solver with a ``degraded:unsharded_solve`` record in
``failures.json`` (riding the PR 2-4 retry/quarantine/drain stack), so the
sharded path can never produce a worse outcome than not having it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime import trace as trace_mod
from ..utils import function_utils as fu

#: env knobs of the worker-group driver (inherited by the workers)
_ENV_DIR = "CT_RT_DIR"
_ENV_WAIT = "CT_RT_WAIT_S"

#: default patience of a worker polling for a sibling's packet before it
#: declares the reduce hop lost and exits nonzero (the driver then degrades
#: to the unsharded solve)
DEFAULT_HOP_WAIT_S = 120.0


class ShardedSolveError(RuntimeError):
    """The sharded solve could not complete (worker death, lost packet,
    malformed shard state).  Callers degrade to the single-host solver."""


def _host_impl(impl: Optional[str] = None) -> str:
    """Concrete host-side contraction impl (``native``/``numpy``), never
    ``auto``: ``auto``'s accelerator probe initializes the XLA client,
    which must not happen inside reduce-tree workers (see
    :func:`reduce_worker_main`)."""
    if impl and impl not in ("auto", "host"):
        return impl
    from .. import native

    return "native" if native.available() else "numpy"


# -- process-wide solver metrics ---------------------------------------------
# Same snapshot/delta pattern as the executor's dispatch counters: the task
# runtime snapshots around run_impl and merges the delta into
# io_metrics.json, so the sharded solve's per-level work is observable per
# task (docs/PERFORMANCE.md "Distributed agglomeration").

_METRICS_LOCK = threading.Lock()
_SOLVE_COUNTERS = {
    "sharded_solves": 0,        # sharded_solve invocations (any driver)
    "unsharded_fallbacks": 0,   # degraded:unsharded_solve degradations
    "solve_shards": 0,          # leaf shards solved
    "solve_levels": 0,          # reduce-tree levels traversed
    "tree_rounds": 0,           # frontier-contraction rounds across nodes
    "tree_solve_s": 0.0,        # wall time inside per-group solver calls
    "tree_merge_s": 0.0,        # wall time relabeling/merging boundary edges
    "boundary_edges_in": 0,     # edges entering the reduce tree (leaf level)
    "boundary_edges_out": 0,    # edges surviving to the root solve
    # -- collective reduce plane (docs/PERFORMANCE.md) --
    "collective_hops": 0,          # per-level all_gather exchanges
    "packet_fallbacks": 0,         # degraded:packet_plane degradations
    "bytes_over_interconnect": 0,  # bytes the collective hops moved
    "contraction_dispatches": 0,   # host round dispatches + level programs
}


def solve_snapshot() -> Dict[str, float]:
    """Current process-wide reduce-tree counters (monotonic; diff two
    snapshots with :func:`solve_delta` to attribute a task's share)."""
    with _METRICS_LOCK:
        return dict(_SOLVE_COUNTERS)


def solve_delta(snapshot: Dict[str, float]) -> Dict[str, float]:
    """Counter movement since ``snapshot`` (same keys)."""
    cur = solve_snapshot()
    return {k: cur[k] - snapshot.get(k, 0) for k in cur}


def _record_solve_metrics(**deltas) -> None:
    with _METRICS_LOCK:
        for k, v in deltas.items():
            _SOLVE_COUNTERS[k] += v


# -- tree topology ------------------------------------------------------------


def reduce_tree_levels(n_shards: int, fanout: int) -> List[List[Tuple[int, ...]]]:
    """Merge-group plan: one entry per tree level above the leaves.

    ``levels[0]`` is the LEAF level — one singleton group per shard, the
    "run contraction locally per shard" stage (it is where the bulk of the
    edges contract, in parallel).  Each later level's groups are tuples of
    *previous-level node indices*, ``fanout`` consecutive children fusing
    per group — Morton-contiguous shards merge with their spatial
    neighbors first — until the last level's single root group.
    ``n_shards == 1`` yields just the root level, one (trivial) global
    solve.
    """
    n_shards = int(n_shards)
    fanout = int(fanout)
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if fanout < 2:
        raise ValueError(f"fanout must be >= 2, got {fanout}")
    levels: List[List[Tuple[int, ...]]] = [
        [(s,) for s in range(n_shards)]
    ]
    width = n_shards
    while width > 1:
        groups = [
            tuple(range(i, min(i + fanout, width)))
            for i in range(0, width, fanout)
        ]
        levels.append(groups)
        width = len(groups)
    return levels


# -- shard partitions ---------------------------------------------------------


def morton_argsort(positions: np.ndarray) -> np.ndarray:
    """Indices sorting integer grid ``positions`` [k, d] along the Z-order
    curve (bit interleave, axis 0 most significant within each bit plane —
    the same octant-contiguity the executor's Morton sweep uses)."""
    pos = np.asarray(positions, dtype=np.int64)
    if pos.ndim != 2:
        raise ValueError(f"positions must be [k, d], got shape {pos.shape}")
    if len(pos) == 0:
        return np.zeros(0, np.int64)
    nbits = max(1, int(pos.max()).bit_length())
    codes = np.zeros(len(pos), dtype=np.int64)
    d = pos.shape[1]
    for bit in range(nbits):
        for ax in range(d):
            codes |= ((pos[:, ax] >> bit) & 1) << (bit * d + (d - 1 - ax))
    return np.argsort(codes, kind="stable")


def morton_node_shards(positions: np.ndarray, n_shards: int) -> np.ndarray:
    """Shard id per row of ``positions``: Morton-sort the grid positions and
    split the curve into ``n_shards`` near-equal contiguous runs — each
    shard is an octant-shaped neighborhood of the grid."""
    order = morton_argsort(positions)
    shards = np.empty(len(order), np.int64)
    shards[order] = (
        np.arange(len(order), dtype=np.int64) * int(n_shards) // max(1, len(order))
    )
    return shards


def contiguous_node_shards(n_nodes: int, n_shards: int) -> np.ndarray:
    """Id-range partition: node ids assigned blockwise by supervoxel
    labeling order.  The fallback for callers without block geometry (the
    stitching face graph, synthetic bench instances) — blockwise label
    assignment makes consecutive ids spatial neighbors, so contiguous
    ranges approximate the Morton octants."""
    n_nodes = int(n_nodes)
    k = max(1, min(int(n_shards), max(1, n_nodes)))
    return np.arange(n_nodes, dtype=np.int64) * k // max(1, n_nodes)


# -- the level machinery (shared by both drivers) -----------------------------


def _as_payload(costs: np.ndarray, m: int) -> np.ndarray:
    payload = np.asarray(costs, dtype=np.float64)
    if payload.ndim == 1:
        payload = payload.reshape(-1, 1)
    if len(payload) != m:
        raise ValueError(f"payload rows {len(payload)} != edges {m}")
    return payload


def _aggregate_frontier(
    f_node: np.ndarray, f_ghost: np.ndarray, f_payload: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Merge parallel frontier edges per (member node, ghost) pair via the
    contraction engine's own :func:`..ops.contraction.sum_by_key` — one
    implementation of the load-bearing accumulation order."""
    if len(f_node) == 0:
        return f_node, f_ghost, f_payload
    from ..ops.contraction import sum_by_key

    mult = np.int64(int(f_ghost.max()) + 1)
    key = f_node.astype(np.int64) * mult + f_ghost.astype(np.int64)
    uniq, out = sum_by_key(key, f_payload)
    return (
        (uniq // mult).astype(np.int64),
        (uniq % mult).astype(np.int64),
        out,
    )


def frontier_contraction(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    f_node: np.ndarray,
    f_ghost: np.ndarray,
    f_payload: np.ndarray,
    mode: str = "max",
    threshold: float = 0.0,
) -> np.ndarray:
    """Mutual-best contraction rounds with frontier abstention.

    The same rounds as :func:`..ops.contraction._contract_rounds_numpy`
    (per-node best-pick -> mutual matching -> depth-1 union -> canonical
    re-aggregation; ties toward the smallest edge id), except that the
    still-external *frontier* edges — ``f_node`` (member endpoint, local
    id) to ``f_ghost`` (the remote supernode, an opaque key) with
    ``f_payload`` columns — compete in the best-pick scatter but can never
    match: a node whose best incident edge is external abstains this
    round, deferring its merge to the ancestor tree level where the edge
    becomes internal.  Frontier edges re-aggregate as internal contraction
    merges their member endpoints, so their priorities stay consistent
    with what the merge level will see.  Deterministic; returns int64
    labels 0..k-1 over the ``n_nodes`` members.
    """
    n = int(n_nodes)
    sign = 1.0 if mode == "max" else -1.0
    thr = sign * float(threshold)
    labels = np.arange(n, dtype=np.int64)
    if n == 0 or len(edges) == 0:
        return labels
    from ..ops.contraction import _canonical_edges

    u, v, payload = _canonical_edges(n, edges, payload)
    f_node = np.asarray(f_node, dtype=np.int64)
    f_ghost = np.asarray(f_ghost, dtype=np.int64)
    f_payload = _as_payload(f_payload, len(f_node))
    f_node, f_ghost, f_payload = _aggregate_frontier(f_node, f_ghost, f_payload)
    rounds = 0

    def prio_of(pay):
        if pay.shape[1] == 1:
            p = pay[:, 0]
        else:
            p = pay[:, 0] / np.maximum(pay[:, 1], 1e-300)
        return sign * p

    while len(u):
        prio = prio_of(payload)
        elig = prio > thr
        if not elig.any():
            break
        eid = np.arange(len(u), dtype=np.int64)
        best_p = np.full(n, -np.inf)
        np.maximum.at(best_p, u[elig], prio[elig])
        np.maximum.at(best_p, v[elig], prio[elig])
        if len(f_node):
            fprio = prio_of(f_payload)
            felig = fprio > thr
            if felig.any():
                # external competition: raises best_p but never places a
                # candidate edge id -> the node abstains if it wins
                np.maximum.at(best_p, f_node[felig], fprio[felig])
        best_e = np.full(n, len(u), dtype=np.int64)
        cand_u = elig & (prio == best_p[u])
        cand_v = elig & (prio == best_p[v])
        np.minimum.at(best_e, u[cand_u], eid[cand_u])
        np.minimum.at(best_e, v[cand_v], eid[cand_v])
        mutual = elig & (best_e[u] == eid) & (best_e[v] == eid)
        if not mutual.any():
            break
        rounds += 1
        root = np.arange(n, dtype=np.int64)
        root[v[mutual]] = u[mutual]
        labels = root[labels]
        u, v, payload = _canonical_edges(
            n, np.stack([root[u], root[v]], axis=1), payload
        )
        if len(f_node):
            f_node, f_ghost, f_payload = _aggregate_frontier(
                root[f_node], f_ghost, f_payload
            )
    # one host-driven dispatch per mutual-best round — the figure the
    # collective plane's one-dispatch-per-level program is measured against
    _record_solve_metrics(tree_rounds=rounds, contraction_dispatches=rounds)
    _, out = np.unique(labels, return_inverse=True)
    return out.astype(np.int64)


def default_tree_solver(
    mode: str = "max", threshold: float = 0.0, impl: str = "auto"
) -> Callable:
    """The default per-tree-node solver: frontier-aware contraction rounds
    (GAEC for ``mode='max'``, average linkage for ``'min'``).  Lifted edges
    at a node route to the lifted GAEC (boundary-blind: the lifted
    objective has no frontier formulation yet); a node with no frontier
    and no lifted edges runs the plain contraction engine (jax/native/
    numpy ladder — device rounds where an accelerator mesh is available).
    """

    def solve(n, edges, payload, frontier, lifted_edges, lifted_payload):
        if lifted_edges is not None and len(lifted_edges):
            from ..ops.multicut import lifted_greedy_additive

            return lifted_greedy_additive(
                n, edges, payload[:, 0], lifted_edges, lifted_payload[:, 0]
            )
        if len(edges) == 0:
            return np.arange(n, dtype=np.int64)
        if frontier is not None and len(frontier[0]):
            return frontier_contraction(
                n, edges, payload, *frontier, mode=mode, threshold=threshold
            )
        from ..ops.contraction import parallel_contraction

        return parallel_contraction(n, edges, payload, mode, threshold, impl=impl)

    return solve


class _TreeState:
    """Mutable per-level solve state: the current contracted problem."""

    __slots__ = (
        "n", "edges", "payload", "ledges", "lpayload", "owner", "node_to_cur",
    )

    def __init__(self, n_nodes, edges, payload, ledges, lpayload, node_shard):
        self.n = int(n_nodes)
        self.edges = edges
        self.payload = payload
        self.ledges = ledges
        self.lpayload = lpayload
        self.owner = np.asarray(node_shard, dtype=np.int64).copy()
        self.node_to_cur = np.arange(self.n, dtype=np.int64)


def _aggregate(n_new: int, edges: np.ndarray, payload: np.ndarray):
    """Canonical (lo<hi) unique edges with payload summed over parallels —
    the deterministic accumulation order of the contraction engine."""
    from ..ops.contraction import _canonical_edges

    if len(edges) == 0:
        return edges.reshape(0, 2), payload.reshape(0, payload.shape[-1])
    u, v, pay = _canonical_edges(n_new, edges, payload)
    return np.stack([u, v], axis=1), pay


def _group_problem(
    state: _TreeState,
    children: Tuple[int, ...],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[tuple],
           Optional[np.ndarray], Optional[np.ndarray], int]:
    """Extract one merge group's subproblem from the level state:
    ``(members, sub_edges, sub_payload, frontier, sub_le, sub_lp,
    n_internal)``.  ``members`` are the group's supernodes (ascending —
    the deterministic local index); ``frontier`` is the ``(f_node,
    f_ghost, f_payload)`` still-external edge context or None.  Shared by
    the host solver path (:func:`_solve_group`) and the collective
    plane's lane marshalling — both rungs see byte-identical problems."""
    members = np.flatnonzero(np.isin(state.owner, children))
    if len(members) == 0:
        return (members, np.zeros((0, 2), np.int64),
                np.zeros((0, state.payload.shape[-1])), None, None, None, 0)

    def side_masks(edges):
        in_u = np.isin(state.owner[edges[:, 0]], children)
        in_v = np.isin(state.owner[edges[:, 1]], children)
        return in_u, in_v

    in_u, in_v = (
        side_masks(state.edges) if len(state.edges) else
        (np.zeros(0, bool), np.zeros(0, bool))
    )
    e_mask = in_u & in_v
    sub_edges = np.searchsorted(members, state.edges[e_mask])
    sub_payload = state.payload[e_mask]
    cross = in_u ^ in_v
    frontier = None
    if cross.any():
        ce = state.edges[cross]
        member_side = in_u[cross]
        f_node = np.searchsorted(
            members, np.where(member_side, ce[:, 0], ce[:, 1])
        )
        f_ghost = np.where(member_side, ce[:, 1], ce[:, 0])
        frontier = (f_node, f_ghost, state.payload[cross])
    sub_le, sub_lp = None, None
    if state.ledges is not None and len(state.ledges):
        lin_u, lin_v = side_masks(state.ledges)
        l_mask = lin_u & lin_v
        sub_le = np.searchsorted(members, state.ledges[l_mask])
        sub_lp = state.lpayload[l_mask]
    return (members, sub_edges, sub_payload, frontier, sub_le, sub_lp,
            int(e_mask.sum()))


def _solve_group(
    state: _TreeState,
    children: Tuple[int, ...],
    solver: Callable,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Solve one merge group: ``(members, sub_labels, n_internal_edges)``.

    The group's *frontier* — edges with exactly one endpoint inside the
    span, keyed by the remote supernode id — is handed to the solver so it
    can defer boundary-best nodes (:func:`frontier_contraction`)."""
    members, sub_edges, sub_payload, frontier, sub_le, sub_lp, n_int = (
        _group_problem(state, children)
    )
    if len(members) == 0:
        return members, np.zeros(0, np.int64), 0
    labels = np.asarray(
        solver(len(members), sub_edges, sub_payload, frontier, sub_le, sub_lp),
        dtype=np.int64,
    )
    if len(labels) != len(members):
        raise ShardedSolveError(
            f"group solver returned {len(labels)} labels for "
            f"{len(members)} supernodes"
        )
    return members, labels, n_int


def _apply_level(
    state: _TreeState,
    groups: List[Tuple[int, ...]],
    results: Dict[int, Tuple[np.ndarray, np.ndarray]],
) -> int:
    """Fold one level's group solutions into the state (deterministic:
    offsets assigned in group order, edges re-aggregated canonically).
    Returns the number of supernodes after the level."""
    new_map = np.full(len(state.owner), -1, np.int64)
    owner_new: List[int] = []
    offset = 0
    for gi in range(len(groups)):
        members, labels = results[gi]
        k = int(labels.max()) + 1 if len(labels) else 0
        new_map[members] = offset + labels
        owner_new.extend([gi] * k)
        offset += k
    if (new_map < 0).any():
        raise ShardedSolveError("level left supernodes unmapped")
    state.node_to_cur = new_map[state.node_to_cur]
    state.edges, state.payload = _aggregate(
        offset, new_map[state.edges], state.payload
    )
    if state.ledges is not None and len(state.ledges):
        state.ledges, state.lpayload = _aggregate(
            offset, new_map[state.ledges], state.lpayload
        )
    state.owner = np.asarray(owner_new, dtype=np.int64)
    return offset


def _final_labels(state: _TreeState) -> np.ndarray:
    """Compose the per-level relabelings down to original nodes (dense)."""
    _, labels = np.unique(state.node_to_cur, return_inverse=True)
    return labels.astype(np.int64)


# -- collective reduce plane --------------------------------------------------
# Boundary-edge packets as device collectives (ROADMAP item 2d, the thesis
# of "Near-Optimal Wafer-Scale Reduce" and "Large Scale Distributed Linear
# Algebra With TPUs", PAPERS.md): a tree level's merge groups are dealt as
# padded lanes over the 1-D sibling mesh, each device contracts its lanes
# with the fused on-device round program (ops/contraction.py
# lane_frontier_rounds — convergence predicate inside lax.while_loop, so a
# level costs ONE dispatch instead of one per mutual-best round), and one
# in-program all_gather over the sibling axis replaces the npz packet
# exchange.  Ragged group problems marshal to fixed lanes through the
# PR-14/16 page-table + valid-extent descriptors and stage through the
# resident device pool, so a warm re-solve of the same problem pays zero
# h2d.  Bit-identical to the host rungs by construction (the kernel's
# documented contract); any failure degrades to the filesystem packet
# plane, attributed ``degraded:packet_plane``.

#: plane selection: operator env overrides the task knob
#: (``auto`` | ``collective`` | ``packet``)
_ENV_PLANE = "CT_REDUCE_PLANE"
#: force-disable switch — plane init refuses, exercising the attributed
#: init-failure rung (the bench's fallback arm, chaos drills)
_ENV_COLLECTIVES_OFF = "CT_COLLECTIVES_DISABLED"
#: wall-clock budget for one level's collective program (dispatch + the
#: all_gather hop); a level that exceeds it degrades to the packet plane
_ENV_HOP_DEADLINE = "CT_HOP_DEADLINE_S"
DEFAULT_HOP_DEADLINE_S = 60.0
#: ``reduce_plane='auto'`` floor: below this many live edges the jit
#: compile + d2h overhead outweighs the dispatch savings, stay on host
_ENV_AUTO_MIN_EDGES = "CT_REDUCE_PLANE_MIN_EDGES"
_AUTO_MIN_EDGES = 20_000

#: lane-capacity floors — capacities quantize to powers of two above
#: these so the compiled-program population stays bounded (the same
#: policy as the ragged pool's ``_quantize_pages``)
_MIN_LANE_NODES = 64
_MIN_LANE_EDGES = 128


def _pow2_at_least(n: int, floor: int) -> int:
    cap = int(floor)
    while cap < int(n):
        cap *= 2
    return cap


def _hop_deadline_s(explicit: Optional[float] = None) -> float:
    if explicit is not None:
        return float(explicit)
    return float(os.environ.get(_ENV_HOP_DEADLINE, DEFAULT_HOP_DEADLINE_S))


def _record_packet_degrade(
    failures_path: Optional[str], task_name: str, err: BaseException,
    record: bool = True,
) -> None:
    """Attribute one collective→packet degradation: the
    ``packet_fallbacks`` counter (→ io_metrics via the task's solve
    delta), a trace instant, and — when ``record`` — a resolved
    failures.json record at the ``hop`` site.  ctlint CT015 enforces that
    every ``degraded:packet_plane`` site routes through a
    ``record_failures`` writer; this helper is that one site.  ``record``
    is False only for ``reduce_plane='auto'`` picking the supported rung
    up front (not a runtime failure, counter-only)."""
    _record_solve_metrics(packet_fallbacks=1)
    trace_mod.instant(
        "degraded:packet_plane", task=task_name,
        error=f"{type(err).__name__}: {err}"[:200],
    )
    if not record or not failures_path:
        return
    try:
        fu.record_failures(failures_path, task_name, [{
            "block_id": None,
            "sites": {"hop": 1},
            "error": fu.cap_traceback(f"{type(err).__name__}: {err}"),
            "quarantined": False,
            "resolved": True,
            "resolution": "degraded:packet_plane",
        }])
    except Exception:
        pass  # attribution is best effort; the solve must still land


class CollectiveReducePlane:
    """One tree level as one collective device program.

    Construction is the degrade ladder's first rung: it raises (→ packet
    plane) when collectives are force-disabled, the ``hop`` fault site
    fires, fewer than two devices are visible, or the payload shape has
    no device kernel.  ``solve_level`` marshals every group of a level
    into fixed-capacity lanes, stages them through the resident device
    pool, and runs the jitted ``shard_map`` program under a wall-clock
    hop deadline; its failures are the ladder's second rung.

    Everything numeric runs under the thread-local
    ``jax.experimental.enable_x64`` context — staging included: without
    it ``device_put``/``jnp.zeros`` silently downcast f64→f32 and the
    bit-identity contract breaks.
    """

    def __init__(
        self,
        mode: str,
        threshold: float,
        k: int,
        *,
        hop_deadline_s: Optional[float] = None,
        n_devices: Optional[int] = None,
    ):
        from ..runtime import faults as faults_mod

        if os.environ.get(_ENV_COLLECTIVES_OFF):
            raise ShardedSolveError(
                f"collectives force-disabled ({_ENV_COLLECTIVES_OFF})"
            )
        # init-failure injection rung: a `hop` error fault here models
        # jax.distributed refusing to wire the plane up
        faults_mod.get_injector().maybe_fail("hop")
        if mode not in ("max", "min"):
            raise ShardedSolveError(f"unsupported mode {mode!r}")
        if int(k) not in (1, 2):
            raise ShardedSolveError(
                f"no device kernel for payload width {k} (expected 1 or 2)"
            )
        from jax.sharding import NamedSharding, PartitionSpec

        from .mesh import sibling_mesh

        self.mode = mode
        self.threshold = float(threshold)
        self.k = int(k)
        self.hop_deadline_s = _hop_deadline_s(hop_deadline_s)
        self.mesh = sibling_mesh(n_devices)
        self.ndev = int(self.mesh.devices.size)
        if self.ndev < 2:
            raise ShardedSolveError(
                "collective plane needs >= 2 devices on the sibling mesh"
            )
        self._replicated = NamedSharding(self.mesh, PartitionSpec())
        from .mesh import SIBLING_AXIS

        self._lane_sharded = NamedSharding(
            self.mesh, PartitionSpec(SIBLING_AXIS)
        )
        self._dev_key = tuple(
            d.id for d in self.mesh.devices.reshape(-1)
        )
        self._programs: Dict[tuple, Callable] = {}

    # -- the per-level program (cached per node capacity) -------------------

    def _program(self, Wn: int) -> Callable:
        prog = self._programs.get((Wn,))
        if prog is not None:
            return prog
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from ..ops.contraction import lane_frontier_rounds
        from .mesh import SIBLING_AXIS

        mode, k = self.mode, self.k

        def per_device(up, vp, pp, fnp, fgp, fpp, tabs, thr):
            # tabs [local_lanes, 6]: this device's lanes' page slots, one
            # per pool — the ragged page-table indirection on device
            def one_lane(t):
                return lane_frontier_rounds(
                    up[t[0]], vp[t[1]], pp[t[2]],
                    fnp[t[3]], fgp[t[4]], fpp[t[5]],
                    thr, n_pad=Wn, mode=mode, k=k,
                )

            labels, rounds = jax.vmap(one_lane)(tabs)
            # THE reduce hop: every sibling's lane labels in one gather
            # over the interconnect — the packet exchange, minus the
            # filesystem
            labels = lax.all_gather(labels, SIBLING_AXIS, tiled=True)
            rounds = lax.all_gather(rounds, SIBLING_AXIS, tiled=True)
            return labels, rounds

        prog = jax.jit(shard_map(
            per_device, self.mesh,
            in_specs=(P(),) * 6 + (P(SIBLING_AXIS), P()),
            out_specs=(P(), P()),
            check_rep=False,
        ))
        self._programs[(Wn,)] = prog
        return prog

    # -- lane marshalling ---------------------------------------------------

    def _marshal(self, probs: List[tuple]):
        """Pack the level's group problems into one 6-spec ragged batch:
        fixed ``(We,)``/``(We,k)``/``(Wf,)``/``(Wf,k)`` pages, one page
        per lane, lane count padded to a multiple of the device count —
        page-table + valid-extent descriptors exactly like the executor's
        ragged sweeps, so the device pool's content-addressed staging
        dedupes warm re-solves to zero h2d."""
        from .block_pool import RaggedArgSpec, RaggedBatch, _quantize_pages

        Wn = _pow2_at_least(
            max(len(m) for _, m, _, _, _ in probs), _MIN_LANE_NODES
        )
        We = _pow2_at_least(
            max(max((len(e) for _, _, e, _, _ in probs), default=0), 1),
            _MIN_LANE_EDGES,
        )
        Wf = _pow2_at_least(
            max(max((len(f[0]) for _, _, _, _, f in probs
                     if f is not None), default=0), 1),
            _MIN_LANE_EDGES,
        )
        lanes = -(-len(probs) // self.ndev) * self.ndev
        k = self.k
        # (page_shape, dtype, fill) per pool: u, v, pay, f_node, f_ghost,
        # f_pay.  Wn is the kernel's padding sentinel for endpoints.
        layout = [
            ((We,), np.int64, Wn), ((We,), np.int64, Wn),
            ((We, k), np.float64, 0.0),
            ((Wf,), np.int64, Wn), ((Wf,), np.int64, 0),
            ((Wf, k), np.float64, 0.0),
        ]
        specs, pools, tables, valids = [], [], [], []
        for shape, dtype, fill in layout:
            cap = _quantize_pages(1 + len(probs))
            pool = np.full((cap,) + shape, fill, dtype)
            specs.append(RaggedArgSpec(
                (1,) * len(shape), shape, np.dtype(dtype).name,
                fill if isinstance(fill, float) else int(fill), cap,
            ))
            pools.append(pool)
            tables.append(np.zeros((lanes, 1), np.int32))
            valids.append(np.zeros((lanes, len(shape)), np.int32))
        for li, (gi, members, sub_edges, sub_payload, frontier) in enumerate(
            probs
        ):
            m = len(sub_edges)
            pools[0][1 + li, :m] = sub_edges[:, 0] if m else 0
            pools[1][1 + li, :m] = sub_edges[:, 1] if m else 0
            pools[2][1 + li, :m] = sub_payload
            rows = [(m,), (m,), (m, k)]
            if frontier is not None:
                f_node, f_ghost, f_pay = frontier
                fm = len(f_node)
                pools[3][1 + li, :fm] = f_node
                pools[4][1 + li, :fm] = f_ghost
                pools[5][1 + li, :fm] = np.asarray(f_pay, np.float64)
                rows += [(fm,), (fm,), (fm, k)]
            else:
                rows += [(0,), (0,), (0, k)]
            for a, extent in enumerate(rows):
                tables[a][li, 0] = 1 + li
                valids[a][li] = extent
        rb = RaggedBatch(
            specs, pools, tables, valids, n_lanes=len(probs), width=lanes,
            pages_in_use=6 * len(probs),
        )
        return rb, Wn

    # -- one level, one dispatch, one hop -----------------------------------

    def solve_level(
        self,
        state: _TreeState,
        groups: List[Tuple[int, ...]],
        *,
        level: int,
        deadline_s: Optional[float] = None,
    ) -> Tuple[Dict[int, Tuple[np.ndarray, np.ndarray]], int]:
        """Solve every group of one tree level collectively; returns the
        ``{group: (members, labels)}`` results for :func:`_apply_level`
        plus the level's internal-edge total.  ``deadline_s`` caps the
        whole dispatch+hop (default: the plane's hop deadline) — a level
        that cannot make the deadline raises :class:`ShardedSolveError`
        and the caller degrades to the packet plane."""
        deadline = self.hop_deadline_s if deadline_s is None else float(
            deadline_s
        )
        results: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        probs: List[tuple] = []
        internal_total = 0
        for gi, children in enumerate(groups):
            (members, sub_edges, sub_payload, frontier, sub_le, sub_lp,
             n_int) = _group_problem(state, children)
            if sub_le is not None and len(sub_le):
                from ..ops.multicut import lifted_frontier_capable

                if not lifted_frontier_capable():
                    raise ShardedSolveError(
                        "lifted edges have no frontier formulation — "
                        "collective plane refuses the group"
                    )
            internal_total += n_int
            if len(members) == 0:
                results[gi] = (members, np.zeros(0, np.int64))
                continue
            probs.append((gi, members, sub_edges, sub_payload, frontier))
        if not probs:
            return results, internal_total
        raw = self._dispatch(probs, level, deadline)
        for li, (gi, members, _, _, _) in enumerate(probs):
            lane = raw[li, : len(members)]
            # the kernel returns raw union roots; the consecutive relabel
            # is the same np.unique the host rung applies
            _, labels = np.unique(lane, return_inverse=True)
            results[gi] = (members, labels.astype(np.int64))
        return results, internal_total

    def _dispatch(
        self, probs: List[tuple], level: int, deadline: float
    ) -> np.ndarray:
        import jax
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        from ..runtime import faults as faults_mod
        from .device_pool import get_device_pool

        rb, Wn = self._marshal(probs)
        injector = faults_mod.get_injector()
        box: Dict[str, object] = {}

        def run():
            try:
                # thread-local x64: staging AND the call must both see it,
                # and this worker thread is where both happen
                with enable_x64():
                    # hop chaos: a hang here is a wedged interconnect the
                    # deadline must notice; an error a failed collective
                    injector.maybe_hang("hop", block_id=level)
                    injector.maybe_fail("hop", block_id=level)
                    sb = get_device_pool().stage(
                        rb, self._dev_key, self._replicated, block_id=level
                    )
                    tabs = jax.device_put(
                        np.concatenate(sb.tables, axis=1).astype(np.int32),
                        self._lane_sharded,
                    )
                    thr = jnp.float64(self.threshold)
                    prog = self._program(Wn)
                    labels, rounds = prog(*sb.pools, tabs, thr)
                    box["labels"] = np.asarray(jax.device_get(labels))
                    box["rounds"] = np.asarray(jax.device_get(rounds))
                    box["staged"] = sb.staged_bytes
            # marshalled across the thread boundary: the caller re-raises
            # non-Exception BaseExceptions (DrainInterrupt) verbatim below
            except BaseException as e:  # ctlint: disable=CT006
                box["error"] = e

        t = threading.Thread(
            target=run, name=f"collective-hop-l{level}", daemon=True
        )
        with trace_mod.span(
            "solve.collective_level", level=level, groups=len(probs),
            devices=self.ndev,
        ):
            t.start()
            t.join(timeout=deadline)
        if t.is_alive():
            raise ShardedSolveError(
                f"collective hop deadline: level {level} program exceeded "
                f"{deadline:g}s"
            )
        if "error" in box:
            err = box["error"]
            if isinstance(err, BaseException) and not isinstance(
                err, Exception
            ):
                raise err  # DrainInterrupt etc. pass through
            raise ShardedSolveError(
                f"collective level {level} failed: "
                f"{type(err).__name__}: {err}"
            ) from err
        out = box["labels"]
        rounds = box["rounds"]
        # interconnect accounting: the all_gather hands every device all
        # other devices' shard — (ndev-1)/ndev of the gathered bytes moved
        # over the fabric
        moved = int(
            (out.nbytes + np.asarray(rounds).nbytes)
            * (self.ndev - 1) // self.ndev
        )
        _record_solve_metrics(
            collective_hops=1,
            contraction_dispatches=1,
            bytes_over_interconnect=moved,
            tree_rounds=int(np.asarray(rounds).sum()),
        )
        return out


# -- in-process driver --------------------------------------------------------


def sharded_solve(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    node_shard: np.ndarray,
    *,
    fanout: int = 2,
    solver: Optional[Callable] = None,
    mode: str = "max",
    threshold: float = 0.0,
    lifted_edges: Optional[np.ndarray] = None,
    lifted_payload: Optional[np.ndarray] = None,
    max_workers: int = 1,
    reduce_plane: str = "auto",
    hop_deadline_s: Optional[float] = None,
    failures_path: Optional[str] = None,
    task_name: str = "sharded_solve",
) -> Tuple[np.ndarray, Dict]:
    """Shard-contract-merge in one process.  Returns ``(labels, info)``:
    int64 labels 0..k-1 over the original nodes and the per-level stats
    dict the calling task surfaces in its success manifest.

    ``solver(n, edges, payload, frontier, lifted_edges, lifted_payload)
    -> labels`` runs once per tree node (default:
    :func:`default_tree_solver`; ``frontier`` is the ``(f_node, f_ghost,
    f_payload)`` still-external edge context, or None).  Group solves
    within a level are independent and fan out on a thread pool
    (``max_workers``); the result is invariant to their completion order.

    ``reduce_plane`` picks the level engine (``CT_REDUCE_PLANE``
    overrides): ``collective`` demands the
    :class:`CollectiveReducePlane` (one device program + one all_gather
    hop per level) and attributes ``degraded:packet_plane`` if it cannot
    run; ``auto`` uses it when it is eligible (≥ 2 devices, ≥
    ``CT_REDUCE_PLANE_MIN_EDGES`` live edges, default solver, no lifted
    edges) and otherwise stays on the host path silently; ``packet``
    never touches devices.  Either way the labels are bit-identical —
    the plane choice is pure performance.  ``hop_deadline_s`` caps each
    level's collective dispatch (``CT_HOP_DEADLINE_S``, default
    :data:`DEFAULT_HOP_DEADLINE_S`).
    """
    n_nodes = int(n_nodes)
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    payload = _as_payload(payload, len(edges))
    node_shard = np.asarray(node_shard, dtype=np.int64)
    if len(node_shard) != n_nodes:
        raise ValueError(
            f"node_shard has {len(node_shard)} entries for {n_nodes} nodes"
        )
    custom_solver = solver is not None
    if solver is None:
        solver = default_tree_solver(mode, threshold)
    ledges = (
        np.asarray(lifted_edges, dtype=np.int64).reshape(-1, 2)
        if lifted_edges is not None
        else None
    )
    lpayload = (
        _as_payload(lifted_payload, len(ledges)) if ledges is not None else None
    )

    n_shards = int(node_shard.max()) + 1 if n_nodes else 1
    levels = reduce_tree_levels(n_shards, fanout)
    state = _TreeState(n_nodes, edges, payload, ledges, lpayload, node_shard)

    plane_req = os.environ.get(_ENV_PLANE) or (reduce_plane or "auto")
    if plane_req not in ("auto", "collective", "packet"):
        raise ValueError(
            f"reduce_plane must be auto|collective|packet, got {plane_req!r}"
        )
    hop_deadline = _hop_deadline_s(hop_deadline_s)
    plane: Optional[CollectiveReducePlane] = None
    if plane_req != "packet":
        has_lifted = ledges is not None and len(ledges) > 0
        auto_floor = int(
            os.environ.get(_ENV_AUTO_MIN_EDGES, _AUTO_MIN_EDGES)
        )
        if plane_req == "collective" or (
            not custom_solver and not has_lifted and len(edges) >= auto_floor
        ):
            try:
                if custom_solver or has_lifted:
                    raise ShardedSolveError(
                        "collective plane needs the default solver and "
                        "no lifted edges"
                    )
                plane = CollectiveReducePlane(
                    mode, threshold, payload.shape[1],
                    hop_deadline_s=hop_deadline,
                )
            except Exception as e:
                # init-failure rung: attributed when the plane was
                # demanded, counter-only when auto was probing
                _record_packet_degrade(
                    failures_path, task_name, e,
                    record=(plane_req == "collective"),
                )

    info: Dict = {
        "sharded": True,
        "shards": n_shards,
        "fanout": int(fanout),
        "reduce_plane": "collective" if plane is not None else "host",
        "levels": [],
    }
    _record_solve_metrics(
        sharded_solves=1,
        solve_shards=n_shards,
        boundary_edges_in=len(edges),
    )

    from concurrent.futures import ThreadPoolExecutor

    # the merge queue: group results land here as solves finish; guarded by
    # the merge lock because pool threads publish concurrently.  Offsets
    # are assigned later, in group order, so completion order is invisible.
    merge_lock = threading.Lock()

    for li, groups in enumerate(levels):
        edges_in = len(state.edges)
        results: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        internal_total = 0
        # the level spans double as the solve_s/merge_s clocks
        # (docs/OBSERVABILITY.md): one timing source, and a traced run
        # shows every reduce-tree level as its own timeline extent
        solve_span = trace_mod.begin(
            "solve.level_solve", level=li, groups=len(groups),
            edges_in=int(edges_in),
        )
        level_plane = "host"
        if plane is not None:
            try:
                results, internal_total = plane.solve_level(
                    state, groups, level=li, deadline_s=hop_deadline
                )
                level_plane = "collective"
            except Exception as e:
                # runtime rung of the degrade ladder (hop deadline, a
                # failed collective, pool exhaustion): this and every
                # remaining level re-solve on the host path — the plane
                # was live, so the degradation is always attributed
                _record_packet_degrade(failures_path, task_name, e)
                info["degraded_plane"] = f"{type(e).__name__}: {e}"[:200]
                info["reduce_plane"] = "host"
                plane = None
                results = {}
                internal_total = 0
        if level_plane == "host":

            def run_group(gi, _groups=groups, _li=li):
                with trace_mod.span("solve.group", level=_li, group=gi):
                    members, labels, n_int = _solve_group(
                        state, _groups[gi], solver
                    )
                with merge_lock:
                    results[gi] = (members, labels)
                return n_int

            if max_workers > 1 and len(groups) > 1:
                with ThreadPoolExecutor(max_workers=int(max_workers)) as pool:
                    internal_total = sum(
                        pool.map(run_group, range(len(groups)))
                    )
            else:
                internal_total = sum(
                    run_group(gi) for gi in range(len(groups))
                )
        t_solve = solve_span.end()

        merge_span = trace_mod.begin("solve.level_merge", level=li)
        _apply_level(state, groups, results)
        t_merge = merge_span.end()
        info["levels"].append({
            "level": li,
            "groups": len(groups),
            "plane": level_plane,
            "edges_in": int(edges_in),
            "internal_edges": int(internal_total),
            "edges_out": int(len(state.edges)),
            "solve_s": round(t_solve, 6),
            "merge_s": round(t_merge, 6),
        })
        _record_solve_metrics(
            solve_levels=1, tree_solve_s=t_solve, tree_merge_s=t_merge
        )

    _record_solve_metrics(boundary_edges_out=len(state.edges))
    info["boundary_edges_root"] = int(len(state.edges))
    return _final_labels(state), info


# -- worker-group driver (inter-host reduce hops) -----------------------------


def _packet_path(scratch: str, level: int, group: int) -> str:
    return os.path.join(scratch, f"packet_l{level}_g{group}.npz")


def _publish_npz(path: str, **arrays) -> None:
    """Atomic packet publish: a reader either sees the whole packet or no
    packet — half-written reduce hops cannot exist."""
    tmp = f"{path}.{os.getpid()}.tmp"
    np.savez(tmp, **arrays)
    if not tmp.endswith(".npz") and os.path.exists(tmp + ".npz"):
        tmp = tmp + ".npz"
    os.replace(tmp, path)


def _worker_pid_path(scratch: str, worker: int) -> str:
    return os.path.join(scratch, f"worker_{int(worker)}.json")


def _read_worker_os_pid(pid_path: str) -> Optional[int]:
    """The OS pid a reduce worker advertised at boot, or None while the
    file has not landed yet (the worker may still be initializing)."""
    try:
        with open(pid_path) as f:
            return int(json.load(f)["os_pid"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _wait_npz(
    path: str,
    wait_s: float,
    *,
    deadline: Optional[float] = None,
    owner_pid_path: Optional[str] = None,
) -> Dict[str, np.ndarray]:
    """Poll for a sibling's packet with ``wait_s`` of patience per hop —
    re-armed for every packet, so a worker whose own (possibly long) solve
    consumed wall time still grants its siblings the full window.

    Two fast-fail guards bound the worst case (a worker dying *between*
    publishing level L and reading level L+1 used to burn the full
    patience window per remaining hop — levels × patience):

    - ``deadline`` (absolute ``time.monotonic()``) caps the TOTAL wait of
      the enclosing level: however many packets are still missing, the
      level fails in one window.
    - ``owner_pid_path`` points at the publishing worker's boot-time pid
      record; a ~4/s same-host liveness probe (``os.kill(pid, 0)``, the
      PR-19 file_lock fast-break idiom) surfaces a dead publisher in a
      quarter second, naming the pid instead of "worker death?".
    """
    hop_deadline = time.monotonic() + wait_s
    if deadline is not None:
        hop_deadline = min(hop_deadline, float(deadline))
    next_probe = time.monotonic() + 0.25
    while True:
        if os.path.exists(path):
            try:
                with np.load(path, allow_pickle=False) as f:
                    return {k: f[k] for k in f.files}
            except (OSError, ValueError) as e:
                # packets publish via os.replace, so a torn file here is
                # real corruption, not a mid-write read
                raise ShardedSolveError(f"unreadable packet {path}: {e}")
        now = time.monotonic()
        if now > hop_deadline:
            raise ShardedSolveError(
                f"reduce hop lost: packet {os.path.basename(path)} did not "
                f"arrive within {wait_s:g}s"
                + (" (level deadline)" if deadline is not None
                   and hop_deadline == float(deadline) else "")
                + " (worker death?)"
            )
        if owner_pid_path is not None and now >= next_probe:
            next_probe = now + 0.25
            owner_pid = _read_worker_os_pid(owner_pid_path)
            if owner_pid is not None:
                try:
                    os.kill(owner_pid, 0)
                except ProcessLookupError:
                    raise ShardedSolveError(
                        f"reduce hop lost: worker owning "
                        f"{os.path.basename(path)} (os pid {owner_pid}) "
                        f"is dead"
                    )
                except (PermissionError, OSError):
                    pass  # alive but unprobeable — keep the deadlines
        time.sleep(0.02)


def _group_owner(level: int, group: int, n_workers: int) -> int:
    """Deterministic round-robin deal of tree nodes over the worker group."""
    return int(group) % max(1, int(n_workers))


def reduce_worker_main() -> None:
    """SPMD body of one reduce-tree worker (entered through
    :func:`~cluster_tools_tpu.parallel.multihost.worker_main`, i.e. after
    ``jax.distributed.initialize`` joined this process into the worker
    group).  Solves the leaf shards and merge groups this worker owns,
    publishes their packets, and replays every level from all packets so
    its state stays bit-identical to its siblings'.  Worker 0 publishes the
    final labels.

    A worker that FAILS (lost hop, bad packet) flushes its traceback and
    then SIGKILLs itself: a normal exit would run ``jax.distributed``'s
    shutdown barrier, which blocks until the runtime's ~100 s heartbeat
    timeout aborts the process when a sibling is already dead — turning
    an 8-second degrade into a two-minute stall.  ``DrainInterrupt`` is a
    BaseException and still propagates normally."""
    import sys
    import traceback

    try:
        _reduce_worker_body()
    except Exception:
        import signal as signal_mod

        traceback.print_exc()
        sys.stderr.flush()
        sys.stdout.flush()
        try:
            # the shard of a FAILING worker is the one the post-mortem
            # needs most (it shows the hop wait that never returned) —
            # flush before the self-SIGKILL
            trace_mod.flush()
        except Exception:
            pass
        os.kill(os.getpid(), signal_mod.SIGKILL)


def _reduce_worker_body() -> None:
    from ..runtime import faults as faults_mod
    from . import multihost

    scratch = os.environ[_ENV_DIR]
    pid = int(os.environ[multihost._ENV_PID])
    n_workers = int(os.environ[multihost._ENV_NPROC])
    hop_wait_s = float(os.environ.get(_ENV_WAIT, DEFAULT_HOP_WAIT_S))
    # boot-time pid record: siblings probe it to fast-fail on this
    # worker's death instead of burning their hop patience (_wait_npz)
    fu.atomic_write_json(
        _worker_pid_path(scratch, pid), {"os_pid": os.getpid()}
    )
    # solver-worker lifetime span (docs/OBSERVABILITY.md): tracing is on
    # only when the driver exported CTT_TRACE=<dir>, pointing this process
    # at the submitter's shard directory
    worker_span = trace_mod.begin(
        "solve.worker", worker=pid, workers=n_workers
    )

    with open(os.path.join(scratch, "meta.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(scratch, "problem.npz"), allow_pickle=False) as f:
        edges = f["edges"].astype(np.int64)
        payload = f["payload"].astype(np.float64)
        node_shard = f["node_shard"].astype(np.int64)
        ledges = f["lifted_edges"].astype(np.int64) if "lifted_edges" in f.files else None
        lpayload = f["lifted_payload"].astype(np.float64) if "lifted_payload" in f.files else None

    # chaos crossing: a `solve` fault targeted at this worker id models a
    # host lost mid-reduce — die like hardware (SIGKILL, no cleanup, no
    # packet), so siblings see a lost hop and the driver degrades
    try:
        faults_mod.get_injector().maybe_fail("solve", block_id=pid)
    except Exception:
        import signal as signal_mod

        os.kill(os.getpid(), signal_mod.SIGKILL)

    n_nodes = int(meta["n_nodes"])
    # resolve the contraction impl WITHOUT the jax backend probe: touching
    # the XLA client from inside a multi-process distributed runtime hangs
    # on jaxlib CPU backends without multiprocess collectives (the same
    # limitation the test_multihost env-skip covers) — and the tree-node
    # solves are host work here anyway (native C++ rung, numpy fallback)
    solver = default_tree_solver(
        meta["mode"], float(meta["threshold"]), impl=_host_impl(meta.get("impl"))
    )
    levels = reduce_tree_levels(int(meta["n_shards"]), int(meta["fanout"]))
    state = _TreeState(n_nodes, edges, payload, ledges, lpayload, node_shard)

    # plane choice is made ONCE, deterministically, before the levels:
    # every worker runs the same probe on the same backend, so the group
    # either all takes the collective path (SPMD level programs over the
    # pod mesh, no packets) or all exchanges filesystem packets.  A
    # worker cannot switch rungs mid-solve — its siblings would wait on
    # packets that are never coming.
    plane: Optional[CollectiveReducePlane] = None
    plane_reason = "packet plane requested"
    plane_req = str(meta.get("reduce_plane", "packet"))
    hop_deadline = _hop_deadline_s(meta.get("hop_deadline_s"))
    if plane_req in ("auto", "collective"):
        supported, reason = multihost.collectives_supported(
            deadline_s=hop_deadline
        )
        if not supported:
            # the known old-jaxlib CPU backends take initialize() but
            # abort the first multi-process collective — degrade here,
            # before any level committed to device hops
            plane_reason = f"collectives unsupported: {reason}"
        elif ledges is not None and len(ledges):
            plane_reason = "lifted edges have no frontier formulation"
        else:
            try:
                plane = CollectiveReducePlane(
                    meta["mode"], float(meta["threshold"]),
                    payload.shape[1] if payload.ndim > 1 else 1,
                    hop_deadline_s=hop_deadline,
                )
                plane_reason = "collective"
            except Exception as e:
                plane_reason = f"plane init failed: {e}"[:200]

    for li, groups in enumerate(levels):
        if plane is not None:
            # the collective rung: ONE SPMD program solves every group of
            # the level on the pod mesh and the in-program all_gather IS
            # the reduce hop — no packets, no polling.  Any failure here
            # is a worker failure (SIGKILL via reduce_worker_main); the
            # driver retries the whole solve on the packet plane.
            results, _ = plane.solve_level(
                state, groups, level=li, deadline_s=hop_deadline
            )
            _apply_level(state, groups, results)
            try:
                trace_mod.flush()
            except Exception:
                pass
            continue
        # solve + publish the groups dealt to this worker
        for gi in range(len(groups)):
            if _group_owner(li, gi, n_workers) != pid:
                continue
            with trace_mod.span(
                "solve.group", level=li, group=gi, worker=pid
            ):
                members, labels, n_int = _solve_group(
                    state, groups[gi], solver
                )
            _publish_npz(
                _packet_path(scratch, li, gi),
                members=members, labels=labels,
                n_internal=np.int64(n_int),
            )
        # collect every group's packet (the reduce hop) and fold the
        # level.  The level deadline is armed AFTER this worker's own
        # solves: however many siblings' packets are still missing, a
        # dead group fails within ONE patience window, not one per hop.
        level_deadline = time.monotonic() + hop_wait_s
        results = {}
        for gi in range(len(groups)):
            # the hop wait is the inter-host latency PAPERS.md's wafer-
            # scale-reduce analysis says must be measured per hop — one
            # span per awaited packet, worker- and level-attributed
            with trace_mod.span(
                "solve.hop_wait", level=li, group=gi, worker=pid
            ):
                pkt = _wait_npz(
                    _packet_path(scratch, li, gi), hop_wait_s,
                    deadline=level_deadline,
                    owner_pid_path=_worker_pid_path(
                        scratch, _group_owner(li, gi, n_workers)
                    ),
                )
            results[gi] = (
                pkt["members"].astype(np.int64),
                pkt["labels"].astype(np.int64),
            )
        _apply_level(state, groups, results)
        # crash-safe: each level's flush rewrites the full shard, so a
        # worker killed at level N leaves its spans through level N-1 —
        # but a tracing write failure must never fail a healthy worker
        try:
            trace_mod.flush()
        except Exception:
            pass

    if pid == 0:
        _publish_npz(
            os.path.join(scratch, "result.npz"),
            labels=_final_labels(state),
            # root residual for the driver's observability counters (its
            # own snapshot cannot see this process's state)
            boundary_edges_root=np.int64(len(state.edges)),
            # which rung actually ran, for the driver's attribution
            plane_used=np.str_(
                "collective" if plane is not None else "packet"
            ),
            plane_reason=np.str_(plane_reason),
        )
    worker_span.end()
    try:
        trace_mod.flush()
    except Exception:
        pass
    print(f"REDUCE_TREE_OK pid={pid} workers={n_workers}", flush=True)


def solve_over_workers(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    node_shard: np.ndarray,
    *,
    fanout: int = 2,
    mode: str = "max",
    threshold: float = 0.0,
    lifted_edges: Optional[np.ndarray] = None,
    lifted_payload: Optional[np.ndarray] = None,
    n_workers: int = 2,
    scratch_dir: str,
    timeout: Optional[float] = None,
    hop_wait_s: Optional[float] = None,
    impl: Optional[str] = None,
    reduce_plane: str = "packet",
    hop_deadline_s: Optional[float] = None,
) -> Tuple[np.ndarray, Dict]:
    """Run the reduce tree over a :func:`multihost.launch_workers` group.

    The problem is staged once into ``scratch_dir``; each worker joins the
    ``jax.distributed`` runtime, solves the shards/groups it owns, and the
    boundary-edge packets between levels are the inter-host hops.  Raises
    :class:`ShardedSolveError` on any worker failure or lost packet — the
    caller's cue to degrade to the single-host solve.

    ``reduce_plane`` ∈ ``packet|auto|collective``: with ``auto`` or
    ``collective`` the workers probe
    :func:`multihost.collectives_supported` once at boot and — where the
    backend can run multi-process collectives — replace the packet
    exchange with SPMD level programs over the pod mesh
    (:class:`CollectiveReducePlane`); otherwise all workers
    deterministically stay on packets.  ``info["reduce_plane"]`` reports
    the rung that actually ran, ``info["plane_reason"]`` why.
    """
    from .multihost import launch_workers

    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    payload = _as_payload(payload, len(edges))
    node_shard = np.asarray(node_shard, dtype=np.int64)
    n_shards = int(node_shard.max()) + 1 if int(n_nodes) else 1
    os.makedirs(scratch_dir, exist_ok=True)
    for stale in os.listdir(scratch_dir):
        if stale.startswith(("packet_", "result", "worker_")):
            try:
                os.unlink(os.path.join(scratch_dir, stale))
            except OSError:
                pass
    arrays = {"edges": edges, "payload": payload, "node_shard": node_shard}
    if lifted_edges is not None and len(lifted_edges):
        arrays["lifted_edges"] = np.asarray(lifted_edges, np.int64)
        arrays["lifted_payload"] = _as_payload(
            lifted_payload, len(arrays["lifted_edges"])
        )
    _publish_npz(os.path.join(scratch_dir, "problem.npz"), **arrays)
    fu.atomic_write_json(
        os.path.join(scratch_dir, "meta.json"),
        {
            "n_nodes": int(n_nodes),
            "n_shards": n_shards,
            "fanout": int(fanout),
            "mode": mode,
            "threshold": float(threshold),
            "impl": impl or "host",
            "reduce_plane": str(reduce_plane),
            "hop_deadline_s": _hop_deadline_s(hop_deadline_s),
        },
    )

    if timeout is None:
        # driver patience for the whole worker group; must outlast the
        # workers' own per-hop wait so a lost packet surfaces as a worker
        # rc, not a group kill
        timeout = float(os.environ.get("CT_RT_TIMEOUT_S", "600"))
    group_span = trace_mod.begin(
        "solve.worker_group", workers=int(n_workers), shards=n_shards
    )
    extra_env = {
        _ENV_DIR: scratch_dir,
        # explicit arg > operator env > default — launch_workers
        # applies extra_env over os.environ, so the env knob must
        # be threaded through here to reach the workers at all
        _ENV_WAIT: str(
            hop_wait_s if hop_wait_s is not None
            else os.environ.get(_ENV_WAIT, DEFAULT_HOP_WAIT_S)
        ),
    }
    if trace_mod.enabled() and trace_mod.trace_dir():
        # a traced driver hands the workers its shard directory — the env
        # value both enables tracing and pins the directory, so a run
        # enabled programmatically (configure()) still traces its workers
        extra_env[trace_mod.ENV_VAR] = trace_mod.trace_dir()
    try:
        results = launch_workers(
            int(n_workers),
            "cluster_tools_tpu.parallel.reduce_tree:reduce_worker_main",
            timeout=timeout,
            extra_env=extra_env,
        )
    except TimeoutError as e:
        group_span.end(error=True)
        raise ShardedSolveError(f"worker group timed out: {e}") from e
    failed = [
        (pid, rc, (err or "")[-500:])
        for pid, (rc, _, err) in enumerate(results)
        if rc != 0
    ]
    if failed:
        group_span.end(error=True)
        raise ShardedSolveError(
            "worker(s) died during the sharded solve: "
            + "; ".join(f"pid {p} rc={rc}" for p, rc, _ in failed)
            + "\n" + "\n".join(t for _, _, t in failed)
        )
    result_path = os.path.join(scratch_dir, "result.npz")
    if not os.path.exists(result_path):
        group_span.end(error=True)
        raise ShardedSolveError("worker group finished without a result packet")
    with np.load(result_path, allow_pickle=False) as f:
        labels = f["labels"].astype(np.int64)
        root_edges = int(f["boundary_edges_root"]) \
            if "boundary_edges_root" in f.files else 0
        plane_used = str(f["plane_used"]) if "plane_used" in f.files \
            else "packet"
        plane_reason = str(f["plane_reason"]) if "plane_reason" in f.files \
            else ""
    wall = group_span.end()
    levels = reduce_tree_levels(n_shards, fanout)
    info = {
        "sharded": True,
        "shards": n_shards,
        "fanout": int(fanout),
        "workers": int(n_workers),
        "reduce_plane": plane_used,
        "plane_reason": plane_reason,
        "levels": [{"level": i, "groups": len(g)} for i, g in enumerate(levels)],
        "wall_s": round(wall, 4),
        "boundary_edges_root": root_edges,
        # contraction rounds tick inside the worker processes — invisible
        # to this process's counters, so manifests of worker-group solves
        # report rounds=0 by design (the root residual above is shipped
        # back explicitly for the same reason)
    }
    _record_solve_metrics(
        sharded_solves=1, solve_shards=n_shards,
        solve_levels=len(levels), boundary_edges_in=len(edges),
        boundary_edges_out=root_edges, tree_solve_s=wall,
    )
    return labels, info


# -- the attributed task entry point ------------------------------------------


def solve_with_reduce_tree(
    n_nodes: int,
    edges: np.ndarray,
    payload: np.ndarray,
    *,
    node_shard: Optional[np.ndarray],
    solver_shards: int,
    fanout: int,
    failures_path: str,
    task_name: str,
    unsharded: Callable[[], np.ndarray],
    solver: Optional[Callable] = None,
    mode: str = "max",
    threshold: float = 0.0,
    lifted_edges: Optional[np.ndarray] = None,
    lifted_payload: Optional[np.ndarray] = None,
    workers: int = 1,
    scratch_dir: Optional[str] = None,
    worker_timeout: Optional[float] = None,
    max_workers: int = 1,
    reduce_plane: str = "auto",
    hop_deadline_s: Optional[float] = None,
) -> Tuple[np.ndarray, Dict]:
    """Sharded solve with the single-host path as the degenerate case AND
    the degrade fallback.  Returns ``(labels, info)``.

    ``node_shard`` may be the partition array, a zero-arg callable
    building it (resolved inside the fallback ladder — partition
    construction re-opens block geometry and must not be able to fail the
    task), or None (nothing to shard by: single-host, no failure record).

    ``solver_shards <= 1`` (or a graph too small to shard) runs
    ``unsharded()`` directly — today's behavior, bit for bit.  Otherwise the
    reduce tree runs (in-process, or over a ``workers``-process
    :mod:`..parallel.multihost` group when ``workers > 1``; the worker
    path always uses the default frontier-aware solver — a custom
    ``solver`` callback cannot cross process boundaries); ANY failure in
    it — a killed worker, a lost reduce hop, an injected ``solve`` fault —
    is recorded in ``failures.json`` with resolution
    ``degraded:unsharded_solve`` and the single-host solver produces the
    answer, so the result is exactly what the unsharded run would have
    computed (docs/ROBUSTNESS.md "Graceful degradation").
    ``DrainInterrupt`` is a BaseException and passes through: a preemption
    mid-solve drains, it does not burn a fallback.

    ``reduce_plane``/``hop_deadline_s`` pick the level engine (see
    :func:`sharded_solve`): ``collective`` rides the degrade ladder
    collective → packet plane → unsharded, each rung attributed
    (``degraded:packet_plane`` / ``degraded:unsharded_solve``); ``auto``
    takes the best supported rung; ``packet`` never touches devices.
    """
    shards = int(solver_shards or 1)
    if shards <= 1 or node_shard is None or int(n_nodes) == 0 \
            or len(edges) == 0:
        return unsharded(), {"sharded": False, "shards": 1}
    no_partition = False
    try:
        from ..runtime import faults as faults_mod

        faults_mod.get_injector().maybe_fail("solve")
        # the partition may be a thunk (tasks re-open block geometry to
        # build it): resolve it INSIDE the ladder, so an unreachable store
        # or a torn block-nodes file degrades instead of failing the task
        if callable(node_shard):
            node_shard = node_shard()
            if node_shard is None:
                # legitimately nothing to shard by (no block geometry) —
                # single-host, but not a failure worth attributing
                no_partition = True
                raise ShardedSolveError("no block geometry to shard by")
        plane_req = os.environ.get(_ENV_PLANE) or (reduce_plane or "auto")
        if int(workers) > 1:
            if scratch_dir is None:
                raise ShardedSolveError(
                    "worker-group solve needs a scratch_dir for the hops"
                )

            def worker_solve(rp):
                return solve_over_workers(
                    n_nodes, edges, payload, node_shard,
                    fanout=fanout, mode=mode, threshold=threshold,
                    lifted_edges=lifted_edges, lifted_payload=lifted_payload,
                    n_workers=int(workers), scratch_dir=scratch_dir,
                    timeout=worker_timeout, reduce_plane=rp,
                    hop_deadline_s=hop_deadline_s,
                )

            if plane_req != "collective":
                return worker_solve(plane_req)
            # demanded collective: one retry rung on the packet plane
            # before the unsharded ladder below — a mid-solve collective
            # failure (hop deadline, failed gather → worker SIGKILL)
            # re-runs the whole group on packets, bit-identically
            try:
                labels, winfo = worker_solve("collective")
            except ShardedSolveError as hop_err:
                _record_packet_degrade(failures_path, task_name, hop_err)
                labels, winfo = worker_solve("packet")
                winfo["degraded_plane"] = str(hop_err)[:200]
                return labels, winfo
            if winfo.get("reduce_plane") != "collective":
                # the workers degraded up front (unsupported backend /
                # init failure) — attribute it here, once, driver-side
                _record_packet_degrade(
                    failures_path, task_name,
                    ShardedSolveError(
                        winfo.get("plane_reason") or "collective plane "
                        "unavailable in the worker group"
                    ),
                )
            return labels, winfo
        return sharded_solve(
            n_nodes, edges, payload, node_shard,
            fanout=fanout, solver=solver, mode=mode, threshold=threshold,
            lifted_edges=lifted_edges, lifted_payload=lifted_payload,
            max_workers=max_workers, reduce_plane=plane_req,
            hop_deadline_s=hop_deadline_s, failures_path=failures_path,
            task_name=task_name,
        )
    except Exception as e:
        if no_partition:
            return unsharded(), {"sharded": False, "shards": 1}
        # the fallback ladder: anything short of a drain degrades to the
        # single-host solve, attributed like every other degradation —
        # and lands on the trace timeline next to the solve latency it
        # causes (docs/OBSERVABILITY.md)
        _record_solve_metrics(unsharded_fallbacks=1)
        trace_mod.instant(
            "degraded:unsharded_solve", task=task_name,
            error=f"{type(e).__name__}: {e}"[:200],
        )
        tb = fu.cap_traceback(
            f"{type(e).__name__}: {e}"
        )
        try:
            fu.record_failures(failures_path, task_name, [{
                "block_id": None,
                "sites": {"solve": 1},
                "error": tb,
                "quarantined": False,
                "resolved": True,
                "resolution": "degraded:unsharded_solve",
            }])
        except Exception:
            pass  # attribution is best effort; the solve must still land
        labels = unsharded()
        return labels, {
            "sharded": False,
            "shards": shards,
            "degraded": "unsharded_solve",
            "error": str(e)[:300],
        }
