"""Shard-axis transposition over ICI: the all-to-all reshard primitive.

The reference had no analogue — its jobs only ever exchanged data through
the filesystem (SURVEY.md §2d).  On a mesh, changing which *spatial* axis is
sharded is one ``lax.all_to_all`` over ICI, the exact pattern
sequence-parallel attention uses to flip between sequence- and head-sharded
layouts (SURVEY.md §5.7 maps sequence parallelism onto spatial
decomposition).

Use it when an op needs one axis resident in full — e.g. an exact
(uncapped) separable EDT pass along z on a z-sharded volume: reshard so x is
the sharded axis, run the z pass locally at full extent, reshard back.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map


def reshard_axis(
    x: jnp.ndarray, axis_name: str, from_axis: int, to_axis: int
) -> jnp.ndarray:
    """Inside ``shard_map``: move the sharded dimension of a volume.

    ``x`` is the local shard of a volume globally sharded along
    ``from_axis``; the result is the local shard of the same volume sharded
    along ``to_axis`` (``from_axis`` becomes fully resident).  ``to_axis``'s
    local extent must be divisible by the mesh axis size.
    """
    if from_axis == to_axis:
        return x
    return lax.all_to_all(
        x, axis_name, split_axis=to_axis, concat_axis=from_axis, tiled=True
    )


@partial(jax.jit, static_argnames=("mesh", "axis_name", "from_axis", "to_axis"))
def transpose_sharding(
    vol: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    from_axis: int = 0,
    to_axis: int = 2,
) -> jnp.ndarray:
    """Whole-volume wrapper: input sharded along ``from_axis``, output along
    ``to_axis`` — one ICI all-to-all, no host round trip."""
    spec_in = [None] * vol.ndim
    spec_in[from_axis] = axis_name
    spec_out = [None] * vol.ndim
    spec_out[to_axis] = axis_name

    fn = shard_map(
        partial(
            reshard_axis,
            axis_name=axis_name,
            from_axis=from_axis,
            to_axis=to_axis,
        ),
        mesh=mesh,
        in_specs=P(*spec_in),
        out_specs=P(*spec_out),
    )
    return fn(vol)
