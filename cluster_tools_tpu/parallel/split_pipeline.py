"""Split execution mode: the fused ws+cc step as a chain of per-stage
jitted SPMD programs with device-resident (HBM-pinned) intermediates.

Why this exists: on the tunneled TPU backend the fused monolith's remote
compile has exceeded every operational cap (Mosaic >=600s, portable XLA
>=440s for a ~4.5-6.3k-line HLO that XLA:CPU compiles in 19s —
docs/PERFORMANCE.md round-4 log), while the per-stage programs are
individually in the class of the tiled CCL (~1.4k lines), the one program
PROVEN to compile on-chip in round 3.  Splitting the step into four
programs whose intermediates never leave the device makes the headline
number robust to the monolith never compiling:

1. ``seeds``   — halo exchange, (optionally mesh-exact) EDT, maxima,
                 seed CCL (collectives: ppermute halo, EDT reshard).
2. ``flow``    — descent directions, in-tile VMEM flow, exit chase +
                 remap (no collectives).
3. ``fill``    — unseeded-basin fill, remap, halo crop, fragment-id
                 globalization, cross-shard stitch (collectives:
                 all_gather merge).
4. ``cc``      — distributed CCL of the foreground + global stats
                 (collectives: all_gather merge, psum).

Each stage is its own ``jax.jit(shard_map(...))`` over the same mesh and
specs as the fused step (``make_ws_ccl_step``); outputs equal the fused
step's bit-for-bit on every oracle in tests/test_split_pipeline.py.  The
cost is a few host dispatches per batch instead of one — measured on the
8-device CPU mesh the overhead is small compared to any stage's compute
(recorded by ``bench.py``'s split path and the A/B test).

Intermediates are donated where consumed (``padded`` to flow, ``values``/
``h`` to fill) so peak HBM stays in the fused step's class.

Reference mapping (SURVEY.md §3.5): this IS the reference's five-task
blockwise decomposition (write block -> ws block -> merge faces ->
merge assignments -> write relabeled) re-cut on program-compile
boundaries instead of luigi-task/filesystem boundaries.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from ..ops.ccl import _match_vma, relabel_consecutive
from ..ops.tile_ccl import DEFAULT_TABLE_CAP
from ..ops.tile_ws import (
    _dt_seeds_core,
    _resolve_fill_mode,
    _resolve_seed_mode,
    _ws_flow_core,
    _ws_fill_core,
)
from .distributed_ccl import (
    linearized_shard_rank,
    sharded_label_components,
    sp_axes_for_mesh,
)
from .halo import crop_halo, exchange_halo
from .pipeline import _stitch_ws_fragments


class SplitWsCclStep:
    """Callable chain of per-stage programs; see the module docstring.

    ``step(boundaries)`` returns ``(ws_labels, cc_labels, n_foreground,
    overflow)`` — the same contract as the fused step from
    ``make_ws_ccl_step``.  ``stages`` maps stage name to its jitted
    function for individual compile-probing / cache warming; ``run_staged``
    exposes per-stage sync points for stage-resolved timing.
    """

    def __init__(self, stages, runner):
        self.stages = stages
        self._runner = runner

    def __call__(self, boundaries):
        return self._runner(boundaries, sync=None)

    def run_staged(self, boundaries, sync):
        """Run with ``sync(name, *arrays)`` called after dispatching each
        stage — pass a blocking sync to time stages individually."""
        return self._runner(boundaries, sync=sync)


def make_ws_ccl_split(
    mesh: Mesh,
    halo: int = 4,
    threshold: float = 0.3,
    connectivity: int = 1,
    dp_axis: str = "dp",
    sp_axis: Union[str, Sequence[str]] = "sp",
    dt_max_distance: Optional[float] = None,
    min_seed_distance: float = 0.0,
    max_labels_per_shard: Optional[int] = None,
    impl: str = "auto",
    exact_edt: bool = False,
    stitch_ws_threshold: Optional[float] = None,
    fill_mode: Optional[str] = None,
    seed_mode: Optional[str] = None,
) -> SplitWsCclStep:
    """Build the split-mode twin of ``make_ws_ccl_step`` for ``mesh``.

    Same arguments and output contract as the fused builder; ``impl`` is
    restricted to the tiled kernel family ("auto"/"pallas"/"xla"/"tiled")
    because the split exists to deploy the tiled path on compile-capped
    backends — "legacy" has no phase seams to cut (its fused program is
    small enough to compile everywhere).  3-D volumes, connectivity 1.

    ``fill_mode``/``seed_mode``: as in ``dt_watershed_tiled`` — ``None``
    resolves ``CT_FILL_MODE``/``CT_SEED_CCL`` here, at build time, so the
    env values are fixed into the stage programs.
    """
    if impl == "legacy":
        raise ValueError("split mode covers the tiled kernels only")
    if connectivity != 1:
        raise ValueError("split mode supports connectivity=1 only")
    names = (sp_axis,) if isinstance(sp_axis, str) else tuple(sp_axis)
    sp_axes = sp_axes_for_mesh(mesh, sp_axis)
    n_shards = int(np.prod([s for _, _, s in sp_axes]))
    fill_mode = _resolve_fill_mode(fill_mode)
    seed_mode = _resolve_seed_mode(seed_mode)
    # tier_mode() is read at trace time inside the tiered sites; each call
    # to this builder returns FRESH jitted closures (fresh caches), so the
    # env value at first use is the one compiled — same contract as the
    # fused builder.
    tiled_impl = "xla" if impl == "tiled" else impl
    spec = P(dp_axis, *names)
    rep = P()

    def _smap(body, in_specs, out_specs, donate=()):
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=donate,
        )
        return fn

    def exchange_all(x, fill):
        # one ppermute per sharded axis; later exchanges forward the halos
        # received by earlier ones, so corner regions arrive correctly
        for a, name, size in sp_axes:
            x = exchange_halo(x, halo, a, name, size, fill=fill)
        return x

    def _reduce_all(v):
        for _, name, _ in sp_axes:
            v = lax.pmax(v, name)
        return lax.pmax(v, dp_axis)

    # ---- stage 1: halo exchange + EDT + maxima + seed CCL ----
    def seeds_body(boundaries):
        if boundaries.ndim - 1 != 3:
            raise ValueError("split mode expects 3-D volumes")
        local_b = boundaries.shape[0]
        pad_out, seed_out = [], []
        ovf = _match_vma(jnp.zeros((), jnp.int32), boundaries)
        for b in range(local_b):
            vol = boundaries[b]
            padded = exchange_all(vol, fill=1.0)
            dist_pad = None
            if exact_edt:
                from .distributed_edt import (
                    sharded_distance_transform_squared,
                )

                dist_sq = sharded_distance_transform_squared(
                    vol < threshold,
                    shard_axes=sp_axes,
                    max_distance=dt_max_distance,
                    impl="xla" if impl in ("xla", "tiled") else "auto",
                )
                dist_pad = exchange_all(dist_sq, fill=0.0)
            seeds, _, s_ovf = _dt_seeds_core(
                padded, None, dist_pad, threshold=threshold,
                sigma_seeds=0.0, min_seed_distance=min_seed_distance,
                sampling=None, dt_max_distance=dt_max_distance,
                impl=tiled_impl, tile=None, pair_cap=None, edge_cap=None,
                table_cap=DEFAULT_TABLE_CAP, interpret=False,
                seed_cap=None, seed_mode=seed_mode,
            )
            ovf = jnp.maximum(ovf, s_ovf.astype(jnp.int32))
            pad_out.append(padded)
            seed_out.append(seeds)
        return jnp.stack(pad_out), jnp.stack(seed_out), _reduce_all(ovf)

    # ---- stage 2: descent + in-tile flow + exit chase/remap ----
    def flow_body(padded, seeds, ovf_in):
        local_b = padded.shape[0]
        val_out, h_out = [], []
        ovf = ovf_in
        for b in range(local_b):
            values, h, o = _ws_flow_core(
                padded[b], seeds[b], None, impl=tiled_impl, tile=None,
                exit_cap=None, table_cap=DEFAULT_TABLE_CAP, interpret=False,
            )
            ovf = jnp.maximum(ovf, o.astype(jnp.int32))
            val_out.append(values)
            h_out.append(h)
        # pmax so the replicated out_spec is honest (check_vma is off —
        # an unreduced per-shard flag would silently take one shard's copy)
        return jnp.stack(val_out), jnp.stack(h_out), _reduce_all(ovf)

    # ---- stage 3: fill + halo crop + globalize + stitch ----
    def fill_body(values, h, boundaries, ovf_in):
        local_b = values.shape[0]
        rank = linearized_shard_rank(sp_axes)
        pad_shape = tuple(
            boundaries.shape[1 + i]
            + (2 * halo if i in [a for a, _, _ in sp_axes] else 0)
            for i in range(3)
        )
        n_pad = int(np.prod(pad_shape))
        ws_out = []
        ovf = ovf_in
        for b in range(local_b):
            ws, o = _ws_fill_core(
                values[b], h[b], pad_shape, impl=tiled_impl, tile=None,
                exit_cap=None, fill_cap=None, table_cap=DEFAULT_TABLE_CAP,
                interpret=False, adj_cap=None, fill_rounds=None,
                fill_mode=fill_mode,
            )
            ovf = jnp.maximum(ovf, o.astype(jnp.int32))
            for a, _, _ in sp_axes:
                ws = crop_halo(ws, halo, a)
            # globalize fragment ids by shard rank (identical arithmetic to
            # the fused body — parallel/pipeline.py _ws_ccl_shard)
            if max_labels_per_shard is not None:
                cap = int(max_labels_per_shard)
                if n_shards * (cap + 1) >= 2**31:
                    raise ValueError(
                        f"{n_shards} shards x {cap} ws fragments overflow int32"
                    )
                ws, n_frag = relabel_consecutive(
                    ws, max_labels=cap, value_bound=n_pad + 1
                )
                ovf = jnp.maximum(ovf, (n_frag > cap).astype(jnp.int32))
                ws = jnp.where(ws > 0, ws + rank * jnp.int32(cap + 1), 0)
                ws_span = cap + 1
            else:
                if n_shards * n_pad >= 2**31:
                    raise ValueError(
                        f"{n_shards} shards of {n_pad} padded voxels overflow "
                        "int32 labels; pass max_labels_per_shard"
                    )
                ws = jnp.where(ws > 0, ws + rank * jnp.int32(n_pad), 0)
                ws_span = n_pad
            if stitch_ws_threshold is not None and n_shards > 1:
                ws = _stitch_ws_fragments(
                    ws, boundaries[b], sp_axes, rank, ws_span,
                    float(stitch_ws_threshold),
                )
            ws_out.append(ws)
        return jnp.stack(ws_out), _reduce_all(ovf)

    # ---- stage 4: distributed CC of the foreground + global stats ----
    def cc_body(boundaries, ovf_in):
        local_b = boundaries.shape[0]
        cc_out = []
        ovf = ovf_in
        for b in range(local_b):
            vol = boundaries[b]
            cc, cc_over = sharded_label_components(
                vol < threshold,
                shard_axes=sp_axes,
                connectivity=connectivity,
                max_labels_per_shard=max_labels_per_shard,
                return_overflow=True,
                impl=impl,
            )
            ovf = jnp.maximum(ovf, cc_over.astype(jnp.int32))
            cc_out.append(cc)
        cc_lab = jnp.stack(cc_out)
        # float32 psum: an int32 count would wrap past 2**31 global
        # foreground voxels (same rationale as the fused step)
        n_fg = jnp.sum(cc_lab > 0).astype(jnp.float32)
        for _, name, _ in sp_axes:
            n_fg = lax.psum(n_fg, name)
        n_fg = lax.psum(n_fg, dp_axis)
        overflow = _reduce_all(ovf) > 0
        return cc_lab, n_fg, overflow

    stages = {
        "seeds": _smap(seeds_body, (spec,), (spec, spec, rep)),
        # donate the padded volume (consumed by flow) and values/h
        # (consumed by fill) so peak HBM stays in the fused step's class
        "flow": _smap(
            flow_body, (spec, spec, rep), (spec, spec, rep), donate=(0, 1)
        ),
        "fill": _smap(
            fill_body, (spec, spec, spec, rep), (spec, rep), donate=(0, 1)
        ),
        "cc": _smap(cc_body, (spec, rep), (spec, rep, rep)),
    }

    def runner(boundaries, sync=None):
        padded, seeds, ovf = stages["seeds"](boundaries)
        if sync is not None:
            sync("seeds", seeds)
        values, h, ovf = stages["flow"](padded, seeds, ovf)
        if sync is not None:
            sync("flow", values)
        ws_lab, ovf = stages["fill"](values, h, boundaries, ovf)
        if sync is not None:
            sync("fill", ws_lab)
        cc_lab, n_fg, overflow = stages["cc"](boundaries, ovf)
        if sync is not None:
            sync("cc", cc_lab)
        return ws_lab, cc_lab, n_fg, overflow

    return SplitWsCclStep(stages, runner)
