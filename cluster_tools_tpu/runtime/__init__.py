from .task import BaseTask, SuccessTarget, build, DummyTask, WorkflowBase, get_task_cls
from .executor import (
    BlockwiseExecutor,
    check_finite_outputs,
    get_devices,
    get_mesh,
    validate_labels,
)
from .faults import FaultInjector, InjectedFault, configure, get_injector
