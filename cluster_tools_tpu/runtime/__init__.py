from .task import BaseTask, SuccessTarget, build, DummyTask, WorkflowBase, get_task_cls
from .executor import BlockwiseExecutor, get_devices, get_mesh
