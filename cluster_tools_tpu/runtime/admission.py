"""Per-tenant admission control for the resident pipeline server.

The batch runtime already rations *bytes*: PR-4's executor admission gate
caps loaded-but-unstored batch bytes against a MemAvailable-derived budget.
Service mode (docs/SERVING.md) adds the missing dimension — *who* the bytes
belong to.  A resident server admits concurrent workflow requests from many
tenants against one process's caches and devices, so admission must be
per-tenant:

- **Quotas** (:class:`TenantQuota`): queue depth (how many requests a
  tenant may have waiting), in-flight workflows (how many may run at
  once), and bytes in flight (the sum of the running requests' declared
  ``est_bytes``).  A submission that cannot ever be admitted — queue full,
  or ``est_bytes`` exceeding the tenant's whole byte quota — is rejected
  *immediately* with a typed :class:`AdmissionError`, never silently
  queued to rot.
- **Deficit-round-robin dispatch** (:meth:`AdmissionController.
  next_request`): tenants are served in rotation, each accruing
  ``quantum`` credits per visit and paying a byte-derived cost per
  dispatched request, so an aggressor tenant flooding the queue cannot
  starve a well-behaved one — the fairness property the serve bench
  measures (``BENCH_r10.json``).
- **Deadlines**: a queued request whose ``deadline_s`` elapses before
  dispatch is rejected (``rejected:deadline``) instead of burning a worker
  on an answer nobody is waiting for.
- **Typed backpressure**: every rejection carries a machine-readable
  ``code`` (the :data:`REJECT_*` constants) that the HTTP layer maps to a
  429/503 and the server records in ``failures.json`` — admission failures
  are attributed like any other fault (``kind='reject'`` at site
  ``admit`` in ``runtime/faults.py`` injects them for chaos).

The module also owns the **ambient request context**
(:func:`request_context` / :func:`current_request`): a thread-local
``(tenant, request_id, byte_cap)`` the server opens around each request's
``build()``.  Downstream layers read it instead of plumbing a tenant
through every call site — the handoff registry namespaces identities by
``request_id`` (``runtime/handoff.py``) and the executor caps its
auto-derived inflight byte budget at the tenant's share
(``runtime/executor.py``).  ``host_block_map`` re-enters the context on
its worker threads (:func:`request_scope`), so block-grain artifact
publishes stay namespaced.

Lock discipline (docs/ANALYSIS.md CT009): ``_admission_lock`` guards pure
bookkeeping only — no storage IO, sleeps, or future waits ever run under
it; workers block on a (lock-free) event between dispatch scans and all
rejection *recording* happens after the lock is released.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

#: typed backpressure codes carried by :class:`AdmissionError` and recorded
#: as the rejection's ``resolution`` in failures.json
REJECT_QUEUE = "rejected:queue_depth"
REJECT_BYTES = "rejected:byte_quota"
REJECT_DEADLINE = "rejected:deadline"
REJECT_DRAINING = "rejected:draining"
REJECT_FAULT = "rejected:fault"
REJECT_DUPLICATE = "rejected:duplicate"
#: gateway-layer backpressure (runtime/fleet.py, docs/SERVING.md "Fleet"):
#: no placeable member at all (everyone dead/draining — the failover
#: window, HTTP 503) vs. every member over its queue cap (transient
#: fleet-wide pressure, HTTP 429).  Both are retry-with-backoff codes.
REJECT_FLEET_NO_MEMBER = "rejected:fleet_no_member"
REJECT_FLEET_BACKLOG = "rejected:fleet_backlog"
#: every otherwise-placeable member sits behind an OPEN circuit breaker
#: (consecutive timeouts/resets — a wedged member, not a dead one).  HTTP
#: 503, retry-with-backoff: the breaker half-opens after its cooldown
#: (docs/SERVING.md "Gray failures").
REJECT_FLEET_BREAKER = "rejected:fleet_breaker_open"

#: one DRR credit buys this many bytes of request cost (requests without a
#: size declaration cost exactly one credit)
BYTE_COST_UNIT = 64 << 20


class AdmissionError(RuntimeError):
    """A typed admission rejection: ``code`` is one of the ``REJECT_*``
    constants, ``tenant`` the quota owner it was charged against.  The
    server maps it to an HTTP 429 (quota/deadline/fault) or 503
    (draining) and records it in ``failures.json``."""

    def __init__(self, code: str, tenant: Optional[str], detail: str = ""):
        self.code = code
        self.tenant = tenant
        self.detail = detail
        msg = code if not detail else f"{code}: {detail}"
        if tenant is not None:
            msg = f"[tenant {tenant}] {msg}"
        super().__init__(msg)


@dataclass(frozen=True)
class TenantQuota:
    """Admission quotas for one tenant (docs/SERVING.md "Tenant quotas").

    ``max_queue_depth`` — queued (admitted, not yet running) requests;
    ``max_inflight`` — concurrently running workflows;
    ``max_bytes_in_flight`` — sum of running requests' ``est_bytes`` (a
    request declaring more than this alone is rejected outright);
    ``quantum`` — DRR credits accrued per scheduler visit (raise to give a
    tenant a larger share of dispatch bandwidth).
    """

    max_queue_depth: int = 16
    max_inflight: int = 2
    max_bytes_in_flight: int = 2 << 30
    quantum: float = 1.0

    @classmethod
    def from_config(cls, doc: Optional[Dict[str, Any]]) -> "TenantQuota":
        doc = dict(doc or {})
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in doc.items() if k in known})


@dataclass
class Request:
    """One admitted (or rejected) workflow request, scheduler-visible
    fields only — the server keeps its own record of workflow payloads."""

    tenant: str
    request_id: str
    est_bytes: int = 0
    deadline_s: Optional[float] = None
    payload: Any = None
    enqueued_at: float = field(default_factory=time.monotonic)
    #: per-request executor byte cap, computed at dispatch (the tenant's
    #: byte quota split across its running requests); read by the executor
    #: through the ambient request context
    byte_cap: Optional[int] = None

    def cost(self) -> float:
        """DRR cost in credits: byte-proportional, floor one credit."""
        return max(1.0, float(self.est_bytes) / BYTE_COST_UNIT)

    def expired(self, now: Optional[float] = None) -> bool:
        if not self.deadline_s:
            return False
        now = time.monotonic() if now is None else now
        return (now - self.enqueued_at) > float(self.deadline_s)


class _TenantState:
    __slots__ = ("quota", "queue", "inflight", "bytes_in_flight", "deficit",
                 "submitted", "completed", "rejected", "dispatched")

    def __init__(self, quota: TenantQuota):
        self.quota = quota
        self.queue: deque = deque()
        self.inflight = 0
        self.bytes_in_flight = 0
        self.deficit = 0.0
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.dispatched = 0


class AdmissionController:
    """Thread-safe per-tenant admission + deficit-round-robin dispatch.

    ``on_reject(request_or_none, tenant, code, detail)`` is called for
    every rejection — including deadline expiries discovered at dispatch
    time — strictly *outside* ``_admission_lock`` (it may do storage IO).
    """

    def __init__(
        self,
        quotas: Optional[Dict[str, TenantQuota]] = None,
        default_quota: Optional[TenantQuota] = None,
        on_reject: Optional[Callable[..., None]] = None,
    ):
        self._admission_lock = threading.Lock()
        self._event = threading.Event()
        self._tenants: "OrderedDict[str, _TenantState]" = OrderedDict()
        self._default_quota = default_quota or TenantQuota()
        self._on_reject = on_reject
        self._draining = False
        self._rr: List[str] = []  # rotation order
        self._rr_next = 0
        for name, quota in (quotas or {}).items():
            self._tenant(name, register=True)
            self._tenants[name].quota = quota

    # -- internals (call under _admission_lock) ----------------------------
    def _tenant(self, name: str, register: bool = False) -> _TenantState:
        state = self._tenants.get(name)
        if state is None:
            state = _TenantState(self._default_quota)
            self._tenants[name] = state
            self._rr.append(name)
        return state

    # -- submission --------------------------------------------------------
    def submit(self, request: Request, admitted: bool = False) -> None:
        """Admit ``request`` into its tenant's queue or raise a typed
        :class:`AdmissionError`.  The injected ``reject`` fault (site
        ``admit``) is the caller's to check — it needs the tenant name
        before a Request even exists.

        ``admitted=True`` re-enters a request a previous server
        incarnation already acknowledged (journal replay,
        docs/SERVING.md "Durability"): the quota was charged when the
        durable 200 was issued, so the quota gates are not re-litigated —
        the request goes straight to its tenant's queue."""
        code = detail = None
        with self._admission_lock:
            state = self._tenant(request.tenant)
            if admitted:
                state.submitted += 1
                state.queue.append(request)
            elif self._draining:
                code, detail = REJECT_DRAINING, "server is draining"
            elif request.est_bytes > state.quota.max_bytes_in_flight:
                code = REJECT_BYTES
                detail = (
                    f"est_bytes {request.est_bytes} exceeds the tenant byte "
                    f"quota {state.quota.max_bytes_in_flight}"
                )
            elif len(state.queue) >= state.quota.max_queue_depth:
                code = REJECT_QUEUE
                detail = (
                    f"queue depth {len(state.queue)} at quota "
                    f"{state.quota.max_queue_depth}"
                )
            else:
                state.submitted += 1
                state.queue.append(request)
        if code is not None:
            self._reject(request, request.tenant, code, detail or "")
            raise AdmissionError(code, request.tenant, detail or "")
        self._event.set()

    def _reject(self, request, tenant, code, detail) -> None:
        with self._admission_lock:
            self._tenant(tenant).rejected += 1
        if self._on_reject is not None:
            try:
                self._on_reject(request, tenant, code, detail)
            except Exception:
                pass  # attribution is best-effort; the rejection stands

    # -- dispatch ----------------------------------------------------------
    def _try_dispatch(self) -> tuple:
        """One DRR scan under the lock: ``(request_or_None, expired)``.
        Visits every tenant once starting after the last-served one; a
        tenant with queued work accrues its quantum, and dispatches its
        head request when the deficit covers the cost AND its inflight /
        byte quotas have room.  Empty queues accrue nothing (classic DRR:
        only backlogged flows hold credit)."""
        expired: List[Request] = []
        with self._admission_lock:
            if self._draining:
                # drain latch: stop DISPATCH too — queued requests stay
                # queued (the restarted server's clients resubmit them);
                # only the already-running ones finish (docs/SERVING.md
                # "Lifecycle")
                return None, expired
            n = len(self._rr)
            now = time.monotonic()
            for off in range(n):
                name = self._rr[(self._rr_next + off) % n]
                state = self._tenants[name]
                # expired-deadline requests never dispatch; collect for
                # recording outside the lock
                while state.queue and state.queue[0].expired(now):
                    expired.append(state.queue.popleft())
                if not state.queue:
                    state.deficit = 0.0
                    continue
                state.deficit = min(
                    state.deficit + state.quota.quantum,
                    8 * max(state.quota.quantum, state.queue[0].cost()),
                )
                head = state.queue[0]
                if head.cost() > state.deficit:
                    continue
                if state.inflight >= state.quota.max_inflight:
                    continue
                if (state.bytes_in_flight + head.est_bytes
                        > state.quota.max_bytes_in_flight):
                    continue
                state.queue.popleft()
                state.deficit -= head.cost()
                state.inflight += 1
                state.dispatched += 1
                state.bytes_in_flight += head.est_bytes
                # the executor's tenant-tagged budget: this request's share
                # of the tenant's byte quota while its siblings run.
                # Work-conserving on purpose: earlier dispatches keep the
                # larger cap they started with (a lone request gets the
                # whole quota), so a tenant's LIVE caps can transiently sum
                # past the quota — admission still gates actual est_bytes
                # at the quota, and the executor additionally bounds its
                # budget by real host headroom.
                head.byte_cap = max(
                    1, state.quota.max_bytes_in_flight // state.inflight
                )
                self._rr_next = (self._rr_next + off + 1) % n
                return head, expired
        return None, expired

    def next_request(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Dispatch the next runnable request (DRR order), waiting up to
        ``timeout`` seconds for one to become available.  Deadline-expired
        requests encountered on the way are rejected
        (``rejected:deadline``) through ``on_reject``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            request, expired = self._try_dispatch()
            for r in expired:
                self._reject(
                    r, r.tenant, REJECT_DEADLINE,
                    f"deadline_s={r.deadline_s:g} elapsed in queue",
                )
            if request is not None:
                return request
            if deadline is not None and time.monotonic() >= deadline:
                return None
            # wait OUTSIDE the admission lock for a submit/release to nudge
            self._event.wait(0.05)
            self._event.clear()

    def release(self, request: Request, completed: bool = True) -> None:
        """A dispatched request finished (any terminal state): return its
        inflight/byte claims to the tenant."""
        with self._admission_lock:
            state = self._tenant(request.tenant)
            state.inflight = max(0, state.inflight - 1)
            state.bytes_in_flight = max(
                0, state.bytes_in_flight - request.est_bytes
            )
            if completed:
                state.completed += 1
        self._event.set()

    def restore_counts(self, tenant: str, submitted: int = 0,
                       dispatched: int = 0, completed: int = 0,
                       rejected: int = 0) -> None:
        """Seed a tenant's lifetime counters from a journal replay
        (docs/SERVING.md "Durability").  Quota *state* (queue depth,
        inflight, bytes) rebuilds naturally as replayed requests re-enter
        through :meth:`submit`; the monotonic counters would otherwise
        reset to zero across a restart and lie to the operator view and
        the fairness accounting."""
        with self._admission_lock:
            state = self._tenant(tenant)
            state.submitted += int(submitted)
            state.dispatched += int(dispatched)
            state.completed += int(completed)
            state.rejected += int(rejected)

    # -- drain + introspection --------------------------------------------
    def begin_drain(self) -> None:
        """Stop admitting: subsequent submits are rejected
        ``rejected:draining``; queued requests stay queued (the restart
        resubmits them — docs/SERVING.md "Lifecycle")."""
        with self._admission_lock:
            self._draining = True
        self._event.set()

    def draining(self) -> bool:
        with self._admission_lock:
            return self._draining

    def idle(self) -> bool:
        """No request running anywhere (queued ones may remain)."""
        with self._admission_lock:
            return all(s.inflight == 0 for s in self._tenants.values())

    def queued(self) -> int:
        with self._admission_lock:
            return sum(len(s.queue) for s in self._tenants.values())

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant stats for the server state file / ``/status``."""
        with self._admission_lock:
            return {
                name: {
                    "queued": len(s.queue),
                    "inflight": s.inflight,
                    "bytes_in_flight": int(s.bytes_in_flight),
                    "submitted": s.submitted,
                    "dispatched": s.dispatched,
                    "completed": s.completed,
                    "rejected": s.rejected,
                    "quota": {
                        "max_queue_depth": s.quota.max_queue_depth,
                        "max_inflight": s.quota.max_inflight,
                        "max_bytes_in_flight": int(
                            s.quota.max_bytes_in_flight
                        ),
                        "quantum": s.quota.quantum,
                    },
                }
                for name, s in self._tenants.items()
            }


# -- ambient request context --------------------------------------------------
# Thread-local on purpose: one request's build() owns its worker thread, and
# the layers that read the context (handoff identity namespacing, executor
# byte caps) are called from that thread.  Pools spawned inside a request
# (host_block_map's IO workers) re-enter it via request_scope().


class RequestContext:
    __slots__ = ("tenant", "request_id", "byte_cap")

    def __init__(self, tenant: str, request_id: str,
                 byte_cap: Optional[int] = None):
        self.tenant = tenant
        self.request_id = request_id
        self.byte_cap = byte_cap


_tls = threading.local()


def current_request() -> Optional[RequestContext]:
    """The request context of THIS thread, or None outside service mode."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def request_context(tenant: str, request_id: str,
                    byte_cap: Optional[int] = None):
    """Open a request context on this thread (the server wraps each
    request's ``build()`` in one)."""
    prev = current_request()
    _tls.ctx = RequestContext(tenant, request_id, byte_cap)
    try:
        yield _tls.ctx
    finally:
        _tls.ctx = prev


@contextlib.contextmanager
def request_scope(ctx: Optional[RequestContext]):
    """Re-enter a captured context on another thread (``host_block_map``
    worker pools); a None context is a no-op, so batch-mode callers pay
    nothing."""
    prev = current_request()
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


def ambient_byte_cap() -> Optional[int]:
    """The executor-facing view of the context: the running request's
    share of its tenant's byte quota (None outside service mode)."""
    ctx = current_request()
    return None if ctx is None else ctx.byte_cap
