"""Cluster-scheduler targets: ``target='slurm'`` / ``target='lsf'``.

The reference ran every task as cluster jobs — per-job scripts submitted
with ``sbatch``/``bsub``, progress tracked through block markers on the
shared filesystem (SURVEY.md §1 L2', §7).  This framework schedules
*compute* onto the device mesh, so its cluster backend exists for the
ingest side: IO-heavy host tasks (copy_volume, downscaling, ingest
conversions) running on a cluster node that feeds the TPU host.

Design differences from the reference, on purpose:

- The unit of submission is the TASK, not per-block job arrays: blocks
  already parallelize inside one process (device batches + IO threads),
  so one node per task keeps the scheduler interaction minimal while the
  manifests + block markers keep the same resume grain.
- The submitting process stays the DAG owner: ``build()`` resolves
  dependencies and writes success manifests; the remote job only executes
  ``run_impl`` via :mod:`.cluster_runner` and reports its result in a
  JSON file.  A shared filesystem between submitter and nodes is assumed
  (the reference assumed the same).

Scheduler interaction is isolated in :class:`SlurmSubmitter` /
:class:`LSFSubmitter` (submit + liveness probe), so tests drive the full
machinery with stub ``sbatch``/``squeue`` executables and no cluster.

Config keys (per-task JSON, matching the reference's slurm knobs):
``partition``, ``time_limit`` (minutes), ``mem_limit`` (GB), ``qos``,
``poll_interval_s``, ``submit_timeout_s``, ``result_grace_s`` (wait for
the result file after the job leaves the queue — NFS cache lag),
``probe_failure_grace_s`` (continuous scheduler-unreachable stretch
tolerated before declaring the job gone).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Dict, Optional

from ..utils import function_utils as fu
from . import faults as faults_mod


class ClusterSubmitter:
    """Submit a job script and probe whether the job still runs."""

    flavor = "abstract"

    def submit(self, script_path: str, job_name: str, out_path: str,
               cfg: Dict[str, Any]) -> str:
        raise NotImplementedError

    def is_running(self, job_id: str) -> Optional[bool]:
        """True = queued/running, False = gone from the queue, None =
        probe failed (scheduler hiccup — status unknown)."""
        raise NotImplementedError

    def cancel(self, job_id: str) -> None:
        """Best-effort kill — failure paths must not leave a zombie job
        racing a resubmission on the same uid-keyed paths."""
        raise NotImplementedError


class SlurmSubmitter(ClusterSubmitter):
    flavor = "slurm"

    def submit(self, script_path, job_name, out_path, cfg):
        cmd = ["sbatch", "--parsable", "-J", job_name, "-o", out_path]
        if cfg.get("partition"):
            cmd += ["-p", str(cfg["partition"])]
        if cfg.get("time_limit"):
            cmd += ["-t", str(int(cfg["time_limit"]))]
        if cfg.get("mem_limit"):
            cmd += ["--mem", f"{int(float(cfg['mem_limit']) * 1024)}M"]
        if cfg.get("qos"):
            cmd += ["--qos", str(cfg["qos"])]
        cmd.append(script_path)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"sbatch failed (exit {proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        # --parsable prints "<jobid>[;cluster]"
        return proc.stdout.strip().split(";")[0].strip()

    def is_running(self, job_id):
        # squeue exits 0 with no rows once the job left the queue, but
        # after MinJobAge it exits nonzero with "Invalid job id" — that is
        # a definite finish, while any other nonzero exit is a scheduler
        # hiccup with the status unknown
        probe = subprocess.run(
            ["squeue", "-h", "-j", job_id], capture_output=True, text=True
        )
        if probe.returncode != 0:
            blob = probe.stdout + probe.stderr
            if "Invalid job id" in blob:
                return False
            return None
        return bool(probe.stdout.strip())

    def cancel(self, job_id):
        subprocess.run(["scancel", job_id], capture_output=True, text=True)


class LSFSubmitter(ClusterSubmitter):
    flavor = "lsf"

    def submit(self, script_path, job_name, out_path, cfg):
        cmd = ["bsub", "-J", job_name, "-o", out_path]
        if cfg.get("partition"):
            cmd += ["-q", str(cfg["partition"])]
        if cfg.get("time_limit"):
            cmd += ["-W", str(int(cfg["time_limit"]))]
        if cfg.get("mem_limit"):
            mb = int(float(cfg["mem_limit"]) * 1024)
            cmd += ["-M", str(mb)]
        with open(script_path) as f:
            proc = subprocess.run(cmd, stdin=f, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"bsub failed (exit {proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        out = proc.stdout
        # "Job <123> is submitted to ..."
        try:
            return out.split("<", 1)[1].split(">", 1)[0]
        except IndexError:
            raise RuntimeError(f"cannot parse bsub output: {out!r}")

    def is_running(self, job_id):
        probe = subprocess.run(
            ["bjobs", "-noheader", job_id], capture_output=True, text=True
        )
        blob = probe.stdout + probe.stderr
        if "is not found" in blob:  # purged from history: definite finish
            return False
        if probe.returncode != 0:
            return None
        line = probe.stdout.strip()
        return bool(line) and (" DONE " not in line and " EXIT " not in line)

    def cancel(self, job_id):
        subprocess.run(["bkill", job_id], capture_output=True, text=True)


_SUBMITTERS = {"slurm": SlurmSubmitter, "lsf": LSFSubmitter}


def submit_with_retries(
    submitter: ClusterSubmitter,
    script_path: str,
    job_name: str,
    out_path: str,
    cfg: Dict[str, Any],
    logger=None,
) -> str:
    """Submit, retrying transient scheduler failures (slurmctld restarts,
    comm timeouts — the submit-side twin of the probe-failure grace) with
    capped exponential backoff + jitter.

    Config keys: ``submit_retries`` (default 3), ``submit_backoff_s``
    (base, default 2), ``submit_backoff_max_s`` (cap, default 30).
    """
    retries = int(cfg.get("submit_retries", 3))
    base = float(cfg.get("submit_backoff_s", 2.0))
    cap = float(cfg.get("submit_backoff_max_s", 30.0))
    for attempt in range(retries + 1):
        try:
            faults_mod.get_injector().maybe_fail("submit")
            return submitter.submit(script_path, job_name, out_path, cfg)
        except FileNotFoundError:
            # sbatch/bsub not on PATH: a configuration error, not an
            # outage — retrying only delays the real message
            raise
        except Exception as e:
            if attempt >= retries:
                raise
            delay = fu.backoff_delay(attempt, base, cap)
            if logger is not None:
                logger.warning(
                    f"{submitter.flavor} submit failed (attempt "
                    f"{attempt + 1}/{retries + 1}): {e}; retrying in "
                    f"{delay:.1f}s"
                )
            time.sleep(delay)


def _spec_default(obj):
    """Numpy scalars/arrays become their Python equivalents; anything else
    fails AT SUBMIT TIME instead of reaching the remote node stringified."""
    item = getattr(obj, "item", None)
    if item is not None and getattr(obj, "ndim", 1) == 0:
        return item()
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return tolist()
    raise TypeError(
        f"task param of type {type(obj).__name__} is not JSON-serializable; "
        "cluster targets re-execute the task from a JSON spec, so params "
        "must be plain Python / numpy values"
    )


def cluster_dir(tmp_folder: str) -> str:
    d = os.path.join(tmp_folder, "cluster")
    os.makedirs(d, exist_ok=True)
    return d


def make_cluster_task(local_cls, flavor: str):
    """Wrap an ``<Op>Local`` class into a submitting ``<Op>Slurm``/``LSF``.

    The wrapper's ``run_impl`` serializes the task spec, submits a batch
    script that re-executes the LOCAL variant remotely
    (:mod:`.cluster_runner`), polls the scheduler plus the result file,
    and returns the remote result — so manifests, markers, logs, and
    resume behave exactly as for a local run.
    """
    submitter_cls = _SUBMITTERS[flavor]

    def run_impl(self):
        cfg = self.get_config()
        cdir = cluster_dir(self.tmp_folder)
        spec = {
            "module": local_cls.__module__,
            "cls": local_cls.__name__,
            "tmp_folder": self.tmp_folder,
            "config_dir": self.config_dir,
            "max_jobs": self.max_jobs,
            "params": self.params,
            "result_path": os.path.join(cdir, f"{self.uid}.result.json"),
        }
        spec_path = os.path.join(cdir, f"{self.uid}.spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f, indent=2, default=_spec_default)
        script_path = os.path.join(cdir, f"{self.uid}.sh")
        out_path = os.path.join(cdir, f"{self.uid}.out")
        # the remote interpreter must find this package regardless of the
        # job's working directory (the reference wrote shebang/env lines
        # into its job scripts for the same reason)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        with open(script_path, "w") as f:
            f.write(
                "#!/bin/bash\n"
                f"export PYTHONPATH={pkg_root}:$PYTHONPATH\n"
                f"exec {fu.python_executable()} -m "
                f"cluster_tools_tpu.runtime.cluster_runner {spec_path}\n"
            )
        os.chmod(script_path, 0o755)
        # a retry must not consume the previous attempt's result
        try:
            os.unlink(spec["result_path"])
        except OSError:
            pass

        submitter = submitter_cls()
        job_id = submit_with_retries(
            submitter, script_path, self.uid, out_path, cfg, self.logger
        )
        self.logger.info(f"{flavor} job {job_id} submitted ({script_path})")

        poll = float(cfg.get("poll_interval_s", 5.0))
        timeout = cfg.get("submit_timeout_s")
        # NFS attribute/dentry caches commonly delay file visibility by
        # 30-60 s, so after the job leaves the queue keep re-checking for
        # the result file for a full grace window before declaring failure
        grace = float(cfg.get("result_grace_s", 60.0))
        # scheduler outages (slurmctld restart, comm timeouts) last
        # minutes, not polls — tolerate a continuous stretch of unknown
        # status before concluding the job is gone
        probe_grace = float(cfg.get("probe_failure_grace_s", 600.0))
        t0 = time.time()
        unknown_since = None
        while True:
            if os.path.exists(spec["result_path"]):
                break
            running = submitter.is_running(job_id)
            if running is None:
                unknown_since = unknown_since or time.time()
            else:
                unknown_since = None
            probe_exhausted = (
                unknown_since is not None
                and time.time() - unknown_since > probe_grace
            )
            if running is False or probe_exhausted:
                t_gone = time.time()
                while (time.time() - t_gone < grace
                       and not os.path.exists(spec["result_path"])):
                    time.sleep(min(poll, 2.0))
                break
            if timeout and time.time() - t0 > float(timeout):
                submitter.cancel(job_id)
                raise RuntimeError(
                    f"{flavor} job {job_id} exceeded submit_timeout_s="
                    f"{timeout} (job cancelled); see {out_path}"
                )
            time.sleep(poll)

        if not os.path.exists(spec["result_path"]):
            # the job may still exist (probe-grace exhaustion): kill it so
            # it cannot race a resubmission on the same uid-keyed paths
            submitter.cancel(job_id)
            tail = ""
            try:
                with open(out_path) as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            raise RuntimeError(
                f"{flavor} job {job_id} finished without a result file — "
                f"remote failure (job cancelled).  Job output tail:\n{tail}"
            )
        with open(spec["result_path"]) as f:
            remote = json.load(f)
        if not remote.get("ok"):
            raise RuntimeError(
                f"{flavor} job {job_id} failed remotely: "
                f"{remote.get('error', 'unknown error')}"
            )
        return remote.get("result", {})

    return type(
        local_cls.__name__.replace("Local", flavor.upper() if flavor == "lsf"
                                   else flavor.capitalize()),
        (local_cls,),
        {"target": flavor, "run_impl": run_impl},
    )
